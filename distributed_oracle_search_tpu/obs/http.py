"""Live scrape endpoints: ``/metrics``, ``/healthz``, ``/statusz``.

PR 1 gave every process a metrics registry; until now the only way out
was dump-at-exit JSON — you could not ask a *running* deployment "what
is p99 right now, which breaker is open, which replica is absorbing
failover". This module is the answer: a stdlib-only
(``http.server.ThreadingHTTPServer``) scrape server any resident
process opts into with ``--obs-port N`` / ``DOS_OBS_PORT=N`` (``0`` =
OS-assigned ephemeral port, logged at startup; unset = off, exactly the
pre-PR behavior). Binds loopback unless ``DOS_OBS_HOST`` widens it —
the endpoints are unauthenticated and ``/statusz`` names FIFO paths
and topology, so exposure to a scraped network is an explicit operator
decision.

* ``GET /metrics`` — Prometheus text exposition 0.0.4: the cumulative
  registry (``obs.metrics.to_prometheus``, per-worker gauges folded
  into ``{worker="N"}`` labels) **plus** the live sliding-window
  quantile gauges with exemplar trace ids (``obs.quantiles``) and the
  per-compiled-program XLA cost gauges (``obs.device``);
* ``GET /healthz`` — liveness JSON with the supervisor's
  :class:`~..transport.wire.HealthStatus` semantics: HTTP 200 when the
  provider says ``ok``, 503 otherwise, so a k8s-style probe needs no
  JSON parsing;
* ``GET /statusz`` — one JSON object merging every registered status
  provider: breaker states, per-shard queue depths, the
  replica/failover map, hedge rates, build-ledger progress — whatever
  the hosting process wires in. A provider that raises reports its
  error under its own key instead of failing the whole page.

The server runs on a daemon thread named ``dos-obs-http`` and is joined
by :meth:`ObsServer.close` (the test suite's leak check holds every
``dos-*`` thread to that contract). Handlers are deliberately read-only
— scraping a production fleet must never mutate it.
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..utils.env import env_cast, env_str
from ..utils.log import get_logger
from . import device as obs_device
from . import metrics as obs_metrics
from . import quantiles as obs_quantiles

log = get_logger(__name__)

M_SCRAPES = obs_metrics.counter(
    "obs_scrapes_total", "HTTP requests answered by the obs endpoints")


def resolve_obs_port(flag_value=None) -> tuple[int | None, str]:
    """``(port, source)`` the obs server should listen on: an explicit
    flag wins (source ``"flag"``), else ``DOS_OBS_PORT`` (source
    ``"env"``), else ``(None, "off")``. Negative values are off — the
    degrade-don't-crash policy of every ``DOS_*`` knob."""
    if flag_value is not None:
        return (None, "off") if flag_value < 0 else (int(flag_value),
                                                     "flag")
    port = env_cast("DOS_OBS_PORT", None, int)
    if port is None or port < 0:
        return None, "off"
    return int(port), "env"


class ObsServer:
    """One process's scrape server. ``health_fn() -> dict`` should
    return at least ``{"ok": bool}``; ``status_providers`` maps section
    name -> zero-arg callable returning a JSON-able object."""

    def __init__(self, port: int, health_fn=None,
                 status_providers: dict | None = None,
                 registry: obs_metrics.MetricsRegistry | None = None,
                 windows: obs_quantiles.QuantileWindows | None = None,
                 host: str | None = None, slo_provider=None):
        self.registry = registry or obs_metrics.REGISTRY
        self.windows = windows or obs_quantiles.WINDOWS
        self.health_fn = health_fn
        self.status_providers = dict(status_providers or {})
        #: zero-arg callable returning the ``/slo`` JSON payload (the
        #: SLO engine's fresh evaluation); absent = 404, pre-SLO shape
        self.slo_provider = slo_provider
        if host is None:
            # loopback by default: the endpoints are unauthenticated
            # and /statusz names FIFO paths and topology — widening to
            # a routable interface is an explicit operator decision
            # (DOS_OBS_HOST=0.0.0.0 for a scraped fleet)
            host = env_str("DOS_OBS_HOST", "127.0.0.1")
        self._httpd = ThreadingHTTPServer((host, int(port)),
                                          self._make_handler())
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="dos-obs-http")

    # --------------------------------------------------------- lifecycle
    def start(self) -> "ObsServer":
        self._thread.start()
        log.info("obs endpoints up on :%d (/metrics /healthz /statusz%s)",
                 self.port,
                 " /slo" if self.slo_provider is not None else "")
        return self

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)

    def add_provider(self, name: str, fn) -> None:
        """Register/replace one ``/statusz`` section after start."""
        self.status_providers[name] = fn

    # ----------------------------------------------------------- payload
    def metrics_text(self) -> str:
        parts = [self.registry.to_prometheus(),
                 self.windows.to_prometheus(),
                 obs_device.to_prometheus()]
        return "".join(p for p in parts if p)

    def health(self) -> dict:
        if self.health_fn is None:
            return {"ok": True}
        try:
            return dict(self.health_fn())
        except Exception as e:  # noqa: BLE001 — a health-provider bug
            # must surface as unhealthy, never as a scrape crash
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}

    def slo(self) -> dict:
        try:
            return dict(self.slo_provider())
        except Exception as e:  # noqa: BLE001 — a burn-eval bug must
            # not take down the page the operator is paged ON
            return {"error": f"{type(e).__name__}: {e}"}

    def statusz(self) -> dict:
        out = {}
        for name, fn in sorted(self.status_providers.items()):
            try:
                out[name] = fn()
            except Exception as e:  # noqa: BLE001 — one broken section
                # must not take down the page the operator is debugging
                # WITH
                out[name] = {"error": f"{type(e).__name__}: {e}"}
        return out

    # ----------------------------------------------------------- handler
    def _make_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):   # quiet: obs, not access
                pass                             # logs

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                M_SCRAPES.inc()
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        self._send(
                            200, server.metrics_text().encode(),
                            "text/plain; version=0.0.4; charset=utf-8")
                    elif path == "/healthz":
                        h = server.health()
                        self._send(
                            200 if h.get("ok") else 503,
                            (json.dumps(h) + "\n").encode(),
                            "application/json")
                    elif path == "/statusz":
                        self._send(
                            200,
                            (json.dumps(server.statusz(), indent=1,
                                        default=str) + "\n").encode(),
                            "application/json")
                    elif (path == "/slo"
                          and server.slo_provider is not None):
                        self._send(
                            200,
                            (json.dumps(server.slo(), indent=1,
                                        default=str) + "\n").encode(),
                            "application/json")
                    else:
                        self._send(404, b"not found\n", "text/plain")
                except BrokenPipeError:
                    pass          # scraper went away mid-reply

        return Handler


def start_obs_server(port, health_fn=None, status_providers=None,
                     **kw) -> ObsServer | None:
    """Start an :class:`ObsServer` when ``port`` resolves to a port
    (see :func:`resolve_obs_port`); None otherwise. Callers own
    ``close()``.

    A bind failure on an ENV-derived port degrades to no-endpoints
    with a warning (the ``DOS_*`` knob policy — and the fleet case:
    ``DOS_OBS_PORT`` in a shared environment must not crash every
    process that inherits it onto one port). An explicit ``--obs-port``
    flag still raises: the operator asked for exactly that port."""
    resolved, source = resolve_obs_port(port)
    if resolved is None:
        return None
    try:
        srv = ObsServer(resolved, health_fn=health_fn,
                        status_providers=status_providers, **kw)
    except OSError as e:
        if source == "flag":
            raise
        log.warning("ignoring DOS_OBS_PORT=%d (cannot bind: %s); "
                    "obs endpoints disabled for this process",
                    resolved, e)
        return None
    return srv.start()
