"""Head-side fleet timeseries: bounded rings of telemetry samples.

The telemetry bus (:mod:`.telemetry`) streams each worker's counters,
gauges and window snapshots to the head on a fixed cadence; this module
is where those ticks land — a queryable, *bounded* in-memory store the
SLO burn-rate engine (:mod:`.slo`) and ``dos-obs top`` read instead of
polling ``/statusz`` across the fleet.

Layout: one fixed-capacity ring per series, keyed ``(worker, name)``.
Appends are O(1) (preallocated ``array`` pairs of timestamp + value,
head index wraps); timestamps are bucketed to absolute ``bucket_s``
boundaries so samples from different workers land in comparable
buckets — two samples of one series in one bucket merge (counters sum
their deltas, gauges keep the last write) rather than burning ring
slots on a fast publisher.

Byte budget: ``DOS_TELEMETRY_BYTES`` caps the whole store. When a new
series would cross the budget, the least-recently-written series is
evicted (and counted) — a fleet that grows series faster than the head
budgeted for degrades to shorter memory, never to OOM.

Series kinds:

* ``"delta"`` — per-tick counter increments (the ingest layer already
  clamped monotonic resets); :meth:`TimeseriesStore.rate` sums them
  over a trailing window;
* ``"gauge"`` — point-in-time values; :meth:`latest` / :meth:`query`;
* window snapshots are stored whole (latest per ``(worker, name)``)
  plus their p99 as a ``<name>:p99`` gauge series, so both "the
  worker's own view" and "the fleet trend" are queryable.
"""

from __future__ import annotations

import time
from array import array

from ..utils.env import env_cast
from ..utils.locks import OrderedLock
from ..utils.log import get_logger
from . import metrics as obs_metrics

log = get_logger(__name__)

M_POINTS = obs_metrics.counter(
    "telemetry_points_total", "samples appended to the fleet store")
M_EVICTED = obs_metrics.counter(
    "telemetry_series_evicted_total",
    "series dropped by the DOS_TELEMETRY_BYTES budget")
G_SERIES = obs_metrics.gauge(
    "telemetry_series", "live series rings in the fleet store")
G_BYTES = obs_metrics.gauge(
    "telemetry_store_bytes", "bytes held by the fleet store's rings")

#: per-ring sample capacity — ts+value doubles, ~16 B/slot; 360 slots
#: at a 5 s cadence is half an hour of memory per series
DEFAULT_CAPACITY = 360


class SeriesRing:
    """One series' fixed-capacity ring: O(1) append, oldest-first read."""

    __slots__ = ("capacity", "kind", "_ts", "_val", "_head", "_n",
                 "last_write")

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 kind: str = "gauge"):
        self.capacity = int(capacity)
        self.kind = kind
        self._ts = array("d", [0.0]) * self.capacity
        self._val = array("d", [0.0]) * self.capacity
        self._head = 0          # next write slot
        self._n = 0
        self.last_write = 0.0

    def append(self, ts: float, value: float) -> None:
        if self._n:
            last = (self._head - 1) % self.capacity
            if self._ts[last] == ts:
                # same absolute bucket: merge instead of spending a slot
                if self.kind == "delta":
                    self._val[last] += value
                else:
                    self._val[last] = value
                self.last_write = ts
                return
        self._ts[self._head] = ts
        self._val[self._head] = value
        self._head = (self._head + 1) % self.capacity
        self._n = min(self._n + 1, self.capacity)
        self.last_write = ts

    def points(self, since: float | None = None) -> list[tuple]:
        """Oldest-first ``(ts, value)`` pairs (``since`` filters)."""
        start = (self._head - self._n) % self.capacity
        out = []
        for i in range(self._n):
            j = (start + i) % self.capacity
            if since is None or self._ts[j] >= since:
                out.append((self._ts[j], self._val[j]))
        return out

    def latest(self) -> tuple | None:
        if not self._n:
            return None
        j = (self._head - 1) % self.capacity
        return (self._ts[j], self._val[j])

    @property
    def nbytes(self) -> int:
        return self._ts.itemsize * self.capacity * 2

    def __len__(self) -> int:
        return self._n


class TimeseriesStore:
    """The fleet store: ``(worker, name)``-keyed rings + latest window
    snapshots, byte-budgeted."""

    def __init__(self, max_bytes: int | None = None,
                 capacity: int = DEFAULT_CAPACITY,
                 bucket_s: float | None = None, clock=time.time):
        self.max_bytes = int(
            max_bytes if max_bytes is not None
            else env_cast("DOS_TELEMETRY_BYTES", 8 << 20, int))
        self.capacity = int(capacity)
        self.bucket_s = float(
            bucket_s if bucket_s is not None
            else env_cast("DOS_TELEMETRY_BUCKET_S", 5.0, float))
        if self.bucket_s <= 0:
            self.bucket_s = 5.0
        self.clock = clock
        self._series: dict[tuple, SeriesRing] = {}
        self._windows: dict[tuple, tuple] = {}   # (worker,name)->(ts,snap)
        self._bytes = 0
        self._lock = OrderedLock("timeseries.TimeseriesStore")

    # ------------------------------------------------------------- write
    def bucket(self, ts: float) -> float:
        return (ts // self.bucket_s) * self.bucket_s

    def _ring_locked(self, worker: str, name: str,
                     kind: str) -> SeriesRing:
        key = (worker, name)
        ring = self._series.get(key)
        if ring is None:
            ring = SeriesRing(self.capacity, kind=kind)
            while (self._series
                   and self._bytes + ring.nbytes > self.max_bytes):
                victim = min(self._series,
                             key=lambda k: self._series[k].last_write)
                self._bytes -= self._series.pop(victim).nbytes
                M_EVICTED.inc()
                log.warning("telemetry store over budget: evicted "
                            "series %s/%s", victim[0], victim[1])
            self._series[key] = ring
            self._bytes += ring.nbytes
            G_SERIES.set(len(self._series))
            G_BYTES.set(self._bytes)
        return ring

    def append(self, worker: str, name: str, ts: float, value: float,
               kind: str = "gauge") -> None:
        with self._lock:
            self._ring_locked(worker, name, kind).append(
                self.bucket(ts), float(value))
        M_POINTS.inc()

    def put_window(self, worker: str, name: str, ts: float,
                   snap: dict) -> None:
        """Latest window snapshot per ``(worker, name)``, plus its p99
        and count as trend series."""
        with self._lock:
            self._windows[(worker, name)] = (float(ts), dict(snap))
        qs = snap.get("quantiles") or {}
        p99 = qs.get("p99")
        if isinstance(p99, (int, float)):
            self.append(worker, f"{name}:p99", ts, float(p99))
        count = snap.get("count")
        if isinstance(count, (int, float)):
            self.append(worker, f"{name}:count", ts, float(count))

    # -------------------------------------------------------------- read
    def workers(self) -> list[str]:
        with self._lock:
            return sorted({w for w, _ in self._series}
                          | {w for w, _ in self._windows})

    def query(self, name: str, worker: str | None = None,
              since: float | None = None) -> dict[str, list]:
        """``{worker: [(ts, value), ...]}`` for one series name."""
        with self._lock:
            keys = [(w, n) for (w, n) in self._series
                    if n == name and (worker is None or w == worker)]
            return {w: self._series[(w, n)].points(since=since)
                    for w, n in keys}

    def latest(self, name: str,
               worker: str | None = None) -> dict[str, tuple]:
        with self._lock:
            keys = [(w, n) for (w, n) in self._series
                    if n == name and (worker is None or w == worker)]
            out = {}
            for w, n in keys:
                p = self._series[(w, n)].latest()
                if p is not None:
                    out[w] = p
            return out

    def rate(self, name: str, window_s: float,
             worker: str | None = None,
             now: float | None = None) -> float:
        """Summed delta-series increments over the trailing window,
        per second, across the selected workers (the fleet rate when
        ``worker`` is None)."""
        now = self.clock() if now is None else now
        since = self.bucket(now - window_s)
        total = 0.0
        for pts in self.query(name, worker=worker,
                              since=since).values():
            total += sum(v for _, v in pts)
        return total / window_s if window_s > 0 else 0.0

    def window(self, name: str,
               worker: str | None = None) -> dict[str, dict]:
        """Latest stored window snapshots ``{worker: snap}``."""
        with self._lock:
            return {w: snap for (w, n), (_, snap)
                    in self._windows.items()
                    if n == name and (worker is None or w == worker)}

    def fleet_window(self, name: str,
                     max_age_s: float | None = None,
                     now: float | None = None) -> dict | None:
        """The fleet-merged view of one quantile window: counts sum,
        each quantile takes the worst (max) across workers — a
        conservative fleet p99 that can never hide a slow replica
        behind a fast one. None when no worker has reported."""
        now = self.clock() if now is None else now
        with self._lock:
            snaps = [(ts, snap) for (w, n), (ts, snap)
                     in self._windows.items() if n == name]
        if max_age_s is not None:
            snaps = [(ts, s) for ts, s in snaps if now - ts <= max_age_s]
        live = [s for _, s in snaps if s.get("count")]
        if not live:
            return None
        out = {"count": sum(int(s.get("count", 0)) for s in live),
               "workers": len(live),
               "window_s": max(float(s.get("window_s", 0.0))
                               for s in live),
               "quantiles": {}}
        for q in ("p50", "p95", "p99"):
            vals = [s["quantiles"][q] for s in live
                    if isinstance((s.get("quantiles") or {}).get(q),
                                  (int, float))]
            if vals:
                out["quantiles"][q] = max(vals)
        return out

    # ------------------------------------------------------------ status
    def statusz(self) -> dict:
        with self._lock:
            return {"series": len(self._series),
                    "windows": len(self._windows),
                    "bytes": self._bytes,
                    "max_bytes": self.max_bytes,
                    "bucket_s": self.bucket_s}
