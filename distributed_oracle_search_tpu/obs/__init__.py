"""Observability: metrics registry + span tracing for the query path.

The serving system fans query batches from a head node to shard-owning
workers (``cli.process_query`` → FIFO wire → ``worker.server`` →
``worker.engine``); this package is the standing instrumentation layer
every perf/robustness change reports through:

* :mod:`.metrics` — thread-safe counters / gauges / histograms with JSON
  snapshot and Prometheus text exposition (``--metrics-dump PATH``, and
  ``bench.py`` embeds a snapshot in ``BENCH_DETAIL.json``); per-worker
  name suffixes (``serve_queue_depth_w3``) fold into
  ``{worker="3"}`` labels on the text exposition;
* :mod:`.trace` — nested span tracing exporting Chrome trace-event JSON
  (``--trace PATH``, open in Perfetto), with a per-batch ``trace_id``
  propagated head→worker as a ``RuntimeConfig`` wire extension so both
  sides of one batch join on a single timeline;
* :mod:`.quantiles` — live sliding-window p50/p95/p99 over the last N
  seconds (``DOS_OBS_WINDOW_S``) for the latency histograms that matter
  online (``serve_request_seconds``, ``serve_dispatch_seconds``,
  ``worker_search_seconds``), each window keeping a worst-case
  **exemplar** ``trace_id`` that links a bad p99 to its Perfetto
  timeline;
* :mod:`.http` — the stdlib scrape server every resident process opts
  into with ``--obs-port`` / ``DOS_OBS_PORT``: ``/metrics`` (Prometheus
  text incl. live quantiles + per-program XLA costs), ``/healthz``
  (200/503 with ``HealthStatus`` semantics), ``/statusz`` (JSON:
  breakers, queue depths, replica/failover map, hedge rates, ledger
  progress);
* :mod:`.fleet` — head-side aggregation behind the ``dos-obs`` CLI:
  merge per-worker ``obs_metrics.json`` into ``fleet_metrics.json``,
  merge head + worker ``.trace`` sidecars into one campaign-wide
  Perfetto timeline, poll ``/statusz`` for a live fleet table, and
  gate ``BENCH_r*.json`` rounds against each other (``bench-diff``);
* :mod:`.device` — per-compiled-program XLA ``cost_analysis`` /
  ``memory_analysis`` capture (FLOPs, bytes accessed, HBM footprint)
  keyed by the engine's program cache, feeding the ``/metrics``
  ``device_program_*`` gauges and the roofline fields in
  ``BENCH_DETAIL.json``.

Mapping to the reference paper's per-batch stats fields (the wire CSV,
``transport.wire.ENGINE_STAT_FIELDS``) — the histograms decompose what
the reference reports only as three wall-clock totals:

=============  =====================================================
stats field    obs metrics covering the same interval
=============  =====================================================
``t_receive``  ``worker_receive_seconds`` — batch prep INCLUDING the
               weights load; ``worker_weights_load_seconds`` is the
               contained sub-phase (diff read + device upload), NOT an
               additional interval. The query-file read happens in the
               server, outside the engine's timers, and appears as the
               ``worker.receive`` span only.
``t_astar``    ``worker_search_seconds`` (the search call itself;
               first-call XLA compile time is split out into
               ``worker_jit_compile_seconds`` so steady-state latency
               is not polluted by one-time compilation)
``t_search``   receive + search — the worker's whole batch; the
               head-side view of the same batch is
               ``head_prepare_seconds`` + ``head_send_seconds``
               (FIFO round-trip, includes the worker's t_search)
=============  =====================================================

Campaign-path volume/phase series (head and worker sides of the same
batches): ``head_batches_total`` / ``head_batches_failed_total`` and
``head_partition_seconds`` / ``head_prepare_seconds`` /
``head_send_seconds`` / ``head_search_seconds`` on the head;
``worker_batches_total`` / ``worker_queries_total`` and
``server_replies_sent_total`` on the worker (sent replies are the
complement of the drop counters below).

Server failure paths (no stats-field analog — the reference dropped
these on the floor): ``server_frames_received_total``,
``server_frames_malformed_total``, ``server_frames_half_total``,
``server_replies_dropped_total``, ``server_ping_replies_dropped_total``
(control-frame drops split out so they never pollute the data-plane
drop alert), ``server_batches_failed_total``, and
``server_reply_open_wait_seconds`` (how long replies waited for the
head's answer-FIFO reader).

Fault-tolerance layer (PR 2 — every recovery path proves it fired
through one of these):

* head retries / circuit breaking — ``head_retries_total``,
  ``head_circuit_open_total``, ``head_circuit_rejected_total``,
  ``head_circuit_closed_total``, ``head_circuit_half_open_total``,
  ``head_circuits_open`` (gauge), ``head_stale_fifos_cleaned_total``;
* liveness — ``head_probes_total`` / ``head_probe_failures_total``
  (``transport.fifo.probe``) and ``server_pings_answered_total``
  (the ``__DOS_PING__`` control frame);
* supervision — ``supervisor_respawns_total``,
  ``supervisor_pings_total``, ``supervisor_ping_failures_total``,
  ``supervisor_workers_alive`` (gauge);
* fault harness — ``faults_injected_total`` (``DOS_FAULTS`` rules that
  fired; in a chaos run the recovery counters above should move in
  lock-step with it).

Online serving layer (``serving/`` — the open-workload frontend; every
admission decision, batch, and cache outcome is visible):

* requests — ``serve_requests_total`` / ``serve_requests_ok_total``,
  end-to-end ``serve_request_seconds`` (submit → completion, cache hits
  included);
* admission control — ``serve_shed_busy_total`` (queue full),
  ``serve_shed_unavailable_total`` (open breaker / shutdown),
  ``serve_timeouts_total`` (deadline expired while queued),
  ``serve_errors_total``; ``serve_queue_depth`` gauge;
* micro-batching — ``serve_batches_total``, ``serve_batch_fill`` and
  ``serve_time_to_flush_seconds`` histograms (is coalescing working?),
  ``serve_flush_full_total`` vs ``serve_flush_wait_total`` (which
  trigger fired), ``serve_dispatch_seconds``,
  ``serve_batches_in_flight`` gauge;
* result cache — ``serve_cache_{hits,misses,evictions}_total``,
  ``serve_cache_{entries,bytes}`` gauges;
* worker-side dedup (the batch-level twin of the cache) —
  ``worker_duplicate_queries_total``.

Artifact durability layer (the index data plane — atomic writes,
checksummed manifests, crash-resume, self-healing loads; see the
README's "Artifact durability & resume"):

* load/verify — ``cpd_blocks_verified_total`` (blocks that passed the
  digest/shape check), ``cpd_blocks_corrupt_total`` (missing, torn, or
  digest-mismatched blocks found at load or ``make_cpds --verify``),
  ``cpd_blocks_rebuilt_total`` (quarantined blocks rebuilt in place
  from the graph); ``cpd.verify`` / ``cpd.rebuild`` spans carry the
  per-block timings;
* crash-resume — ``build_blocks_resumed_total`` (blocks a restarted
  build skipped because the per-worker ledger records them complete
  with a matching on-disk digest);
* build pipeline (``models.cpd.build_worker_shard`` — async
  host→device staging) — ``build_rows_staged_total`` (rows whose
  frontier/target inputs the host stager prepared),
  ``build_stage_overlap_seconds`` (host staging time per block:
  padded-target device upload + pre-opened block writer, overlapped
  with device compute when the pipeline is on),
  ``build_pipeline_stall_seconds`` (time the device-dispatch loop
  waited on the stager — the number the pipeline drives toward zero);
* delta rebuilds (``models.cpd.delta_build_index`` — epoch-keyed
  incremental CPD refresh) — ``build_delta_rows_recomputed_total``
  (rows the tense-edge pass marked dirty and the delta recomputed),
  ``build_delta_skipped_blocks_total`` (blocks reused as byte copies
  from the old index, digests journaled, zero device work);
* sweep — ``artifacts_swept_total`` (stale ``*.tmp`` debris and
  leftover ``*.quarantined`` blocks removed at build/campaign start,
  the artifact-plane analog of ``head_stale_fifos_cleaned_total``).

Replication layer (R-way shard replication — failover routing, hedged
dispatch, replica anti-entropy; README "Replication & failover"):

* failover — ``failover_total`` (batches re-routed off a dead/failed
  primary to a live replica; booked by the campaign head's
  ``send_failover`` AND the serving frontend's dispatch loop),
  ``server_replica_batches_total`` (batches a worker answered from a
  hosted replica shard — the worker-side view of the same traffic);
* hedging — ``hedges_issued_total`` / ``hedges_won_total`` (duplicates
  sent after the adaptive per-shard latency-quantile delay, and how
  often the replica beat the primary),
  ``hedges_budget_denied_total`` (hedges declined by the
  ``DOS_HEDGE_BUDGET`` rate cap — the overload-amplification guard),
  per-shard ``serve_queue_depth_w<wid>`` gauges (failover load shifts
  made visible per queue);
* anti-entropy — ``replica_digest_mismatches_total`` (replica blocks
  whose crc32 diverged from their primary's; quarantined + healed),
  ``replica_blocks_copied_total`` (replica blocks materialized by
  copying a digest-valid primary instead of recomputing).

Elastic fleet membership (``parallel.membership`` — epoch-versioned
shard→worker assignment, drain-free join/leave; README "Elastic
fleet"):

* epoch / reconfiguration — ``reshard_epoch`` (gauge: the committed
  partition-table epoch; 0 = the static pre-elastic fleet),
  ``reshard_migrations_total`` (windows begun),
  ``reshard_shards_moved_total`` (ownership transfers committed),
  ``reshard_aborted_total`` (windows closed without the bump),
  ``reshard_leave_refused_total`` (leave plans refused because a shard
  had no live replica-chain adopter — R=1 sole owner; refusing beats
  stranding it mid-window),
  ``reshard_catchup_seconds`` (per-shard adopter verify+heal);
* catch-up data plane — ``reshard_blocks_adopted_total`` (blocks
  digest-verified/healed by an adopting worker; the heal path itself
  books the ``cpd_blocks_*`` series as usual);
* version gate — ``server_stale_epoch_total`` (batches a worker
  refused with the ``STALE_EPOCH`` wire sentinel: routed under a
  NEWER table than the worker could see even after a membership
  refresh).

Live traffic plane (``traffic/`` — streaming congestion diffs, scoped
cache invalidation, and the typed query families; README "Live
traffic"):

* epoch swaps — ``traffic_epoch`` (gauge: the active diff epoch, 0 =
  the static base diff), ``traffic_segments_applied_total`` (stream
  segments fused into swaps), ``traffic_edges_updated_total`` (edges
  whose weight actually changed), ``traffic_swap_seconds`` (segment
  merge + fused-diff materialization per swap);
* scoped invalidation — ``serve_cache_invalidated_scoped_total`` /
  ``serve_cache_invalidated_full_total`` (entries dropped by reason:
  a SCOPED pass drops only entries whose cached path touches an
  updated edge and re-keys the provable survivors; FULL counts manual
  diff changes and swaps past the ``DOS_TRAFFIC_SCOPED_MAX`` bound),
  ``serve_cache_rekeyed_total`` (the survivors a SCOPED pass re-keyed
  to the new epoch — kept / (kept + scoped-dropped) is the scoped
  hit rate the bench headlines);
* query families — ``serve_matrix_requests_total`` (one-to-many ETA
  rows), ``serve_alt_requests_total`` (k-alternative routes),
  ``serve_reverse_requests_total`` (reverse source-owner routing),
  ``serve_shed_family_total`` (typed family requests answered BUSY by
  the control plane's brownout ladder — level >= 2 sheds mat/alt
  while plain pair queries keep flowing);
* version gate — ``server_stale_diff_total`` (batches a worker refused
  with the ``STALE_DIFF`` wire sentinel: fused at a NEWER diff epoch
  than the worker's segment stream shows even after a refresh — the
  traffic twin of ``server_stale_epoch_total``).

Live observability plane (this PR's standing layer — the scrape-time
series every resident process exposes):

* scrape endpoints — ``obs_scrapes_total`` (requests answered by
  ``/metrics`` / ``/healthz`` / ``/statusz``);
* live quantiles (``obs.quantiles``, window gauges on ``/metrics``
  only, not in JSON snapshots) —
  ``serve_request_seconds_window{quantile=...}`` with
  ``serve_request_seconds_window_worst{trace_id=...}`` exemplar,
  likewise for ``serve_dispatch_seconds`` and
  ``worker_search_seconds``;
* per-worker labels — ``serve_queue_depth{worker="N"}`` is the text-
  exposition form of the flat ``serve_queue_depth_w<N>`` gauges (JSON
  snapshots keep the flat names);
* XLA program costs (``obs.device``) — ``device_programs_analyzed``
  (gauge) plus per-program ``device_program_flops`` /
  ``device_program_bytes_accessed`` / ``device_program_hbm_bytes``
  labeled gauges, captured once per engine program-cache key and
  embedded in ``BENCH_DETAIL.json`` as the roofline denominators;
* walk-kernel selection (``ops.pallas_walk`` via ``worker.engine``) —
  ``walk_{pallas,xla}_batches_total``: table-search batches by the
  kernel that answered them (``DOS_WALK_KERNEL`` resolution; a
  pallas-requested batch that failed the VMEM-fit check books the
  xla counter — the fleet-wide signal that ``auto`` actually engaged
  the fused kernel, next to its ``table-search[pallas]/...`` program
  cost capture).

Worker mesh (multi-device sharded execution — one worker driving a
lane mesh, ``DOS_MESH_DEVICES``; README "Worker mesh"):

* ``mesh_devices`` (gauge) — devices in this worker's local lane mesh
  (1 = the legacy single-device engine);
* ``mesh_walk_batches_total`` — table-search batches split across the
  worker's mesh lanes (per-device bucket subsets under shard_map,
  bit-identical unsort);
* ``mesh_collective_seconds`` — on-mesh collective join per mat-family
  row (``CPDOracle.query_mat``: walk + scatter + psum, replacing the
  head-side fan-out/join).

Streaming RPC data plane (``transport.frames``/``transport.rpc`` +
the worker's socket accept loop — persistent multiplexed connections
replacing per-batch files and FIFO round-trips, ``DOS_TRANSPORT``;
README "Streaming data plane"):

* frame codec — ``rpc_frames_sent_total`` / ``rpc_frames_received_total``
  (every frame on every socket, both directions),
  ``rpc_frames_torn_total`` (frames that died mid-read: peer gone,
  reset, bad magic — each surfaced as a retryable TransportError);
* client connections — ``rpc_connects_total`` /
  ``rpc_reconnects_total`` (persistent connections established /
  re-established after a failure), ``rpc_transport_errors_total``
  (calls failed by transport faults, the breaker/failover feed),
  ``rpc_heartbeats_total`` (pings riding the HealthStatus vocabulary
  over live connections, ``DOS_RPC_HEARTBEAT_S``);
* backpressure — ``rpc_busy_frames_total`` (explicit BUSY credit-
  window refusals, client and server sides both book here — the
  timeout-discovery replacement);
* dispatch — ``rpc_dispatch_seconds`` (one serving batch over the
  socket transport, send to decoded reply);
* worker accept loop — ``rpc_server_connections`` (gauge: live client
  connections), ``rpc_server_batches_total`` (batches answered over
  sockets — the RPC twin of ``server_replies_sent_total``),
  ``rpc_server_replies_dropped_total`` (drop-reply fault or the
  client vanished), ``rpc_server_frames_malformed_total``
  (undecodable request configs answered FAIL — the socket twin of
  ``server_frames_malformed_total``);
* hedged FIFO dispatch (the compat backend's satellite fix) —
  ``serve_hedge_qfile_reused_total`` (hedge duplicates that reused
  the primary attempt's already-written query file instead of paying
  a second filesystem round-trip per candidate).

Gateway tier (``gateway/`` — N stateless frontends behind a binary
client protocol, plus the shard-owner L2 result cache,
``DOS_GATEWAY_*``; README "Gateway tier"):

* client ingress — ``gateway_requests_total`` (frames received on
  client connections: queries, hellos, pings),
  ``gateway_queries_total`` (individual queries inside batched query
  frames, all families), ``gateway_clients`` (gauge: live client
  connections across this process's frontends);
* backpressure — ``gateway_busy_total`` (query frames refused with an
  explicit BUSY because the connection's credit window was full — the
  gateway twin of ``rpc_busy_frames_total``);
* protocol hygiene — ``gateway_frames_malformed_total`` (client
  frames that failed to decode and were answered with a typed ERROR
  frame instead of a torn connection);
* shard-owner L2 cache — ``worker_l2_hits_total`` (queries answered
  from the worker's ``(s, t, diff-epoch)`` cache before the kernel)
  and ``worker_l2_misses_total`` (L2 lookups that fell through to the
  kernel); ``gateway_l2_admit_denied_total`` (inserts withheld by the
  second-hit admission doorkeeper,
  ``DOS_GATEWAY_L2_ADMIT=second-hit``); entry counts and per-replica
  hit rates ride ``/statusz``, not the registry;
* high availability (leased endpoint registry + client failover,
  README "Gateway HA") — ``gateway_lease_renewals_total`` (endpoint
  lease heartbeats written to ``gateway.json``),
  ``gateway_live_frontends`` (gauge: frontends with an unexpired
  lease at the last registry read), ``gateway_client_failovers_total``
  (client connection moves to another live frontend, unanswered
  frames resubmitted under their original ids),
  ``gateway_resubmits_deduped_total`` (resubmitted frames a frontend
  had already answered, replayed from the ``(cid, id)`` memo — the
  exactly-once accounting guarantee), and
  ``gateway_failover_frames_total`` (resubmitted frames re-executed
  on a frontend that had NOT answered them — the at-least-once
  execution half; answers stay bit-identical).

Compressed residency (``models.resident`` — RLE/pack4 CPD shards kept
compressed in device memory and decompressed only at the point of use,
``DOS_CPD_RESIDENT``; README "Compressed residency"):

* ``cpd_resident_bytes`` (gauge) — device bytes of the most recently
  materialized resident first-move table after codec selection (the
  raw bytes when the codec degraded);
* ``cpd_resident_degraded_total`` — resident tables whose requested
  codec was not viable (escape slots for pack4, incompressible runs
  for rle) and were served raw instead — the fit-degrade is a
  counter, never a fault;
* ``cpd_decompress_seconds`` — per-batch decompress-at-use (pack4
  nibble unpack / rle run-start search) before the walk kernel runs;
* ``walk_compressed_batches_total`` — table-search batches answered
  from a compressed-resident shard (the Pallas kernel's
  decompress-on-tile path or the XLA run-start decode feeding either
  kernel).

Fleet telemetry bus (``obs.telemetry`` + ``obs.timeseries`` — workers
push delta-encoded metric snapshots to the head over the RPC wire or
the FIFO lane's ``.telemetry`` sidecar, ``DOS_TELEMETRY_INTERVAL_S``;
README "Fleet telemetry & SLOs"):

* publisher — ``telemetry_ticks_published_total`` (snapshots emitted
  on the cadence), ``telemetry_publish_errors_total`` (sinks that
  raised; per-sink, the tick still reaches the others),
  ``telemetry_publish_seconds`` (one tick build+fan-out — the bench's
  publish-overhead numerator), ``rpc_heartbeat_seconds`` window
  (heartbeat round-trips per connection, plus the per-worker
  ``rpc_heartbeat_seconds_w<wid>`` twins);
* head ingest — ``telemetry_ticks_ingested_total`` /
  ``telemetry_ticks_dropped_total`` (undecodable or wrong-shape
  ticks), ``telemetry_counter_resets_total`` (source restarts
  detected by incarnation change or counter regression — deltas clamp
  to absolute-from-zero, never negative);
* timeseries store (byte-budgeted ring, ``DOS_TELEMETRY_BYTES``) —
  ``telemetry_points_total`` (points appended),
  ``telemetry_series_evicted_total`` (rings dropped by the budget,
  oldest-written first), ``telemetry_series`` / ``telemetry_store_bytes``
  (gauges: live ring count and retained bytes).

SLO burn-rate engine (``obs.slo`` — declarative objectives evaluated
as multi-window burn rates with hysteresis, ``DOS_SLO_SPECS``; the
``/slo`` endpoint and ``dos-obs slo``):

* ``slo_evaluations_total`` / ``slo_alerts_total`` (evaluation passes,
  and alerts that TRIPPED — clears don't count);
* per-objective gauges ``slo_fast_burn_<name>`` / ``slo_slow_burn_<name>``
  (burn = bad-fraction / error-budget over the fast/slow windows) and
  ``slo_alerting_<name>`` (1 while tripped; hysteresis clears at half
  the trip threshold).

Black-box flight recorder (``obs.recorder`` — bounded on-disk ring of
telemetry ticks + structured events, ``DOS_RECORDER_DIR``; ``dos-obs
record`` / ``dos-obs replay``):

* ``recorder_events_total`` (structured events emitted fleet-wide:
  epoch swaps, breaker transitions, respawns, membership commits,
  BUSY storms, fault injections, SLO alerts/clears),
  ``recorder_records_total`` (records written to the tape),
  ``recorder_segments_total`` (segment rotations),
  ``recorder_torn_lines_total`` (torn tail lines skipped at replay),
  ``recorder_ring_bytes`` (gauge: on-disk ring footprint).

Closed-loop control (``control/`` — the policy daemon that turns the
sensors above into automatic recovery actions, ``DOS_CONTROL``;
README "Closed-loop control"):

* loop — ``control_ticks_total`` (sense->decide->act passes),
  ``control_decisions_total`` (decisions reached: executed, dry-run,
  or budget-denied), ``control_actions_total`` (actions executed),
  ``control_budget_denied_total`` (decisions past the global action
  budget), ``control_errors_total`` (actuator executions that raised);
* quarantine — ``control_quarantines_total`` (sick workers removed
  from routing: breaker pin + respawn kick),
  ``control_readmissions_total`` (re-admitted after N clean probes);
* brownout — ``control_brownout_shifts_total`` (ladder level changes),
  ``control_brownout_level`` (gauge: current level, 0 = full service);
* repair / scale — ``control_repairs_total`` (plan_join / plan_leave /
  hot-shard replication executed), ``control_scale_advised_total``
  (scale-up advisories booked where the daemon owns no actuator:
  no join host configured, or lane widening needing a worker restart);
* warming — ``control_warms_total`` (next diff epoch pre-fused /
  registered warmers run ahead of the pump cadence);
* gateway HA arm — ``control_gateway_kicks_total`` (dead gateway
  frontends kicked for respawn after their ``gateway.json`` endpoint
  lease expired).

Answer-integrity plane (``integrity/`` — resident-table scrubbing,
sampled dual-execution audit, and wire/cache answer fingerprints,
``DOS_SCRUB_*`` / ``DOS_AUDIT_*`` / ``DOS_ANSWER_FP``; README "Answer
integrity & auditing"):

* resident scrubber — ``scrub_blocks_checked_total`` (resident blocks
  crc32-compared against their digest-verified on-disk truth),
  ``scrub_blocks_corrupt_total`` (blocks whose resident rows diverged
  — silent in-memory corruption; the table re-binds from disk),
  ``scrub_passes_total`` / ``scrub_pass_seconds`` (pass cadence and
  wall cost — the overhead numerator the bench's integrity section
  holds under its budget);
* dual-execution audit — ``audit_batches_total`` (served batches
  re-executed on an independent lane: replica, CPU reference, or
  uncached recompute), ``audit_divergence_total`` (audits whose
  re-execution DISAGREED with the served answer — the wrong-answer
  alarm feeding the control loop's divergence-quarantine arm),
  ``audit_dropped_total`` (samples dropped at the bounded queue — the
  audit plane never backpressures serving), ``audit_lane_seconds``
  (one re-execution + compare, by whichever lane ran);
* answer fingerprints — ``answer_fp_mismatch_total`` (replies whose
  crc32 answer fingerprint failed verification at a dispatcher or
  results-sidecar decode; the batch fails over instead of serving
  corrupted answers), ``cache_fingerprint_mismatch_total`` (cache
  hits whose stored entry no longer matches its insertion-time
  fingerprint — dropped and recomputed, never served);
* control arm — ``control_divergence_quarantines_total`` (shards
  pulled from routing on a confirmed audit divergence: breaker
  force-open + scrub-now, re-admitted only after clean probes).
"""

from . import device, fleet, metrics, quantiles, trace
from .metrics import REGISTRY, counter, gauge, histogram
from .quantiles import WINDOWS
from .trace import span

#: imported lazily (PEP 562): these modules use ``utils.atomicio``,
#: which itself registers metrics — an eager import here would close
#: an import cycle through the package __init__
_LAZY = ("recorder", "slo", "telemetry", "timeseries")


def __getattr__(name):
    if name in _LAZY:
        import importlib
        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute "
                         f"{name!r}")

__all__ = ["device", "fleet", "metrics", "quantiles", "recorder",
           "slo", "telemetry", "timeseries", "trace",
           "REGISTRY", "WINDOWS", "counter", "gauge", "histogram",
           "span"]
