"""Zero-dependency metrics: counters, gauges, histograms.

The registry is the numeric half of the observability layer (``obs/``):
every hot-path event the serve loop can hit — frames received, malformed
frames, dropped replies, per-phase latencies — increments a named metric
here, and campaigns/benches snapshot the registry next to their other
artifacts. Two export forms:

* :meth:`MetricsRegistry.snapshot` — a JSON-able dict
  (``{"counters": ..., "gauges": ..., "histograms": ...}``), written by
  ``--metrics-dump PATH`` and embedded in ``BENCH_DETAIL.json``;
* :meth:`MetricsRegistry.to_prometheus` — Prometheus text exposition
  (version 0.0.4), so a scrape endpoint or textfile collector can serve
  the same numbers without any new dependency.

Everything is thread-safe under one lock per metric family; increments
are a dict lookup + integer add, cheap enough to stay unconditional (no
enable flag — unlike spans, counters have no per-event allocation).
Metrics are get-or-create by name, so instrumented modules can declare
their counters at import time and a snapshot shows them at zero even
when the failure path never fired.
"""

from __future__ import annotations

import json
import re

from ..utils.locks import OrderedLock

#: default latency buckets (seconds) — tuned for the serve path, where a
#: batch spans ~100us (warm gather) to minutes (cold XLA compile)
DEFAULT_BUCKETS = (0.0001, 0.001, 0.01, 0.1, 0.5, 1.0, 5.0, 30.0, 120.0)


class Counter:
    """Monotonic counter."""

    __slots__ = ("name", "help", "_value", "_lock")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0
        self._lock = OrderedLock("metrics.Counter")

    def inc(self, n: int | float = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self):
        return self._value


class Gauge:
    """Last-write-wins value."""

    __slots__ = ("name", "help", "_value", "_lock")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._value = 0.0
        self._lock = OrderedLock("metrics.Gauge")

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v

    def add(self, v: float) -> None:
        with self._lock:
            self._value += v

    @property
    def value(self):
        return self._value


class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics: each bucket
    counts observations ``<= le``; ``+Inf`` is the total count)."""

    __slots__ = ("name", "help", "buckets", "_counts", "_sum", "_count",
                 "_lock")

    def __init__(self, name: str, help: str = "",
                 buckets: tuple = DEFAULT_BUCKETS):
        self.name = name
        self.help = help
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * len(self.buckets)
        self._sum = 0.0
        self._count = 0
        self._lock = OrderedLock("metrics.Histogram")

    def observe(self, v: float) -> None:
        with self._lock:
            self._sum += v
            self._count += 1
            # per-bucket raw counts; as_dict cumulates on export
            for i, le in enumerate(self.buckets):
                if v <= le:
                    self._counts[i] += 1
                    break

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def as_dict(self) -> dict:
        with self._lock:
            cum = 0
            buckets = {}
            for le, c in zip(self.buckets, self._counts):
                cum += c
                buckets[repr(le)] = cum
            return {"count": self._count, "sum": self._sum,
                    "buckets": buckets}


class MetricsRegistry:
    """Thread-safe, name-keyed registry of the three metric kinds.

    ``counter``/``gauge``/``histogram`` are get-or-create: the first
    caller fixes the kind (a name reused across kinds raises), so modules
    can idempotently declare metrics at import time.
    """

    def __init__(self):
        self._metrics: dict[str, object] = {}
        self._lock = OrderedLock("metrics.MetricsRegistry")

    def _get_or_create(self, name: str, kind, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = kind(name, **kwargs)
                self._metrics[name] = m
            elif not isinstance(m, kind):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {kind.__name__}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(name, Counter, help=help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(name, Gauge, help=help)

    def histogram(self, name: str, help: str = "",
                  buckets: tuple = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(name, Histogram, help=help,
                                   buckets=buckets)

    # ---------------------------------------------------------- export
    def snapshot(self) -> dict:
        """JSON-able dump of every registered metric, grouped by kind."""
        with self._lock:
            metrics = dict(self._metrics)
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for name, m in sorted(metrics.items()):
            if isinstance(m, Counter):
                out["counters"][name] = m.value
            elif isinstance(m, Gauge):
                out["gauges"][name] = m.value
            else:
                out["histograms"][name] = m.as_dict()
        return out

    def dump_json(self, path: str) -> None:
        """Atomic snapshot write (tmp+fsync+rename): a concurrent
        scrape, NFS copy, or fleet-aggregation pass never reads a torn
        JSON file. Lazy import — ``utils.atomicio`` imports this module
        for its own counters."""
        from ..utils.atomicio import atomic_write_bytes
        atomic_write_bytes(
            path, (json.dumps(self.snapshot(), indent=1) + "\n").encode())

    def to_prometheus(self) -> str:
        """Text exposition format 0.0.4 (``# TYPE`` lines + samples).

        Dynamically-suffixed per-worker metrics (``serve_queue_depth_w3``
        — the replicated frontend's per-shard gauges) are folded into
        proper labels (``serve_queue_depth{worker="3"}``) so a scrape
        sees one metric family per name instead of unbounded name
        cardinality; JSON snapshots keep the flat names for backward
        compatibility."""
        with self._lock:
            metrics = dict(self._metrics)
        # (family, worker-label) in family order, labeled samples last so
        # each family's TYPE/HELP is emitted once, before its samples
        families: dict[str, list] = {}
        for name, m in metrics.items():
            fam, labels = name, ""
            mt = re.fullmatch(r"(.+)_w(\d+)", name)
            if mt:
                fam, labels = mt.group(1), f'worker="{mt.group(2)}"'
            families.setdefault(fam, []).append((labels, m, name))
        # a fold is only valid within one metric kind: a name that merely
        # LOOKS per-worker but collides with a different-kinded family
        # falls back to its flat name
        for fam in list(families):
            kinds = {type(m) for _, m, _ in families[fam]}
            if len(kinds) > 1:
                members = families.pop(fam)
                for labels, m, name in members:
                    families.setdefault(name, []).append(("", m, name))
        lines = []
        for fam in sorted(families):
            samples = sorted(families[fam], key=lambda s: s[0])
            kind = samples[0][1]
            helps = [m.help for _, m, _ in samples if m.help]
            if helps:
                lines.append(f"# HELP {fam} {helps[0]}")
            if isinstance(kind, Counter):
                lines.append(f"# TYPE {fam} counter")
            elif isinstance(kind, Gauge):
                lines.append(f"# TYPE {fam} gauge")
            else:
                lines.append(f"# TYPE {fam} histogram")
            for labels, m, _name in samples:
                sfx = f"{{{labels}}}" if labels else ""
                if isinstance(m, (Counter, Gauge)):
                    lines.append(f"{fam}{sfx} {m.value}")
                    continue
                extra = f",{labels}" if labels else ""
                d = m.as_dict()
                for le, c in d["buckets"].items():
                    lines.append(
                        f'{fam}_bucket{{le="{le}"{extra}}} {c}')
                lines.append(
                    f'{fam}_bucket{{le="+Inf"{extra}}} {d["count"]}')
                lines.append(f"{fam}_sum{sfx} {d['sum']}")
                lines.append(f"{fam}_count{sfx} {d['count']}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Zero every metric IN PLACE (tests only — production metrics
        are process-lifetime monotonic). The metric handles stay
        registered: instrumented modules hold them from import time, and
        dropping them from the registry would leave those handles
        incrementing objects no snapshot can see."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            with m._lock:
                if isinstance(m, Histogram):
                    m._counts = [0] * len(m.buckets)
                    m._sum = 0.0
                    m._count = 0
                elif isinstance(m, Counter):
                    m._value = 0
                else:
                    m._value = 0.0


#: process-wide default registry — instrumented modules and exporters
#: share it unless a test injects its own
REGISTRY = MetricsRegistry()


def counter(name: str, help: str = "") -> Counter:
    return REGISTRY.counter(name, help=help)


def gauge(name: str, help: str = "") -> Gauge:
    return REGISTRY.gauge(name, help=help)


def histogram(name: str, help: str = "",
              buckets: tuple = DEFAULT_BUCKETS) -> Histogram:
    return REGISTRY.histogram(name, help=help, buckets=buckets)
