"""SLO burn-rate engine: declarative objectives over the fleet store.

An SLO here is a declarative spec evaluated against the telemetry
timeseries (:mod:`.timeseries`), Google-SRE style multi-window burn
rates: the **burn rate** is how fast the error budget is being spent
(1.0 = exactly on budget; 14.4 over a 5-minute window means a 30-day
99.9% budget gone in ~2 days). Two windows per spec:

* **fast** (``DOS_SLO_FAST_S``, default 300 s, trip threshold
  ``DOS_SLO_FAST_BURN`` = 14.4) — pages on sudden incineration;
* **slow** (``DOS_SLO_SLOW_S``, default 3600 s, threshold
  ``DOS_SLO_SLOW_BURN`` = 6.0) — catches the slow leak the fast window
  averages away.

Alerting has **hysteresis**: a spec trips when its fast burn crosses
the fast threshold, and clears only when the fast burn falls below
``clear_frac`` (default 0.5) of it — a burn oscillating around the
line must not flap the alert.

Spec kinds:

* ``availability`` — bad-event counters (shed/timeout/error series)
  over a total counter, as per-window rates from the store's delta
  series. Burn = (bad/total) / (1 - objective).
* ``latency`` — a quantile-window series (``serve_request_seconds``)
  against a threshold. The bad fraction is estimated from the
  fleet-merged window's quantile ladder (threshold above p99 → within
  budget; below p50 → most requests are slow), which is exactly the
  resolution the windows ship — coarse, monotone, and enough to flip
  a 14.4× burn alert when a fault lands.

Specs come from ``DOS_SLO_SPECS`` (a JSON file of spec objects —
unknown keys tolerated, the annotation contract) or default to the
serving availability + latency pair. Results are exposed three ways:
``slo_*`` gauges on ``/metrics``, the ``/slo`` JSON endpoint
(``obs.http``), and ``dos-obs slo``. Alert transitions land on the
flight-recorder bus (:func:`.recorder.emit`).
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time

from ..utils.env import env_cast, env_str
from ..utils.locks import OrderedLock
from ..utils.log import get_logger
from . import metrics as obs_metrics
from . import recorder as obs_recorder

log = get_logger(__name__)

M_EVALS = obs_metrics.counter(
    "slo_evaluations_total", "burn-rate evaluation passes")
M_ALERTS = obs_metrics.counter(
    "slo_alerts_total", "specs that transitioned into alerting")

#: default bad-event counters for the serving availability SLO — the
#: frontend's shed/degrade paths (obs map: "admission control")
_DEFAULT_BAD = ("serve_shed_busy_total", "serve_shed_unavailable_total",
                "serve_timeouts_total", "serve_errors_total")


@dataclasses.dataclass
class SLOSpec:
    """One declarative objective. ``kind`` is ``availability`` (bad
    counters / total counter) or ``latency`` (quantile window vs
    threshold)."""

    name: str
    kind: str = "availability"
    objective: float = 0.999          # good fraction promised
    # availability inputs
    total: str = "serve_requests_total"
    bad: tuple = _DEFAULT_BAD
    # latency inputs
    window: str = "serve_request_seconds"
    threshold_s: float = 0.5

    @property
    def budget(self) -> float:
        """The error budget (bad fraction allowed)."""
        return max(1.0 - float(self.objective), 1e-9)


def default_specs() -> list[SLOSpec]:
    return [
        SLOSpec(name="serve_availability", kind="availability",
                objective=0.999),
        SLOSpec(name="serve_latency", kind="latency", objective=0.99,
                threshold_s=env_cast("DOS_SLO_LATENCY_THRESHOLD_S",
                                     0.5, float)),
    ]


def parse_specs(doc) -> list[SLOSpec]:
    """Spec objects from a JSON document (list of dicts). Unknown keys
    are tolerated per entry; a malformed entry is skipped with a log
    line — one typo must not disarm the whole SLO page."""
    out = []
    if not isinstance(doc, list):
        raise ValueError("SLO spec document must be a JSON list")
    fields = {f.name for f in dataclasses.fields(SLOSpec)}
    for i, entry in enumerate(doc):
        if not isinstance(entry, dict) or not entry.get("name"):
            log.warning("skipping malformed SLO spec #%d: %r", i, entry)
            continue
        kw = {k: v for k, v in entry.items() if k in fields}
        if isinstance(kw.get("bad"), list):
            kw["bad"] = tuple(kw["bad"])
        try:
            out.append(SLOSpec(**kw))
        except (TypeError, ValueError) as e:
            log.warning("skipping malformed SLO spec #%d: %s", i, e)
    return out


def load_specs() -> list[SLOSpec]:
    """Specs from ``DOS_SLO_SPECS`` (JSON file path), defaulting to the
    serving pair. Unreadable file degrades to the defaults, logged —
    the knob policy."""
    path = env_str("DOS_SLO_SPECS")
    if not path:
        return default_specs()
    try:
        with open(path) as f:
            return parse_specs(json.load(f))
    except (OSError, ValueError) as e:
        log.warning("ignoring DOS_SLO_SPECS=%r (%s); using defaults",
                    path, e)
        return default_specs()


def _bad_fraction_from_window(snap: dict, threshold_s: float) -> float:
    """Estimate the slow-request fraction from a quantile ladder:
    monotone steps at the quantiles the window ships. Threshold above
    p99 → 0 (unresolvable below 1%, which is within a 99% objective's
    budget); below p50 → 0.75 (most of the window is slow)."""
    qs = snap.get("quantiles") or {}
    bad = 0.0
    for q, frac in (("p99", 0.01), ("p95", 0.05), ("p50", 0.75)):
        v = qs.get(q)
        if isinstance(v, (int, float)) and threshold_s < v:
            bad = frac
    return bad


class SLOEngine:
    """Evaluates every spec's fast/slow burn against the store and
    keeps the ``slo_*`` gauges, the ``/slo`` payload, and the alert
    state machine current."""

    def __init__(self, store, specs: list[SLOSpec] | None = None,
                 fast_s: float | None = None,
                 slow_s: float | None = None,
                 fast_threshold: float | None = None,
                 slow_threshold: float | None = None,
                 clear_frac: float = 0.5, clock=time.time):
        self.store = store
        self.specs = list(specs) if specs is not None else load_specs()
        self.fast_s = float(fast_s if fast_s is not None
                            else env_cast("DOS_SLO_FAST_S", 300.0,
                                          float))
        self.slow_s = float(slow_s if slow_s is not None
                            else env_cast("DOS_SLO_SLOW_S", 3600.0,
                                          float))
        self.fast_threshold = float(
            fast_threshold if fast_threshold is not None
            else env_cast("DOS_SLO_FAST_BURN", 14.4, float))
        self.slow_threshold = float(
            slow_threshold if slow_threshold is not None
            else env_cast("DOS_SLO_SLOW_BURN", 6.0, float))
        self.clear_frac = float(clear_frac)
        self.clock = clock
        self._alerting: dict[str, float] = {}   # name -> trip ts
        self._last: dict = {}
        self._lock = OrderedLock("slo.SLOEngine")
        self._gauges = {}
        for spec in self.specs:
            self._gauges[spec.name] = (
                obs_metrics.gauge(
                    f"slo_fast_burn_{spec.name}",
                    f"fast-window burn rate of SLO {spec.name}"),
                obs_metrics.gauge(
                    f"slo_slow_burn_{spec.name}",
                    f"slow-window burn rate of SLO {spec.name}"),
                obs_metrics.gauge(
                    f"slo_alerting_{spec.name}",
                    f"1 while SLO {spec.name} is in alert"))
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # --------------------------------------------------------- evaluate
    def _burn(self, spec: SLOSpec, window_s: float,
              now: float) -> float | None:
        """One spec's burn over one window; None with no data."""
        if spec.kind == "latency":
            snap = self.store.fleet_window(
                spec.window, max_age_s=max(window_s, 60.0), now=now)
            if snap is None:
                return None
            bad = _bad_fraction_from_window(snap, spec.threshold_s)
            return bad / spec.budget
        total = self.store.rate(spec.total, window_s, now=now)
        if total <= 0:
            return None
        bad = sum(self.store.rate(name, window_s, now=now)
                  for name in spec.bad)
        return (bad / total) / spec.budget

    def evaluate(self, now: float | None = None) -> dict:
        """One pass over every spec: update gauges, run the hysteresis
        state machine, return the ``/slo`` payload."""
        now = self.clock() if now is None else now
        M_EVALS.inc()
        out = {}
        transitions = []
        with self._lock:
            for spec in self.specs:
                fast = self._burn(spec, self.fast_s, now)
                slow = self._burn(spec, self.slow_s, now)
                g_fast, g_slow, g_alert = self._gauges[spec.name]
                g_fast.set(fast or 0.0)
                g_slow.set(slow or 0.0)
                tripped = spec.name in self._alerting
                if (not tripped and fast is not None
                        and fast >= self.fast_threshold):
                    self._alerting[spec.name] = now
                    tripped = True
                    M_ALERTS.inc()
                    transitions.append(("slo_alert", spec, fast))
                elif tripped and (
                        fast is None
                        or fast <= self.fast_threshold
                        * self.clear_frac):
                    del self._alerting[spec.name]
                    tripped = False
                    transitions.append(("slo_clear", spec, fast))
                g_alert.set(1.0 if tripped else 0.0)
                out[spec.name] = {
                    "kind": spec.kind,
                    "objective": spec.objective,
                    "fast_burn": fast,
                    "slow_burn": slow,
                    "fast_window_s": self.fast_s,
                    "slow_window_s": self.slow_s,
                    "fast_threshold": self.fast_threshold,
                    "slow_threshold": self.slow_threshold,
                    "alerting": tripped,
                    "alert_since": self._alerting.get(spec.name),
                }
                if spec.kind == "latency":
                    out[spec.name]["threshold_s"] = spec.threshold_s
            self._last = out
        for kind, spec, burn in transitions:
            # emitted OUTSIDE the engine lock: the bus appends to its
            # own ring and may write the on-disk tape
            log.warning("%s: %s (fast burn %.2f, threshold %.2f)",
                        kind, spec.name, burn or 0.0,
                        self.fast_threshold)
            obs_recorder.emit(kind, slo=spec.name,
                              burn=round(burn, 3) if burn is not None
                              else None,
                              threshold=self.fast_threshold, ts=now)
        return out

    # ----------------------------------------------------------- access
    def payload(self) -> dict:
        """The ``/slo`` endpoint body (evaluates fresh — a scrape sees
        the current burn, not the last eval tick's)."""
        return self.evaluate()

    def alerting(self) -> list[str]:
        with self._lock:
            return sorted(self._alerting)

    def statusz(self) -> dict:
        with self._lock:
            last = dict(self._last)
            alerting = sorted(self._alerting)
        return {"specs": [s.name for s in self.specs],
                "alerting": alerting,
                "fast_window_s": self.fast_s,
                "slow_window_s": self.slow_s,
                "burn": {name: {"fast": v.get("fast_burn"),
                                "slow": v.get("slow_burn"),
                                "alerting": v.get("alerting")}
                         for name, v in last.items()}}

    # -------------------------------------------------------- lifecycle
    def start(self, interval_s: float | None = None) -> "SLOEngine":
        """Background evaluation loop (``DOS_SLO_EVAL_S``, default 5 s)
        so gauges and the alert state machine advance even between
        scrapes."""
        if self._thread is not None:
            return self
        interval = float(interval_s if interval_s is not None
                         else env_cast("DOS_SLO_EVAL_S", 5.0, float))
        if interval <= 0:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval):
                try:
                    self.evaluate()
                except Exception as e:  # noqa: BLE001 — the eval loop
                    # outlives any one bad pass
                    log.exception("slo evaluation failed: %s", e)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="dos-slo-eval")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
