"""Nested span tracing with Chrome trace-event export.

The timing half of the observability layer (``obs/``): phases of a query
batch — head-side prepare/partition/send, worker-side
receive/weights/search — run inside :func:`span` context managers, and
the collected events serialize as Chrome trace-event JSON
(``{"traceEvents": [...]}``, "X" complete events) loadable in Perfetto or
``chrome://tracing``.

Head and worker are separate processes in host mode, so spans join
across the FIFO wire via a **trace id**: the head stamps each batch's
``RuntimeConfig.trace_id`` (a backward-compatible wire extension — old
servers filter the unknown key), the worker captures its spans for that
batch under the same id and materializes them as a ``<queryfile>.trace``
sidecar (the same shared-dir channel the ``.paths`` extension rides),
and the head ingests the sidecars into one merged trace file.

Clock discipline: event **timestamps** are epoch microseconds
(``time.time_ns``) so events from different processes land on one
timeline without negotiation; **durations** come from the monotonic
``perf_counter_ns`` so a span is immune to wall-clock steps.

Cost discipline: tracing is off by default, and a disabled :func:`span`
returns one shared no-op context manager — no allocation, no clock
read — so instrumented hot paths are no-op-cheap unless ``--trace``
turns collection on process-wide or an incoming ``trace_id`` opens a
per-thread :class:`capture` for one batch.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid

_lock = threading.Lock()
_events: list[dict] = []
_enabled = False
_tls = threading.local()


def enable(on: bool = True) -> None:
    """Turn span collection on/off process-wide."""
    global _enabled
    _enabled = on


def enabled() -> bool:
    return _enabled


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def set_trace_id(trace_id: str | None) -> None:
    """Set the current thread's trace id (stamped on every span it
    opens; explicit ``trace_id=`` span args override)."""
    _tls.trace_id = trace_id


def current_trace_id() -> str | None:
    return getattr(_tls, "trace_id", None)


class _NullSpan:
    """Shared do-nothing context manager: the disabled fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


def _active() -> bool:
    """Spans record when tracing is on process-wide OR this thread is
    inside a :class:`capture` block."""
    return _enabled or getattr(_tls, "capture", None) is not None


def _emit(ev: dict) -> None:
    """Route a finished event: to the thread's capture buffer when one
    is open (per-request worker capture), else the global buffer."""
    buf = getattr(_tls, "capture", None)
    if buf is not None:
        buf.append(ev)
        return
    with _lock:
        _events.append(ev)


def _make_event(name: str, ts_us: int, dur_us: int, args: dict) -> dict:
    if "trace_id" not in args:
        tid = current_trace_id()
        if tid is not None:
            args = {**args, "trace_id": tid}
    return {
        "name": name,
        "ph": "X",
        "ts": ts_us,
        "dur": dur_us,
        "pid": os.getpid(),
        "tid": threading.get_ident() & 0x7FFFFFFF,
        "args": args,
    }


class _Span:
    __slots__ = ("name", "args", "_t0_wall_us", "_t0_perf")

    def __init__(self, name: str, args: dict):
        self.name = name
        self.args = args

    def __enter__(self):
        self._t0_wall_us = time.time_ns() // 1000
        self._t0_perf = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        dur_us = (time.perf_counter_ns() - self._t0_perf) // 1000
        _emit(_make_event(self.name, self._t0_wall_us, dur_us, self.args))
        return False


def span(name: str, **args):
    """Context manager timing one phase. ``args`` land in the event's
    ``args`` dict (``trace_id`` defaults to the thread's current id).
    Returns a shared no-op when tracing is disabled."""
    if not _active():
        return _NULL_SPAN
    return _Span(name, args)


def add_span(name: str, duration_s: float, **args) -> None:
    """Record an already-measured phase as a complete event ending now.

    For code that times itself with ``perf_counter`` deltas (the engine's
    stats-field timers): the event's start is back-dated by the duration.
    No-op when tracing is disabled."""
    if not _active():
        return
    dur_us = int(duration_s * 1e6)
    _emit(_make_event(name, time.time_ns() // 1000 - dur_us, dur_us,
                      args))


def events() -> list[dict]:
    with _lock:
        return list(_events)


def clear() -> None:
    with _lock:
        _events.clear()


def ingest(evs: list[dict]) -> None:
    """Merge externally collected events (e.g. a worker sidecar) into
    this process's buffer."""
    with _lock:
        _events.extend(evs)


class capture:
    """Divert the spans THIS THREAD opens during the ``with`` block into
    ``self.events`` (activating span collection for the thread if
    tracing was otherwise off).

    The worker server uses this per request: an incoming ``trace_id``
    turns collection on for exactly that batch, the captured events are
    stamped with the id and shipped back via the batch's sidecar — they
    deliberately bypass the global buffer, so an in-process server (test
    harnesses run head + workers in one process) never double-reports a
    span both directly and through the sidecar the head ingests.
    Captures nest per thread; other threads are unaffected.
    """

    def __init__(self, trace_id: str | None = None):
        self.trace_id = trace_id
        self.events: list[dict] = []

    def __enter__(self) -> "capture":
        self._prev_buf = getattr(_tls, "capture", None)
        _tls.capture = self.events
        if self.trace_id is not None:
            self._prev_tid = current_trace_id()
            set_trace_id(self.trace_id)
        return self

    def __exit__(self, *exc) -> bool:
        _tls.capture = self._prev_buf
        if self.trace_id is not None:
            set_trace_id(self._prev_tid)
        return False


# --------------------------------------------------------------- files

def trace_sidecar_for(queryfile: str) -> str:
    """Where a worker materializes a batch's span events for the head to
    collect (the ``.paths`` pattern: rides the shared dir, not the
    stats FIFO)."""
    return queryfile + ".trace"


def write_events(path: str, evs: list[dict]) -> None:
    """Atomic sidecar write: the head (or a fleet-aggregation pass)
    polls for sidecars over NFS and must never ingest a torn JSON list.
    Lazy import — ``utils.atomicio`` registers its own obs counters."""
    from ..utils.atomicio import atomic_write_bytes
    atomic_write_bytes(path, json.dumps(evs).encode())


def read_events(path: str) -> list[dict]:
    with open(path) as f:
        out = json.load(f)
    if not isinstance(out, list):
        raise ValueError(f"{path}: expected a JSON list of events")
    return out


def write_trace(path: str, extra_events: list[dict] | None = None) -> None:
    """Write the full Chrome trace-event file (buffered events plus any
    ``extra_events``), loadable in Perfetto / chrome://tracing."""
    evs = events()
    if extra_events:
        evs = evs + list(extra_events)
    from ..utils.atomicio import atomic_write_bytes
    atomic_write_bytes(path, json.dumps(
        {"traceEvents": evs, "displayTimeUnit": "ms"}, indent=1).encode())
