"""Fleet-wide aggregation: merge worker snapshots, traces, statusz.

One process's registry answers for one process; a campaign or serving
deployment is a *fleet* — a head plus N workers (plus replicas), each
already materializing ``obs_metrics.json`` snapshots and ``.trace``
span sidecars over the shared NFS data plane. This module is the
head-side merge logic behind the ``dos-obs`` CLI (``cli.obs``):

* :func:`merge_snapshots` — N labeled per-process snapshots into one
  ``fleet_metrics.json``: counters and histograms sum (bucket-wise —
  every process runs the same code, so bucket edges agree; a
  mismatched histogram degrades to count+sum), gauges sum with the
  per-worker values preserved under ``workers`` so a fleet total never
  hides a skewed replica. Duplicate labels are disambiguated
  (``w0``, ``w0#2``) rather than silently overwritten — two workers
  claiming one identity is exactly the kind of thing a merge must
  surface.
* :func:`merge_traces` — head trace files (``{"traceEvents": ...}``)
  and worker span sidecars (bare event lists) into ONE Perfetto-
  loadable timeline; events keep their pids so every process is its
  own track, and batches still join across tracks on ``trace_id``.
* :func:`fetch_statusz` / :func:`render_top` — poll live ``/statusz``
  endpoints (``obs.http``) and render the fleet table ``dos-obs top``
  shows: queue depths, open breakers, hedge rate, replica map per
  endpoint.
* :func:`compare_bench` — the regression gate behind ``dos-obs
  bench-diff``: newest ``BENCH_r*.json`` vs the previous one with
  per-key tolerances; throughput-like keys must not fall, latency-like
  keys must not rise.
"""

from __future__ import annotations

import glob
import json
import os
import re
import urllib.request

from ..utils.log import get_logger

log = get_logger(__name__)


# ------------------------------------------------------------- snapshots

def _merge_histogram(agg: dict, h: dict) -> dict:
    """Sum one histogram into the aggregate (cumulative buckets are
    additive per edge). Mismatched bucket edges — which only happens
    across code versions — degrade to count+sum."""
    if not agg:
        return {"count": h.get("count", 0), "sum": h.get("sum", 0.0),
                "buckets": dict(h.get("buckets", {}))}
    agg = {"count": agg.get("count", 0) + h.get("count", 0),
           "sum": agg.get("sum", 0.0) + h.get("sum", 0.0),
           "buckets": dict(agg.get("buckets", {}))}
    mine, theirs = agg["buckets"], h.get("buckets", {})
    if set(mine) == set(theirs):
        for le in mine:
            mine[le] += theirs[le]
    else:
        log.warning("histogram bucket edges differ across workers; "
                    "keeping count+sum only")
        agg["buckets"] = {}
    return agg


def dedupe_labels(labels: list[str]) -> list[str]:
    """Disambiguate duplicate worker labels in input order:
    ``w0, w0 -> w0, w0#2``."""
    seen: dict[str, int] = {}
    out = []
    for lab in labels:
        n = seen.get(lab, 0) + 1
        seen[lab] = n
        out.append(lab if n == 1 else f"{lab}#{n}")
    return out


def merge_snapshots(inputs: list[tuple[str, dict]]) -> dict:
    """``[(label, snapshot), ...]`` -> the fleet document: per-worker
    snapshots under ``workers`` (labels deduped), summed counters /
    gauges / histograms under ``fleet``."""
    labels = dedupe_labels([lab for lab, _ in inputs])
    workers = {lab: snap for lab, (_, snap) in zip(labels, inputs)}
    fleet = {"counters": {}, "gauges": {}, "histograms": {}}
    for snap in workers.values():
        for name, v in snap.get("counters", {}).items():
            fleet["counters"][name] = fleet["counters"].get(name, 0) + v
        for name, v in snap.get("gauges", {}).items():
            fleet["gauges"][name] = fleet["gauges"].get(name, 0) + v
        for name, h in snap.get("histograms", {}).items():
            fleet["histograms"][name] = _merge_histogram(
                fleet["histograms"].get(name, {}), h)
    return {"workers": workers, "fleet": fleet,
            "n_workers": len(workers)}


def load_snapshot_files(paths: list[str],
                        labels: list[str] | None = None) -> list:
    """Read snapshot JSONs into ``merge_snapshots`` input. Default
    labels come from the parent dir + filename, which is how per-worker
    artifact dirs differ."""
    out = []
    for i, p in enumerate(paths):
        with open(p) as f:
            snap = json.load(f)
        if labels and i < len(labels):
            lab = labels[i]
        else:
            lab = os.path.join(os.path.basename(os.path.dirname(p)),
                               os.path.basename(p))
        out.append((lab, snap))
    return out


# ---------------------------------------------------------------- traces

def _events_of(path: str) -> list[dict]:
    """Events from either container format: a full Chrome trace doc
    (``{"traceEvents": [...]}``) or a bare sidecar list."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict):
        evs = doc.get("traceEvents", [])
    else:
        evs = doc
    if not isinstance(evs, list):
        raise ValueError(f"{path}: no trace events found")
    return evs


def merge_traces(inputs: list[str], out_path: str) -> int:
    """Merge trace files/sidecars (directories glob ``*.trace``) into
    one Perfetto-loadable Chrome trace doc. Returns the event count."""
    paths = []
    for p in inputs:
        if os.path.isdir(p):
            paths.extend(sorted(glob.glob(os.path.join(p, "*.trace"))))
        else:
            paths.append(p)
    events: list[dict] = []
    for p in paths:
        evs = _events_of(p)
        events.extend(evs)
        log.info("merge-traces: %s -> %d event(s)", p, len(evs))
    events.sort(key=lambda e: e.get("ts", 0))
    from ..utils.atomicio import atomic_write_bytes
    atomic_write_bytes(out_path, json.dumps(
        {"traceEvents": events, "displayTimeUnit": "ms"},
        indent=1).encode())
    return len(events)


# --------------------------------------------------------------- statusz

def fetch_json(endpoint: str, path: str = "/statusz",
               timeout_s: float = 3.0) -> dict:
    """``host:port`` + path -> its JSON (``{"error": ...}`` when
    unreachable — a dead worker is a row in the fleet table, not a
    crash of the tool watching for dead workers)."""
    url = endpoint if "://" in endpoint else f"http://{endpoint}"
    try:
        with urllib.request.urlopen(f"{url}{path}",
                                    timeout=timeout_s) as r:
            return json.loads(r.read().decode())
    except Exception as e:  # noqa: BLE001
        return {"error": f"{type(e).__name__}: {e}"}


def fetch_statusz(endpoint: str, timeout_s: float = 3.0) -> dict:
    return fetch_json(endpoint, "/statusz", timeout_s=timeout_s)


def _summarize(status: dict) -> dict:
    """Flatten one endpoint's statusz into the fleet-table columns.

    Schema-heterogeneous by design: a rolling upgrade mixes workers
    that export the elastic-membership keys (``epoch``, ``migration``)
    with workers that predate them — a missing or oddly-typed key
    renders as a blank cell in that endpoint's row, never a crash of
    the tool watching the upgrade."""
    if "error" in status:
        return {"state": "UNREACHABLE", "detail": status["error"]}

    def _num(v, default=0):
        # bool is an int subclass but not a count; null/str render as
        # the default instead of raising out of a sum()
        return (v if isinstance(v, (int, float))
                and not isinstance(v, bool) else default)

    out: dict = {"state": "up"}
    serving = status.get("serving", {})
    if not isinstance(serving, dict):
        serving = {}
    if serving:
        shards = serving.get("shards", {})
        if isinstance(shards, dict):
            out["queued"] = sum(_num(s.get("queue_depth"))
                                for s in shards.values()
                                if isinstance(s, dict))
            out["shards"] = len(shards)
        hedge = serving.get("hedge", {})
        if isinstance(hedge, dict) and hedge:
            out["hedge_rate"] = _num(hedge.get("rate"), 0.0)
    # the serve frontend nests its breaker section under "serving";
    # a bare BreakerRegistry provider sits at the top level
    braw = serving.get("breakers") or status.get("breakers") or {}
    breakers = (braw.get("breakers", {}) if isinstance(braw, dict)
                else {})
    if isinstance(breakers, dict) and breakers:
        out["breakers_open"] = sum(
            1 for b in breakers.values()
            if isinstance(b, dict)
            and b.get("state") in ("open", "half-open"))
    worker = status.get("worker", {})
    if not isinstance(worker, dict):
        worker = {}
    if worker:
        out["batches"] = _num(worker.get("batches"))
        out["failures"] = _num(worker.get("batch_failures"))
    sup = status.get("supervisor", {})
    if isinstance(sup, dict) and sup:
        out["alive"] = _num(sup.get("alive"))
        out["respawns"] = _num(sup.get("respawns"))
    # elastic-membership columns: present only when the endpoint
    # exports them (a pre-elastic worker's row shows "-" blanks)
    for sec in (serving, worker):
        if "epoch" in sec and isinstance(sec["epoch"], (int, float)):
            out["epoch"] = int(sec["epoch"])
            break
    # live-traffic column: the active DIFF epoch — same mixed-schema
    # tolerance (a pre-traffic endpoint's row shows a blank)
    for sec in (serving, worker):
        if ("diff_epoch" in sec
                and isinstance(sec["diff_epoch"], (int, float))
                and not isinstance(sec["diff_epoch"], bool)):
            out["diff epoch"] = int(sec["diff_epoch"])
            break
    # worker-mesh column: lanes per worker (multi-device engines) —
    # same mixed-schema tolerance: an older worker omits the key (or
    # ships an odd type) and its row shows a blank, never a crash
    for sec in (serving, worker):
        mesh = sec.get("mesh")
        if (isinstance(mesh, dict)
                and isinstance(mesh.get("devices"), (int, float))
                and not isinstance(mesh.get("devices"), bool)):
            out["mesh"] = int(mesh["devices"])
            break
    # streaming-transport columns (the RPC data plane): connections,
    # in-flight frames, credit window — a worker row reads its accept
    # loop, a head row folds its per-worker client table. Pre-RPC
    # endpoints omit the section and their rows show "-" blanks, never
    # a crash (the same mixed-schema tolerance as every other column)
    for sec in (serving, worker):
        tr = sec.get("transport")
        if not isinstance(tr, dict) or not tr:
            continue
        conns = tr.get("connections")
        if isinstance(conns, dict):
            # head side (RpcDispatcher/AutoDispatcher): one entry per
            # worker connection
            out["conns"] = len(conns)
            out["inflight"] = sum(
                _num(c.get("inflight")) for c in conns.values()
                if isinstance(c, dict))
        elif isinstance(conns, (int, float)) \
                and not isinstance(conns, bool):
            # worker side (RpcServeLoop.statusz)
            out["conns"] = int(conns)
            out["inflight"] = _num(tr.get("inflight"))
        credit = tr.get("credit")
        if isinstance(credit, (int, float)) \
                and not isinstance(credit, bool):
            out["credit"] = int(credit)
        break
    # gateway-tier columns: replica identity, client connections, and
    # the two cache levels' hit rates. A gateway process ships a
    # top-level "gateway" section (a tier reports its replica count, a
    # single replica its frontend id), a worker ships "l2" under its
    # worker section; pre-gateway fleets omit both and their rows show
    # "-" blanks, never a crash
    gw = status.get("gateway")
    if isinstance(gw, dict) and gw:
        reps = gw.get("replicas")
        fe_id = gw.get("frontend")
        if isinstance(reps, (int, float)) \
                and not isinstance(reps, bool):
            out["gw"] = f"x{int(reps)}"
        elif isinstance(fe_id, (int, float)) \
                and not isinstance(fe_id, bool):
            out["gw"] = f"f{int(fe_id)}"
        clients = gw.get("clients")
        if isinstance(clients, (int, float)) \
                and not isinstance(clients, bool):
            out["clients"] = int(clients)
        l1 = gw.get("l1_hit_rate")
        if isinstance(l1, (int, float)) and not isinstance(l1, bool):
            out["l1 hit"] = round(float(l1), 2)
        # HA columns (PR 19): fleet-wide live peer count from the
        # endpoint registry, worst lease age across local replicas,
        # and frames re-executed here after a client failover. Pre-HA
        # gateways omit all three — blanks, never a crash
        peers = gw.get("peers")
        if isinstance(peers, (int, float)) \
                and not isinstance(peers, bool):
            out["peers"] = int(peers)
        lease = gw.get("lease_age_s")
        if isinstance(lease, (int, float)) \
                and not isinstance(lease, bool):
            out["lease s"] = round(float(lease), 1)
        fo = gw.get("failovers")
        if isinstance(fo, (int, float)) and not isinstance(fo, bool):
            out["failover"] = int(fo)
    l2 = worker.get("l2")
    if isinstance(l2, dict):
        rate = l2.get("hit_rate")
        if isinstance(rate, (int, float)) \
                and not isinstance(rate, bool):
            out["l2 hit"] = round(float(rate), 2)
    # SLO / telemetry columns (the head's fleet-health plane): worst
    # fast-burn across objectives (the page-now signal) and worst
    # telemetry source lag (a stalled publisher or dead wire shows up
    # as lag before anything else does). Pre-telemetry endpoints omit
    # both sections and their rows show "-" blanks, never a crash
    slo_sec = status.get("slo")
    if isinstance(slo_sec, dict):
        burn_sec = slo_sec.get("burn")
        burns = [_num(b.get("fast"), None)
                 for b in (burn_sec.values()
                           if isinstance(burn_sec, dict) else ())
                 if isinstance(b, dict)]
        burns = [b for b in burns if b is not None]
        if burns:
            out["slo burn"] = round(max(burns), 2)
        alerting = slo_sec.get("alerting")
        if isinstance(alerting, list) and alerting:
            out["state"] = "SLO:" + ",".join(str(a) for a in alerting)
    tele = status.get("telemetry")
    if isinstance(tele, dict):
        src_sec = tele.get("sources")
        lags = [_num(s.get("lag_s"), None)
                for s in (src_sec.values()
                          if isinstance(src_sec, dict) else ())
                if isinstance(s, dict)]
        lags = [v for v in lags if v is not None]
        if lags:
            out["tel lag"] = round(max(lags), 1)
    # closed-loop control columns: policy state (brownout level, dry-run
    # tag), last action, quarantined workers. Only a daemon-enabled
    # endpoint ships the section; every other row shows "-" blanks —
    # the same mixed-schema tolerance as the slo/telemetry columns
    ctl = status.get("control")
    if isinstance(ctl, dict) and ctl:
        lvl = ctl.get("brownout_level")
        if isinstance(lvl, (int, float)) and not isinstance(lvl, bool):
            tag = "dry:" if ctl.get("dry_run") is True else ""
            out["policy"] = f"{tag}L{int(lvl)}"
        last = ctl.get("last_action")
        if isinstance(last, str) and last:
            out["last action"] = last.split(" ", 1)[0]
        quarantined = ctl.get("quarantined")
        if isinstance(quarantined, list) and quarantined:
            out["quarantined"] = ",".join(
                str(w) for w in quarantined)
    mig = serving.get("migration") or worker.get("migration")
    if isinstance(mig, dict):
        moves = mig.get("moves") if isinstance(mig.get("moves"), list) \
            else []
        done = mig.get("done") if isinstance(mig.get("done"), list) \
            else []
        out["migration"] = (f"{mig.get('kind', '?')}->e"
                            f"{mig.get('epoch', '?')} "
                            f"{len(done)}/{len(moves)}")
    return out


def render_top(statuses: dict[str, dict]) -> str:
    """The ``dos-obs top`` fleet table: one row per endpoint, columns
    unioned across roles (a frontend shows queues/hedges, a worker
    batches/failures, a supervisor alive/respawns)."""
    rows = {ep: _summarize(st) for ep, st in statuses.items()}
    cols = ["endpoint"]
    for r in rows.values():
        for k in r:
            if k not in cols:
                cols.append(k)
    table = [cols]
    for ep, r in rows.items():
        table.append([ep] + [str(r.get(c, "-")) for c in cols[1:]])
    widths = [max(len(row[i]) for row in table)
              for i in range(len(cols))]
    lines = ["  ".join(c.ljust(w) for c, w in zip(row, widths)).rstrip()
             for row in table]
    lines.insert(1, "-" * len(lines[0]))
    return "\n".join(lines)


# ------------------------------------------------------------ bench gate

#: default fractional tolerance — the README documents ±20% swings on
#: the tunneled shared device, so the gate trips only on clear breaks
DEFAULT_TOLERANCE = 0.3

#: key patterns whose value IMPROVES downward (everything else is
#: treated as higher-is-better throughput/ratio)
_LOWER_BETTER = re.compile(
    r"(_ms|_seconds|_s)$|(^|_)p\d+_ms$|break[-_]?even")

#: explicit per-key directions for headline keys whose names defeat the
#: suffix heuristic — the walk-kernel roofline family (PR 10): q/s and
#: utilization/efficiency fractions improve UP, stall/kernel time
#: improves DOWN (listed even where the suffix would catch it, so the
#: family's contract is in one place)
_KEY_DIRECTIONS = {
    "walk_gather_utilization": "higher",
    "walk_issue_efficiency": "higher",
    "walk_useful_lane_fraction": "higher",
    "walk_pallas_useful_lane_fraction": "higher",
    "walk_pallas_queries_per_sec": "higher",
    "walk_pallas_speedup": "higher",
    "walk_pallas_kernel_seconds": "lower",
    "walk_pallas_stall_p99_ms": "lower",
    # the build family (pipelined + delta builds, ROADMAP item 1):
    # build rates and the delta-vs-full ratio improve UP, pipeline
    # stall improves DOWN — and staging OVERLAP improves UP despite
    # its _seconds suffix (overlap won is host work hidden behind the
    # device, exactly what the pipeline exists for), so it MUST be
    # listed here or the suffix heuristic gates it backwards
    "scale_build_rows_per_sec": "higher",
    "road_tpu_build_rows_per_sec": "higher",
    "build_delta_vs_full_ratio": "higher",
    "build_full_rows_per_sec": "higher",
    "build_delta_rows_per_sec": "higher",
    "build_pipeline_stall_seconds": "lower",
    "build_stage_overlap_seconds": "higher",
    # the worker-mesh family (multi-device sharded execution): per-
    # device-count rates improve UP, the strong-scaling overhead split
    # improves DOWN, and the multichip smoke is a 0/1 health bit whose
    # only regression is 1 -> 0 (tolerance 0 below). The
    # shard_strong_scaling_* scalars pin the PR 13 headline: the W=8
    # rate regressing vs W=1 was the bug this family measures.
    "mesh_build_rows_per_sec_d8": "higher",
    "mesh_walk_queries_per_sec_d8": "higher",
    "mesh_mat_rows_per_sec_d8": "higher",
    "shard_strong_scaling_rows_per_sec_w1": "higher",
    "shard_strong_scaling_rows_per_sec_w8": "higher",
    "shard_strong_scaling_overhead_w8_seconds": "lower",
    "multichip_smoke_ok": "higher",
    # the compressed-residency family (RLE/pack4 resident CPD shards,
    # ROADMAP item 1): the resident-bytes ratio and compressed walk
    # rates improve UP, the per-batch decompress overhead improves
    # DOWN (its _seconds suffix would catch it — listed so the
    # family's contract is in one place like the others)
    "cpd_resident_bytes_ratio": "higher",
    "compressed_walk_queries_per_sec": "higher",
    "compressed_raw_walk_queries_per_sec": "higher",
    "compressed_vs_raw_walk_ratio": "higher",
    "compressed_decompress_seconds": "lower",
    # the streaming-transport family (RPC vs FIFO head-to-head on the
    # same workload): the dispatch-overhead ratio improves UP (fifo
    # per-batch cost / rpc per-batch cost), per-batch overheads and
    # tail latency improve DOWN (the _ms suffix would catch those —
    # listed so the family's contract is in one place like the others)
    "serve_rpc_vs_fifo_dispatch_ratio": "higher",
    "serve_rpc_dispatch_ms": "lower",
    "serve_fifo_dispatch_ms": "lower",
    "serve_rpc_p99_ms": "lower",
    "serve_fifo_p99_ms": "lower",
    "serve_rpc_queries_per_sec": "higher",
    "serve_fifo_queries_per_sec": "higher",
    # the telemetry family (fleet telemetry bus, PR 16): the head's
    # ingest rate improves UP; the publish tail and the overhead
    # fraction (mean tick build time / publish interval — the "< 1%
    # of serve throughput" acceptance) improve DOWN (the p99_ms suffix
    # would catch the first — listed so the family's contract is in
    # one place like the others)
    "telemetry_head_ingest_per_sec": "higher",
    "telemetry_publish_p99_ms": "lower",
    "telemetry_publish_overhead_frac": "lower",
    # the closed-loop control family (policy daemon, PR 17): both arms'
    # time-to-recover and shed rate improve DOWN — shed_rate defeats
    # the suffix heuristic (no _ms/_seconds), and the policy-off
    # baselines gate too so a regression in the daemon-off recovery
    # path (supervisor backoff, breaker heal) cannot hide behind the
    # policy-on deltas
    "control_recover_seconds": "lower",
    "control_shed_rate": "lower",
    "control_p99_ms": "lower",
    "control_off_recover_seconds": "lower",
    "control_off_shed_rate": "lower",
    "control_off_p99_ms": "lower",
    # the gateway family (N-replica tier vs the single head, PR 18):
    # aggregate throughput, the tier-vs-head ratio, answer bit-identity
    # (a 0/1 health bit), and both cache-plane hit rates improve UP;
    # per-frontend fairness is a max/min q/s ratio whose ideal is 1.0,
    # so it improves DOWN (no suffix catches it — listed like the
    # other family contracts, in one place)
    "gateway_aggregate_queries_per_sec": "higher",
    "gateway_single_head_queries_per_sec": "higher",
    "gateway_vs_single_head_ratio": "higher",
    "gateway_fairness_ratio": "lower",
    "gateway_answers_match": "higher",
    "gateway_fleet_cache_hit_rate": "higher",
    "gateway_single_head_cache_hit_rate": "higher",
    # the gateway HA family (leased discovery + failover, PR 19): lost
    # requests and duplicate answers are correctness counts whose ideal
    # is 0, failover recovery time improves DOWN like any latency
    "gateway_ha_lost_requests": "lower",
    "gateway_ha_duplicate_answers": "lower",
    "gateway_ha_failover_p99_ms": "lower",
    # the answer-integrity family (scrub + audit + fingerprints,
    # PR 20): divergences on a clean run and corrupted answers served
    # in the drill are correctness counts whose ideal is 0; the
    # audit/scrub overhead fractions (1 - audited q/s / baseline q/s)
    # and the corrupt-resident detection latency improve DOWN; the
    # throughput columns improve UP like any q/s
    "integrity_audit_divergence": "lower",
    "integrity_wrong_answers_served": "lower",
    "integrity_audit_overhead_frac": "lower",
    "integrity_scrub_overhead_frac": "lower",
    "integrity_detect_seconds": "lower",
    "integrity_base_queries_per_sec": "higher",
    "integrity_audit1_queries_per_sec": "higher",
    "integrity_audit10_queries_per_sec": "higher",
    "integrity_scrub_queries_per_sec": "higher",
}

#: per-key default tolerances (CLI --key-tolerance still overrides):
#: lane/utilization fractions are stable kernel properties — a real
#: regression there is structural, so gate them tighter than raw
#: throughput on the jittery tunneled link
_KEY_TOLERANCES = {
    "walk_useful_lane_fraction": 0.15,
    "walk_pallas_useful_lane_fraction": 0.15,
    "walk_gather_utilization": 0.15,
    "walk_issue_efficiency": 0.15,
    # the delta-vs-full ratio is a structural property of the dirty-set
    # pass (work skipped / work done), not a raw device timing — a real
    # drop means the pass stopped skipping, so gate it tighter than the
    # jittery-link default
    "build_delta_vs_full_ratio": 0.2,
    # the multichip smoke is pass/fail: ANY drop (1 -> 0) gates
    "multichip_smoke_ok": 0.0,
    # the resident-bytes ratio is a structural property of the codec
    # on a fixed synthetic graph (bytes in / bytes out), not a timing
    # — a real drop means the encoder stopped compressing
    "cpd_resident_bytes_ratio": 0.15,
    # the rpc-vs-fifo dispatch ratio measures transport overhead
    # (subprocess + files + FIFO rendezvous vs one socket round-trip)
    # on the SAME engine and workload; it sits far above 1 and jitter
    # affects both lanes alike, but the FIFO lane's bash-subprocess
    # cost swings with host load — gate it loosely (a real regression
    # to ~1 still trips)
    "serve_rpc_vs_fifo_dispatch_ratio": 0.5,
    # tick build cost is microseconds measured against host jitter —
    # the p99 and the derived overhead fraction both swing with host
    # load on the shared device, so gate them loosely (a real
    # regression — publish cost approaching the interval — still
    # trips); the ingest rate is in-process dict work, same story
    "telemetry_publish_p99_ms": 0.5,
    "telemetry_publish_overhead_frac": 0.5,
    "telemetry_head_ingest_per_sec": 0.5,
    # recovery timings are dominated by backoff/probe cadences racing
    # host scheduling jitter; shed rates depend on exactly how many
    # requests land inside the outage window — gate all four loosely
    # (a real regression, e.g. re-admission stops happening, blows far
    # past 2x)
    "control_recover_seconds": 0.5,
    "control_shed_rate": 0.5,
    "control_off_recover_seconds": 0.5,
    "control_off_shed_rate": 0.5,
    "control_p99_ms": 0.5,
    "control_off_p99_ms": 0.5,
    # answer bit-identity between the gateway tier and the single-head
    # line protocol is pass/fail: ANY drop (1 -> 0) gates
    "gateway_answers_match": 0.0,
    # hit rates on the fixed zipf pool are structural cache properties
    # (keyspace skew / capacity), not timings — gate tighter than the
    # throughput default
    "gateway_fleet_cache_hit_rate": 0.2,
    "gateway_single_head_cache_hit_rate": 0.2,
    # tier throughput and fairness race thread scheduling on a shared
    # host — gate loosely (a real regression, e.g. one replica starved
    # to a halt, blows far past 2x)
    "gateway_aggregate_queries_per_sec": 0.5,
    "gateway_single_head_queries_per_sec": 0.5,
    "gateway_vs_single_head_ratio": 0.5,
    "gateway_fairness_ratio": 0.5,
    # HA drill correctness is absolute: losing ANY accepted request or
    # double-booking ANY answer across a failover gates at zero
    "gateway_ha_lost_requests": 0.0,
    "gateway_ha_duplicate_answers": 0.0,
    # failover latency is bounded by the lease TTL racing thread
    # scheduling on a shared host — gate loosely (a real regression,
    # e.g. failover stops working and waits burn their full deadline,
    # blows far past 2x)
    "gateway_ha_failover_p99_ms": 1.0,
    # integrity correctness is absolute: an audit divergence on an
    # uncorrupted run, or ANY corrupted answer reaching a client in
    # the drill, gates at zero
    "integrity_audit_divergence": 0.0,
    "integrity_wrong_answers_served": 0.0,
    # overhead fractions compare two q/s measurements racing host
    # jitter (both near the noise floor at 1 per mille), and detection
    # latency is a poll-cadence race — gate all three loosely; the
    # raw q/s columns inherit the same story
    "integrity_audit_overhead_frac": 1.0,
    "integrity_scrub_overhead_frac": 1.0,
    "integrity_detect_seconds": 0.5,
    "integrity_base_queries_per_sec": 0.5,
    "integrity_audit1_queries_per_sec": 0.5,
    "integrity_audit10_queries_per_sec": 0.5,
    "integrity_scrub_queries_per_sec": 0.5,
}


def find_bench_records(dirname: str) -> list[str]:
    """``BENCH_r*.json`` sorted by round number."""
    paths = glob.glob(os.path.join(dirname, "BENCH_r[0-9]*.json"))
    def _round(p):
        m = re.search(r"BENCH_r(\d+)\.json$", p)
        return int(m.group(1)) if m else -1
    return sorted((p for p in paths if _round(p) >= 0), key=_round)


def bench_numbers(path: str) -> dict[str, float]:
    """The comparable scalar metrics of one bench record: the headline
    value plus every numeric entry of ``parsed.headline`` (the driver's
    record format; a raw bench payload's top-level ``value``/
    ``detail`` also works). A record whose ``parsed`` is null (the r04
    overflow failure mode) falls back to the last JSON object in its
    stdout ``tail``; records with no numbers at all yield ``{}`` —
    the CLI then walks further back for a comparable round."""
    with open(path) as f:
        doc = json.load(f)
    parsed = doc.get("parsed") or doc
    if not isinstance(parsed, dict) or (
            "parsed" in doc and doc["parsed"] is None):
        parsed = None
        tail = doc.get("tail", "")
        if isinstance(tail, str):
            start = tail.rfind('\n{"metric"')
            if start < 0 and tail.startswith('{"metric"'):
                start = -1      # tail IS the line
            try:
                parsed = json.loads(tail[start + 1:])
            except ValueError:
                parsed = None
    if not isinstance(parsed, dict):
        return {}
    out: dict[str, float] = {}
    if isinstance(parsed.get("value"), (int, float)):
        out[parsed.get("metric", "value")] = float(parsed["value"])
    headline = parsed.get("headline") or parsed.get("detail") or {}
    for k, v in headline.items():
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[k] = float(v)
    return out


#: recorded per-key baseline waivers live next to the BENCH_r*.json
#: history (checked into the repo, so the acceptance is reviewable)
WAIVER_FILE = "BENCH_WAIVERS.json"


def bench_round(path: str) -> str:
    """``BENCH_r05.json`` -> ``"r05"`` (empty for non-canonical
    names — explicit OLD NEW paths can be anything)."""
    m = re.search(r"BENCH_(r\d+)\.json$", os.path.basename(path))
    return m.group(1) if m else ""


def load_waivers(dirname: str) -> dict:
    """The recorded waiver map ``{key: {"round": "rNN", ...}}``; absent
    or unreadable file = no waivers (logged — a corrupt waiver file
    must fail toward GATING, never toward silently passing). Unknown
    per-entry keys are tolerated (the annotation contract of every
    other on-disk codec here)."""
    path = os.path.join(dirname, WAIVER_FILE)
    try:
        with open(path) as f:
            doc = json.load(f)
    except OSError:
        return {}
    except ValueError as e:
        log.error("unreadable %s: %s (treating as NO waivers)", path, e)
        return {}
    return doc if isinstance(doc, dict) else {}


def record_waiver(dirname: str, key: str, round_name: str,
                  entry: dict | None = None) -> dict:
    """Merge one waiver into the recorded file (atomic write) and
    return the updated map. ``entry`` carries the context a reviewer
    needs (old/new values, reason)."""
    from ..utils.atomicio import atomic_write_bytes

    waivers = load_waivers(dirname)
    rec = {"round": round_name}
    if entry:
        rec.update(entry)
    waivers[key] = rec
    atomic_write_bytes(
        os.path.join(dirname, WAIVER_FILE),
        (json.dumps(waivers, indent=1, sort_keys=True) + "\n").encode())
    return waivers


def compare_bench(old_path: str, new_path: str,
                  tolerance: float = DEFAULT_TOLERANCE,
                  key_tolerances: dict[str, float] | None = None,
                  waivers: dict | None = None) -> dict:
    """Per-key regression check; returns ``{"regressions": [...],
    "improved": [...], "waived": [...], "checked": N, ...}``. A key
    present only on one side is skipped (workloads grow across rounds;
    absence is not a regression). A regression whose key carries a
    recorded waiver FOR THE NEW ROUND moves to ``waived`` instead — the
    waiver is a per-round baseline acceptance, so a fresh regression in
    a later round gates again."""
    old = bench_numbers(old_path)
    new = bench_numbers(new_path)
    key_tolerances = key_tolerances or {}
    waivers = waivers or {}
    new_round = bench_round(new_path)
    regressions, improved, waived, checked = [], [], [], []
    for key in sorted(set(old) & set(new)):
        tol = key_tolerances.get(
            key, _KEY_TOLERANCES.get(key, tolerance))
        ov, nv = old[key], new[key]
        checked.append(key)
        if ov == 0:
            continue
        direction = _KEY_DIRECTIONS.get(key)
        lower_better = (direction == "lower" if direction
                        else bool(_LOWER_BETTER.search(key)))
        ratio = nv / ov
        entry = {"key": key, "old": ov, "new": nv,
                 "ratio": round(ratio, 3), "tolerance": tol,
                 "direction": "lower" if lower_better else "higher"}
        if lower_better:
            regressed = ratio > 1.0 + tol
            better = ratio < 1.0
        else:
            regressed = ratio < 1.0 - tol
            better = ratio > 1.0
        if regressed:
            waiver = waivers.get(key)
            if (isinstance(waiver, dict) and new_round
                    and waiver.get("round") == new_round):
                entry["waiver"] = waiver
                waived.append(entry)
            else:
                regressions.append(entry)
        elif better:
            improved.append(entry)
    return {"old": os.path.basename(old_path),
            "new": os.path.basename(new_path),
            "checked": len(checked), "regressions": regressions,
            "improved": improved, "waived": waived}
