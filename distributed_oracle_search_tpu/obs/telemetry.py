"""Fleet telemetry bus: push-based metric snapshots, worker → head.

Every process already keeps a metrics registry and live quantile
windows; until now the head could only see them by polling ``/statusz``
or merging dump files after the fact. This module is the push half:
workers and supervisors publish **ticks** — delta-encoded snapshots of
their counters, gauges, window quantiles and pending flight-recorder
events — on a ``DOS_TELEMETRY_INTERVAL_S`` cadence, and the head
ingests them into the fleet timeseries store (:mod:`.timeseries`) the
SLO engine (:mod:`.slo`) and ``dos-obs top`` read.

Two lanes, mirroring the data plane:

* **RPC** — a ``telemetry`` frame (``transport.frames``) pushed on
  every live serve connection; the head's :class:`~..transport.rpc
  .RpcClient` read loop hands it to the registered sink. No request
  id, no reply — pure fire-and-forget on an already-open socket.
* **FIFO sidecar** — a ``<fifo>.telemetry`` JSONL file of the last few
  ticks, atomically replaced each tick; the head polls the directory.
  A torn tail line is skipped (the reader may race a non-atomic NFS
  copy), mirroring the frame codec's torn-tail tolerance.

Tick schema (its own version, independent of the frame schema): the
usual compat contract — unknown keys tolerated, ONLY newer versions
refused (:class:`TelemetrySchemaError`). Delta encoding is on the *key
set*: after the first (``full``) tick, counters and gauges ship only
the entries that changed since the previous tick; values stay
**absolute** so the head can detect monotonic resets (a respawned
worker's counters restart at zero — the ingest layer clamps the
negative delta and books the new absolute value from zero, never a
negative rate). A full tick rides every ``DOS_TELEMETRY_FULL_EVERY``
ticks (default 12) so a head that attached late converges.

Env knobs: ``DOS_TELEMETRY_INTERVAL_S`` (publish cadence, default 5 s,
``0`` = off), ``DOS_TELEMETRY_FULL_EVERY``,
``DOS_TELEMETRY_SIDECAR_KEEP`` (ticks kept per sidecar, default 16),
``DOS_TELEMETRY_BUSY_STORM`` (BUSY sheds per tick that flag a storm
event, default 50). The head-side store budget is
``DOS_TELEMETRY_BYTES`` (see :mod:`.timeseries`).
"""

from __future__ import annotations

import glob
import json
import os
import threading
import time

from ..utils.atomicio import atomic_replace_bytes
from ..utils.env import env_cast
from ..utils.locks import OrderedLock
from ..utils.log import get_logger
from . import metrics as obs_metrics
from . import quantiles as obs_quantiles
from . import recorder as obs_recorder

log = get_logger(__name__)

#: the tick schema this build writes; readers tolerate unknown keys and
#: refuse ONLY newer versions (the wire/manifest compat contract)
TELEMETRY_SCHEMA_VERSION = 1

#: sidecar filename suffix next to a worker's command FIFO
SIDECAR_SUFFIX = ".telemetry"

M_PUBLISHED = obs_metrics.counter(
    "telemetry_ticks_published_total", "ticks built and handed to sinks")
M_PUB_ERRORS = obs_metrics.counter(
    "telemetry_publish_errors_total",
    "telemetry sinks that raised (tick dropped on that lane only)")
H_PUBLISH = obs_metrics.histogram(
    "telemetry_publish_seconds",
    "one tick: snapshot + delta-encode + every sink")
M_INGESTED = obs_metrics.counter(
    "telemetry_ticks_ingested_total", "ticks accepted by the head")
M_DROPPED = obs_metrics.counter(
    "telemetry_ticks_dropped_total",
    "ticks the head dropped: replays, schema refusals, malformed")
M_RESETS = obs_metrics.counter(
    "telemetry_counter_resets_total",
    "monotonic counter resets clamped at ingest (worker respawns)")


class TelemetrySchemaError(ValueError):
    """A tick written by a NEWER schema than this reader understands.
    Deliberately not a transport error: reconnecting meets the same
    peer."""


def interval_s() -> float:
    """The publish cadence (0 = telemetry off)."""
    return max(env_cast("DOS_TELEMETRY_INTERVAL_S", 5.0, float), 0.0)


# ------------------------------------------------------------ tick codec

def decode_tick(raw) -> dict:
    """A tick from wire bytes / str / an already-parsed frame header.
    Unknown keys pass through untouched; ONLY a newer ``v`` refuses."""
    if isinstance(raw, (bytes, bytearray)):
        raw = raw.decode("utf-8", errors="replace")
    if isinstance(raw, str):
        try:
            raw = json.loads(raw)
        except ValueError as e:
            raise ValueError(f"undecodable telemetry tick: {e}")
    if not isinstance(raw, dict):
        raise ValueError(f"telemetry tick must be an object, got "
                         f"{type(raw).__name__}")
    v = raw.get("v", 0)
    if not isinstance(v, int) or isinstance(v, bool):
        v = 0           # annotation, not a gate — degrade like frames
    if v > TELEMETRY_SCHEMA_VERSION:
        raise TelemetrySchemaError(
            f"telemetry tick schema v{v} is newer than this reader "
            f"(v{TELEMETRY_SCHEMA_VERSION}); upgrade the head")
    return raw


def encode_tick(tick: dict) -> bytes:
    return json.dumps(tick, sort_keys=True, default=str).encode()


# --------------------------------------------------------------- sidecar

def write_sidecar(path: str, ticks: list[dict]) -> None:
    """The last few ticks as JSONL, atomically replaced (transient
    telemetry: rename-atomic visibility without paying fsync per
    tick)."""
    atomic_replace_bytes(
        path, b"".join(encode_tick(t) + b"\n" for t in ticks))


def read_sidecar(path: str) -> list[dict]:
    """Ticks from a sidecar. A torn TAIL line is skipped (a reader may
    race a non-atomic copy of the file); an undecodable line anywhere
    else — or a newer schema — raises, mirroring the frame codec's
    torn-vs-corrupt split. A missing file is simply no ticks."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except FileNotFoundError:
        return []
    lines = raw.split(b"\n")
    if lines and lines[-1] == b"":
        lines.pop()
    out = []
    for i, line in enumerate(lines):
        try:
            out.append(decode_tick(line))
        except TelemetrySchemaError:
            raise
        except ValueError:
            if i == len(lines) - 1:
                log.debug("skipping torn telemetry sidecar tail in %s",
                          path)
                continue
            raise ValueError(
                f"{path}: undecodable telemetry tick mid-file "
                f"(line {i + 1})")
    return out


def sidecar_sink(path: str, keep: int | None = None):
    """A publisher sink writing the rolling sidecar file at ``path``."""
    keep = int(keep if keep is not None
               else env_cast("DOS_TELEMETRY_SIDECAR_KEEP", 16, int))
    ring: list[dict] = []

    def sink(tick: dict) -> None:
        ring.append(tick)
        del ring[:-keep]
        write_sidecar(path, ring)

    return sink


# ------------------------------------------------------------- publisher

class TelemetryPublisher:
    """One process's tick builder + publish loop.

    ``sinks`` are callables taking the tick dict: the RPC broadcast,
    the sidecar writer, or (head self-ingest) the ingest itself. A sink
    that raises loses that lane's tick only — publishing keeps going on
    the others, and the error is counted, never raised into the serve
    path."""

    def __init__(self, source: str, sinks=(),
                 interval: float | None = None,
                 registry: obs_metrics.MetricsRegistry | None = None,
                 windows: obs_quantiles.QuantileWindows | None = None,
                 full_every: int | None = None, clock=time.time):
        self.source = str(source)
        self.sinks = list(sinks)
        self.interval = float(interval if interval is not None
                              else interval_s())
        self.registry = registry or obs_metrics.REGISTRY
        self.windows = windows or obs_quantiles.WINDOWS
        self.full_every = int(
            full_every if full_every is not None
            else env_cast("DOS_TELEMETRY_FULL_EVERY", 12, int))
        self.clock = clock
        #: process incarnation: lets the head tell a respawn (fresh
        #: counters) from a counter that actually went backwards
        self.incarnation = f"{os.getpid():x}-{int(time.monotonic() * 1e3):x}"
        self._seq = 0
        self._last_counters: dict[str, float] = {}
        self._last_gauges: dict[str, float] = {}
        self._lock = OrderedLock("telemetry.TelemetryPublisher")
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def add_sink(self, sink) -> None:
        self.sinks.append(sink)

    # ----------------------------------------------------------- ticking
    def _changed(self, cur: dict, last: dict, full: bool) -> dict:
        if full:
            return dict(cur)
        return {k: v for k, v in cur.items() if last.get(k) != v}

    def tick_once(self) -> dict:
        """Build and publish one tick; returns it (tests and the bench
        drive this inline)."""
        t0 = time.perf_counter()
        with self._lock:
            full = (self._seq % max(self.full_every, 1)) == 0
            snap = self.registry.snapshot()
            counters = {k: float(v) for k, v
                        in snap.get("counters", {}).items()}
            gauges = {k: float(v) for k, v
                      in snap.get("gauges", {}).items()}
            tick = {
                "v": TELEMETRY_SCHEMA_VERSION,
                "source": self.source,
                "incarnation": self.incarnation,
                "seq": self._seq,
                "ts": float(self.clock()),
                "full": full,
                "counters": self._changed(counters,
                                          self._last_counters, full),
                "gauges": self._changed(gauges, self._last_gauges,
                                        full),
                "windows": {name: s for name, s
                            in self.windows.snapshot().items()
                            if s.get("count")},
                "events": obs_recorder.drain_pending(),
            }
            self._last_counters = counters
            self._last_gauges = gauges
            self._seq += 1
        for sink in self.sinks:
            try:
                sink(tick)
            except Exception as e:  # noqa: BLE001 — one dead lane must
                # not stop the others (or the serve path) from ticking
                M_PUB_ERRORS.inc()
                log.debug("telemetry sink failed: %s", e)
        M_PUBLISHED.inc()
        H_PUBLISH.observe(time.perf_counter() - t0)
        return tick

    # --------------------------------------------------------- lifecycle
    def start(self) -> "TelemetryPublisher":
        if self._thread is not None or self.interval <= 0:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval):
                try:
                    self.tick_once()
                except Exception as e:  # noqa: BLE001 — the publish
                    # loop outlives any one bad tick
                    log.exception("telemetry tick failed: %s", e)

        self._thread = threading.Thread(
            target=loop, daemon=True,
            name=f"dos-telemetry-{self.source}")
        self._thread.start()
        log.info("telemetry publisher up: source=%s interval=%.1fs "
                 "sinks=%d", self.source, self.interval,
                 len(self.sinks))
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def statusz(self) -> dict:
        with self._lock:
            return {"source": self.source, "seq": self._seq,
                    "interval_s": self.interval,
                    "incarnation": self.incarnation,
                    "sinks": len(self.sinks)}


# ---------------------------------------------------------------- ingest

class TelemetryIngest:
    """The head's tick consumer: dedupe, delta, store, record.

    Per ``(source, incarnation)`` it tracks the last seq (replayed
    sidecar reads drop silently) and the last absolute counter values
    (per-tick deltas go to the store; a NEW incarnation or a value
    below the last one is a monotonic reset — the new absolute value
    books from zero, never a negative delta)."""

    def __init__(self, store, recorder=None, clock=time.time):
        self.store = store
        self.recorder = recorder
        self.clock = clock
        self.busy_storm = env_cast("DOS_TELEMETRY_BUSY_STORM", 50.0,
                                   float)
        self._sources: dict[str, dict] = {}
        self._lock = OrderedLock("telemetry.TelemetryIngest")

    def ingest(self, raw) -> bool:
        """One tick (bytes / str / dict). True when accepted; replays
        and malformed/newer ticks are dropped-and-counted — a bad
        publisher must not crash the head's ingest lane."""
        try:
            tick = decode_tick(raw)
        except ValueError as e:
            M_DROPPED.inc()
            log.warning("dropping telemetry tick: %s", e)
            return False
        source = tick.get("source")
        if not isinstance(source, str) or not source:
            M_DROPPED.inc()
            return False
        ts = tick.get("ts")
        ts = float(ts) if isinstance(ts, (int, float)) else self.clock()
        seq = tick.get("seq")
        seq = int(seq) if isinstance(seq, int) else -1
        inc = str(tick.get("incarnation", ""))
        events = []
        with self._lock:
            st = self._sources.get(source)
            if st is not None and st["incarnation"] == inc \
                    and seq >= 0 and seq <= st["seq"]:
                M_DROPPED.inc()    # a sidecar poll re-read this tick
                return False
            reset = st is None or st["incarnation"] != inc
            if reset and st is not None:
                M_RESETS.inc()
                log.info("telemetry source %s reincarnated (%s -> %s)",
                         source, st["incarnation"], inc)
                events.append({"ts": ts, "kind": "source_restart",
                               "source": source})
            last = {} if reset else st["counters"]
            counters = tick.get("counters")
            counters = counters if isinstance(counters, dict) else {}
            deltas = {}
            for name, val in counters.items():
                if not isinstance(val, (int, float)) \
                        or isinstance(val, bool):
                    continue
                prev = last.get(name)
                if prev is None or val < prev:
                    if prev is not None:
                        M_RESETS.inc()
                    delta = float(val)   # reset clamp: book from zero
                else:
                    delta = float(val) - float(prev)
                last[name] = float(val)
                if delta:
                    deltas[name] = delta
            self._sources[source] = {
                "incarnation": inc, "seq": seq, "counters": last,
                "ts": ts, "recv_ts": self.clock(),
            }
        # store writes happen OUTSIDE the ingest lock: the store has
        # its own lock and the sidecar poller / rpc read loops must
        # not serialize behind each other's appends
        for name, delta in deltas.items():
            self.store.append(source, name, ts, delta, kind="delta")
        gauges = tick.get("gauges")
        if isinstance(gauges, dict):
            for name, val in gauges.items():
                if isinstance(val, (int, float)) \
                        and not isinstance(val, bool):
                    self.store.append(source, name, ts, float(val),
                                      kind="gauge")
        windows = tick.get("windows")
        if isinstance(windows, dict):
            for name, snap in windows.items():
                if isinstance(snap, dict):
                    self.store.put_window(source, name, ts, snap)
        raw_events = tick.get("events")
        if isinstance(raw_events, list):
            events.extend(e for e in raw_events if isinstance(e, dict))
        busy = deltas.get("serve_shed_busy_total", 0.0) \
            + deltas.get("rpc_busy_frames_total", 0.0)
        if self.busy_storm > 0 and busy >= self.busy_storm:
            events.append({"ts": ts, "kind": "busy_storm",
                           "source": source, "sheds": busy})
        rec = self.recorder or obs_recorder.get_recorder()
        if rec is not None:
            try:
                rec.record_tick(tick)
                for ev in events:
                    ev.setdefault("source", source)
                    rec.record_event(ev)
            except Exception as e:  # noqa: BLE001 — tape trouble must
                # not fail the metrics path
                log.warning("flight recorder ingest write failed: %s", e)
        M_INGESTED.inc()
        return True

    def statusz(self) -> dict:
        """Per-source freshness for ``/statusz`` and ``dos-obs top``:
        lag (now - last tick's publish ts), seq, incarnation."""
        now = self.clock()
        with self._lock:
            sources = {
                src: {"lag_s": round(now - st["ts"], 3),
                      "seq": st["seq"],
                      "incarnation": st["incarnation"]}
                for src, st in sorted(self._sources.items())}
        return {"sources": sources, "store": self.store.statusz()}


class SidecarPoller:
    """Head-side FIFO-lane collector: scan a directory for
    ``*.telemetry`` sidecars on the telemetry cadence and feed every
    tick to the ingest (its seq dedupe makes re-reads free)."""

    def __init__(self, dirname: str, ingest: TelemetryIngest,
                 interval: float | None = None):
        self.dirname = dirname
        self.ingest = ingest
        self.interval = float(interval if interval is not None
                              else max(interval_s(), 0.5))
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def poll_once(self) -> int:
        n = 0
        for path in sorted(glob.glob(os.path.join(
                self.dirname, f"*{SIDECAR_SUFFIX}"))):
            try:
                ticks = read_sidecar(path)
            except ValueError as e:
                M_DROPPED.inc()
                log.warning("unreadable telemetry sidecar %s: %s",
                            path, e)
                continue
            for tick in ticks:
                if self.ingest.ingest(tick):
                    n += 1
        return n

    def start(self) -> "SidecarPoller":
        if self._thread is not None or self.interval <= 0:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval):
                try:
                    self.poll_once()
                except Exception as e:  # noqa: BLE001 — keep polling
                    log.exception("telemetry sidecar poll failed: %s", e)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="dos-telemetry-poll")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
