"""Head-side resilience: per-worker circuit breakers over the FIFO wire.

A dead or sick worker must not keep eating a campaign's time budget one
timeout at a time: after ``K`` consecutive batch failures the worker's
breaker OPENs and further sends short-circuit to an instant failure row.
An OPEN breaker half-opens two ways:

* **background probes** (preferred): the registry pings the worker on the
  cooldown cadence from a named daemon thread; the first healthy
  :class:`~.wire.HealthStatus` moves the breaker to HALF_OPEN;
* **cooldown fallback** (no ``probe_fn``): after ``cooldown_s`` the next
  ``allow()`` is granted as the trial.

HALF_OPEN admits exactly one trial send: success CLOSEs (consecutive
count reset), failure re-OPENs (and restarts the probe loop).

Env knobs: ``DOS_CIRCUIT_THRESHOLD`` (K, default 3),
``DOS_CIRCUIT_COOLDOWN_S`` (default 5), ``DOS_CIRCUIT_DISABLE=1``
(breakers always allow — the pre-PR-2 behavior).

Everything takes an injectable ``clock`` so tests drive the state machine
without sleeping; probe threads are named ``dos-probe-*`` and joined by
:meth:`BreakerRegistry.shutdown` so the test suite's leak check can prove
no campaign leaves one behind.
"""

from __future__ import annotations

import threading
import time

from ..obs import metrics as obs_metrics
from ..obs import recorder as obs_recorder
from ..utils.env import env_cast, env_flag
from ..utils.locks import OrderedLock
from ..utils.log import get_logger

log = get_logger(__name__)

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half-open"

M_OPENED = obs_metrics.counter(
    "head_circuit_open_total", "breaker transitions to OPEN")
M_REJECTED = obs_metrics.counter(
    "head_circuit_rejected_total",
    "batch sends short-circuited by an OPEN breaker")
M_CLOSED = obs_metrics.counter(
    "head_circuit_closed_total", "breakers re-CLOSED after a good trial")
M_PROBE_HALF_OPEN = obs_metrics.counter(
    "head_circuit_half_open_total",
    "OPEN->HALF_OPEN transitions (probe success or cooldown lapse)")
G_OPEN = obs_metrics.gauge(
    "head_circuits_open", "breakers currently OPEN or HALF_OPEN")
M_FAILOVER = obs_metrics.counter(
    "failover_total",
    "batches re-routed from a dead/failed primary to a live replica "
    "(head campaign path and serving frontend both book here)")


class CircuitBreaker:
    """One worker's breaker (thread-safe; ``fan_out`` drives it from a
    pool thread while the probe loop half-opens it from another)."""

    def __init__(self, key, threshold: int = 3, cooldown_s: float = 5.0,
                 clock=time.monotonic):
        self.key = key
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self.clock = clock
        self.state = CLOSED
        self.consecutive_failures = 0
        self.opened_at = 0.0
        self._trial_in_flight = False
        #: control-plane quarantine pin: while True the breaker is held
        #: OPEN and nothing in the normal state machine — cooldown
        #: lapse, probe half-open, a lucky good record() — can heal it.
        #: Only :meth:`release` (the daemon's re-admission decision,
        #: after N clean probes) clears it.
        self.pinned = False
        self._lock = OrderedLock("resilience.CircuitBreaker")

    def allow(self) -> bool:
        """May the caller send a batch to this worker right now?"""
        # the transition event fires in the finally, AFTER the lock is
        # released: the recorder bus takes its own lock and must never
        # nest inside the breaker's
        ev = None
        try:
            with self._lock:
                if self.pinned:
                    M_REJECTED.inc()
                    return False
                if self.state == CLOSED:
                    return True
                if self.state == OPEN:
                    # cooldown fallback: without a probe loop the breaker
                    # still half-opens on its own after cooldown_s
                    if self.clock() - self.opened_at >= self.cooldown_s:
                        self._to_half_open_locked("cooldown")
                        ev = ("breaker_half_open", "cooldown")
                    else:
                        M_REJECTED.inc()
                        return False
                # HALF_OPEN: exactly one trial at a time
                if self._trial_in_flight:
                    M_REJECTED.inc()
                    return False
                self._trial_in_flight = True
                return True
        finally:
            if ev is not None:
                obs_recorder.emit(ev[0], key=str(self.key), why=ev[1])

    def record(self, ok: bool) -> None:
        ev = None
        try:
            with self._lock:
                trial = self._trial_in_flight
                self._trial_in_flight = False
                if self.pinned:
                    # outcomes recorded while quarantined must not heal
                    # (or further trip) the pinned state machine
                    return
                if ok:
                    self.consecutive_failures = 0
                    if self.state != CLOSED:
                        log.info("circuit for %s CLOSED (good %s)",
                                 self.key, "trial" if trial else "send")
                        self.state = CLOSED
                        M_CLOSED.inc()
                        G_OPEN.add(-1)
                        ev = ("breaker_close",
                              "trial" if trial else "send")
                    return
                self.consecutive_failures += 1
                if self.state == HALF_OPEN:
                    log.warning("circuit for %s trial failed; re-OPEN",
                                self.key)
                    self.state = OPEN
                    self.opened_at = self.clock()
                    M_OPENED.inc()
                    ev = ("breaker_open", "trial failed")
                elif (self.state == CLOSED
                      and self.consecutive_failures >= self.threshold):
                    log.error("circuit for %s OPEN after %d consecutive "
                              "failures", self.key,
                              self.consecutive_failures)
                    self.state = OPEN
                    self.opened_at = self.clock()
                    M_OPENED.inc()
                    G_OPEN.add(1)
                    ev = (
                        "breaker_open",
                        f"{self.consecutive_failures} consecutive "
                        f"failures")
        finally:
            if ev is not None:
                obs_recorder.emit(ev[0], key=str(self.key), why=ev[1])

    def would_allow(self) -> bool:
        """Read-only: could a send plausibly be admitted right now?
        Unlike :meth:`allow` this neither consumes the half-open trial
        slot nor books a rejection — the replicated frontend uses it to
        pick admission/hedge targets without disturbing the breaker's
        state machine."""
        with self._lock:
            if self.pinned:
                return False
            if self.state == OPEN:
                return self.clock() - self.opened_at >= self.cooldown_s
            return True

    def half_open(self, why: str = "probe") -> None:
        fired = False
        with self._lock:
            if self.state == OPEN and not self.pinned:
                self._to_half_open_locked(why)
                fired = True
        if fired:    # outside the breaker lock, like every transition
            obs_recorder.emit("breaker_half_open", key=str(self.key),
                              why=why)

    def _to_half_open_locked(self, why: str) -> None:
        log.info("circuit for %s HALF_OPEN (%s)", self.key, why)
        self.state = HALF_OPEN
        self._trial_in_flight = False
        M_PROBE_HALF_OPEN.inc()

    # ------------------------------------------------ control-plane pin
    def force_open(self, why: str = "quarantine") -> None:
        """Pin the breaker OPEN (sick-worker quarantine). Idempotent."""
        ev = None
        with self._lock:
            if self.pinned:
                return
            self.pinned = True
            if self.state == CLOSED:
                G_OPEN.add(1)
            if self.state != OPEN:
                self.state = OPEN
                self.opened_at = self.clock()
                self._trial_in_flight = False
                M_OPENED.inc()
                ev = ("breaker_open", f"pinned: {why}")
        if ev is not None:
            obs_recorder.emit(ev[0], key=str(self.key), why=ev[1])
        log.warning("circuit for %s pinned OPEN (%s)", self.key, why)

    def release(self, close: bool = True,
                why: str = "quarantine cleared") -> None:
        """Unpin. ``close=True`` (the daemon's post-probation
        re-admission) CLOSEs outright; ``close=False`` hands the worker
        back to the normal OPEN machinery (cooldown/probe trial)."""
        ev = None
        with self._lock:
            if not self.pinned:
                return
            self.pinned = False
            if close and self.state != CLOSED:
                self.state = CLOSED
                self.consecutive_failures = 0
                self._trial_in_flight = False
                M_CLOSED.inc()
                G_OPEN.add(-1)
                ev = ("breaker_close", why)
        if ev is not None:
            obs_recorder.emit(ev[0], key=str(self.key), why=ev[1])
        log.info("circuit for %s unpinned (%s, close=%s)", self.key,
                 why, close)


class BreakerRegistry:
    """Per-worker breakers keyed by ``(host, wid)`` + the probe loops.

    ``probe_fn(key) -> HealthStatus | None`` is supplied by the campaign
    driver (it knows the nfs dir and FIFO layout); when present, every
    OPEN transition starts one short-lived ``dos-probe-*`` daemon thread
    that pings on the cooldown cadence until the worker answers healthy
    (→ HALF_OPEN) or the registry shuts down.
    """

    def __init__(self, threshold: int | None = None,
                 cooldown_s: float | None = None,
                 probe_fn=None, enabled: bool | None = None,
                 clock=time.monotonic):
        self.threshold = (threshold if threshold is not None
                          else env_cast("DOS_CIRCUIT_THRESHOLD", 3, int))
        self.cooldown_s = (cooldown_s if cooldown_s is not None
                           else env_cast("DOS_CIRCUIT_COOLDOWN_S", 5.0,
                                         float))
        self.enabled = (enabled if enabled is not None
                        else not env_flag("DOS_CIRCUIT_DISABLE", False))
        self.probe_fn = probe_fn
        self.clock = clock
        self._breakers: dict = {}
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        self._lock = OrderedLock("resilience.BreakerRegistry")

    def get(self, key) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(key)
            if br is None:
                br = CircuitBreaker(key, threshold=self.threshold,
                                    cooldown_s=self.cooldown_s,
                                    clock=self.clock)
                self._breakers[key] = br
            return br

    def allow(self, key) -> bool:
        return self.get(key).allow() if self.enabled else True

    def available(self, key) -> bool:
        """Read-only :meth:`CircuitBreaker.would_allow` (no breaker is
        created for an unseen key — unseen means healthy)."""
        if not self.enabled:
            return True
        with self._lock:
            br = self._breakers.get(key)
        return br is None or br.would_allow()

    def record(self, key, ok: bool) -> None:
        if not self.enabled:
            return
        br = self.get(key)
        was_open = br.state
        br.record(ok)
        if br.state == OPEN and was_open != OPEN:
            self._start_probe(br)

    def force_open(self, key, why: str = "quarantine") -> bool:
        """Control-plane quarantine: pin ``key``'s breaker OPEN. Returns
        False (no-op) when breakers are disabled."""
        if not self.enabled:
            return False
        self.get(key).force_open(why)
        return True

    def release(self, key, close: bool = True,
                why: str = "quarantine cleared") -> None:
        if not self.enabled:
            return
        with self._lock:
            br = self._breakers.get(key)
        if br is not None:
            br.release(close=close, why=why)

    # ------------------------------------------------------ probe loops
    def _start_probe(self, br: CircuitBreaker) -> None:
        if self.probe_fn is None or self._stop.is_set():
            return

        def loop():
            while not self._stop.wait(self.cooldown_s):
                if br.state != OPEN:
                    return
                try:
                    st = self.probe_fn(br.key)
                except Exception as e:  # noqa: BLE001 — a probe bug
                    # must not kill the loop that heals the breaker
                    log.warning("probe of %s raised: %s", br.key, e)
                    st = None
                if st is not None and getattr(st, "ok", False):
                    br.half_open("probe")
                    return

        t = threading.Thread(target=loop, daemon=True,
                             name=f"dos-probe-{br.key}")
        with self._lock:
            self._threads.append(t)
        t.start()

    def shutdown(self, join_s: float = 5.0) -> None:
        """Stop probe loops and join their threads (campaign end)."""
        self._stop.set()
        with self._lock:
            threads, self._threads = self._threads, []
        for t in threads:
            t.join(timeout=join_s)

    def snapshot(self) -> dict:
        """State of every breaker (for ``degraded.json`` and logs)."""
        with self._lock:
            return {repr(k): {"state": b.state,
                              "pinned": b.pinned,
                              "consecutive_failures":
                                  b.consecutive_failures}
                    for k, b in self._breakers.items()}

    def statusz(self) -> dict:
        """The ``/statusz`` section (``obs.http``): breaker states plus
        the registry's knobs and the open count — "which breaker is
        open" answered by a live scrape instead of a post-mortem
        ``degraded.json``."""
        breakers = self.snapshot()
        return {
            "enabled": self.enabled,
            "threshold": self.threshold,
            "cooldown_s": self.cooldown_s,
            "open": sum(1 for b in breakers.values()
                        if b["state"] != CLOSED),
            "breakers": breakers,
        }


def send_failover(candidates, send_fn, registry=None):
    """Walk a shard's replica chain until one worker answers.

    ``candidates`` is the failover order (primary first) of breaker
    keys; ``send_fn(key)`` attempts one candidate and returns an object
    with an ``ok`` attribute (a :class:`~.wire.StatsRow` on the
    campaign path). A candidate whose breaker is OPEN is skipped
    without a send — the short-circuit that makes a dead primary cost
    nothing per batch — and every attempted candidate's outcome is
    recorded on its own breaker, so replica health is tracked
    independently of primary health.

    Any dispatch to a non-primary candidate books ``failover_total``
    once per batch. Returns ``(row, served_key, reasons)``: ``row`` is
    the last attempt's result (or None when every candidate was
    short-circuited), ``served_key`` the candidate that answered OK (or
    None), and ``reasons`` the per-candidate failure list
    ``[(key, "circuit-open" | "send-failed"), ...]``.
    """
    reasons: list = []
    row = None
    failed_over = False
    for key in candidates:
        if registry is not None and not registry.allow(key):
            reasons.append((key, "circuit-open"))
            continue
        if reasons and not failed_over:
            # first dispatch off the primary: this batch failed over
            failed_over = True
            M_FAILOVER.inc()
        row = send_fn(key)
        if registry is not None:
            registry.record(key, row.ok)
        if row.ok:
            return row, key, reasons
        reasons.append((key, "send-failed"))
    return row, None, reasons
