"""Head↔worker data plane: wire formats, the FIFO/NFS campaign
transport, the streaming RPC transport (:mod:`.frames` length-prefixed
zero-copy frames over :mod:`.rpc` persistent multiplexed sockets,
``DOS_TRANSPORT={fifo,rpc,auto}``), job launch, liveness probes, and
head-side resilience (retry + circuit breaking)."""

from .wire import (
    ENGINE_STAT_FIELDS, HEAD_STAT_FIELDS, STATS_HEADER,
    HealthStatus, Request, RuntimeConfig, StatsRow,
    read_query_file, write_query_file,
)
from .fifo import (
    RetryPolicy, answer_fifo_path, clean_stale_answer_fifos,
    command_fifo_path, fan_out, probe, send, send_with_retry,
)
from .launch import kill_session, launch, session_name
from .resilience import BreakerRegistry, CircuitBreaker

__all__ = [
    "ENGINE_STAT_FIELDS", "HEAD_STAT_FIELDS", "STATS_HEADER",
    "HealthStatus", "Request", "RuntimeConfig", "StatsRow",
    "read_query_file", "write_query_file",
    "RetryPolicy", "answer_fifo_path", "clean_stale_answer_fifos",
    "command_fifo_path", "fan_out", "probe", "send", "send_with_retry",
    "kill_session", "launch", "session_name",
    "BreakerRegistry", "CircuitBreaker",
]
