"""Head↔worker data plane: wire formats, FIFO transport, job launch."""

from .wire import (
    ENGINE_STAT_FIELDS, HEAD_STAT_FIELDS, STATS_HEADER,
    Request, RuntimeConfig, StatsRow,
    read_query_file, write_query_file,
)
from .fifo import (
    answer_fifo_path, command_fifo_path, fan_out, send, send_with_retry,
)
from .launch import kill_session, launch, session_name

__all__ = [
    "ENGINE_STAT_FIELDS", "HEAD_STAT_FIELDS", "STATS_HEADER",
    "Request", "RuntimeConfig", "StatsRow",
    "read_query_file", "write_query_file",
    "answer_fifo_path", "command_fifo_path", "fan_out", "send",
    "send_with_retry", "kill_session", "launch", "session_name",
]
