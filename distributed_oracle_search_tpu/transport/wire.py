"""Wire formats of the head↔worker data plane.

The reference's de-facto RPC schema (reference ``process_query.py:66-111``)
is preserved exactly so artifacts and tooling stay interchangeable:

* **request** — two text lines pushed through a worker's command FIFO:
  line 1 = JSON runtime config (``hscale, fscale, time, itrs, k_moves,
  threads, verbose, debug, thread_alloc, no_cache`` —
  reference ``process_query.py:149-160``); line 2 =
  ``<queryfile> <answerfifo> <difffile>`` (reference ``process_query.py:89``).
* **query file** — first line = count, then one ``s t`` pair per line
  (reference ``process_query.py:93-96``).
* **response** — ONE CSV line of batch stats, field order fixed by the
  header at reference ``process_query.py:198-213``:
  ``n_expanded, n_inserted, n_touched, n_updated, n_surplus, plen,
  finished, t_receive, t_astar, t_search``; the head appends
  ``t_prepare, t_partition, size``.

Everything here is pure encode/decode — no IO beyond the query-file
helpers — so both the Python/JAX worker and the C++ engine can speak it.
"""

from __future__ import annotations

import dataclasses
import io
import json

import numpy as np

from ..utils.atomicio import atomic_replace_bytes

#: engine-side stats fields, in wire order
ENGINE_STAT_FIELDS = (
    "n_expanded", "n_inserted", "n_touched", "n_updated", "n_surplus",
    "plen", "finished", "t_receive", "t_astar", "t_search",
)
#: head-side appended fields
HEAD_STAT_FIELDS = ("t_prepare", "t_partition", "size")

#: answer-FIFO sentinel for an engine-side failure (a success row is a
#: 10-field CSV line and can never equal this)
FAIL_LINE = "FAIL"

#: answer-FIFO sentinel for the fleet-membership version gate: a worker
#: whose partition-table epoch is OLDER than the request's
#: ``RuntimeConfig.epoch`` (and that stayed older after refreshing its
#: membership state) refuses the batch with this line instead of
#: serving rows it may no longer own. New heads read it as a failed row
#: with ``stale_epoch`` set (failover walks on to the next candidate);
#: old heads see an undecodable line and book the same failed row —
#: the sentinel only ever appears when a NEW head stamped a nonzero
#: epoch, so legacy deployments never meet it.
STALE_EPOCH_LINE = "STALE_EPOCH"

#: answer-FIFO sentinel for the live-traffic version gate: a worker
#: whose DIFF epoch is OLDER than the request's
#: ``RuntimeConfig.diff_epoch`` (and that stayed older after refreshing
#: its segment stream) refuses the batch rather than read a fused diff
#: file its filesystem view may not have yet. Same compat shape as
#: ``STALE_EPOCH``: new heads read a failed row with ``stale_diff``
#: set; the sentinel only appears when a new head stamped a nonzero
#: diff epoch, so legacy deployments never meet it.
STALE_DIFF_LINE = "STALE_DIFF"

#: liveness control frame: ``__DOS_PING__ <answerfifo>`` as a single
#: command-FIFO line asks the server to write one health JSON line
#: (:class:`HealthStatus`) to the named FIFO — the wire half of
#: ``transport.fifo.probe`` and the supervisor's monitoring loop
PING_TOKEN = "__DOS_PING__"

#: full per-row CSV header (reference ``process_query.py:198-213`` plus the
#: leading experiment index the print path shows)
STATS_HEADER = ["expe", *ENGINE_STAT_FIELDS, *HEAD_STAT_FIELDS]


@dataclasses.dataclass
class RuntimeConfig:
    """Per-batch engine knobs (wire line 1).

    ``extract`` is a wire extension beyond the reference's key set: with
    ``k_moves > 0`` it asks the engine to materialize each query's first
    ``k_moves`` path nodes into ``<queryfile>.paths`` next to the query
    file (the reference's ``--k-moves`` "number of moves to extract",
    reference ``args.py:31-36``, never shipped the nodes anywhere; here
    they ride the shared dir, keeping the stats CSV wire unchanged).
    Servers that predate the key ignore it (``from_json`` filters unknown
    keys symmetrically).

    ``trace_id`` is the observability wire extension (``obs.trace``): a
    non-empty id asks the server to capture its spans for this batch and
    materialize them as ``<queryfile>.trace`` for the head to merge —
    the head's and worker's halves of one batch join on this id. Same
    compat contract as ``extract``: old peers filter the unknown key,
    and ``""`` (the default) disables capture.

    ``epoch`` is the elastic-membership wire extension
    (``parallel.membership``): the head stamps the partition-table
    epoch its routing decisions were made under. A worker at a NEWER
    epoch serves the batch anyway (older routing is a superset the
    worker can still answer during the migration window); a worker at
    an OLDER epoch refreshes its membership state and, if still older,
    refuses with the ``STALE_EPOCH`` sentinel so the head fails over —
    the version-gate contract of the other codecs (tolerate older,
    gate only on newer) applied to routing state. ``0`` (the default)
    is the pre-elastic world and never gates.

    ``results`` is the online-serving wire extension (``serving``): the
    reference's campaign wire only ever returns aggregate batch stats —
    per-query costs stay on the workers. A serving frontend needs them
    back, so ``results=True`` asks the server to materialize each
    query's ``cost plen finished`` into ``<queryfile>.results`` next to
    the query file (the ``.paths`` sidecar pattern; stats CSV wire
    unchanged). Same compat contract as ``extract``/``trace_id``.

    ``diff_epoch`` is the live-traffic wire extension (``traffic``):
    the head stamps the DIFF epoch the batch's ``difffile`` was fused
    at, exactly parallel to the membership ``epoch`` — a worker at a
    NEWER diff epoch serves anyway (older fused files stay readable in
    the spool window), a worker at an OLDER one refreshes its segment
    stream and, if still older, refuses with the ``STALE_DIFF``
    sentinel so the head fails over instead of the worker failing an
    open() on a fused file its NFS view has not seen yet. ``0`` is the
    static-diff world and never gates.

    ``sig_k`` asks the engine for a bounded **path signature** next to
    the answers: the first ``sig_k`` path nodes of each query,
    materialized through the existing ``.paths`` sidecar — WITHOUT
    touching the walk semantics (``k_moves`` still governs the move
    budget; ``sig_k`` only adds the cheap extraction scan). The serving
    cache keys scoped invalidation off these signatures. Same compat
    contract: old servers filter the unknown key and simply ship no
    sidecar, and the cache degrades to conservative (signature-less)
    invalidation.

    ``answer_fp`` is the answer-integrity wire extension
    (``integrity``): the server fingerprints the reply's answer
    segments (crc32, :mod:`integrity.fingerprint`) right after the
    engine returns and ships the checksum with the answers — an extra
    ``fp=<hex>`` token on the results-file header line (old readers
    take ``int(header[0])`` and tolerate extra tokens) or an ``fp``
    key on the RPC reply header. The dispatcher re-checks before
    trusting the payload; a mismatch is a dispatch error (failover),
    never a served answer. Same compat contract: old servers filter
    the unknown key and ship no fingerprint, and verification simply
    does not happen for that hop.
    """

    hscale: float = 1.0
    fscale: float = 0.0
    time: int = 0            # ns budget; 0 = unlimited
    itrs: int = 1
    k_moves: int = -1
    threads: int = 0         # 0 = all
    verbose: int = 0
    debug: bool = False
    thread_alloc: int = 0
    no_cache: bool = False
    extract: bool = False
    trace_id: str = ""
    results: bool = False
    epoch: int = 0
    diff_epoch: int = 0
    sig_k: int = 0
    answer_fp: bool = False

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @classmethod
    def from_json(cls, line: str) -> "RuntimeConfig":
        d = json.loads(line)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


@dataclasses.dataclass
class Request:
    """A full 2-line command-FIFO request."""

    config: RuntimeConfig
    queryfile: str
    answerfifo: str
    difffile: str = "-"

    def encode(self) -> str:
        return (self.config.to_json() + "\n"
                + f"{self.queryfile} {self.answerfifo} {self.difffile}\n")

    @classmethod
    def decode(cls, text: str) -> "Request":
        lines = text.strip("\n").split("\n")
        if len(lines) < 2:
            raise ValueError(f"request needs 2 lines, got {len(lines)}")
        qf, af, df = lines[1].split()
        return cls(RuntimeConfig.from_json(lines[0]), qf, af, df)


@dataclasses.dataclass
class StatsRow:
    """One batch's engine stats (wire CSV line)."""

    n_expanded: int = 0
    n_inserted: int = 0
    n_touched: int = 0
    n_updated: int = 0
    n_surplus: int = 0
    plen: int = 0
    finished: int = 0
    t_receive: float = 0.0
    t_astar: float = 0.0
    t_search: float = 0.0
    ok: bool = True          # head-side: False marks a failed worker batch
    #: head-side: the worker refused the batch because its partition
    #: table is OLDER than the request's epoch (the ``STALE_EPOCH``
    #: wire sentinel) — a routing-state failure, not an engine one
    stale_epoch: bool = False
    #: head-side: the worker refused the batch because its DIFF epoch
    #: is OLDER than the request's ``diff_epoch`` (the ``STALE_DIFF``
    #: wire sentinel) — the traffic-plane twin of ``stale_epoch``
    stale_diff: bool = False

    def encode(self) -> str:
        vals = [getattr(self, f) for f in ENGINE_STAT_FIELDS]
        return ",".join(repr(v) if isinstance(v, float) else str(v)
                        for v in vals)

    @classmethod
    def decode(cls, line: str) -> "StatsRow":
        if line.strip() == FAIL_LINE:
            return cls.failed()
        if line.strip().startswith(STALE_EPOCH_LINE):
            # "STALE_EPOCH [<worker epoch>]": a failed row flagged so
            # the head can tell a routing-state refusal from an engine
            # crash (failover treats both the same; operators do not)
            return cls(ok=False, stale_epoch=True)
        if line.strip().startswith(STALE_DIFF_LINE):
            return cls(ok=False, stale_diff=True)
        parts = line.strip().split(",")
        if len(parts) != len(ENGINE_STAT_FIELDS):
            raise ValueError(
                f"stats row has {len(parts)} fields, "
                f"want {len(ENGINE_STAT_FIELDS)}: {line!r}")
        kwargs = {}
        for name, raw in zip(ENGINE_STAT_FIELDS, parts):
            kwargs[name] = float(raw) if name.startswith("t_") else int(
                float(raw))
        return cls(**kwargs)

    @classmethod
    def failed(cls) -> "StatsRow":
        """Explicit failure marker (vs the reference's garbage-row behavior,
        reference ``process_query.py:107-109``)."""
        return cls(ok=False)

    def encode_wire(self) -> str:
        """Wire line including the failure marker: failed rows encode as the
        ``FAIL`` sentinel so the head can tell them from an all-zero batch
        (success rows keep the reference's 10-field CSV exactly;
        stale-epoch refusals carry their own sentinel)."""
        if self.stale_epoch:
            return STALE_EPOCH_LINE
        if self.stale_diff:
            return STALE_DIFF_LINE
        return FAIL_LINE if not self.ok else self.encode()

    def as_list(self, t_prepare: float = 0.0, t_partition: float = 0.0,
                size: int = 0) -> list:
        """Full head-side row (engine fields + appended head fields)."""
        return ([getattr(self, f) for f in ENGINE_STAT_FIELDS]
                + [t_prepare, t_partition, size])


@dataclasses.dataclass
class HealthStatus:
    """One server's answer to a ``__DOS_PING__`` control frame.

    Same compat contract as :class:`RuntimeConfig`: ``from_json`` filters
    unknown keys symmetrically, so old heads can probe new servers and
    vice versa. ``dropped``/``batch_failures`` mirror the server's obs
    counters so a head-side probe can read a remote worker's failure
    counters without a metrics endpoint."""

    ok: bool = True
    wid: int = -1
    pid: int = 0
    uptime_s: float = 0.0
    batches: int = 0            # requests answered since start
    batch_failures: int = 0     # batches answered with FAIL
    dropped: int = 0            # replies dropped (no reader)
    last_error: str = ""

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self))

    @classmethod
    def from_json(cls, line: str) -> "HealthStatus":
        d = json.loads(line)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


# ------------------------------------------------------------ paths files

def paths_file_for(queryfile: str) -> str:
    """Where a server materializes extracted path prefixes for a batch."""
    return queryfile + ".paths"


def write_paths_file(path: str, nodes: np.ndarray, plen: np.ndarray) -> None:
    """``Q k`` header, then per query: ``<moves taken> n0 n1 ... nk``
    (node ids; after the path ends the last node repeats — the layout of
    ``ops.extract_paths``)."""
    nodes = np.asarray(nodes)
    plen = np.asarray(plen).reshape(-1, 1)
    buf = io.BytesIO()
    buf.write(f"{nodes.shape[0]} {nodes.shape[1] - 1}\n".encode())
    np.savetxt(buf, np.concatenate([plen, nodes], axis=1), fmt="%d")
    atomic_replace_bytes(path, buf.getvalue())


def read_paths_file(path: str) -> tuple[np.ndarray, np.ndarray]:
    """Returns ``(nodes [Q, k+1], plen [Q])``."""
    with open(path) as f:
        q, k = (int(x) for x in f.readline().split())
        if q == 0:
            return np.zeros((0, k + 1), np.int64), np.zeros(0, np.int64)
        out = np.loadtxt(f, dtype=np.int64, ndmin=2)
    if out.shape != (q, k + 2):
        raise ValueError(f"{path}: header says {(q, k + 2)}, "
                         f"found {out.shape}")
    return out[:, 1:], out[:, 0]


# ---------------------------------------------------------- results files

def results_file_for(queryfile: str) -> str:
    """Where a server materializes per-query answers for a batch when the
    request set ``RuntimeConfig.results`` (online-serving wire
    extension)."""
    return queryfile + ".results"


def write_results_file(path: str, cost: np.ndarray, plen: np.ndarray,
                       finished: np.ndarray,
                       fp: int | None = None) -> None:
    """``Q`` header, then one ``cost plen finished`` row per query, in
    the query file's order.

    ``fp`` (the ``RuntimeConfig.answer_fp`` extension) rides the header
    line as an extra ``fp=<hex8>`` token — old readers take
    ``int(header[0])`` and ignore trailing tokens, so a fingerprinting
    server stays readable by a pre-integrity head."""
    cost = np.asarray(cost, np.int64)
    plen = np.asarray(plen, np.int64)
    fin = np.asarray(finished).astype(np.int64)
    buf = io.BytesIO()
    header = f"{len(cost)}"
    if fp is not None:
        header += f" fp={int(fp) & 0xFFFFFFFF:08x}"
    buf.write((header + "\n").encode())
    np.savetxt(buf, np.stack([cost, plen, fin], axis=1), fmt="%d")
    atomic_replace_bytes(path, buf.getvalue())


def read_results_file(path: str) -> tuple[np.ndarray, np.ndarray,
                                          np.ndarray]:
    """Returns ``(cost [Q] int64, plen [Q] int64, finished [Q] bool)``.

    When the header carries an ``fp=`` fingerprint token the answer
    bytes are re-checked before being returned; a mismatch raises
    :class:`~..integrity.fingerprint.FingerprintError` (a ``ValueError``
    subclass, so pre-integrity decode-error handlers still fail over)
    and books ``answer_fp_mismatch_total`` — a corrupted sidecar is
    never handed up."""
    with open(path) as f:
        header = f.readline().split()
        if not header:
            # a worker killed between creating the sidecar and writing
            # the header leaves a zero-byte file — a decode error the
            # dispatcher translates, not an opaque IndexError
            raise ValueError(f"{path}: empty results file")
        count = int(header[0])
        fp_want = None
        for tok in header[1:]:
            if tok.startswith("fp="):
                fp_want = int(tok[3:], 16)
        if count == 0:
            out = np.zeros((0, 3), np.int64)
        else:
            out = np.loadtxt(f, dtype=np.int64, ndmin=2)
    if out.shape != (count, 3):
        raise ValueError(f"{path}: header says {(count, 3)}, "
                         f"found {out.shape}")
    cost, plen, fin = out[:, 0], out[:, 1], out[:, 2] != 0
    if fp_want is not None:
        # lazy import: legacy (fingerprint-less) decode stays free of
        # the integrity package entirely
        from ..integrity.fingerprint import (
            FingerprintError, M_FP_MISMATCH, answer_fingerprint)
        got = answer_fingerprint(cost, plen, fin)
        if got != fp_want:
            M_FP_MISMATCH.inc()
            raise FingerprintError(
                f"{path}: answer fingerprint mismatch (header "
                f"{fp_want:08x}, computed {got:08x}) — corrupted "
                "results sidecar")
    return cost, plen, fin


# ----------------------------------------------------------- query files

def write_query_file(path: str, queries: np.ndarray) -> None:
    """count line, then ``s t`` per line (reference process_query.py:93-96)."""
    queries = np.asarray(queries)
    buf = io.BytesIO()
    buf.write(f"{len(queries)}\n".encode())
    np.savetxt(buf, queries, fmt="%d")
    atomic_replace_bytes(path, buf.getvalue())


def read_query_file(path: str) -> np.ndarray:
    with open(path) as f:
        count = int(f.readline().split()[0])
        if count == 0:
            return np.zeros((0, 2), np.int64)
        out = np.loadtxt(f, dtype=np.int64, ndmin=2)
    if len(out) != count:
        raise ValueError(f"{path}: header says {count} queries, "
                         f"found {len(out)}")
    return out.reshape(count, 2) if count else np.zeros((0, 2), np.int64)
