"""Head-side transport: push a request to a worker, read its answer.

Mechanism parity with the reference (``process_query.py:66-111``,
``offline.py:70-125``): the head generates a small bash script —

    mkfifo <answer>
    cat > /tmp/worker<wid>.fifo <<EOF
    <2-line request>
    EOF
    cat <answer>
    rm <answer>

— and pipes it through ``ssh <host> 'bash -s'``. The blocking FIFO opens are
the rendezvous: the script blocks until the resident worker reads the
command, and ``cat <answer>`` blocks until the worker writes its one CSV
stats line.

Improvements over the reference (SURVEY.md §2.1 quirks):

* **real local path everywhere** — ``localhost``/``127.0.0.1`` runs the same
  script via a local ``bash -s`` subprocess, no ssh round-trip (the reference
  only had this in the legacy ``offline.py`` driver);
* **explicit failure** — a dead worker yields ``StatsRow.failed()`` (and an
  optional retry), not a garbage row silently entering the CSV
  (reference ``process_query.py:107-109``);
* timeouts on every blocking step;
* **per-attempt answer FIFOs** — each retry attempt reads a uniquely named
  FIFO (``<answer>.a<attempt>``), so a late reply from a timed-out attempt
  can never satisfy (or corrupt) the retry — the worker replies to the
  FIFO named in the request it actually read;
* **liveness probes** — :func:`probe` pushes a ``__DOS_PING__`` control
  frame and returns the server's :class:`~.wire.HealthStatus` line.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import random
import subprocess
import time
import zlib
from multiprocessing.dummy import Pool

from .launch import LOCAL_HOSTS
from .wire import HealthStatus, PING_TOKEN, Request, StatsRow
from ..obs import metrics as obs_metrics
from ..testing import faults
from ..utils.env import env_cast
from ..utils.log import get_logger

log = get_logger(__name__)

#: default transport timeout: generous enough for a cold-compile first
#: batch over a slow link, finite so a dead worker cannot hang the campaign
DEFAULT_TIMEOUT = 600.0

M_RETRIES = obs_metrics.counter(
    "head_retries_total", "batch send attempts beyond the first")
M_STALE_CLEANED = obs_metrics.counter(
    "head_stale_fifos_cleaned_total",
    "leftover answer FIFOs removed at campaign start")
M_PROBES = obs_metrics.counter(
    "head_probes_total", "liveness pings sent to workers")
M_PROBE_FAILURES = obs_metrics.counter(
    "head_probe_failures_total", "liveness pings that got no health line")


def command_fifo_path(wid: int) -> str:
    """Per-worker command FIFO (reference ``make_fifos.py`` convention)."""
    return f"/tmp/worker{wid}.fifo"


def answer_fifo_path(nfs: str, host: str, wid: int) -> str:
    return f"{nfs.rstrip('/')}/answer.{host}{wid}"


def clean_stale_answer_fifos(nfs: str) -> int:
    """Remove leftover ``answer.*`` FIFOs in the shared dir.

    A killed transfer script never reaches its ``rm -f``, so crashed runs
    accumulate stale answer FIFOs; campaigns call this once at start.
    Only FIFOs are touched — regular files matching the glob are not
    ours, and ``answer.ping.*`` probe FIFOs are skipped: a supervisor
    pinging through the same nfs dir may have one in flight right now.
    """
    import glob as _glob
    import stat as _stat

    n = 0
    for p in _glob.glob(os.path.join(nfs, "answer.*")):
        if os.path.basename(p).startswith("answer.ping."):
            continue
        try:
            if _stat.S_ISFIFO(os.stat(p).st_mode):
                os.remove(p)
                n += 1
        except OSError:
            continue
    if n:
        log.info("cleaned %d stale answer FIFO(s) in %s", n, nfs)
        M_STALE_CLEANED.inc(n)
    return n


def clean_stale_epoch_files(nfs: str,
                            min_age_s: float | None = None) -> int:
    """Remove epoch-suffixed ``query.*``/``answer.*`` wire files
    (names carrying ``.e<epoch>`` — the dual-read migration window's
    re-routed batch names) left behind by an aborted or crashed
    reconfiguration: unlike a normal batch, a window torn down
    mid-dispatch has no surviving owner to sweep its files on the next
    round. Age-gated like the artifact sweep — a young file may be a
    LIVE dual-read batch of a concurrent campaign — and counted by
    ``artifacts_swept_total`` (these are artifact debris, not FIFOs in
    rendezvous; stale epoch-suffixed answer FIFOs are removed too)."""
    import glob as _glob
    import re as _re

    from ..utils.atomicio import M_SWEPT, SWEEP_MIN_AGE_S

    if min_age_s is None:
        min_age_s = SWEEP_MIN_AGE_S
    pat = _re.compile(r"\.e\d+(\.|$)")
    now = time.time()
    n = 0
    for stem in ("query.*", "answer.*"):
        for p in _glob.glob(os.path.join(nfs, stem)):
            if not pat.search(os.path.basename(p)):
                continue
            try:
                if now - os.path.getmtime(p) >= min_age_s:
                    os.remove(p)
                    n += 1
            except OSError:
                continue
    if n:
        log.info("swept %d stale epoch-suffixed wire file(s) in %s",
                 n, nfs)
        M_SWEPT.inc(n)
    return n


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Retry with capped exponential backoff + deterministic jitter.

    Env knobs (``from_env``): ``DOS_RETRY_MAX`` (attempts beyond the
    first, default 1), ``DOS_RETRY_BASE_S`` (first backoff, default 0.2),
    ``DOS_RETRY_CAP_S`` (backoff ceiling, default 5), ``DOS_RETRY_JITTER``
    (fractional spread, default 0.5). Jitter is seeded from the answer
    FIFO path (crc32, not ``hash`` — ``PYTHONHASHSEED`` randomizes that),
    so a rerun backs off identically: campaigns stay reproducible."""

    retries: int = 1
    base_s: float = 0.2
    cap_s: float = 5.0
    jitter: float = 0.5

    @classmethod
    def from_env(cls) -> "RetryPolicy":
        return cls(
            retries=env_cast("DOS_RETRY_MAX", cls.retries, int),
            base_s=env_cast("DOS_RETRY_BASE_S", cls.base_s, float),
            cap_s=env_cast("DOS_RETRY_CAP_S", cls.cap_s, float),
            jitter=env_cast("DOS_RETRY_JITTER", cls.jitter, float),
        )

    def backoff_s(self, attempt: int, seed: str = "") -> float:
        """Delay before retry ``attempt`` (0-based: the first retry)."""
        raw = min(self.cap_s, self.base_s * (2 ** attempt))
        if not self.jitter or raw <= 0:
            return max(raw, 0.0)
        rnd = random.Random(zlib.crc32(f"{seed}:{attempt}".encode()))
        return raw * (1.0 + self.jitter * (2 * rnd.random() - 1.0))


def make_script(request: Request, command_fifo: str,
                corrupt: bool = False,
                answer_wait_s: float | None = None) -> str:
    """The transfer script run on the worker host (local or over ssh).

    Guards the command FIFO with ``[ -p ... ]``: if no server is resident,
    the reference's script shape would create a regular file and then block
    forever on the answer; we fail fast with a distinct exit code instead.

    ``answer_wait_s`` bounds the ``cat <answer>`` read itself: when the
    head's ssh/bash wrapper is killed on timeout, the orphaned ``cat``
    would otherwise hold the answer FIFO open forever on a dead worker.
    ``corrupt`` garbles the frame (the ``corrupt-frame`` fault point).
    """
    payload = request.encode()
    if corrupt:
        # breaks line 1's JSON shape: the server must count the frame
        # malformed and FAIL the answer FIFO instead of wedging the head
        payload = "CORRUPT " + payload
    fifo = request.answerfifo
    # never render `timeout 0` — GNU timeout treats 0 as "no timeout",
    # which would silently disarm the orphan-cat bound for sub-second
    # deadlines
    catcmd = (f"timeout {max(1, int(round(answer_wait_s)))} cat {fifo}"
              if answer_wait_s else f"cat {fifo}")
    return (
        f"[ -p {command_fifo} ] || "
        f"{{ echo 'no resident worker on {command_fifo}' >&2; exit 3; }}\n"
        f"mkfifo {fifo} 2>/dev/null || true\n"
        f"cat > {command_fifo} <<'__DOS_EOF__'\n"
        f"{payload}"
        f"__DOS_EOF__\n"
        f"{catcmd}\n"
        f"rm -f {fifo}\n"
    )


def _run_script(host: str, script: str,
                timeout: float | None) -> subprocess.CompletedProcess:
    if host in LOCAL_HOSTS:
        argv = ["bash", "-s"]
    else:
        argv = ["ssh", host, "bash -s"]
    return subprocess.run(argv, input=script, capture_output=True,
                          text=True, timeout=timeout)


def send(host: str, request: Request, command_fifo: str,
         timeout: float | None = DEFAULT_TIMEOUT,
         wid: int | None = None) -> StatsRow:
    """Run the transfer script on ``host`` and parse the stats line."""
    corrupt = faults.inject("corrupt-frame", wid=wid) is not None
    script = make_script(request, command_fifo, corrupt=corrupt,
                         answer_wait_s=timeout)
    proc = _run_script(host, script, timeout)
    if proc.returncode != 0:
        log.error("worker transfer on %s failed (rc=%d): %s",
                  host, proc.returncode, proc.stderr.strip())
        return StatsRow.failed()
    line = proc.stdout.strip().splitlines()
    if not line:
        log.error("worker on %s returned no stats line", host)
        return StatsRow.failed()
    try:
        return StatsRow.decode(line[-1])
    except ValueError as e:
        log.error("bad stats line from %s: %s", host, e)
        return StatsRow.failed()


def send_with_retry(host: str, request: Request, command_fifo: str,
                    timeout: float | None = DEFAULT_TIMEOUT,
                    retries: int | None = None,
                    policy: RetryPolicy | None = None,
                    wid: int | None = None) -> StatsRow:
    """``send`` with capped-exponential-backoff retries.

    Each attempt reads its own answer FIFO (``<base>.a<attempt>``): the
    worker replies to the FIFO named in the request it actually read, so
    a late reply from a timed-out attempt lands in that attempt's FIFO
    (draining into the orphaned, dying ``cat``) and can never satisfy or
    corrupt a newer attempt — the stale-reply race of a shared FIFO name.
    """
    policy = policy or RetryPolicy.from_env()
    if retries is not None:
        policy = dataclasses.replace(policy, retries=retries)
    base_fifo = request.answerfifo
    row = StatsRow.failed()
    for attempt in range(policy.retries + 1):
        if attempt:
            M_RETRIES.inc()
            delay = policy.backoff_s(attempt - 1, seed=base_fifo)
            log.warning("retrying worker on %s (attempt %d) in %.2fs",
                        host, attempt, delay)
            time.sleep(delay)
        req = dataclasses.replace(request,
                                  answerfifo=f"{base_fifo}.a{attempt}")
        try:
            row = send(host, req, command_fifo, timeout=timeout, wid=wid)
        except subprocess.TimeoutExpired:
            log.error("worker on %s timed out (attempt %d)", host, attempt)
            row = StatsRow.failed()
        if row.ok:
            return row
    return row


# ------------------------------------------------------------------ probing

_PROBE_SEQ = itertools.count()


def ping_script(command_fifo: str, answerfifo: str,
                wait_s: float) -> str:
    """Transfer script for one liveness probe: push the ping control
    frame, read one health line. Both blocking FIFO opens are bounded by
    ``timeout`` — a hard-crashed server leaves its command FIFO behind
    with no reader, and an unbounded ``> fifo`` open would wedge the
    probe exactly like the failure it is trying to detect."""
    w = max(1, int(wait_s))
    return (
        f"[ -p {command_fifo} ] || "
        f"{{ echo 'no resident worker on {command_fifo}' >&2; exit 3; }}\n"
        f"mkfifo {answerfifo} 2>/dev/null || true\n"
        f"timeout {w} bash -c 'printf \"%s\\n\" "
        f"\"{PING_TOKEN} {answerfifo}\" > {command_fifo}' || "
        f"{{ rm -f {answerfifo}; exit 4; }}\n"
        f"timeout {w} cat {answerfifo}\n"
        f"rc=$?\n"
        f"rm -f {answerfifo}\n"
        f"exit $rc\n"
    )


def probe(host: str, wid: int, command_fifo: str | None = None,
          nfs: str = "/tmp",
          timeout: float = 10.0) -> HealthStatus | None:
    """Ping the resident server for worker ``wid`` on ``host``.

    Returns its :class:`~.wire.HealthStatus`, or None when the worker is
    dead/unreachable (no FIFO, no reader, no reply within ``timeout``, or
    an undecodable health line). The answer FIFO name is unique per probe
    (pid + sequence), so concurrent probes never cross replies.
    """
    command_fifo = command_fifo or command_fifo_path(wid)
    answer = (f"{nfs.rstrip('/')}/answer.ping.{host}{wid}"
              f".{os.getpid()}.{next(_PROBE_SEQ)}")
    M_PROBES.inc()
    script = ping_script(command_fifo, answer, timeout)
    try:
        proc = _run_script(host, script, timeout + 5.0)
    except (subprocess.TimeoutExpired, OSError) as e:
        log.warning("probe of worker %d on %s errored: %s", wid, host, e)
        M_PROBE_FAILURES.inc()
        return None
    lines = proc.stdout.strip().splitlines()
    if proc.returncode != 0 or not lines:
        log.warning("probe of worker %d on %s failed (rc=%d): %s", wid,
                    host, proc.returncode, proc.stderr.strip())
        M_PROBE_FAILURES.inc()
        return None
    try:
        return HealthStatus.from_json(lines[-1])
    except (ValueError, TypeError) as e:
        log.warning("bad health line from worker %d on %s: %s", wid,
                    host, e)
        M_PROBE_FAILURES.inc()
        return None


def fan_out(jobs, fn, pool_size: int | None = None) -> list:
    """Drive all workers concurrently, one thread per worker (parity with the
    reference's ``multiprocessing.dummy.Pool``, ``process_query.py:180-185``).
    """
    if not jobs:
        return []
    with Pool(pool_size or len(jobs)) as pool:
        return pool.map(fn, jobs)
