"""Head-side transport: push a request to a worker, read its answer.

Mechanism parity with the reference (``process_query.py:66-111``,
``offline.py:70-125``): the head generates a small bash script —

    mkfifo <answer>
    cat > /tmp/worker<wid>.fifo <<EOF
    <2-line request>
    EOF
    cat <answer>
    rm <answer>

— and pipes it through ``ssh <host> 'bash -s'``. The blocking FIFO opens are
the rendezvous: the script blocks until the resident worker reads the
command, and ``cat <answer>`` blocks until the worker writes its one CSV
stats line.

Improvements over the reference (SURVEY.md §2.1 quirks):

* **real local path everywhere** — ``localhost``/``127.0.0.1`` runs the same
  script via a local ``bash -s`` subprocess, no ssh round-trip (the reference
  only had this in the legacy ``offline.py`` driver);
* **explicit failure** — a dead worker yields ``StatsRow.failed()`` (and an
  optional retry), not a garbage row silently entering the CSV
  (reference ``process_query.py:107-109``);
* timeouts on every blocking step.
"""

from __future__ import annotations

import subprocess
from multiprocessing.dummy import Pool

from .launch import LOCAL_HOSTS
from .wire import Request, StatsRow
from ..utils.log import get_logger

log = get_logger(__name__)

#: default transport timeout: generous enough for a cold-compile first
#: batch over a slow link, finite so a dead worker cannot hang the campaign
DEFAULT_TIMEOUT = 600.0


def command_fifo_path(wid: int) -> str:
    """Per-worker command FIFO (reference ``make_fifos.py`` convention)."""
    return f"/tmp/worker{wid}.fifo"


def answer_fifo_path(nfs: str, host: str, wid: int) -> str:
    return f"{nfs.rstrip('/')}/answer.{host}{wid}"


def make_script(request: Request, command_fifo: str) -> str:
    """The transfer script run on the worker host (local or over ssh).

    Guards the command FIFO with ``[ -p ... ]``: if no server is resident,
    the reference's script shape would create a regular file and then block
    forever on the answer; we fail fast with a distinct exit code instead.
    """
    payload = request.encode()
    fifo = request.answerfifo
    return (
        f"[ -p {command_fifo} ] || "
        f"{{ echo 'no resident worker on {command_fifo}' >&2; exit 3; }}\n"
        f"mkfifo {fifo} 2>/dev/null || true\n"
        f"cat > {command_fifo} <<'__DOS_EOF__'\n"
        f"{payload}"
        f"__DOS_EOF__\n"
        f"cat {fifo}\n"
        f"rm -f {fifo}\n"
    )


def send(host: str, request: Request, command_fifo: str,
         timeout: float | None = DEFAULT_TIMEOUT) -> StatsRow:
    """Run the transfer script on ``host`` and parse the stats line."""
    script = make_script(request, command_fifo)
    if host in LOCAL_HOSTS:
        argv = ["bash", "-s"]
    else:
        argv = ["ssh", host, "bash -s"]
    proc = subprocess.run(argv, input=script, capture_output=True,
                          text=True, timeout=timeout)
    if proc.returncode != 0:
        log.error("worker transfer on %s failed (rc=%d): %s",
                  host, proc.returncode, proc.stderr.strip())
        return StatsRow.failed()
    line = proc.stdout.strip().splitlines()
    if not line:
        log.error("worker on %s returned no stats line", host)
        return StatsRow.failed()
    try:
        return StatsRow.decode(line[-1])
    except ValueError as e:
        log.error("bad stats line from %s: %s", host, e)
        return StatsRow.failed()


def send_with_retry(host: str, request: Request, command_fifo: str,
                    timeout: float | None = DEFAULT_TIMEOUT,
                    retries: int = 1) -> StatsRow:
    for attempt in range(retries + 1):
        try:
            row = send(host, request, command_fifo, timeout=timeout)
        except subprocess.TimeoutExpired:
            log.error("worker on %s timed out (attempt %d)", host, attempt)
            row = StatsRow.failed()
        if row.ok:
            return row
    return row


def fan_out(jobs, fn, pool_size: int | None = None) -> list:
    """Drive all workers concurrently, one thread per worker (parity with the
    reference's ``multiprocessing.dummy.Pool``, ``process_query.py:180-185``).
    """
    if not jobs:
        return []
    with Pool(pool_size or len(jobs)) as pool:
        return pool.map(fn, jobs)
