"""Persistent-socket RPC client: the streaming head↔worker data plane.

The FIFO/NFS transport (:mod:`.fifo`) pays multiple filesystem
round-trips per batch — a query file write, a bash transfer script, two
blocking FIFO rendezvous, a ``.results`` sidecar read — which PR 7/8
already had to de-fsync and de-collide. This module carries the SAME
wire contract over one persistent connection per worker instead:

* **frames, not files** (:mod:`.frames`): length-prefixed, JSON header
  (unknown-key tolerant, gate only on NEWER ``v``), ndarray payload
  segments shipped as raw bytes — no savetxt/parse on the hot path;
* **multiplexed in-flight batches**: every request frame carries an
  ``id`` and replies correlate by it, so pipelined batches and a hedge
  duplicate share one socket instead of one-file-one-FIFO each;
* **explicit backpressure**: the server advertises a credit window in
  its ``hello`` frame and answers over-window requests with a ``busy``
  frame — the serving queues consume that instead of discovering
  saturation by timeout;
* **heartbeats** ride the existing ping/:class:`~.wire.HealthStatus`
  vocabulary as ``ping``/``health`` frames (:func:`probe`), feeding
  the same breaker healing loops as FIFO probes;
* **membership + diff epoch gates** travel in the request's
  ``RuntimeConfig`` exactly as on the FIFO wire; a gated worker answers
  the ``STALE_EPOCH``/``STALE_DIFF`` sentinel in the reply's ``stats``
  line and the head fails over.

Knobs (``DOS_TRANSPORT`` selects the lane; all via :mod:`..utils.env`):
``DOS_TRANSPORT={fifo,rpc,auto}`` (default ``fifo`` — byte-identical
legacy), ``DOS_RPC_SOCKET_DIR`` (unix socket directory, default
``/tmp``), ``DOS_RPC_PORT`` (nonzero = TCP base port; worker ``w``
listens on ``port+w`` — the cross-host spelling), ``DOS_RPC_TIMEOUT_S``
(per-call bound, default 600 like the FIFO transport),
``DOS_RPC_MAX_INFLIGHT`` (client-side credit ceiling, default 8),
``DOS_RPC_CREDIT`` (server window, default 8),
``DOS_RPC_HEARTBEAT_S`` (client idle heartbeat cadence, 0 = off).

The server half (accept loop, request handling, fault-injection
points) lives beside the FIFO serve loop in
:mod:`..worker.server` — both share one :class:`~..worker.server
.FifoServer` (engine, epoch gates, health state).
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import os
import queue as _stdqueue
import socket
import threading
import time

import numpy as np

from .frames import (
    FrameReader, FrameSchemaError, FrameWriter, TransportError,
)
from .wire import HealthStatus, RuntimeConfig, StatsRow
from ..obs import metrics as obs_metrics
from ..obs import quantiles as obs_quantiles
from ..obs import trace as obs_trace
from ..testing import faults
from ..utils.env import env_cast, env_str
from ..utils.locks import OrderedLock
from ..utils.log import get_logger

log = get_logger(__name__)

#: same default as the FIFO transport: generous for a cold-compile
#: first batch, finite so a dead worker cannot hang a campaign
DEFAULT_TIMEOUT = 600.0

M_CONNECTS = obs_metrics.counter(
    "rpc_connects_total", "RPC connections established to workers")
M_RECONNECTS = obs_metrics.counter(
    "rpc_reconnects_total",
    "RPC connections re-established after a transport failure")
M_TRANSPORT_ERRORS = obs_metrics.counter(
    "rpc_transport_errors_total",
    "RPC calls failed by transport faults (torn frame, dead socket, "
    "timeout) — each one retryable, feeding the breaker/failover path")
M_BUSY = obs_metrics.counter(
    "rpc_busy_frames_total",
    "explicit BUSY backpressure frames (client+server sides book here)")
M_HEARTBEATS = obs_metrics.counter(
    "rpc_heartbeats_total",
    "ping frames sent over persistent RPC connections")


def shutdown_close(sock) -> None:
    """Tear a socket down so BLOCKED peers wake: ``close()`` alone does
    not interrupt a thread parked in ``recv``/``accept`` on the same fd
    (the classic Linux leak) — ``shutdown(SHUT_RDWR)`` first does."""
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass    # already reset/closed: shutdown has nothing to do
    try:
        sock.close()
    except OSError as e:
        log.debug("socket close failed: %s", e)


#: head-side sink for pushed ``telemetry`` frames (``obs.telemetry``'s
#: ingest installs itself here); None = drop, the pre-telemetry behavior
_telemetry_sink = None


def set_telemetry_sink(fn) -> None:
    """Install (or clear, with None) the callable that receives every
    pushed telemetry tick from every client's read loop."""
    global _telemetry_sink
    _telemetry_sink = fn


class RpcBusy(RuntimeError):
    """The server's credit window refused the request (explicit
    backpressure — NOT a failure of the worker)."""


class RpcUnavailable(TransportError):
    """No RPC listener at the endpoint (connect refused / socket file
    absent). ``DOS_TRANSPORT=auto`` callers fall back to FIFO on this;
    ``rpc`` callers book a failed batch."""


# -------------------------------------------------------------- endpoints

def resolve_transport() -> str:
    """The ``DOS_TRANSPORT`` knob: ``fifo`` (default, byte-identical
    legacy), ``rpc``, or ``auto`` (RPC with per-lane FIFO fallback).
    Malformed values degrade to ``fifo``, logged — never crash."""
    raw = (env_str("DOS_TRANSPORT", "fifo") or "fifo").strip().lower()
    if raw not in ("fifo", "rpc", "auto"):
        log.warning("ignoring malformed DOS_TRANSPORT=%r (using 'fifo')",
                    raw)
        return "fifo"
    return raw


def rpc_socket_path(wid: int) -> str:
    """Per-worker unix socket (the local-host analog of
    ``command_fifo_path``)."""
    d = env_str("DOS_RPC_SOCKET_DIR", "/tmp") or "/tmp"
    return os.path.join(d, f"dos-rpc-worker{wid}.sock")


def endpoint_for(wid: int, host: str = "localhost"):
    """Where worker ``wid`` listens: ``("tcp", host, port+wid)`` when
    ``DOS_RPC_PORT`` names a base port, else the unix socket (which
    only reaches local workers — cross-host fleets set the port)."""
    base = env_cast("DOS_RPC_PORT", 0, int)
    if base > 0:
        return ("tcp", host, base + int(wid))
    return ("unix", rpc_socket_path(wid), None)


def endpoint_str(ep) -> str:
    if ep[0] == "tcp":
        return f"tcp:{ep[1]}:{ep[2]}"
    return f"unix:{ep[1]}"


# ----------------------------------------------------------------- client

class RpcClient:
    """One persistent, multiplexed connection to one worker.

    Thread-safe: any number of callers :meth:`call` concurrently; a
    background reader thread routes reply frames to callers by frame
    id. A transport failure fails every in-flight call with a retryable
    :class:`~.frames.TransportError` and the next call reconnects."""

    def __init__(self, endpoint, timeout_s: float | None = None,
                 max_inflight: int | None = None,
                 connect_timeout_s: float = 10.0,
                 wid: int | None = None):
        self.endpoint = endpoint
        self.wid = wid          # labels this lane's heartbeat window
        self.timeout_s = (timeout_s if timeout_s is not None
                          else env_cast("DOS_RPC_TIMEOUT_S",
                                        DEFAULT_TIMEOUT, float))
        self.max_inflight = (max_inflight if max_inflight is not None
                             else max(1, env_cast("DOS_RPC_MAX_INFLIGHT",
                                                  8, int)))
        self.connect_timeout_s = connect_timeout_s
        self._seq = itertools.count()
        self._lock = OrderedLock("transport.RpcClient")
        self._pending: dict[int, _stdqueue.Queue] = {}
        self._sock = None
        self._writer: FrameWriter | None = None
        self._reader_thread: threading.Thread | None = None
        self._hb_thread: threading.Thread | None = None
        self._hb_stop = threading.Event()
        self._credit: threading.Semaphore | None = None
        self._window = 0
        self._inflight = 0
        self._closed = False
        self._connects = 0
        self.server_hello: dict = {}

    # ------------------------------------------------------- connection
    def _dial(self):
        """Blocking connect + hello handshake (no client lock held)."""
        try:
            if self.endpoint[0] == "tcp":
                sock = socket.create_connection(
                    (self.endpoint[1], self.endpoint[2]),
                    timeout=self.connect_timeout_s)
            else:
                sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                sock.settimeout(self.connect_timeout_s)
                sock.connect(self.endpoint[1])
        except OSError as e:
            raise RpcUnavailable(
                f"no RPC listener at {endpoint_str(self.endpoint)}: {e}"
            ) from e
        try:
            hello = FrameReader(sock).read()
        except (TransportError, FrameSchemaError):
            shutdown_close(sock)
            raise
        if hello is None or hello.kind != "hello":
            shutdown_close(sock)
            raise TransportError(
                f"peer at {endpoint_str(self.endpoint)} sent no hello "
                f"(got {getattr(hello, 'kind', None)!r})")
        sock.settimeout(None)   # per-call deadlines live on the reply
        # wait below, not on the socket (the reader blocks between
        # frames by design)
        return sock, hello.header

    def _ensure_conn(self) -> None:
        with self._lock:
            if self._closed:
                raise TransportError("rpc client is closed")
            if self._sock is not None:
                return
            reconnect = self._connects > 0
        sock, hello = self._dial()
        with self._lock:
            if self._closed or self._sock is not None:
                shutdown_close(sock)
                return
            self._sock = sock
            self._writer = FrameWriter(sock)
            self.server_hello = hello
            credit = hello.get("credit")
            if not isinstance(credit, int) or credit <= 0:
                credit = self.max_inflight
            self._window = min(self.max_inflight, credit)
            self._credit = threading.Semaphore(self._window)
            self._connects += 1
            t = threading.Thread(
                target=self._read_loop, args=(sock,), daemon=True,
                name=f"dos-rpc-read-{endpoint_str(self.endpoint)}")
            self._reader_thread = t
        t.start()
        (M_RECONNECTS if reconnect else M_CONNECTS).inc()
        hb_s = env_cast("DOS_RPC_HEARTBEAT_S", 0.0, float)
        if hb_s > 0 and self._hb_thread is None:
            self._hb_stop.clear()
            self._hb_thread = threading.Thread(
                target=self._hb_loop, args=(hb_s,), daemon=True,
                name=f"dos-rpc-hb-{endpoint_str(self.endpoint)}")
            self._hb_thread.start()
        log.info("rpc connected to %s (credit window %d)",
                 endpoint_str(self.endpoint), self._window)

    def _read_loop(self, sock) -> None:
        reader = FrameReader(sock)
        try:
            while True:
                fr = reader.read()
                if fr is None:
                    raise TransportError("server closed the connection")
                if fr.kind == "hello":
                    continue            # late/duplicate hello: ignore
                if fr.kind == "telemetry":
                    # fire-and-forget push (no id): hand the tick to
                    # the head's ingest if one is installed, else drop
                    sink = _telemetry_sink
                    if sink is not None:
                        try:
                            sink(fr.header.get("tick"))
                        except Exception as e:  # noqa: BLE001 — a bad
                            # tick must not kill the data-plane reader
                            log.warning("telemetry sink failed: %s", e)
                    continue
                fid = fr.header.get("id")
                with self._lock:
                    slot = self._pending.get(fid)
                if slot is not None:
                    slot.put(fr)
                else:
                    # a late reply to a timed-out call: by-id routing
                    # means it can never satisfy a newer call
                    log.debug("unmatched rpc frame id=%r kind=%r "
                              "dropped", fid, fr.kind)
        except (TransportError, FrameSchemaError) as e:
            self._fail_conn(sock, e)

    def _fail_conn(self, sock, exc) -> None:
        with self._lock:
            if self._sock is not sock:
                return                  # an older connection's reader
            self._sock = None
            self._writer = None
            pending = list(self._pending.values())
            self._pending.clear()
        shutdown_close(sock)
        if not self._closed:
            M_TRANSPORT_ERRORS.inc()
            log.warning("rpc connection to %s failed: %s (%d call(s) "
                        "in flight fail retryable)",
                        endpoint_str(self.endpoint), exc, len(pending))
        for slot in pending:
            slot.put(exc)

    def _hb_loop(self, interval_s: float) -> None:
        # heartbeats probe over an EPHEMERAL connection, never the
        # shared one: a ping queued behind a long engine batch on the
        # shared socket would time out and call()'s teardown would
        # fail the healthy in-flight batch — a livelock whenever batch
        # time exceeds the heartbeat interval. A fresh connection gets
        # its own server conn thread and answers even mid-batch.
        while not self._hb_stop.wait(interval_s):
            probe_client = RpcClient(self.endpoint,
                                     connect_timeout_s=min(
                                         interval_s, 10.0))
            try:
                t0 = time.perf_counter()
                probe_client.probe(timeout=interval_s)
                dt = time.perf_counter() - t0
                M_HEARTBEATS.inc()
                # the one continuous liveness signal, with latency
                # history the SLO engine can window (per worker when
                # the lane knows its wid, plus the fleet aggregate)
                obs_quantiles.observe("rpc_heartbeat_seconds", dt)
                if self.wid is not None:
                    obs_quantiles.observe(
                        f"rpc_heartbeat_seconds_w{self.wid}", dt)
            except (TransportError, RpcBusy) as e:
                log.warning("rpc heartbeat to %s failed: %s",
                            endpoint_str(self.endpoint), e)
            finally:
                probe_client.close(join_s=2.0)

    # ------------------------------------------------------------ calls
    def call(self, header: dict, arrays=(), timeout: float | None = None):
        """Send one frame, wait for its correlated reply.

        Raises :class:`~.frames.TransportError` (retryable) on any
        socket-level failure or timeout, :class:`RpcBusy` on an explicit
        backpressure frame, :class:`~.frames.FrameSchemaError` when the
        peer speaks a newer schema."""
        timeout = timeout if timeout is not None else self.timeout_s
        self._ensure_conn()
        with self._lock:
            credit = self._credit
            writer = self._writer
            sock0 = self._sock
        if writer is None or credit is None:
            raise TransportError("rpc connection lost before send")
        # the credit window IS the backpressure surface: a caller
        # blocks here (bounded) instead of piling frames on a saturated
        # worker and discovering it by timeout
        if not credit.acquire(timeout=timeout):
            M_BUSY.inc()
            raise RpcBusy(
                f"rpc credit window ({self._window}) exhausted at "
                f"{endpoint_str(self.endpoint)}")
        try:
            fid = next(self._seq)
            slot: _stdqueue.Queue = _stdqueue.Queue(maxsize=1)
            with self._lock:
                self._pending[fid] = slot
                self._inflight += 1
            try:
                writer.send({**header, "id": fid}, arrays)
                try:
                    got = slot.get(timeout=timeout)
                except _stdqueue.Empty:
                    M_TRANSPORT_ERRORS.inc()
                    raise TransportError(
                        f"rpc call {fid} to "
                        f"{endpoint_str(self.endpoint)} timed out "
                        f"after {timeout:.0f}s") from None
            finally:
                with self._lock:
                    self._pending.pop(fid, None)
                    self._inflight -= 1
            if isinstance(got, Exception):
                raise got
            if got.kind == "busy":
                M_BUSY.inc()
                raise RpcBusy(
                    f"worker at {endpoint_str(self.endpoint)} answered "
                    f"BUSY (server credit window)")
            return got
        except TransportError:
            # fail the shared connection so the next call reconnects
            # instead of every caller timing out one by one (identity-
            # checked: a reconnect raced in by another thread survives)
            if sock0 is not None:
                self._fail_conn(sock0, TransportError("call failed"))
            raise
        finally:
            credit.release()

    def probe(self, timeout: float = 10.0) -> HealthStatus:
        """Liveness over the persistent socket: the ``__DOS_PING__``
        vocabulary as a ``ping`` frame; the reply is the same
        :class:`~.wire.HealthStatus` a FIFO probe reads."""
        fr = self.call({"kind": "ping"}, timeout=timeout)
        status = fr.header.get("status")
        if fr.kind != "health" or not isinstance(status, dict):
            raise TransportError(
                f"ping to {endpoint_str(self.endpoint)} answered "
                f"{fr.kind!r}, not health")
        return HealthStatus.from_json(json.dumps(status))

    # ----------------------------------------------------------- status
    def statusz(self) -> dict:
        with self._lock:
            return {
                "endpoint": endpoint_str(self.endpoint),
                "connected": self._sock is not None,
                "inflight": int(self._inflight),
                "credit": int(self._window),
                # the starvation signal the control daemon reads: how
                # full this connection's credit window is (1.0 = every
                # further call would block or shed BUSY)
                "occupancy": (round(self._inflight / self._window, 4)
                              if self._window > 0 else 0.0),
                "connects": int(self._connects),
            }

    def close(self, join_s: float = 5.0) -> None:
        self._hb_stop.set()
        with self._lock:
            self._closed = True
            sock = self._sock
        if sock is not None:
            self._fail_conn(sock, TransportError("client closed"))
        for t in (self._reader_thread, self._hb_thread):
            if t is not None:
                t.join(timeout=join_s)
        self._reader_thread = self._hb_thread = None


# --------------------------------------------- campaign-path conveniences

_client_cache: dict = {}
_client_cache_lock = OrderedLock("transport.rpc.client_cache")


def client_for(wid: int, host: str = "localhost") -> RpcClient:
    """Process-lifetime client cache: the campaign head keeps ONE
    persistent connection per worker across every round (that is the
    point of the transport). ``close_clients()`` at campaign end."""
    key = (host, int(wid))
    with _client_cache_lock:
        c = _client_cache.get(key)
        if c is None:
            c = _client_cache[key] = RpcClient(
                endpoint_for(wid, host=host), wid=int(wid))
        return c


def close_clients() -> None:
    with _client_cache_lock:
        clients = list(_client_cache.values())
        _client_cache.clear()
    for c in clients:
        c.close()


def probe(wid: int, host: str = "localhost",
          timeout: float = 10.0) -> HealthStatus | None:
    """One-shot liveness probe over a FRESH connection (breaker healing
    loops call this on the cooldown cadence; an ephemeral connection
    also proves the accept loop itself is alive). None on any failure —
    the same contract as ``transport.fifo.probe``."""
    client = RpcClient(endpoint_for(wid, host=host),
                       connect_timeout_s=min(timeout, 10.0))
    try:
        return client.probe(timeout=timeout)
    except (TransportError, RpcBusy, FrameSchemaError) as e:
        log.warning("rpc probe of worker %d on %s failed: %s", wid,
                    host, e)
        return None
    finally:
        client.close(join_s=timeout)


def request_header(rconf: RuntimeConfig, diff: str,
                   wid: int | None = None) -> dict:
    """The ``req`` frame header for one batch. The ``corrupt-frame``
    fault point garbles the config here (the socket analog of the
    transfer-script corruption): the server must count it malformed and
    answer FAIL, never wedge."""
    config = json.loads(rconf.to_json())
    if faults.inject("corrupt-frame", wid=wid) is not None:
        config = "CORRUPT " + rconf.to_json()
    return {"kind": "req", "config": config, "diff": diff or "-"}


def decode_reply_row(fr) -> StatsRow:
    """The reply's stats line -> :class:`~.wire.StatsRow` (FAIL /
    STALE_* sentinels included); garbage decodes as a failed row."""
    try:
        return StatsRow.decode(str(fr.header.get("stats", "")))
    except ValueError as e:
        log.error("bad rpc stats line: %s", e)
        return StatsRow.failed()


def _materialize_sidecars(fr, sidecar_base: str) -> None:
    """Campaign compatibility: a reply's paths/trace payloads land as
    the SAME ``<base>.paths`` / ``<base>.trace`` sidecar files the
    collectors already read — the extraction and trace-merge tooling
    does not know the batch never touched the shared dir."""
    from .wire import paths_file_for, write_paths_file

    if fr.header.get("paths") and len(fr.arrays) >= 2:
        try:
            nodes, moves = fr.arrays[-2], fr.arrays[-1]
            write_paths_file(paths_file_for(sidecar_base),
                             np.asarray(nodes), np.asarray(moves))
        except (OSError, ValueError) as e:
            log.error("cannot write rpc paths sidecar for %s: %s",
                      sidecar_base, e)
    events = fr.header.get("trace")
    if isinstance(events, list) and events:
        try:
            obs_trace.write_events(
                obs_trace.trace_sidecar_for(sidecar_base), events)
        except OSError as e:
            log.error("cannot write rpc trace sidecar for %s: %s",
                      sidecar_base, e)


def send_batch(host: str, wid: int, queries, rconf: RuntimeConfig,
               diff: str, timeout: float | None = None,
               sidecar_base: str = "") -> StatsRow:
    """One campaign batch over the persistent connection: queries ride
    as a raw int64 segment (no query file), the stats line comes back
    in the reply header, and any paths/trace payloads materialize as
    the legacy sidecars next to ``sidecar_base``."""
    client = client_for(wid, host=host)
    q = np.ascontiguousarray(np.asarray(queries, np.int64).reshape(-1, 2))
    fr = client.call(request_header(rconf, diff, wid=wid), [q],
                     timeout=timeout)
    row = decode_reply_row(fr)
    if sidecar_base:
        _materialize_sidecars(fr, sidecar_base)
    return row


def send_batch_with_retry(host: str, wid: int, queries,
                          rconf: RuntimeConfig, diff: str,
                          timeout: float | None = None,
                          policy=None,
                          sidecar_base: str = "") -> StatsRow:
    """:func:`send_batch` under the FIFO transport's retry policy
    (same env knobs, same ``head_retries_total`` accounting). A missing
    listener raises :class:`RpcUnavailable` on the FIRST attempt only —
    that is the ``auto`` fallback signal; once a worker has answered on
    this transport, later transport deaths are worker failures and walk
    the normal retry/failover path."""
    from . import fifo as fifo_transport

    policy = policy or fifo_transport.RetryPolicy.from_env()
    seed = f"rpc:{host}:{wid}"
    row = StatsRow.failed()
    for attempt in range(policy.retries + 1):
        if attempt:
            fifo_transport.M_RETRIES.inc()
            delay = policy.backoff_s(attempt - 1, seed=seed)
            log.warning("retrying rpc batch to worker %d on %s "
                        "(attempt %d) in %.2fs", wid, host, attempt,
                        delay)
            time.sleep(delay)
        try:
            row = send_batch(host, wid, queries, rconf, diff,
                             timeout=timeout, sidecar_base=sidecar_base)
        except RpcUnavailable:
            if attempt == 0:
                raise
            row = StatsRow.failed()
        except (TransportError, RpcBusy) as e:
            log.error("rpc batch to worker %d on %s failed "
                      "(attempt %d): %s", wid, host, attempt, e)
            row = StatsRow.failed()
        if row.ok:
            return row
    return row


def config_from_wire(raw) -> RuntimeConfig:
    """Decode a request frame's ``config`` value with the standard
    codec tolerance (unknown keys filtered; non-dict garbage raises
    ``ValueError`` so the server books it malformed)."""
    if not isinstance(raw, dict):
        raise ValueError(f"config is not an object: {type(raw).__name__}")
    known = {f.name for f in dataclasses.fields(RuntimeConfig)}
    return RuntimeConfig(**{k: v for k, v in raw.items() if k in known})
