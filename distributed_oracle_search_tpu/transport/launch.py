"""Job launch: start a worker process on a host, detached.

Mechanism parity with the reference launchers (``make_cpds.py:10-25``,
``make_fifos.py:9-26``): ``ssh <host> "cd <projectdir>; tmux new -As <name>
-d '<cmd>'"`` — the detached tmux session survives the ssh exit and doubles
as crash forensics (reference ``README.md:23``).

Improvements:

* local hosts skip ssh (and, when tmux is absent, fall back to a plain
  detached subprocess with a logfile — same survive-the-parent semantics);
* ``wait_local`` turns fire-and-forget into tracked completion for local
  builds (the reference has no completion signal, SURVEY.md §3.1).
"""

from __future__ import annotations

import shutil
import subprocess

from ..utils.log import get_logger

log = get_logger(__name__)

LOCAL_HOSTS = ("localhost", "127.0.0.1", "::1")


def session_name(kind: str, wid: int) -> str:
    """``worker-<wid>`` / ``fifo-<wid>`` (reference session naming)."""
    return f"{kind}-{wid}"


def launch(host: str, session: str, cmd: str, projectdir: str = ".",
           logfile: str | None = None,
           prefer_track: bool = False) -> subprocess.Popen | None:
    """Start ``cmd`` detached on ``host``. Returns the Popen handle for
    tracked local subprocesses (so callers can wait), else None.

    ``prefer_track=True`` makes local launches use a tracked subprocess even
    when tmux is available — finite jobs (CPD builds) want completion
    signals; resident servers want tmux's survive-the-parent + forensics.
    """
    if host in LOCAL_HOSTS:
        if shutil.which("tmux") and not prefer_track:
            full = f"cd {projectdir}; tmux new -As {session} -d '{cmd}'"
            subprocess.run(["bash", "-c", full], check=True)
            return None
        out = open(logfile, "ab") if logfile else subprocess.DEVNULL
        return subprocess.Popen(["bash", "-c", cmd], cwd=projectdir,
                                stdout=out, stderr=subprocess.STDOUT,
                                start_new_session=True)
    remote = f"cd {projectdir}; tmux new -As {session} -d '{cmd}'"
    status = subprocess.run(["ssh", host, remote], capture_output=True,
                            text=True)
    if status.returncode != 0:
        raise RuntimeError(
            f"launch on {host} failed: {status.stderr.strip()}")
    return None


def kill_session(host: str, session: str) -> None:
    cmd = f"tmux kill-session -t {session}"
    argv = (["bash", "-c", cmd] if host in LOCAL_HOSTS
            else ["ssh", host, cmd])
    subprocess.run(argv, capture_output=True)
