"""Length-prefixed binary frames: the streaming RPC wire format.

One frame = a fixed preamble, a JSON header, and zero or more raw
ndarray payload segments::

    MAGIC "DOSF" (4)  |  header_len u32 LE  |  payload_len u64 LE
    header JSON (header_len bytes)
    segment bytes (payload_len bytes, concatenated in header order)

The header is an ordinary JSON object carrying the SAME compat contract
as every other codec in this repo (``RuntimeConfig``/``HealthStatus``/
the manifest): readers take the keys they know and IGNORE the rest, and
the only hard gate is the frame-schema version ``v`` — a frame stamped
NEWER than :data:`FRAME_SCHEMA_VERSION` is refused (we cannot know what
its extra segments mean), while older/absent versions always decode.

Array segments are described in the header (``segs: [{dtype, shape},
...]``) and shipped as raw little-endian bytes — **no savetxt/parse on
the hot path**: encode hands the socket a list of buffers (the header
block plus one ``memoryview`` per array, no join/copy of the payload),
and decode reads the whole payload into ONE buffer and returns
``np.frombuffer`` views into it (zero-copy receive; callers that need
to mutate copy explicitly).

This module is the ONLY place in the package allowed to touch
``recv``/``sendall`` (the ``fifo-hygiene`` lint rule's socket half):
every transport failure mode — peer died mid-frame, reset, timeout,
garbage bytes — surfaces here as a typed, retryable
:class:`TransportError` instead of a hang or an attribute error three
layers up.

Frame kinds (the ``kind`` header key — unknown kinds are the RECEIVER'S
problem to skip, same tolerance rule):

``hello``   server -> client on accept: ``wid``, ``credit`` (the
            in-flight window the client may keep on this connection)
``req``     one batch: ``config`` (RuntimeConfig dict), ``diff``,
            segment 0 = queries ``int64 [Q, 2]``
``rep``     the answer: ``stats`` (the wire CSV line / sentinel),
            segments = cost/plen/fin (+ paths nodes/moves) when asked
``busy``    explicit backpressure: the server's credit window is spent
            — the client books BUSY instead of discovering a timeout
``ping``    liveness probe (the ``__DOS_PING__`` vocabulary on sockets)
``health``  the answer to ``ping``: ``status`` = HealthStatus dict
``telemetry``  server -> client push, no ``id``, no reply: ``tick`` =
            one telemetry snapshot (its OWN schema version inside —
            see ``obs.telemetry``); a client that predates it drops
            the frame as unmatched, by the unknown-kind rule
"""

from __future__ import annotations

import json
import struct

import numpy as np

from ..obs import metrics as obs_metrics
from ..utils.log import get_logger

log = get_logger(__name__)

MAGIC = b"DOSF"
#: the frame-schema version this build speaks. Bump ONLY for changes an
#: old reader cannot safely ignore; header-key additions ride for free.
FRAME_SCHEMA_VERSION = 1
_PREAMBLE = struct.Struct("<4sIQ")

#: hard ceiling on one frame's header/payload: a torn preamble must not
#: be able to ask the receiver for a 2^60-byte allocation
MAX_HEADER_BYTES = 1 << 20
MAX_PAYLOAD_BYTES = 1 << 31

#: segments are padded to this boundary so an int64 segment following a
#: uint8 one still decodes as an ALIGNED zero-copy view
SEG_ALIGN = 8


def _aligned(n: int) -> int:
    return (n + SEG_ALIGN - 1) // SEG_ALIGN * SEG_ALIGN

M_SENT = obs_metrics.counter(
    "rpc_frames_sent_total", "frames written to RPC sockets")
M_RECEIVED = obs_metrics.counter(
    "rpc_frames_received_total", "frames decoded off RPC sockets")
M_TORN = obs_metrics.counter(
    "rpc_frames_torn_total",
    "frames that died mid-read (peer gone, reset, bad magic) — each "
    "one surfaced as a retryable TransportError, never a hang")


class TransportError(RuntimeError):
    """A socket-level failure (torn frame, reset, timeout, dead peer).

    Always RETRYABLE: the request may be re-sent on a fresh connection
    or failed over to a replica — the same contract as a FIFO transfer
    script dying, so it feeds the existing breaker/failover paths."""


class TornFrame(TransportError):
    """The peer vanished mid-frame (EOF/garbage inside a frame)."""


class FrameSchemaError(ValueError):
    """The peer speaks a NEWER frame schema than this build.

    NOT retryable (a reconnect meets the same peer): the caller should
    fail the lane loudly — mixed-version fleets gate here instead of
    misreading segments."""


class Frame:
    """One decoded frame: ``kind``, the raw header dict, and the
    payload arrays (zero-copy views into the receive buffer)."""

    __slots__ = ("kind", "header", "arrays")

    def __init__(self, kind: str, header: dict, arrays: list):
        self.kind = kind
        self.header = header
        self.arrays = arrays

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"Frame({self.kind!r}, id={self.header.get('id')}, "
                f"{len(self.arrays)} seg(s))")


def encode_frame(header: dict, arrays=()) -> list:
    """Encode one frame as a list of send buffers.

    The first buffer is preamble+header; each array contributes its own
    ``memoryview`` — the payload is never joined/copied, so a multi-MB
    result batch costs zero host copies on the way out."""
    arrays = [np.ascontiguousarray(a) for a in arrays]
    header = dict(header)
    header.setdefault("v", FRAME_SCHEMA_VERSION)
    header["segs"] = [{"dtype": a.dtype.str, "shape": list(a.shape)}
                      for a in arrays]
    hdr = json.dumps(header).encode()
    if len(hdr) > MAX_HEADER_BYTES:
        raise ValueError(f"frame header too large: {len(hdr)} bytes")
    payload_len = sum(_aligned(a.nbytes) for a in arrays)
    if payload_len > MAX_PAYLOAD_BYTES:
        raise ValueError(f"frame payload too large: {payload_len} bytes")
    bufs = [_PREAMBLE.pack(MAGIC, len(hdr), payload_len) + hdr]
    for a in arrays:
        bufs.append(memoryview(a).cast("B"))
        pad = _aligned(a.nbytes) - a.nbytes
        if pad:
            bufs.append(b"\x00" * pad)
    return bufs


def decode_header(raw: bytes) -> dict:
    """Parse + version-gate a frame header. Unknown keys ride along
    untouched (the caller reads what it knows); only a NEWER ``v``
    refuses."""
    try:
        header = json.loads(raw)
    except ValueError as e:
        raise TornFrame(f"undecodable frame header: {e}") from e
    if not isinstance(header, dict):
        raise TornFrame(f"frame header is not an object: {header!r}")
    v = header.get("v", FRAME_SCHEMA_VERSION)
    if not isinstance(v, int):
        v = FRAME_SCHEMA_VERSION
    if v > FRAME_SCHEMA_VERSION:
        raise FrameSchemaError(
            f"frame schema v{v} is newer than this build's "
            f"v{FRAME_SCHEMA_VERSION} (upgrade this peer)")
    return header


def decode_payload(header: dict, payload) -> list:
    """Slice the payload buffer into the header's described arrays —
    ``np.frombuffer`` views, no copy. A header/payload length mismatch
    is a torn frame."""
    segs = header.get("segs") or []
    if not isinstance(segs, list):
        raise TornFrame(f"bad segs descriptor: {segs!r}")
    mv = memoryview(payload)
    arrays = []
    off = 0
    for seg in segs:
        try:
            dtype = np.dtype(seg["dtype"])
            shape = tuple(int(x) for x in seg["shape"])
        except (KeyError, TypeError, ValueError) as e:
            raise TornFrame(f"bad segment descriptor {seg!r}: {e}") from e
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        nbytes = n * dtype.itemsize
        if off + nbytes > len(mv):
            raise TornFrame(
                f"payload truncated: segment needs {nbytes} bytes at "
                f"offset {off}, have {len(mv)}")
        arrays.append(np.frombuffer(mv[off:off + nbytes],
                                    dtype=dtype).reshape(shape))
        off += _aligned(nbytes)
    return arrays


class FrameWriter:
    """Serialize frames onto one socket. Thread-safe: concurrent callers
    (pipelined batches, a hedge sharing the socket) interleave at frame
    granularity, never mid-frame."""

    def __init__(self, sock, lock=None):
        import threading

        self._sock = sock
        # a plain mutex, not an OrderedLock: held only around the
        # kernel-buffer write below, no other lock is ever taken under
        # it, and the hot path should not pay witness-graph accounting
        self._lock = lock if lock is not None else threading.Lock()

    def send(self, header: dict, arrays=()) -> None:
        bufs = encode_frame(header, arrays)
        try:
            with self._lock:
                for b in bufs:
                    self._sock.sendall(b)
        except (OSError, ValueError) as e:
            # ValueError: write on a socket another thread just closed
            M_TORN.inc()
            raise TransportError(f"frame send failed: {e}") from e
        M_SENT.inc()


class FrameReader:
    """Deserialize frames off one socket.

    ``read()`` returns the next :class:`Frame`, ``None`` on a CLEAN
    end-of-stream (peer closed between frames), and raises
    :class:`TornFrame` when the peer dies mid-frame — the caller never
    sees a half-decoded request, and never blocks forever if the socket
    carries a timeout."""

    def __init__(self, sock):
        self._sock = sock

    def _recv_exact(self, n: int, allow_eof: bool = False):
        buf = bytearray(n)
        mv = memoryview(buf)
        got = 0
        while got < n:
            try:
                k = self._sock.recv_into(mv[got:])
            except (OSError, ValueError) as e:
                M_TORN.inc()
                raise TornFrame(f"socket died mid-frame: {e}") from e
            if k == 0:
                if allow_eof and got == 0:
                    return None
                M_TORN.inc()
                raise TornFrame(
                    f"peer closed mid-frame ({got}/{n} bytes)")
            got += k
        return buf

    def read(self):
        pre = self._recv_exact(_PREAMBLE.size, allow_eof=True)
        if pre is None:
            return None
        magic, header_len, payload_len = _PREAMBLE.unpack(bytes(pre))
        if magic != MAGIC:
            M_TORN.inc()
            raise TornFrame(f"bad frame magic {bytes(magic)!r}")
        if header_len > MAX_HEADER_BYTES or payload_len > \
                MAX_PAYLOAD_BYTES:
            M_TORN.inc()
            raise TornFrame(
                f"implausible frame lengths (header {header_len}, "
                f"payload {payload_len})")
        header = decode_header(bytes(self._recv_exact(header_len)))
        payload = self._recv_exact(payload_len) if payload_len else b""
        arrays = decode_payload(header, payload)
        M_RECEIVED.inc()
        kind = header.get("kind")
        return Frame(kind if isinstance(kind, str) else "",
                     header, arrays)
