"""Decide: declarative rules with trip/clear hysteresis and cooldowns.

The SLO engine's alerting pattern (trip at a threshold, clear only
below ``clear_frac`` of it) applied to *actions*: a rule fires its
trip edge once after ``hold_ticks`` consecutive over-threshold
observations, then cannot fire again until the signal has both cleared
and ``cooldown_s`` has elapsed — an oscillating signal produces a
bounded number of actions, never a flap storm. The
:class:`ActionBudget` is the last line: a fleet-wide cap on executed
actions per sliding window, so even a pathological policy cannot
reconfigure the fleet faster than an operator could follow."""

from __future__ import annotations

import collections
import time


class HysteresisRule:
    """Trip/clear edge detector over a scalar signal.

    ``observe(value, now)`` returns ``"trip"`` on the rising edge,
    ``"clear"`` on the falling edge, else ``None``. ``None`` values
    (sensor absent) hold the current state — missing data is not
    evidence of health."""

    def __init__(self, name: str, trip: float, *,
                 clear: float | None = None, clear_frac: float = 0.5,
                 hold_ticks: int = 2, cooldown_s: float = 0.0):
        self.name = name
        self.trip_at = float(trip)
        self.clear_at = float(clear if clear is not None
                              else trip * clear_frac)
        self.hold_ticks = max(1, int(hold_ticks))
        self.cooldown_s = float(cooldown_s)
        self.tripped = False
        self._above = 0
        self._below = 0
        self._last_fire = float("-inf")

    def observe(self, value: float | None,
                now: float | None = None) -> str | None:
        now = time.monotonic() if now is None else now
        if value is None:
            return None
        if not self.tripped:
            if value >= self.trip_at:
                self._above += 1
                if (self._above >= self.hold_ticks
                        and now - self._last_fire >= self.cooldown_s):
                    self.tripped = True
                    self._below = 0
                    self._last_fire = now
                    return "trip"
            else:
                self._above = 0
            return None
        if value <= self.clear_at:
            self._below += 1
            if self._below >= self.hold_ticks:
                self.tripped = False
                self._above = 0
                return "clear"
        else:
            self._below = 0
        return None


class ActionBudget:
    """Sliding-window cap on executed actions (fleet-wide)."""

    def __init__(self, budget: int, window_s: float):
        self.budget = max(1, int(budget))
        self.window_s = float(window_s)
        self._fired: collections.deque = collections.deque()

    def _prune(self, now: float) -> None:
        while self._fired and now - self._fired[0] > self.window_s:
            self._fired.popleft()

    def allow(self, now: float) -> bool:
        self._prune(now)
        return len(self._fired) < self.budget

    def book(self, now: float) -> None:
        self._prune(now)
        self._fired.append(now)

    def statusz(self, now: float | None = None) -> dict:
        now = time.monotonic() if now is None else now
        self._prune(now)
        return {"budget": self.budget, "window_s": self.window_s,
                "used": len(self._fired)}


class Cooldown:
    """Per-actuator minimum spacing between executions."""

    def __init__(self, cooldown_s: float):
        self.cooldown_s = float(cooldown_s)
        self._last: dict[str, float] = {}

    def ready(self, key: str, now: float) -> bool:
        return now - self._last.get(key, float("-inf")) >= self.cooldown_s

    def mark(self, key: str, now: float) -> None:
        self._last[key] = now


# --------------------------------------------------------------- brownout
#: ladder levels, least to most invasive. Each level's actions are the
#: union of everything up to it; stepping down undoes in reverse.
BROWNOUT_MAX_LEVEL = 3
#: hedge budget multiplier at level >= 1 (shrink speculative duplicates
#: first — they are pure extra load under overload)
BROWNOUT_HEDGE_SCALE = 0.25
#: families shed at level >= 2 (mat fan-out and alt-count cost the
#: most per request; plain s-t queries keep flowing)
BROWNOUT_SHED_FAMILIES = ("mat", "alt")
#: deadline multiplier at level 3 (shed the slowest tail explicitly
#: rather than letting it time out after consuming a slot)
BROWNOUT_DEADLINE_SCALE = 0.25


class BrownoutLadder:
    """Burn-rate driven admission ladder on the serving frontend.

    One hysteresis rule on the max fast burn; each trip steps the level
    up by one, each clear steps it down by one, with the rule's
    cooldown spacing consecutive steps. ``level`` is observable state;
    the daemon applies it through the actuators."""

    def __init__(self, *, burn_trip: float, clear_frac: float,
                 hold_ticks: int, cooldown_s: float):
        self.level = 0
        self._rule = HysteresisRule(
            "brownout_burn", burn_trip, clear_frac=clear_frac,
            hold_ticks=hold_ticks, cooldown_s=cooldown_s)
        self._hold_ticks = max(1, int(hold_ticks))
        self._cooldown_s = float(cooldown_s)
        self._above = 0
        self._last_step = float("-inf")

    def decide(self, fast_burn: float | None, now: float) -> int | None:
        """Returns the new target level, or None for no change."""
        edge = self._rule.observe(fast_burn, now)
        if edge == "trip" and self.level < BROWNOUT_MAX_LEVEL:
            self._above = 0
            self._last_step = now
            return self.level + 1
        if edge == "clear" and self.level > 0:
            # a clear steps all the way down: the burn is back under
            # the clear threshold, holding degraded admission longer
            # only sheds users for no reason
            self._above = 0
            self._last_step = now
            return 0
        # sustained overload escalates: the rule stays tripped (its
        # trip edge cannot re-fire), so a burn HOLDING at/over the
        # threshold — overload the current level did not relieve —
        # steps one more rung, with the same hold/cooldown spacing as
        # the entry edge
        if (self._rule.tripped and fast_burn is not None
                and fast_burn >= self._rule.trip_at
                and self.level < BROWNOUT_MAX_LEVEL):
            self._above += 1
            if (self._above >= self._hold_ticks
                    and now - self._last_step >= self._cooldown_s):
                self._above = 0
                self._last_step = now
                return self.level + 1
        else:
            self._above = 0
        return None


# -------------------------------------------------------------- quarantine
Q_OK = "ok"
Q_QUARANTINED = "quarantined"
Q_LEFT = "left"


class WorkerState:
    """One worker's quarantine state machine."""

    def __init__(self, wid: int):
        self.wid = wid
        self.state = Q_OK
        self.since = 0.0
        self.clean = 0
        self.why = ""
        self.readmitted_at = float("-inf")


class QuarantineManager:
    """Sick-worker detection and re-admission bookkeeping.

    ``decide(signals, now)`` returns a list of decisions the daemon
    executes: ``("quarantine", wid, why)``, ``("readmit", wid)``,
    ``("leave", wid, why)``. Probing is the daemon's job (it owns the
    probe function); this class only tracks state so decisions stay
    unit-testable without a fleet."""

    def __init__(self, *, unhealthy_pings: int, clean_probes: int,
                 dead_after_s: float, telemetry_lag_s: float,
                 readmit_grace_s: float = 5.0):
        self.unhealthy_pings = int(unhealthy_pings)
        self.clean_probes = int(clean_probes)
        self.dead_after_s = float(dead_after_s)
        self.telemetry_lag_s = float(telemetry_lag_s)
        #: sick signals ignored this long after a re-admission: the
        #: supervisor's ping-failure counter and the telemetry lag both
        #: trail a genuinely healed worker by one publish interval, and
        #: re-quarantining on that stale echo would flap
        self.readmit_grace_s = float(readmit_grace_s)
        self.workers: dict[int, WorkerState] = {}

    def _get(self, wid: int) -> WorkerState:
        if wid not in self.workers:
            self.workers[wid] = WorkerState(wid)
        return self.workers[wid]

    def quarantined(self) -> list[int]:
        return sorted(w.wid for w in self.workers.values()
                      if w.state == Q_QUARANTINED)

    def _sick_reason(self, sig, wid: int) -> str | None:
        if sig.worker_running.get(wid) is False:
            return "process dead"
        pf = sig.ping_failures.get(wid, 0)
        if pf >= self.unhealthy_pings:
            return f"{pf} consecutive ping failures"
        lag = sig.telemetry_lag_s.get(wid)
        if lag is not None and lag >= self.telemetry_lag_s:
            return f"telemetry silent {lag:.0f}s"
        return None

    def decide(self, sig, now: float) -> list[tuple]:
        out = []
        for wid in sorted(sig.known_workers()):
            ws = self._get(wid)
            if ws.state == Q_OK:
                if now - ws.readmitted_at < self.readmit_grace_s:
                    continue
                why = self._sick_reason(sig, wid)
                if why is not None:
                    ws.state = Q_QUARANTINED
                    ws.since = now
                    ws.clean = 0
                    ws.why = why
                    out.append(("quarantine", wid, why))
            elif ws.state == Q_QUARANTINED:
                if now - ws.since >= self.dead_after_s:
                    ws.state = Q_LEFT
                    out.append(("leave", wid,
                                f"unhealthy {now - ws.since:.0f}s"))
        return out

    def quarantine_now(self, wid: int, now: float, why: str = "") -> None:
        """Force a worker into quarantine from OUTSIDE the sick-signal
        scan — the DivergenceWatch arm's entry point: an audit
        divergence is direct evidence of wrong answers, not a health
        inference, so it bypasses ``_sick_reason``. The worker then
        earns re-admission through the SAME probation loop (N clean
        probes) as every other quarantine."""
        ws = self._get(wid)
        if ws.state == Q_QUARANTINED:
            return
        ws.state = Q_QUARANTINED
        ws.since = now
        ws.clean = 0
        ws.why = why

    def probe_result(self, wid: int, ok: bool) -> bool:
        """Book one probe outcome for a quarantined worker; True when
        the worker has earned re-admission (caller executes it and then
        calls :meth:`readmitted`)."""
        ws = self._get(wid)
        if ws.state != Q_QUARANTINED:
            return False
        ws.clean = ws.clean + 1 if ok else 0
        return ws.clean >= self.clean_probes

    def readmitted(self, wid: int, now: float | None = None) -> None:
        ws = self._get(wid)
        ws.state = Q_OK
        ws.clean = 0
        ws.why = ""
        ws.readmitted_at = time.monotonic() if now is None else now


# ----------------------------------------------------------------- repair
class RepairScaler:
    """Elastic repair decisions: capacity and placement, not health.

    * Sustained fleet-wide queue saturation trips the *starvation* rule
      → ``("join", host)`` when a join target is configured, else
      ``("scale_advise",)`` (lane widening needs a worker restart with
      a wider ``DOS_MESH_DEVICES``; the daemon cannot re-exec workers,
      so it books the advisory for the operator/orchestrator).
    * A single shard holding more than ``hot_frac`` of all queued work
      while the fleet is busy trips the *hot-shard* rule →
      ``("replicate", shard)`` — raise that shard's replication via
      chained declustering instead of fleet-wide R."""

    def __init__(self, *, starve_frac: float, hot_frac: float,
                 clear_frac: float, hold_ticks: int, cooldown_s: float,
                 join_host: str = ""):
        self.join_host = join_host
        self._starve = HysteresisRule(
            "starvation", starve_frac, clear_frac=clear_frac,
            hold_ticks=hold_ticks, cooldown_s=cooldown_s)
        self._hot = HysteresisRule(
            "hot_shard", hot_frac, clear_frac=clear_frac,
            hold_ticks=hold_ticks, cooldown_s=cooldown_s)

    def decide(self, sig, now: float) -> list[tuple]:
        out = []
        # starvation evidence comes from BOTH admission sensors when
        # present: shard queue saturation (FIFO/engine lanes queue in
        # the frontend) and RPC credit-window occupancy (streaming
        # lanes queue in the worker — a starved RPC fleet shows full
        # windows, not deep frontend queues). Either alone is an
        # observation; neither reporting holds the rule's state (0.0
        # from an absent sensor must not clear it, but a genuinely
        # drained fleet must be able to)
        evidence = []
        if sig.queue_depths:
            evidence.append(sig.queue_frac)
        if getattr(sig, "credit_occupancy", None):
            evidence.append(sig.credit_frac)
        frac = max(evidence) if evidence else None
        if self._starve.observe(frac, now) == "trip":
            if self.join_host:
                out.append(("join", self.join_host))
            else:
                out.append(("scale_advise",))
        # only meaningful when there is real queued work to be skewed
        hot = sig.hot_frac if sum(sig.queue_depths.values()) >= 4 else None
        if (self._hot.observe(hot, now) == "trip"
                and sig.hot_shard is not None):
            out.append(("replicate", sig.hot_shard))
        return out


class DivergenceWatch:
    """Answer-audit divergences → quarantine decisions.

    The auditor already verified the divergence on an independent lane,
    so — like :class:`GatewayWatch` — this arm needs no trip/clear
    hysteresis: ONE confirmed wrong answer is evidence enough. It acts
    on DELTAS of the auditor's per-shard cumulative counts, with a
    per-shard cooldown so a stream of divergences from one rotten shard
    yields one quarantine per window. The high-water mark advances only
    when the decision is actually emitted (cooldown-ready): a
    divergence that arrives mid-cooldown is re-considered on the next
    ready tick rather than silently forgotten."""

    def __init__(self, *, cooldown_s: float = 30.0):
        self._cooldown = Cooldown(cooldown_s)
        self._seen: dict[int, int] = {}

    def decide(self, sig, now: float) -> list[tuple]:
        out = []
        for wid, count in sorted(sig.audit_divergent.items()):
            wid, count = int(wid), int(count)
            fresh = count - self._seen.get(wid, 0)
            if fresh <= 0:
                continue
            key = f"diverge:{wid}"
            if not self._cooldown.ready(key, now):
                continue
            self._cooldown.mark(key, now)
            self._seen[wid] = count
            out.append(("divergence_quarantine", wid,
                        f"{fresh} audit divergence(s) "
                        f"({count} cumulative)"))
        return out


class GatewayWatch:
    """Gateway frontend liveness: turn expired endpoint leases into
    kick decisions.

    The lease TTL already encodes the detection hysteresis (a frontend
    is only in ``sig.gateway_dead`` after a full TTL of silence), so
    this arm needs no trip/clear edge — just a per-frontend cooldown so
    one dead replica yields one kick per window, not one per tick, and
    a respawn gets a full lease of grace to re-register before the
    daemon considers it dead again."""

    def __init__(self, *, cooldown_s: float = 30.0):
        self._cooldown = Cooldown(cooldown_s)

    def decide(self, sig, now: float) -> list[tuple]:
        out = []
        for fid in sig.gateway_dead:
            key = f"gwkick:{int(fid)}"
            if self._cooldown.ready(key, now):
                self._cooldown.mark(key, now)
                stale = sig.gateway_lease_stale_s.get(int(fid))
                why = (f"endpoint lease stale {stale:.1f}s"
                       if isinstance(stale, (int, float))
                       else "endpoint lease expired")
                out.append(("kick", int(fid), why))
        return out
