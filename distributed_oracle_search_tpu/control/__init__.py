"""Closed-loop control plane: sense -> decide -> act.

The fleet grew every sensor (telemetry bus, SLO burn-rate engine,
heartbeat RTT windows, breaker states) and every actuator (supervisor
respawn, replication/hedging, drain-free ``plan_join``/``plan_leave``)
before anything connected them; until this package a hung worker or a
zipf hotspot degraded service until an operator noticed. The
:class:`~distributed_oracle_search_tpu.control.daemon.ControlDaemon`
closes the loop: a single background thread runs on a
``DOS_CONTROL_INTERVAL_S`` cadence, reads the sensors
(:mod:`.signals`), evaluates declarative rules with trip/clear
hysteresis and per-actuator cooldowns (:mod:`.policy`), and executes
recovery actions (:mod:`.actuators`) under a global action budget.
``DOS_CONTROL_DRY_RUN=1`` books every decision (metrics + flight
recorder) without executing anything; ``DOS_CONTROL=0`` (the default)
never constructs the daemon, keeping legacy behavior byte-identical.

Escalation ladder, least to most invasive:

1. **Brownout** — shrink the hedge budget, shed the ``mat``/``alt``
   query families, tighten deadlines; entered and exited by SLO burn
   rate so overload degrades quality before availability.
2. **Quarantine** — a worker failing pings or leaking burn is removed
   from routing (breaker force-open), supervisor-respawned, and
   re-admitted only after N clean probes.
3. **Repair/scale** — sustained starvation executes ``plan_join``
   (or books a lane-widening advisory where a membership move costs
   more); a permanently dead worker executes ``plan_leave`` through
   the dual-read window; zipf-hot shards get selective replication
   raised.
4. **Warming** — the next diff epoch's fused delta is materialized
   ahead of the pump cadence so swap stall never hits a user.
"""

from .config import ControlConfig
from .daemon import ControlDaemon, maybe_daemon

__all__ = ["ControlConfig", "ControlDaemon", "maybe_daemon"]
