"""Control-plane knobs (``DOS_CONTROL*``), one frozen dataclass.

Same policy home as :class:`serving.config.ServeConfig`: every knob is
read through :mod:`utils.env` (malformed values degrade to defaults,
logged), ``validate()`` raises on impossible combinations, and the
daemon only ever sees an immutable snapshot — mid-flight env edits
cannot half-apply."""

from __future__ import annotations

import dataclasses

from ..utils.env import env_cast, env_flag, env_str
from ..utils.log import get_logger

log = get_logger(__name__)


@dataclasses.dataclass(frozen=True)
class ControlConfig:
    """Policy daemon configuration. ``enabled`` gates construction
    entirely: when False the daemon object is never built and no
    ``control_*`` metric or statusz section exists (byte-identical
    legacy behavior)."""

    enabled: bool = False        #: DOS_CONTROL — master switch
    interval_s: float = 2.0      #: DOS_CONTROL_INTERVAL_S — tick cadence
    dry_run: bool = False        #: DOS_CONTROL_DRY_RUN — book, don't act
    budget: int = 12             #: DOS_CONTROL_BUDGET — actions / window
    budget_window_s: float = 300.0  #: DOS_CONTROL_BUDGET_WINDOW_S
    cooldown_s: float = 15.0     #: DOS_CONTROL_COOLDOWN_S — per actuator
    hold_ticks: int = 2          #: consecutive ticks before a rule trips
    clear_frac: float = 0.5      #: clear threshold = trip * clear_frac
    brownout_burn: float = 14.4  #: DOS_CONTROL_BROWNOUT_BURN — fast-burn
    #: ping failures before a running worker is deemed sick (mirrors the
    #: supervisor's DOS_SUPERVISOR_UNHEALTHY_PINGS but trips the
    #: *routing* quarantine, which is safe even when the supervisor's
    #: opt-in kill path is disarmed)
    unhealthy_pings: int = 2     #: DOS_CONTROL_UNHEALTHY_PINGS
    clean_probes: int = 2        #: DOS_CONTROL_CLEAN_PROBES — re-admission
    dead_after_s: float = 120.0  #: DOS_CONTROL_DEAD_AFTER_S — plan_leave
    starve_frac: float = 0.9     #: DOS_CONTROL_STARVE_FRAC — queue frac
    telemetry_lag_s: float = 30.0  #: DOS_CONTROL_TELEMETRY_LAG_S
    hot_shard_frac: float = 0.6  #: DOS_CONTROL_HOT_FRAC — zipf hotspot
    join_host: str = ""          #: DOS_CONTROL_JOIN_HOST — scale target

    @classmethod
    def from_env(cls) -> "ControlConfig":
        cfg = cls(
            enabled=env_flag("DOS_CONTROL", False),
            interval_s=env_cast("DOS_CONTROL_INTERVAL_S", 2.0, float),
            dry_run=env_flag("DOS_CONTROL_DRY_RUN", False),
            budget=env_cast("DOS_CONTROL_BUDGET", 12, int),
            budget_window_s=env_cast(
                "DOS_CONTROL_BUDGET_WINDOW_S", 300.0, float),
            cooldown_s=env_cast("DOS_CONTROL_COOLDOWN_S", 15.0, float),
            hold_ticks=env_cast("DOS_CONTROL_HOLD_TICKS", 2, int),
            clear_frac=env_cast("DOS_CONTROL_CLEAR_FRAC", 0.5, float),
            brownout_burn=env_cast(
                "DOS_CONTROL_BROWNOUT_BURN", 14.4, float),
            unhealthy_pings=env_cast(
                "DOS_CONTROL_UNHEALTHY_PINGS", 2, int),
            clean_probes=env_cast("DOS_CONTROL_CLEAN_PROBES", 2, int),
            dead_after_s=env_cast("DOS_CONTROL_DEAD_AFTER_S", 120.0,
                                  float),
            starve_frac=env_cast("DOS_CONTROL_STARVE_FRAC", 0.9, float),
            telemetry_lag_s=env_cast(
                "DOS_CONTROL_TELEMETRY_LAG_S", 30.0, float),
            hot_shard_frac=env_cast("DOS_CONTROL_HOT_FRAC", 0.6, float),
            join_host=env_str("DOS_CONTROL_JOIN_HOST", "") or "",
        )
        try:
            cfg.validate()
        except ValueError as e:
            log.warning("control config invalid (%s); disabling daemon",
                        e)
            cfg = dataclasses.replace(cfg, enabled=False)
        return cfg

    def validate(self) -> None:
        if self.interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        if self.budget < 1:
            raise ValueError("budget must be >= 1")
        if self.budget_window_s <= 0:
            raise ValueError("budget_window_s must be > 0")
        if self.cooldown_s < 0:
            raise ValueError("cooldown_s must be >= 0")
        if self.hold_ticks < 1:
            raise ValueError("hold_ticks must be >= 1")
        if not (0.0 < self.clear_frac <= 1.0):
            raise ValueError("clear_frac must be in (0, 1]")
        if self.clean_probes < 1:
            raise ValueError("clean_probes must be >= 1")
        if not (0.0 < self.starve_frac <= 1.0):
            raise ValueError("starve_frac must be in (0, 1]")
        if not (0.0 < self.hot_shard_frac <= 1.0):
            raise ValueError("hot_shard_frac must be in (0, 1]")
