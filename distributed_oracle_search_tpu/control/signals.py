"""Sense: one degraded-tolerant read of every fleet sensor.

The daemon's providers are all optional — a supervise-side daemon has
no serving frontend, a head-side daemon may run without a telemetry
store — and any of them can throw mid-incident (which is exactly when
the daemon must keep ticking). Each provider is read inside its own
``try``; a failed read leaves that signal ``None``/empty and the policy
arms treat missing data as "no evidence", never as "healthy".

Telemetry lag is itself a failure signal: a worker whose sidecar
stopped publishing is indistinguishable from a hung worker, so
:attr:`ControlSignals.telemetry_lag_s` feeds the quarantine arm
alongside ping failures."""

from __future__ import annotations

import dataclasses
import time

from ..utils.log import get_logger

log = get_logger(__name__)


@dataclasses.dataclass
class ControlSignals:
    """One tick's sensor snapshot (monotonic ``now``)."""

    now: float
    #: max fast-window burn across SLO specs (None: engine absent/no data)
    fast_burn: float | None = None
    #: SLO spec names currently alerting
    alerting: tuple = ()
    #: max queue_depth / queue_bound across serving shards (0.0 idle)
    queue_frac: float = 0.0
    #: per-shard queue depth {wid: depth} from the frontend
    queue_depths: dict = dataclasses.field(default_factory=dict)
    #: per-lane RPC credit-window occupancy {via: frac} from the
    #: frontend's streaming-transport connection table
    credit_occupancy: dict = dataclasses.field(default_factory=dict)
    #: max credit occupancy across lanes (0.0 idle) — full windows are
    #: the streaming lane's starvation signal: queues live in the
    #: WORKER under RPC, so frontend queue depth alone under-reports
    credit_frac: float = 0.0
    #: per-worker process liveness {wid: bool} from the supervisor
    worker_running: dict = dataclasses.field(default_factory=dict)
    #: per-worker consecutive ping failures {wid: int}
    ping_failures: dict = dataclasses.field(default_factory=dict)
    #: per-worker telemetry staleness {wid: seconds since last sample}
    telemetry_lag_s: dict = dataclasses.field(default_factory=dict)
    #: workers whose breaker is currently open {wid}
    breakers_open: set = dataclasses.field(default_factory=set)
    #: shard with the largest queue share, and that share (0.0 idle)
    hot_shard: int | None = None
    hot_frac: float = 0.0
    #: live frontend count from the gateway endpoint registry (None:
    #: no registry sensor wired)
    gateway_live: int | None = None
    #: per-frontend lease staleness {fid: seconds since last renewal}
    gateway_lease_stale_s: dict = dataclasses.field(default_factory=dict)
    #: frontends whose endpoint lease has EXPIRED — crashed or zombie
    #: (a cleanly-drained frontend unregistered and appears nowhere)
    gateway_dead: tuple = ()
    #: per-shard CUMULATIVE audit-divergence counts {wid: count} from
    #: the answer auditor (integrity.audit) — the DivergenceWatch arm
    #: acts on deltas, so cumulative totals survive a missed tick
    audit_divergent: dict = dataclasses.field(default_factory=dict)

    def known_workers(self) -> set:
        out = set(self.worker_running) | set(self.ping_failures)
        out |= set(self.queue_depths) | set(self.telemetry_lag_s)
        return out


class SignalReader:
    """Reads all providers into one :class:`ControlSignals`.

    Worker telemetry lag comes from the ingest's per-source freshness
    map; worker sources follow the ``w<wid>`` naming convention the
    sidecar publishers use, so lag maps back onto supervisor wids."""

    def __init__(self, *, ingest=None, slo=None, frontend=None,
                 supervisor=None, registry=None, breaker_key=None,
                 gateway=None, integrity=None, clock=time.monotonic):
        self.ingest = ingest
        self.slo = slo
        self.frontend = frontend
        self.supervisor = supervisor
        self.registry = registry      # the BREAKER registry
        self.breaker_key = breaker_key
        self.gateway = gateway        # the gateway ENDPOINT registry
        self.integrity = integrity    # the answer auditor (snapshot())
        self.clock = clock

    def read(self, now: float | None = None) -> ControlSignals:
        sig = ControlSignals(now=self.clock() if now is None else now)
        self._read_slo(sig)
        self._read_frontend(sig)
        self._read_supervisor(sig)
        self._read_telemetry(sig)
        self._read_breakers(sig)
        self._read_gateway(sig)
        self._read_integrity(sig)
        return sig

    # ------------------------------------------------------- providers
    def _read_slo(self, sig: ControlSignals) -> None:
        if self.slo is None:
            return
        try:
            ev = self.slo.evaluate()
            burns = [v.get("fast_burn") for v in ev.values()
                     if isinstance(v, dict)
                     and v.get("fast_burn") is not None]
            sig.fast_burn = max(burns) if burns else None
            sig.alerting = tuple(self.slo.alerting())
        except Exception as e:  # noqa: BLE001 — degrade, keep ticking
            log.debug("control sense: slo read failed: %s", e)

    def _read_frontend(self, sig: ControlSignals) -> None:
        if self.frontend is None:
            return
        try:
            st = self.frontend.statusz()
            transport = st.get("transport")
            conns = (transport.get("connections")
                     if isinstance(transport, dict) else None)
            if isinstance(conns, dict):
                for via, c in conns.items():
                    occ = (c.get("occupancy")
                           if isinstance(c, dict) else None)
                    if isinstance(occ, (int, float)):
                        sig.credit_occupancy[int(via)] = float(occ)
                if sig.credit_occupancy:
                    sig.credit_frac = max(
                        sig.credit_occupancy.values())
            shards = st.get("shards")
            if not isinstance(shards, dict):
                return
            total = 0
            for wid, s in shards.items():
                if not isinstance(s, dict):
                    continue
                depth = s.get("queue_depth")
                bound = s.get("queue_bound")
                if isinstance(depth, (int, float)):
                    sig.queue_depths[int(wid)] = int(depth)
                    total += int(depth)
                    if isinstance(bound, (int, float)) and bound > 0:
                        sig.queue_frac = max(sig.queue_frac,
                                             depth / bound)
            if total > 0:
                hot = max(sig.queue_depths.items(), key=lambda kv: kv[1])
                sig.hot_shard = hot[0]
                sig.hot_frac = hot[1] / total
        except Exception as e:  # noqa: BLE001 — degrade, keep ticking
            log.debug("control sense: frontend read failed: %s", e)

    def _read_supervisor(self, sig: ControlSignals) -> None:
        if self.supervisor is None:
            return
        try:
            st = self.supervisor.statusz()
            workers = st.get("workers")
            if not isinstance(workers, dict):
                return
            for wid, w in workers.items():
                if not isinstance(w, dict):
                    continue
                sig.worker_running[int(wid)] = bool(w.get("running"))
                pf = w.get("ping_failures")
                if isinstance(pf, (int, float)):
                    sig.ping_failures[int(wid)] = int(pf)
        except Exception as e:  # noqa: BLE001 — degrade, keep ticking
            log.debug("control sense: supervisor read failed: %s", e)

    def _read_telemetry(self, sig: ControlSignals) -> None:
        if self.ingest is None:
            return
        try:
            sources = self.ingest.statusz().get("sources")
            if not isinstance(sources, dict):
                return
            for src, st in sources.items():
                if not (isinstance(src, str) and src.startswith("w")
                        and src[1:].isdigit()
                        and isinstance(st, dict)):
                    continue
                lag = st.get("lag_s")
                if isinstance(lag, (int, float)):
                    sig.telemetry_lag_s[int(src[1:])] = float(lag)
        except Exception as e:  # noqa: BLE001 — degrade, keep ticking
            log.debug("control sense: telemetry read failed: %s", e)

    def _read_breakers(self, sig: ControlSignals) -> None:
        if self.registry is None or self.breaker_key is None:
            return
        try:
            for wid in sig.known_workers():
                br = self.registry.get(self.breaker_key(wid))
                if br is not None and not br.would_allow():
                    sig.breakers_open.add(wid)
        except Exception as e:  # noqa: BLE001 — degrade, keep ticking
            log.debug("control sense: breaker read failed: %s", e)

    def _read_gateway(self, sig: ControlSignals) -> None:
        """Gateway endpoint leases: live frontend count, per-frontend
        lease staleness, and the set whose lease EXPIRED (crash or
        ``lease-freeze`` zombie) — the kick arm's evidence."""
        if self.gateway is None:
            return
        try:
            snap = self.gateway.snapshot()
            live = snap.get("live") or []
            dead = snap.get("dead") or []
            sig.gateway_live = len(live)
            for row in list(live) + list(dead):
                if isinstance(row, dict) and "fid" in row:
                    stale = row.get("stale_s")
                    if isinstance(stale, (int, float)):
                        sig.gateway_lease_stale_s[int(row["fid"])] = \
                            float(stale)
            sig.gateway_dead = tuple(sorted(
                int(row["fid"]) for row in dead
                if isinstance(row, dict) and "fid" in row))
        except Exception as e:  # noqa: BLE001 — degrade, keep ticking
            log.debug("control sense: gateway registry read failed: %s",
                      e)

    def _read_integrity(self, sig: ControlSignals) -> None:
        """Answer-audit divergences: the auditor's per-shard cumulative
        counts (``AnswerAuditor.snapshot``) — evidence a shard is
        serving WRONG answers, the one failure mode no availability
        sensor above can see."""
        if self.integrity is None:
            return
        try:
            snap = self.integrity.snapshot()
            if isinstance(snap, dict):
                sig.audit_divergent = {int(k): int(v)
                                       for k, v in snap.items()}
        except Exception as e:  # noqa: BLE001 — degrade, keep ticking
            log.debug("control sense: integrity read failed: %s", e)
