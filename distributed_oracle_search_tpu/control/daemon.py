"""The policy daemon: one thread, sense -> decide -> act, journaled.

Every decision flows through :meth:`ControlDaemon._decide`, which is
where the safety envelope lives: ``DOS_CONTROL_DRY_RUN`` books the
decision (metric + flight-recorder event) without calling the
actuator; the global :class:`~.policy.ActionBudget` caps executed
actions per sliding window; actuator exceptions are counted and the
loop keeps ticking. The flight recorder gets one structured event per
decision (``control_*`` kinds) so ``dos-obs replay`` renders the
causal incident timeline: detect -> quarantine -> respawn -> probe ->
readmit, interleaved with the faults and SLO alerts that caused them.
"""

from __future__ import annotations

import threading
import time

from ..obs import metrics as obs_metrics
from ..obs import recorder as obs_recorder
from ..utils.log import get_logger
from .actuators import Actuators
from .config import ControlConfig
from .policy import (ActionBudget, BrownoutLadder, Cooldown,
                     DivergenceWatch, GatewayWatch, QuarantineManager,
                     RepairScaler)
from .signals import SignalReader

log = get_logger(__name__)

M_TICKS = obs_metrics.counter(
    "control_ticks_total", "sense->decide->act loop passes")
M_DECISIONS = obs_metrics.counter(
    "control_decisions_total",
    "policy decisions reached (executed, dry-run, or budget-denied)")
M_ACTIONS = obs_metrics.counter(
    "control_actions_total", "reconfiguration actions executed")
M_BUDGET_DENIED = obs_metrics.counter(
    "control_budget_denied_total",
    "decisions not executed: global action budget exhausted")
M_ERRORS = obs_metrics.counter(
    "control_errors_total", "actuator executions that raised")
M_QUARANTINES = obs_metrics.counter(
    "control_quarantines_total",
    "sick workers removed from routing (breaker pin + respawn kick)")
M_READMISSIONS = obs_metrics.counter(
    "control_readmissions_total",
    "quarantined workers re-admitted after N clean probes")
M_BROWNOUT_SHIFTS = obs_metrics.counter(
    "control_brownout_shifts_total", "brownout ladder level changes")
G_BROWNOUT = obs_metrics.gauge(
    "control_brownout_level",
    "current brownout ladder level (0 = full service)")
M_REPAIRS = obs_metrics.counter(
    "control_repairs_total",
    "elastic repairs executed (plan_join / plan_leave / replication)")
M_SCALE_ADVISED = obs_metrics.counter(
    "control_scale_advised_total",
    "scale-up advisories booked (no join host / lane widening needs a "
    "worker restart)")
M_WARMS = obs_metrics.counter(
    "control_warms_total",
    "predictive warm actions (next diff epoch pre-fused, warmers run)")
M_GATEWAY_KICKS = obs_metrics.counter(
    "control_gateway_kicks_total",
    "dead gateway frontends kicked for respawn (expired endpoint "
    "lease in gateway.json)")
M_DIVERGENCE_Q = obs_metrics.counter(
    "control_divergence_quarantines_total",
    "shards pulled from routing on a confirmed audit divergence "
    "(breaker force-open + scrub-now; re-admitted after clean probes)")


class ControlDaemon:
    """Sense->decide->act loop over injectable providers (all optional;
    see :class:`~.signals.SignalReader` and
    :class:`~.actuators.Actuators` for what each enables).

    ``probe_fn(wid) -> bool`` is the quarantine probation check; when
    absent it falls back to the supervisor's probe, then to "process is
    running" — the weakest evidence that still beats none."""

    def __init__(self, config: ControlConfig | None = None, *,
                 slo=None, frontend=None, supervisor=None,
                 registry=None, breaker_key=None, membership=None,
                 ingest=None, replicate_fn=None, warm_fns=(),
                 probe_fn=None, gateway=None, gateway_respawn_fn=None,
                 integrity=None, scrub_fn=None, clock=time.monotonic):
        self.config = config or ControlConfig.from_env()
        self.clock = clock
        self.signals = SignalReader(
            ingest=ingest, slo=slo, frontend=frontend,
            supervisor=supervisor, registry=registry,
            breaker_key=breaker_key or (
                getattr(frontend, "_breaker_key", None)),
            gateway=gateway, integrity=integrity, clock=clock)
        self.actuators = Actuators(
            frontend=frontend, supervisor=supervisor, registry=registry,
            breaker_key=breaker_key, membership=membership,
            replicate_fn=replicate_fn, warm_fns=warm_fns,
            gateway_respawn_fn=gateway_respawn_fn, scrub_fn=scrub_fn)
        self.supervisor = supervisor
        self.probe_fn = probe_fn
        cfg = self.config
        self.budget = ActionBudget(cfg.budget, cfg.budget_window_s)
        self.cooldowns = Cooldown(cfg.cooldown_s)
        self.brownout = BrownoutLadder(
            burn_trip=cfg.brownout_burn, clear_frac=cfg.clear_frac,
            hold_ticks=cfg.hold_ticks, cooldown_s=cfg.cooldown_s)
        self.quarantine = QuarantineManager(
            unhealthy_pings=cfg.unhealthy_pings,
            clean_probes=cfg.clean_probes,
            dead_after_s=cfg.dead_after_s,
            telemetry_lag_s=cfg.telemetry_lag_s,
            readmit_grace_s=max(cfg.cooldown_s, 3 * cfg.interval_s))
        self.repair = RepairScaler(
            starve_frac=cfg.starve_frac, hot_frac=cfg.hot_shard_frac,
            clear_frac=cfg.clear_frac, hold_ticks=cfg.hold_ticks,
            cooldown_s=cfg.cooldown_s, join_host=cfg.join_host)
        self.gateway_watch = GatewayWatch(cooldown_s=cfg.cooldown_s)
        self.divergence_watch = DivergenceWatch(cooldown_s=cfg.cooldown_s)
        self.last_action = ""
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ------------------------------------------------- decision plumbing
    def _decide(self, kind: str, counter, fn, now: float,
                **fields) -> bool:
        """One decision through the safety envelope. Returns True when
        the action actually executed."""
        M_DECISIONS.inc()
        executed = False
        if self.config.dry_run:
            mode = "dry-run"
        elif not self.budget.allow(now):
            M_BUDGET_DENIED.inc()
            mode = "budget-denied"
        else:
            try:
                fn()
                executed = True
                self.budget.book(now)
                M_ACTIONS.inc()
                if counter is not None:
                    counter.inc()
                mode = "executed"
            except Exception as e:  # noqa: BLE001 — one broken
                # actuator must not stop the loop that heals the fleet
                M_ERRORS.inc()
                mode = "error"
                fields["error"] = str(e).split("\n")[0]
                log.exception("control: %s failed", kind)
        desc = " ".join(f"{k}={v}" for k, v in sorted(fields.items()))
        self.last_action = f"{kind}({mode}) {desc}".strip()
        log.info("control: %s", self.last_action)
        obs_recorder.emit(f"control_{kind}", mode=mode,
                          executed=executed, **fields)
        return executed

    # -------------------------------------------------------------- tick
    def tick(self, now: float | None = None) -> None:
        now = self.clock() if now is None else now
        M_TICKS.inc()
        sig = self.signals.read(now)
        self._tick_divergence(sig, now)
        self._tick_quarantine(sig, now)
        self._tick_brownout(sig, now)
        self._tick_repair(sig, now)
        self._tick_gateway(sig, now)
        self._tick_warm(now)

    def _tick_divergence(self, sig, now: float) -> None:
        """DivergenceWatch runs BEFORE the health quarantine scan: a
        shard pulled here enters the same QuarantineManager state, so
        the probation loop below probes it this very tick and the
        normal N-clean-probes re-admission applies. Re-admission is
        gated on the scrub having had its say: ``divergence_quarantine``
        triggered a scrub-now, and a corrupt resident table either
        healed (clean probes follow) or keeps diverging (the next delta
        re-quarantines after readmit_grace)."""
        for decision in self.divergence_watch.decide(sig, now):
            _, wid, why = decision
            if self._decide(
                    "divergence_quarantine", M_DIVERGENCE_Q,
                    lambda w=wid, y=why:
                    self.actuators.divergence_quarantine(w, y),
                    now, wid=wid, why=why):
                self.quarantine.quarantine_now(wid, now, why)

    def _tick_quarantine(self, sig, now: float) -> None:
        for decision in self.quarantine.decide(sig, now):
            if decision[0] == "quarantine":
                _, wid, why = decision
                self._decide(
                    "quarantine", M_QUARANTINES,
                    lambda w=wid, y=why: self.actuators.quarantine(w, y),
                    now, wid=wid, why=why)
            elif decision[0] == "leave":
                _, wid, why = decision
                live = {w for w in sig.known_workers()
                        if w != wid
                        and w not in self.quarantine.quarantined()}
                self._decide(
                    "leave", M_REPAIRS,
                    lambda w=wid, lv=live: self.actuators.leave(w, lv),
                    now, wid=wid, why=why)
        # probation: probe every quarantined worker once per tick; N
        # consecutive clean probes earn re-admission
        for wid in self.quarantine.quarantined():
            ok = self._probe(wid)
            if self.quarantine.probe_result(wid, ok):
                if self._decide(
                        "readmit", M_READMISSIONS,
                        lambda w=wid: self.actuators.readmit(w),
                        now, wid=wid,
                        clean_probes=self.config.clean_probes):
                    self.quarantine.readmitted(wid, now)

    def _probe(self, wid: int) -> bool:
        try:
            if self.probe_fn is not None:
                return bool(self.probe_fn(wid))
            sup = self.supervisor
            if sup is not None:
                w = next((x for x in sup._snapshot() if x.wid == wid),
                         None)
                if w is None or w.proc is None or w.proc.poll() is not None:
                    return False
                st = sup.probe_fn(w)
                return st is not None and getattr(st, "ok", False)
        except Exception as e:  # noqa: BLE001 — a probe bug reads as sick
            log.debug("probe of w%d failed: %s", wid, e)
            return False
        return False

    def _tick_brownout(self, sig, now: float) -> None:
        target = self.brownout.decide(sig.fast_burn, now)
        if target is None:
            return
        prev = self.brownout.level
        if self._decide(
                "brownout", M_BROWNOUT_SHIFTS,
                lambda lv=target: self.actuators.apply_brownout(lv),
                now, level=target, prev=prev,
                burn=round(sig.fast_burn, 2)
                if sig.fast_burn is not None else None):
            self.brownout.level = target
            G_BROWNOUT.set(float(target))
        elif self.config.dry_run:
            # the ladder's hysteresis state must advance in dry-run too
            # (otherwise it re-books the same step every tick forever)
            self.brownout.level = target

    def _tick_repair(self, sig, now: float) -> None:
        for decision in self.repair.decide(sig, now):
            if decision[0] == "join":
                self._decide(
                    "join", M_REPAIRS,
                    lambda h=decision[1]: self.actuators.join(h),
                    now, host=decision[1],
                    queue_frac=round(sig.queue_frac, 3))
            elif decision[0] == "replicate":
                self._decide(
                    "replicate", M_REPAIRS,
                    lambda s=decision[1]: self.actuators.replicate(s),
                    now, shard=decision[1],
                    hot_frac=round(sig.hot_frac, 3))
            elif decision[0] == "scale_advise":
                # an advisory is a booked decision with a no-op action:
                # widening DOS_MESH_DEVICES lanes requires a worker
                # restart this daemon does not own
                M_DECISIONS.inc()
                M_SCALE_ADVISED.inc()
                self.last_action = ("scale_advise "
                                    f"queue_frac={sig.queue_frac:.3f}")
                obs_recorder.emit(
                    "control_scale_advise", mode="advisory",
                    executed=False,
                    queue_frac=round(sig.queue_frac, 3))

    def _tick_gateway(self, sig, now: float) -> None:
        for decision in self.gateway_watch.decide(sig, now):
            _, fid, why = decision
            self._decide(
                "gateway_kick", M_GATEWAY_KICKS,
                lambda f=fid: self.actuators.kick_frontend(f),
                now, fid=fid, why=why)

    def _tick_warm(self, now: float) -> None:
        # warming bypasses the action budget: it is a read-mostly local
        # materialization (fuse the already-streamed next epoch, run
        # registered warmers), not a fleet reconfiguration — and it
        # must not be able to starve a quarantine out of budget slots
        fe = self.actuators.frontend
        warmable = ((fe is not None
                     and getattr(fe, "traffic", None) is not None)
                    or self.actuators.warm_fns)
        if not warmable or not self.cooldowns.ready("warm", now):
            return
        self.cooldowns.mark("warm", now)
        M_DECISIONS.inc()
        if self.config.dry_run:
            self.last_action = "warm(dry-run)"
            obs_recorder.emit("control_warm", mode="dry-run",
                              executed=False)
            return
        try:
            warmed = self.actuators.warm()
        except Exception as e:  # noqa: BLE001
            M_ERRORS.inc()
            obs_recorder.emit("control_warm", mode="error",
                              executed=False,
                              error=str(e).split("\n")[0])
            return
        if warmed:
            M_ACTIONS.inc()
            M_WARMS.inc()
            self.last_action = "warm(executed)"
            obs_recorder.emit("control_warm", mode="executed",
                              executed=True)

    # ---------------------------------------------------------- statusz
    def statusz(self) -> dict:
        now = self.clock()
        return {
            "enabled": self.config.enabled,
            "dry_run": self.config.dry_run,
            "interval_s": self.config.interval_s,
            "brownout_level": self.brownout.level,
            "quarantined": self.quarantine.quarantined(),
            "last_action": self.last_action,
            "budget": self.budget.statusz(now),
        }

    # -------------------------------------------------------- lifecycle
    def start(self) -> "ControlDaemon":
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.config.interval_s):
                try:
                    self.tick()
                except Exception as e:  # noqa: BLE001 — the control
                    # loop outlives any one bad tick
                    log.exception("control tick failed: %s", e)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="dos-control")
        self._thread.start()
        log.info("control daemon up: interval=%.1fs dry_run=%s",
                 self.config.interval_s, self.config.dry_run)
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        self.actuators.stop()


def maybe_daemon(**providers) -> ControlDaemon | None:
    """``DOS_CONTROL`` gate used by both CLIs: None (and nothing
    constructed — byte-identical legacy behavior) unless enabled."""
    cfg = ControlConfig.from_env()
    if not cfg.enabled:
        return None
    return ControlDaemon(cfg, **providers).start()
