"""Act: every reconfiguration the daemon can execute, behind one seam.

The daemon never touches a subsystem directly — it calls these
methods, which makes dry-run trivial (skip the call, book the
decision), keeps every action unit-testable against stubs, and gives
the chaos drill one place to spy on. Slow actions (membership moves)
run on short-lived worker threads so a multi-second catch-up never
stalls the sense loop; the daemon joins them on stop.

Brownout state is owned here: the pristine frontend knobs are captured
the first time level 0 is left, and level 0 restores them exactly —
the ladder can never drift the configuration."""

from __future__ import annotations

import threading

from ..utils.log import get_logger
from .policy import (BROWNOUT_DEADLINE_SCALE, BROWNOUT_HEDGE_SCALE,
                     BROWNOUT_SHED_FAMILIES)

log = get_logger(__name__)


class Actuators:
    """Execution seam. Every provider is optional; an action whose
    provider is absent raises ``RuntimeError`` (the daemon books it as
    an error — a policy firing actions it has no actuator for is a
    wiring bug worth surfacing, not silently ignoring)."""

    def __init__(self, *, frontend=None, supervisor=None, registry=None,
                 breaker_key=None, membership=None, replicate_fn=None,
                 warm_fns=(), gateway_respawn_fn=None, scrub_fn=None):
        self.frontend = frontend
        self.supervisor = supervisor
        self.registry = registry
        if breaker_key is None and frontend is not None:
            breaker_key = getattr(frontend, "_breaker_key", None)
        self.breaker_key = breaker_key or (lambda wid: wid)
        self.membership = membership
        self.replicate_fn = replicate_fn
        self.warm_fns = list(warm_fns)
        self.gateway_respawn_fn = gateway_respawn_fn
        #: ``scrub_fn(shard)`` asks the resident-table scrubber for an
        #: immediate pass over one shard (``TableScrubber.scrub_now``)
        self.scrub_fn = scrub_fn
        self._orig = None           # pristine (hedge_budget, deadline_ms)
        self._threads: list[threading.Thread] = []
        self._tlock = threading.Lock()

    # -------------------------------------------------------- brownout
    def apply_brownout(self, level: int) -> None:
        fe = self.frontend
        if fe is None:
            raise RuntimeError("no serving frontend to brown out")
        if self._orig is None:
            self._orig = (fe.hedge.config.budget, fe.sconf.deadline_ms)
        budget0, deadline0 = self._orig
        fe.set_hedge_budget(budget0 * BROWNOUT_HEDGE_SCALE
                            if level >= 1 else budget0)
        fe.set_family_shed(BROWNOUT_SHED_FAMILIES if level >= 2 else ())
        fe.set_deadline_ms(deadline0 * BROWNOUT_DEADLINE_SCALE
                           if level >= 3 else deadline0)

    # ------------------------------------------------------ quarantine
    def quarantine(self, wid: int, why: str) -> None:
        did = False
        if self.registry is not None:
            did |= bool(self.registry.force_open(
                self.breaker_key(wid), why=why))
        if self.supervisor is not None:
            self.supervisor.kick(wid)
            did = True
        if not did:
            raise RuntimeError("no registry or supervisor to "
                               "quarantine with")

    def divergence_quarantine(self, wid: int, why: str) -> None:
        """Pull a shard serving WRONG answers out of routing: force its
        breaker open (wrong answers demand an immediate stop, not a
        supervisor respawn — the process is healthy, its data is not)
        and trigger a scrub-now of that shard so the resident-table
        check runs before the probation loop's clean probes can earn
        re-admission. The scrub half is best-effort: with no scrubber
        wired the breaker pin alone still stops the bleeding."""
        if self.registry is None:
            raise RuntimeError("no breaker registry to quarantine a "
                               "divergent shard with")
        self.registry.force_open(self.breaker_key(wid), why=why)
        if self.scrub_fn is not None:
            try:
                self.scrub_fn(int(wid))
            except Exception as e:  # noqa: BLE001 — the breaker pin is
                # the safety action; a scrub hiccup must not undo it
                log.warning("control: scrub-now of shard %d failed: %s",
                            wid, e)

    def kick_frontend(self, fid: int) -> None:
        """Recover a gateway frontend whose endpoint lease expired:
        the tier runner's ``gateway_respawn_fn`` (which respawns the
        replica in place and re-registers it) when wired, else the
        worker supervisor's kick (gateway-over-supervised-process
        deployments), else a wiring error."""
        if self.gateway_respawn_fn is not None:
            self.gateway_respawn_fn(int(fid))
            return
        if self.supervisor is not None:
            self.supervisor.kick(int(fid))
            return
        raise RuntimeError("no gateway_respawn_fn or supervisor to "
                           "kick a dead gateway frontend with")

    def readmit(self, wid: int) -> None:
        if self.registry is not None:
            self.registry.release(self.breaker_key(wid), close=True)
        # the supervisor needs no undo: a running healthy worker is
        # simply left alone

    # ---------------------------------------------------------- repair
    def _spawn(self, name: str, fn) -> None:
        t = threading.Thread(target=fn, daemon=True, name=name)
        with self._tlock:
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)
        t.start()

    def leave(self, wid: int, live=None) -> None:
        mc = self.membership
        if mc is None:
            raise RuntimeError("no membership controller for leave")

        def run():
            try:
                mc.leave(wid, live=live)
            except Exception as e:  # noqa: BLE001 — a refused/failed
                # leave is journaled by membership itself; the daemon
                # must keep ticking
                log.warning("control: leave of worker %d failed: %s",
                            wid, e)

        self._spawn(f"dos-control-leave-{wid}", run)

    def join(self, host: str) -> None:
        mc = self.membership
        if mc is None:
            raise RuntimeError("no membership controller for join")

        def run():
            try:
                mc.join(host)
            except Exception as e:  # noqa: BLE001
                log.warning("control: join of %s failed: %s", host, e)

        self._spawn("dos-control-join", run)

    def replicate(self, shard: int) -> None:
        if self.replicate_fn is None:
            raise RuntimeError("no replicate_fn for hot-shard repair")
        self.replicate_fn(int(shard))

    # --------------------------------------------------------- warming
    def warm(self) -> bool:
        """Pre-materialize the next diff epoch (the frontend's pump
        does this lazily on its poll cadence; doing it now moves the
        fuse+swap cost off the first post-swap request) and run any
        registered warmers. True when something was actually warmed."""
        did = False
        fe = self.frontend
        if fe is not None and getattr(fe, "traffic", None) is not None:
            did |= bool(fe.poll_traffic())
        for fn in self.warm_fns:
            fn()
            did = True
        return did

    def stop(self, join_s: float = 10.0) -> None:
        with self._tlock:
            threads, self._threads = self._threads, []
        for t in threads:
            t.join(timeout=join_s)
