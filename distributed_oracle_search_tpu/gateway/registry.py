"""The leased gateway endpoint registry (``gateway.json``).

PR 18's tier put N stateless frontends behind one process; this module
makes the tier's MEMBERSHIP durable so replicas can span processes and
hosts, and so death is observable without anyone watching the process.
One artifact — ``gateway.json``, living beside ``membership.json`` in
the index directory, written atomically (``utils.atomicio``) — holds a
lease row per frontend endpoint: who serves where, renewed on a
heartbeat cadence (``DOS_GATEWAY_LEASE_S``). A frontend that dies —
or a zombie that stays alive but stops renewing (the ``lease-freeze``
fault) — simply lets its lease expire: readers mark it dead with no
crash signal required, which is what lets clients discover/fail over
and the control loop kick a respawn.

Schema contract, same as the index manifest and ``membership.json``:
``from_dict`` filters unknown keys (future fields ride along), and only
a file stamped NEWER than :data:`GATEWAY_REGISTRY_VERSION` refuses —
typed, as :class:`GatewayRegistrySchemaError`. A torn or unreadable
file is a plain ``ValueError`` from :func:`load_registry`; the client's
discovery path (:func:`live_endpoints`) catches it and degrades to its
seed endpoints, never a crash.

Concurrency: readers only ever see whole files (atomic rename);
writers — multiple ``dos-gateway --join`` processes sharing one
registry — serialize read-modify-write cycles under an ``fcntl`` lock
on a sidecar lockfile, the same cross-process discipline the fault
harness's state file uses. ``flock`` locks hang off the open file
description, so two threads of ONE process (each ``_mutate`` opens its
own descriptor) serialize exactly like two processes do — no
in-process lock needed.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time

from ..obs import metrics as obs_metrics
from ..obs import recorder as obs_recorder
from ..utils.atomicio import atomic_write_json
from ..utils.log import get_logger

log = get_logger(__name__)

#: the durable endpoint artifact, beside ``membership.json``
REGISTRY_FILE = "gateway.json"

#: gateway.json schema version — unknown keys tolerated, only NEWER
#: versions rejected (typed), exactly the membership/manifest contract
GATEWAY_REGISTRY_VERSION = 1

M_RENEWALS = obs_metrics.counter(
    "gateway_lease_renewals_total",
    "endpoint lease heartbeats written to gateway.json")
G_LIVE = obs_metrics.gauge(
    "gateway_live_frontends",
    "frontends with an unexpired lease at the last registry read")


class GatewayRegistrySchemaError(ValueError):
    """``gateway.json`` is stamped NEWER than this build understands."""


@dataclasses.dataclass
class GatewayLease:
    """One frontend's claim on an endpoint. ``renewed`` is a wall-clock
    UNIX timestamp (the file crosses processes and hosts); expiry is
    ``now - renewed > lease_s`` — no crash signal required."""

    fid: int = -1
    endpoint: str = ""
    pid: int = 0
    renewed: float = 0.0
    lease_s: float = 10.0
    started: float = 0.0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "GatewayLease":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})

    def stale_s(self, now: float | None = None) -> float:
        now = time.time() if now is None else now
        return max(0.0, float(now) - float(self.renewed))

    def live(self, now: float | None = None) -> bool:
        return self.stale_s(now) <= float(self.lease_s)


@dataclasses.dataclass
class RegistryState:
    """The durable content of ``gateway.json``."""

    leases: list = dataclasses.field(default_factory=list)
    version: int = GATEWAY_REGISTRY_VERSION

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "RegistryState":
        version = int(d.get("version", 1))
        if version > GATEWAY_REGISTRY_VERSION:
            raise GatewayRegistrySchemaError(
                f"gateway.json schema v{version} is newer than this "
                f"build's v{GATEWAY_REGISTRY_VERSION} — upgrade the "
                f"serving code before joining this fleet")
        known = {f.name for f in dataclasses.fields(cls)}
        state = cls(**{k: v for k, v in d.items() if k in known})
        if not isinstance(state.leases, list):
            raise ValueError(
                f"gateway.json leases is not a list: {state.leases!r}")
        return state

    def lease_objs(self) -> list:
        """Typed lease rows; garbage rows are skipped, not fatal (one
        bad row must not take discovery down with it)."""
        out = []
        for d in self.leases:
            if isinstance(d, dict) and d.get("endpoint"):
                out.append(GatewayLease.from_dict(d))
        return out


def registry_path(dirname: str) -> str:
    return os.path.join(dirname, REGISTRY_FILE)


def load_registry(dirname: str) -> RegistryState | None:
    """``None`` when no registry exists yet. Raises ``ValueError`` on a
    torn/unreadable file and :class:`GatewayRegistrySchemaError` on a
    NEWER one — discovery callers catch and degrade to seeds."""
    path = registry_path(dirname)
    try:
        with open(path) as f:
            raw = f.read()
    except FileNotFoundError:
        return None
    except OSError as e:
        raise ValueError(f"unreadable gateway registry {path}: {e}")
    try:
        d = json.loads(raw)
    except ValueError as e:
        raise ValueError(f"torn gateway registry {path}: {e}")
    if not isinstance(d, dict):
        raise ValueError(f"gateway registry {path} is not an object")
    return RegistryState.from_dict(d)


def save_registry(dirname: str, state: RegistryState) -> None:
    atomic_write_json(registry_path(dirname), state.to_dict())


def live_endpoints(dirname: str | None, seeds=(),
                   now: float | None = None) -> list:
    """Client discovery: live lease endpoints in ascending-fid order,
    then any seed endpoints not already listed. A torn, stale, NEWER,
    or absent registry degrades to the seeds — never a crash."""
    state = None
    if dirname:
        try:
            state = load_registry(dirname)
        except ValueError as e:
            log.warning("gateway registry unreadable (%s); degrading "
                        "to %d seed endpoint(s)", e, len(tuple(seeds)))
    out = []
    if state is not None:
        for lease in sorted(state.lease_objs(), key=lambda x: x.fid):
            if lease.live(now) and lease.endpoint not in out:
                out.append(lease.endpoint)
    for s in seeds:
        if s and s not in out:
            out.append(s)
    return out


class GatewayRegistry:
    """Writer handle on one registry directory.

    ``register``/``renew``/``unregister`` are read-modify-write cycles
    under a cross-process ``fcntl`` lock (each cycle opens its own
    descriptor, so in-process threads serialize the same way); every
    write lands through ``atomic_write_json`` so readers only ever see
    whole states. A torn existing file is reset with a log line (the
    leases self-heal on the next heartbeat round); a NEWER file is
    never clobbered — :class:`GatewayRegistrySchemaError` propagates.
    """

    def __init__(self, dirname: str, lease_s: float | None = None):
        from .config import GatewayConfig

        self.dir = str(dirname)
        self.lease_s = float(lease_s if lease_s is not None
                             else GatewayConfig.from_env().lease_s)

    # ------------------------------------------------------------ write
    def _mutate(self, fn):
        import fcntl

        os.makedirs(self.dir, exist_ok=True)
        lockpath = registry_path(self.dir) + ".lock"
        with open(lockpath, "a+") as lf:
            fcntl.flock(lf, fcntl.LOCK_EX)
            try:
                state = load_registry(self.dir)
            except GatewayRegistrySchemaError:
                raise              # never clobber a newer fleet's file
            except ValueError as e:
                log.warning("gateway registry reset after torn "
                            "state: %s", e)
                state = None
            state = state or RegistryState()
            out = fn(state)
            save_registry(self.dir, state)
            return out

    def register(self, fid: int, endpoint: str,
                 now: float | None = None) -> None:
        """(Re)claim ``endpoint`` for frontend ``fid`` with a fresh
        lease. Idempotent: an existing row for the endpoint is
        replaced, whatever fid held it before."""
        now = time.time() if now is None else now
        row = GatewayLease(fid=int(fid), endpoint=str(endpoint),
                           pid=os.getpid(), renewed=float(now),
                           lease_s=self.lease_s,
                           started=float(now)).to_dict()

        def add(state: RegistryState) -> None:
            state.leases = [d for d in state.leases
                            if not (isinstance(d, dict)
                                    and d.get("endpoint") == endpoint)]
            state.leases.append(row)

        self._mutate(add)
        obs_recorder.emit("gateway_register", frontend=int(fid),
                          endpoint=str(endpoint), lease_s=self.lease_s)
        log.info("gateway f%d registered %s (lease %.2fs)", fid,
                 endpoint, self.lease_s)

    def renew(self, fid: int, endpoint: str,
              now: float | None = None) -> bool:
        """Heartbeat: refresh the endpoint's lease. False when the row
        vanished (a sweeper or reset) — the caller re-registers."""
        now = time.time() if now is None else now
        found = [False]

        def bump(state: RegistryState) -> None:
            for d in state.leases:
                if isinstance(d, dict) and d.get("endpoint") == endpoint:
                    d["renewed"] = float(now)
                    d["lease_s"] = self.lease_s
                    d["fid"] = int(fid)
                    d["pid"] = os.getpid()
                    found[0] = True

        self._mutate(bump)
        if found[0]:
            M_RENEWALS.inc()
        return found[0]

    def unregister(self, fid: int, endpoint: str) -> None:
        """Clean shutdown: drop the lease row so readers never count
        this frontend dead (an expired row means CRASH, not drain)."""
        def drop(state: RegistryState) -> None:
            state.leases = [d for d in state.leases
                            if not (isinstance(d, dict)
                                    and d.get("endpoint") == endpoint)]

        self._mutate(drop)
        obs_recorder.emit("gateway_unregister", frontend=int(fid),
                          endpoint=str(endpoint))

    def claim(self, n: int, endpoint_of, now: float | None = None) -> int:
        """``dos-gateway --join``: atomically allocate ``n`` fresh
        frontend ids above every id the registry has ever seen (live or
        expired — ids stay unique across respawns) and pre-register
        their endpoints (``endpoint_of(fid)``); the servers re-register
        over the placeholders when they start. Returns the base fid."""
        now = time.time() if now is None else now

        def pick(state: RegistryState) -> int:
            used = [int(d.get("fid", -1)) for d in state.leases
                    if isinstance(d, dict)]
            base = (max(used) + 1) if used else 0
            for i in range(int(n)):
                state.leases.append(GatewayLease(
                    fid=base + i, endpoint=str(endpoint_of(base + i)),
                    pid=os.getpid(), renewed=float(now),
                    lease_s=self.lease_s,
                    started=float(now)).to_dict())
            return base

        base = self._mutate(pick)
        log.info("gateway --join claimed fids %d..%d in %s", base,
                 base + int(n) - 1, self.dir)
        return base

    # ------------------------------------------------------------- read
    def leases(self) -> list:
        """Tolerant read: typed lease rows, ``[]`` on any failure."""
        try:
            state = load_registry(self.dir)
        except ValueError as e:
            log.debug("gateway registry read failed: %s", e)
            return []
        return state.lease_objs() if state is not None else []

    def live(self, now: float | None = None) -> list:
        return [x for x in self.leases() if x.live(now)]

    def dead(self, now: float | None = None) -> list:
        """Registered frontends past their TTL — crashed or zombie
        (``lease-freeze``). A cleanly-drained frontend unregistered and
        is in neither list."""
        return [x for x in self.leases() if not x.live(now)]

    def snapshot(self, now: float | None = None) -> dict:
        """One observable read for ``/statusz`` and the control loop's
        :class:`~..control.signals.SignalReader` sensor."""
        now = time.time() if now is None else now
        live, dead = [], []
        for lease in self.leases():
            row = {"fid": int(lease.fid), "endpoint": lease.endpoint,
                   "pid": int(lease.pid),
                   "stale_s": round(lease.stale_s(now), 3),
                   "lease_s": float(lease.lease_s)}
            (live if lease.live(now) else dead).append(row)
        G_LIVE.set(float(len(live)))
        return {"lease_s": self.lease_s, "live": live, "dead": dead}
