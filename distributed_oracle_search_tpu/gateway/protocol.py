"""The client-facing binary protocol: frame vocabulary + codecs.

The gateway tier speaks the SAME length-prefixed frame container as the
head↔worker RPC lane (:mod:`..transport.frames` — magic, schema-gated
JSON header, 8-aligned raw ndarray segments), pointed the other
direction: client → frontend. This module is the pure codec — frame
builders and parsers with no sockets in them — so the server, the
client library, and the tests all agree on one wire shape.

Frame vocabulary (header ``kind``):

* ``hello`` — first frame on a connection, both directions. The
  gateway's hello advertises ``{"gv": GATEWAY_SCHEMA_VERSION,
  "frontend": fid, "credit": N, "epoch": e, "diff_epoch": de}``; a
  client MAY answer with its own ``{"kind": "hello", "gv": ...}``.
  Version negotiation follows the repo-wide tolerate-older /
  gate-newer contract: either side refuses a peer whose ``gv`` is
  NEWER than its own build and serves anything older.
* ``q`` — one multiplexed query frame: ``{"id": n, "family":
  "pair"|"mat"|"alt"|"rev", "deadline_ms": optional, "epoch":
  optional, "diff_epoch": optional, "cid": optional client identity
  token, "resubmit": optional}``. ``cid`` + ``id`` together name one
  logical request across connections and frontends: a failover client
  resubmits an unanswered frame with its ORIGINAL id, ``resubmit``
  stamped true, and a frontend that already answered ``(cid, id)``
  replays its memoized reply instead of double-booking counters and
  cache inserts — exactly-once *accounting* over at-least-once
  *execution* (answers are deterministic, so a re-execution on a
  different frontend is bit-identical). Both keys ride the
  unknown-key contract: pre-HA gateways simply ignore them.
  ``pair``/``rev`` carry one
  int64 ``[Q, 2]`` payload segment of (s, t) rows — a BATCH per
  frame, retiring per-line text parsing from the hot ingress path;
  ``mat`` carries ``s`` in the header and an int64 ``[K]`` targets
  segment; ``alt`` is header-only (``s``, ``t``, ``k``). The epochs
  are advisory staleness hints; replies carry the serving truth.
* ``r`` — the answer, correlated by ``id``. ``pair``/``rev``:
  per-row ``status``/``detail``/``cached`` lists in the header plus
  ``[cost, plen, finished]`` int64/int64/uint8 segments; ``mat``:
  ``s`` + one costs segment (−1 per unanswered target, exactly the
  MAT sentence semantics); ``alt``: ascending ``[costs, vias]``
  segments. Every reply stamps ``frontend``/``epoch``/``diff_epoch``.
* ``busy`` — explicit backpressure: the frame arrived past the
  connection's advertised credit window. Never silently queued.
* ``err`` — a typed error for a frame the gateway could not serve
  (malformed family, bad payload, newer schema). A malformed frame
  ALWAYS answers ``err`` — never a torn connection.
* ``ping`` / ``health`` — liveness probe and its reply.

All parsers are unknown-key tolerant (new fields ride along for older
peers) and gate only on NEWER ``gv``.
"""

from __future__ import annotations

import numpy as np

from ..transport.frames import Frame

#: bump when the gateway frame vocabulary changes shape. Distinct from
#: the container's FRAME_SCHEMA_VERSION: the container gates how bytes
#: frame, this gates what the frames MEAN.
GATEWAY_SCHEMA_VERSION = 1

FAMILIES = ("pair", "mat", "alt", "rev")


class GatewayProtocolError(ValueError):
    """A frame this build cannot serve (malformed or newer-schema).
    The server answers a typed ``err`` frame and keeps the connection."""


class GatewaySchemaError(GatewayProtocolError):
    """Peer speaks a NEWER gateway schema than this build."""


def check_hello(header: dict) -> dict:
    """Gate a peer hello: tolerate older, refuse newer. Returns the
    header (unknown keys and all) for the caller to pick fields from."""
    gv = header.get("gv", 0)
    if isinstance(gv, (int, float)) and int(gv) > GATEWAY_SCHEMA_VERSION:
        raise GatewaySchemaError(
            f"peer gateway schema v{int(gv)} is newer than "
            f"v{GATEWAY_SCHEMA_VERSION}")
    return header


def hello_header(fid: int, credit: int, *, epoch: int = 0,
                 diff_epoch: int = 0) -> dict:
    return {"kind": "hello", "gv": GATEWAY_SCHEMA_VERSION,
            "frontend": int(fid), "credit": int(credit),
            "epoch": int(epoch), "diff_epoch": int(diff_epoch)}


# ------------------------------------------------------------- queries
def _q_header(fid: int, family: str, deadline_ms=None, epoch=None,
              diff_epoch=None, cid=None, resubmit=None) -> dict:
    h = {"kind": "q", "id": int(fid), "family": family,
         "gv": GATEWAY_SCHEMA_VERSION}
    if deadline_ms is not None:
        h["deadline_ms"] = float(deadline_ms)
    if epoch is not None:
        h["epoch"] = int(epoch)
    if diff_epoch is not None:
        h["diff_epoch"] = int(diff_epoch)
    if cid is not None:
        h["cid"] = str(cid)
    if resubmit:
        h["resubmit"] = True
    return h


def encode_pairs(fid: int, pairs, family: str = "pair",
                 **kw) -> tuple[dict, list]:
    """One batched pair/rev frame: ``pairs`` is anything ndarray-able
    to int64 ``[Q, 2]`` (s, t) rows."""
    arr = np.ascontiguousarray(np.asarray(pairs, dtype=np.int64))
    if arr.ndim != 2 or arr.shape[1] != 2:
        raise GatewayProtocolError(f"pairs must be [Q, 2] "
                                   f"(got shape {arr.shape})")
    return _q_header(fid, family, **kw), [arr]


def encode_mat(fid: int, s: int, targets, **kw) -> tuple[dict, list]:
    h = _q_header(fid, "mat", **kw)
    h["s"] = int(s)
    arr = np.ascontiguousarray(np.asarray(targets, dtype=np.int64))
    if arr.ndim != 1 or not len(arr):
        raise GatewayProtocolError("mat targets must be a non-empty "
                                   "1-D array")
    return h, [arr]


def encode_alt(fid: int, s: int, t: int, k: int, **kw) -> tuple[dict,
                                                                list]:
    h = _q_header(fid, "alt", **kw)
    h.update(s=int(s), t=int(t), k=int(k))
    return h, []


def parse_query_frame(fr: Frame):
    """``(family, payload)`` for one ``q`` frame — ``payload`` is the
    ``[Q, 2]`` pairs array (pair/rev), ``(s, targets)`` (mat), or
    ``(s, t, k)`` (alt). Unknown header keys ride along untouched;
    only a NEWER ``gv`` refuses. Raises :class:`GatewayProtocolError`
    on anything malformed — the server turns that into a typed ``err``
    frame, never a torn connection."""
    check_hello(fr.header)       # same gate: "gv" newer → refuse typed
    family = fr.header.get("family")
    if family not in FAMILIES:
        raise GatewayProtocolError(f"unknown family {family!r}")
    try:
        if family in ("pair", "rev"):
            if len(fr.arrays) != 1:
                raise GatewayProtocolError(
                    f"{family} frame wants 1 payload segment "
                    f"(got {len(fr.arrays)})")
            pairs = np.asarray(fr.arrays[0])
            if pairs.ndim != 2 or pairs.shape[1] != 2:
                raise GatewayProtocolError(
                    f"{family} payload must be [Q, 2] "
                    f"(got shape {pairs.shape})")
            return family, pairs.astype(np.int64, copy=False)
        if family == "mat":
            if len(fr.arrays) != 1:
                raise GatewayProtocolError(
                    f"mat frame wants 1 targets segment "
                    f"(got {len(fr.arrays)})")
            targets = np.asarray(fr.arrays[0]).reshape(-1)
            if not len(targets):
                raise GatewayProtocolError("mat frame with no targets")
            return family, (int(fr.header["s"]),
                            targets.astype(np.int64, copy=False))
        # alt: header-only
        return family, (int(fr.header["s"]), int(fr.header["t"]),
                        int(fr.header["k"]))
    except GatewayProtocolError:
        raise
    except (KeyError, TypeError, ValueError) as e:
        raise GatewayProtocolError(f"malformed {family} frame: {e}")


def frame_id(fr: Frame) -> int:
    """The correlation id, or −1 when the frame has none (the typed
    ``err`` answer to an id-less frame still correlates as 'not any
    in-flight request')."""
    fid = fr.header.get("id", -1)
    return int(fid) if isinstance(fid, (int, float)) else -1


def frame_cid(fr: Frame) -> str | None:
    """The client identity token, or ``None`` when the frame carries
    none (pre-HA clients) — dedup only engages for tokened frames."""
    cid = fr.header.get("cid")
    return cid if isinstance(cid, str) and cid else None


# -------------------------------------------------------------- replies
def _r_header(fid: int, family: str, *, frontend: int, epoch: int,
              diff_epoch: int) -> dict:
    return {"kind": "r", "id": int(fid), "family": family,
            "gv": GATEWAY_SCHEMA_VERSION, "frontend": int(frontend),
            "epoch": int(epoch), "diff_epoch": int(diff_epoch)}


def reply_pairs(fid: int, family: str, results, **ident) -> tuple[dict,
                                                                  list]:
    """``results`` is the in-order list of per-row
    :class:`~..serving.request.ServeResult`."""
    h = _r_header(fid, family, **ident)
    h["status"] = [r.status for r in results]
    h["detail"] = [r.detail for r in results]
    h["cached"] = [bool(r.cached) for r in results]
    cost = np.asarray([int(r.cost) for r in results], np.int64)
    plen = np.asarray([int(r.plen) for r in results], np.int64)
    fin = np.asarray([bool(r.finished) for r in results], np.uint8)
    return h, [cost, plen, fin]


def reply_mat(fid: int, s: int, costs, **ident) -> tuple[dict, list]:
    h = _r_header(fid, "mat", **ident)
    h["s"] = int(s)
    return h, [np.asarray(costs, np.int64)]


def reply_alt(fid: int, s: int, t: int, alternatives,
              **ident) -> tuple[dict, list]:
    """``alternatives`` is the ascending ``[(cost, via), ...]`` list of
    :class:`~..traffic.families.AltResult`."""
    h = _r_header(fid, "alt", **ident)
    h.update(s=int(s), t=int(t))
    costs = np.asarray([int(c) for c, _v in alternatives], np.int64)
    vias = np.asarray([int(v) for _c, v in alternatives], np.int64)
    return h, [costs, vias]


def reply_shed(fid: int, family: str, status: str, detail: str,
               **ident) -> tuple[dict, list]:
    """A whole-frame terminal status (family shed by the brownout
    ladder, or a family future that errored): no payload rows, the
    ``status`` field carries the single frame-level verdict."""
    h = _r_header(fid, family, **ident)
    h["status"] = str(status)
    h["detail"] = str(detail)
    return h, []


def busy_frame(fid: int, **ident) -> tuple[dict, list]:
    h = _r_header(fid, "busy", **ident)
    h["kind"] = "busy"
    return h, []


def error_frame(fid: int, detail: str, **ident) -> tuple[dict, list]:
    h = _r_header(fid, "err", **ident)
    h["kind"] = "err"
    h["error"] = str(detail)
    return h, []
