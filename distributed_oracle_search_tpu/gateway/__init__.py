"""Gateway tier: the head scaled horizontally, facing clients.

Three pieces (ROADMAP item 2):

* :mod:`.protocol` — the client-facing binary frame vocabulary over
  the shared :mod:`..transport.frames` container: multiplexed batched
  query frames for every family, credit-window backpressure with an
  explicit ``busy``, hello negotiation under tolerate-older/gate-newer.
* :mod:`.server` — :class:`GatewayServer`, one stateless frontend
  replica's accept loop, and :class:`GatewayTier`, N of them sharing
  nothing but ``membership.json`` and the diff-epoch spool.
* :mod:`.client` — :class:`DosClient`, the library callers link.
* :mod:`.registry` — the leased endpoint registry (``gateway.json``):
  durable tier membership with heartbeat-renewed TTL leases, so
  replicas span processes, clients discover and fail over, and the
  control loop sees death without a crash signal.

The two-level cache plane rides alongside: each replica's
:class:`~..serving.cache.ResultCache` is a small L1, and workers keep
hot ``(s, t, diff-epoch)`` entries as a shard-owner L2
(``DOS_GATEWAY_L2_BYTES``, see :mod:`..worker.server`) answered before
the kernel — capacity scales with the fleet, and scoped invalidation
runs local to the shard that owns the updated edges.
"""

from .client import DosClient, GatewayBusy, GatewayError
from .config import GatewayConfig
from .protocol import (GATEWAY_SCHEMA_VERSION, GatewayProtocolError,
                       GatewaySchemaError)
from .registry import (GATEWAY_REGISTRY_VERSION, GatewayLease,
                       GatewayRegistry, GatewayRegistrySchemaError,
                       RegistryState, live_endpoints, load_registry,
                       save_registry)
from .server import GatewayServer, GatewayTier

__all__ = [
    "DosClient", "GatewayBusy", "GatewayError", "GatewayConfig",
    "GATEWAY_SCHEMA_VERSION", "GatewayProtocolError",
    "GatewaySchemaError", "GatewayServer", "GatewayTier",
    "GATEWAY_REGISTRY_VERSION", "GatewayLease", "GatewayRegistry",
    "GatewayRegistrySchemaError", "RegistryState", "live_endpoints",
    "load_registry", "save_registry",
]
