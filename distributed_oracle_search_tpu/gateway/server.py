"""The gateway accept loop and the N-replica tier runner.

One :class:`GatewayServer` is one stateless frontend replica facing
clients: a unix-socket accept loop speaking the
:mod:`.protocol` frame vocabulary over the shared
:mod:`..transport.frames` container, in front of ONE
:class:`~..serving.ServingFrontend` (admission, micro-batching,
hedging, breakers, L1 cache — the whole existing head stack). Replicas
share nothing but ``membership.json`` and the diff-epoch spool, both
already safe for concurrent readers, so :class:`GatewayTier` scales the
head horizontally by just running more of them.

Connection protocol: the gateway sends a ``hello`` advertising its
schema version, replica identity, and per-connection credit window.
Query frames past the window answer an explicit ``busy``; malformed
frames answer a typed ``err`` (never a torn connection) and book
``gateway_frames_malformed_total``. Replies drain through one writer
thread per connection in frame-arrival order — the frame ``id`` is the
multiplexing correlate, in-order completion just keeps the writer
trivially serial.

High availability (PR 19): given a :class:`~.registry.GatewayRegistry`
the server registers its endpoint on start, renews the lease on a
heartbeat thread (a third of ``DOS_GATEWAY_LEASE_S``; the
``lease-freeze`` fault point makes a zombie), and unregisters on a
GRACEFUL stop only — an abrupt death leaves the lease to expire, which
is the detection signal. Replies to ``cid``-tokened query frames are
memoized per ``(cid, id)`` in a bounded ring: a failover client's
resubmission of an already-answered frame replays the stored reply and
books ``gateway_resubmits_deduped_total`` instead of double-booking
requests/queries/caches (exactly-once accounting). A CLEAN client
disconnect (EOF after every reply flushed) proves the client saw its
answers, so that connection's ``cid`` entries are purged from the memo
— only crashed clients (torn frames, reset sockets) leave replay state
behind, which keeps memo occupancy proportional to failures instead of
total traffic. The ``blackhole-conn`` fault point turns one connection
half-open — accepted, read, never answered — the asymmetric-partition
drill.
"""

from __future__ import annotations

import collections
import os
import socket
import threading
import queue
import time

from . import protocol
from .config import GatewayConfig
from ..obs import metrics as obs_metrics
from ..obs import recorder as obs_recorder
from ..testing import faults
from ..transport.frames import (FrameReader, FrameWriter, TornFrame,
                                TransportError)
from ..utils.locks import OrderedLock
from ..utils.log import get_logger

log = get_logger(__name__)

#: bounded reply memo per frontend: (cid, id) -> reply. Sized for many
#: full credit windows of history — a resubmission races the original
#: by seconds, not hours, so recency is the right eviction
DEDUP_MEMO_ENTRIES = 4096

M_REQS = obs_metrics.counter(
    "gateway_requests_total",
    "query frames admitted past the credit window")
M_QUERIES = obs_metrics.counter(
    "gateway_queries_total",
    "individual queries across batched gateway frames")
M_BUSY = obs_metrics.counter(
    "gateway_busy_total",
    "query frames answered BUSY at the credit window")
M_MALFORMED = obs_metrics.counter(
    "gateway_frames_malformed_total",
    "client frames answered a typed err frame (malformed family, bad "
    "payload, or newer schema) — never a torn connection")
G_CLIENTS = obs_metrics.gauge(
    "gateway_clients", "live client connections across local replicas")
M_DEDUP = obs_metrics.counter(
    "gateway_resubmits_deduped_total",
    "resubmitted query frames answered from the (cid, id) reply memo — "
    "counters and cache inserts not double-booked (exactly-once "
    "accounting over at-least-once execution)")
M_FAILOVER_FRAMES = obs_metrics.counter(
    "gateway_failover_frames_total",
    "resubmitted query frames this frontend had NOT answered before — "
    "a client failed over here mid-flight and the frame re-executed")


class GatewayServer:
    """One replica's client-facing accept loop (see module docstring)."""

    def __init__(self, frontend, families=None, fid: int = 0,
                 gconf: GatewayConfig | None = None,
                 socket_path: str | None = None, registry=None):
        self.frontend = frontend
        self.families = families
        self.fid = int(fid)
        self.gconf = gconf or GatewayConfig.from_env()
        self.socket_path = socket_path or self.gconf.socket_of(self.fid)
        self.registry = registry
        self._sock: socket.socket | None = None
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._conns: list[socket.socket] = []
        self._accept_thread: threading.Thread | None = None
        self._hb_thread: threading.Thread | None = None
        self._lease_frozen = False
        self._lease_renewed = 0.0
        # reply memo for resubmission dedup: (cid, id) -> (header,
        # arrays), bounded LRU-by-insertion
        self._dedup: collections.OrderedDict = collections.OrderedDict()
        self._dedup_lock = OrderedLock("gateway.GatewayServer.dedup")
        # plain tallies mutated under the GIL by the conn threads —
        # approximate reads in statusz are fine
        self.clients = 0
        self.served = 0
        self.busy = 0
        self.malformed = 0
        self.failovers = 0
        self.deduped = 0

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "GatewayServer":
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.bind(self.socket_path)
        sock.listen(128)
        sock.settimeout(0.25)
        self._sock = sock
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"gateway-f{self.fid}-accept")
        self._accept_thread.start()
        if self.registry is not None:
            self.registry.register(self.fid, self.socket_path)
            self._lease_renewed = time.time()
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop, daemon=True,
                name=f"gateway-f{self.fid}-lease")
            self._hb_thread.start()
        obs_recorder.emit("gateway_up", frontend=self.fid,
                          endpoint=self.socket_path,
                          credit=self.gconf.credit)
        log.info("gateway frontend %d serving on %s (credit %d)",
                 self.fid, self.socket_path, self.gconf.credit)
        return self

    def stop(self, join_s: float = 5.0, graceful: bool = True) -> None:
        """Drain and stop. ``graceful=False`` is the chaos drills'
        process-death stand-in: the endpoint lease is NOT unregistered,
        so readers watch it expire — exactly what a crashed frontend
        looks like from outside."""
        self._stop.set()
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=join_s)
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=join_s)
            self._hb_thread = None
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        # sever established connections so blocked conn readers wake:
        # a crash (graceful=False) tears both directions — clients see
        # the socket die mid-conversation, exactly like a dead process;
        # a drain only shuts the READ side, so replies already queued
        # still flush before each conn loop closes its socket
        how = socket.SHUT_RD if graceful else socket.SHUT_RDWR
        for conn in list(self._conns):
            try:
                conn.shutdown(how)
            except OSError:
                pass
        for th in list(self._threads):
            th.join(timeout=join_s)
        if os.path.exists(self.socket_path):
            try:
                os.unlink(self.socket_path)
            except OSError:
                pass
        if graceful and self.registry is not None:
            try:
                self.registry.unregister(self.fid, self.socket_path)
            except (OSError, ValueError) as e:
                log.warning("gateway f%d unregister failed: %s",
                            self.fid, e)
        obs_recorder.emit("gateway_down", frontend=self.fid,
                          endpoint=self.socket_path, served=self.served,
                          graceful=bool(graceful))

    def _heartbeat_loop(self) -> None:
        interval = max(0.05, float(self.registry.lease_s) / 3.0)
        while not self._stop.wait(interval):
            if self._lease_frozen:
                continue
            if faults.inject("lease-freeze", wid=self.fid) is not None:
                # the zombie case: alive and serving, silent in the
                # registry — sticky for the rest of this server's life
                self._lease_frozen = True
                log.warning("gateway f%d lease renewals frozen (fault)",
                            self.fid)
                continue
            try:
                if not self.registry.renew(self.fid, self.socket_path):
                    # our row vanished (registry reset/sweep): reclaim
                    self.registry.register(self.fid, self.socket_path)
                self._lease_renewed = time.time()
            except Exception as e:  # noqa: BLE001 — a wedged registry
                # write must not kill serving; the lease just goes
                # stale and the control loop's sensor notices
                log.warning("gateway f%d lease renewal failed: %s",
                            self.fid, e)

    # ------------------------------------------------------------- serve
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            th = threading.Thread(
                target=self._conn_loop, args=(conn,), daemon=True,
                name=f"gateway-f{self.fid}-conn")
            th.start()
            self._threads.append(th)
            self._conns.append(conn)
            self._threads = [t for t in self._threads if t.is_alive()]
            self._conns = [c for c in self._conns if c.fileno() != -1]

    def _ident(self) -> dict:
        fe = self.frontend
        try:
            epoch = int(fe._membership_epoch())
        except Exception as e:  # noqa: BLE001 — identity is advisory
            log.debug("gateway f%d: membership epoch unreadable: %s",
                      self.fid, e)
            epoch = 0
        return {"frontend": self.fid, "epoch": epoch,
                "diff_epoch": int(getattr(fe, "_diff_epoch", 0))}

    def _conn_loop(self, conn: socket.socket) -> None:
        conn.settimeout(None)
        reader, writer = FrameReader(conn), FrameWriter(conn)
        pending: queue.Queue = queue.Queue()
        inflight = [0]   # mutated by reader, decremented by writer
        conn_state = {"blackholed": False, "cids": set(),
                      "clean_eof": False}
        wt = threading.Thread(
            target=self._writer_loop, args=(writer, pending, inflight),
            daemon=True, name=f"gateway-f{self.fid}-writer")
        self.clients += 1
        G_CLIENTS.add(1)
        try:
            writer.send(protocol.hello_header(
                self.fid, self.gconf.credit,
                **{k: v for k, v in self._ident().items()
                   if k != "frontend"}))
            wt.start()
            while not self._stop.is_set():
                try:
                    fr = reader.read()
                except TornFrame:
                    break        # client died mid-frame; nothing to
                    # answer — the typed-err contract covers frames
                    # that ARRIVED malformed, not half-sent ones
                if fr is None:
                    # clean EOF: the client closed AFTER reading its
                    # replies — its resubmission window is over, so its
                    # memo entries are purged below (crash paths — torn
                    # frames, reset sockets — keep theirs for failover)
                    conn_state["clean_eof"] = True
                    break
                if not self._serve_frame(fr, pending, inflight,
                                         conn_state):
                    break
        except (TransportError, OSError) as e:
            log.debug("gateway f%d connection dropped: %s", self.fid, e)
        finally:
            pending.put(None)
            if wt.is_alive():
                wt.join(timeout=5.0)
            try:
                conn.close()
            except OSError:
                pass
            if conn_state["clean_eof"]:
                # after the writer joined, so replies memoized during
                # the drain are purged too — nothing leaks back in
                self._dedup_purge(conn_state["cids"])
            self.clients -= 1
            G_CLIENTS.add(-1)

    def _writer_loop(self, writer: FrameWriter, pending: queue.Queue,
                     inflight: list) -> None:
        while True:
            item = pending.get()
            if item is None:
                return
            waiter, is_q, dedup_key = item
            try:
                header, arrays = waiter()
            except Exception as e:  # noqa: BLE001 — one bad frame must
                # not wedge the writer; answer it typed and move on
                log.warning("gateway f%d reply build failed: %s",
                            self.fid, e)
                header, arrays = protocol.error_frame(
                    -1, f"internal: {e}", **self._ident())
            if dedup_key is not None and header.get("kind") == "r":
                # memoize BEFORE the send: a client that dies mid-reply
                # resubmits, and the replay must cover exactly the
                # frames whose accounting already booked
                self._dedup_put(dedup_key, (header, arrays))
            try:
                writer.send(header, arrays)
            except (TransportError, OSError):
                return           # client is gone; reader will see EOF
            finally:
                if is_q:
                    inflight[0] -= 1
                    self.served += 1

    def _dedup_put(self, key, reply) -> None:
        with self._dedup_lock:
            self._dedup[key] = reply
            self._dedup.move_to_end(key)
            while len(self._dedup) > DEDUP_MEMO_ENTRIES:
                self._dedup.popitem(last=False)

    def _dedup_get(self, key):
        with self._dedup_lock:
            return self._dedup.get(key)

    def _dedup_purge(self, cids) -> None:
        """Drop every memo entry belonging to ``cids`` (a cleanly
        disconnected client cannot resubmit, so its replay state is
        dead weight crowding the bounded ring)."""
        if not cids:
            return
        with self._dedup_lock:
            stale = [k for k in self._dedup if k[0] in cids]
            for k in stale:
                del self._dedup[k]

    def _serve_frame(self, fr, pending: queue.Queue, inflight: list,
                     conn_state: dict) -> bool:
        """Dispatch one client frame; False ends the connection (only
        the schema gate does — malformed frames answer typed)."""
        if conn_state["blackholed"] or faults.inject(
                "blackhole-conn", wid=self.fid) is not None:
            # half-open partition: the socket stays accepted and
            # readable (the client's sends succeed) but nothing is
            # served or answered, sticky for the connection's life —
            # the client only learns via its own deadline + failover
            conn_state["blackholed"] = True
            return True
        ident = self._ident()
        if fr.kind == "hello":
            try:
                protocol.check_hello(fr.header)
            except protocol.GatewaySchemaError as e:
                M_MALFORMED.inc()
                self.malformed += 1
                detail = str(e)
                fid = protocol.frame_id(fr)
                pending.put((lambda: protocol.error_frame(
                    fid, detail, **ident), False, None))
                return False     # gate-newer: refuse service cleanly
            return True
        if fr.kind == "ping":
            h = dict(ident)
            h.update(kind="health", id=protocol.frame_id(fr),
                     ok=True, clients=self.clients, served=self.served)
            pending.put((lambda: (h, []), False, None))
            return True
        if fr.kind != "q":
            # unknown kinds are the receiver's to skip (the container
            # contract) — an older gateway ignores a newer client's
            # optional extras rather than erroring them
            log.debug("gateway f%d skipping unknown frame kind %r",
                      self.fid, fr.kind)
            return True
        fid = protocol.frame_id(fr)
        cid = protocol.frame_cid(fr)
        dedup_key = (cid, fid) if cid is not None else None
        if cid is not None:
            conn_state["cids"].add(cid)
        if dedup_key is not None:
            replay = self._dedup_get(dedup_key)
            if replay is not None:
                # already answered this logical request: replay the
                # memoized reply — no request/query counters, no
                # frontend submit, no cache inserts (exactly-once
                # accounting; the client just never saw the answer)
                M_DEDUP.inc()
                self.deduped += 1
                pending.put((lambda r=replay: r, False, None))
                return True
            if fr.header.get("resubmit"):
                # a failover arrival this frontend never answered:
                # executes normally (answers are deterministic), but
                # book the failover so the tier's HA columns show it
                M_FAILOVER_FRAMES.inc()
                self.failovers += 1
        if inflight[0] >= self.gconf.credit:
            M_BUSY.inc()
            self.busy += 1
            pending.put((lambda: protocol.busy_frame(fid, **ident),
                         False, None))
            return True
        try:
            family, payload = protocol.parse_query_frame(fr)
        except protocol.GatewayProtocolError as e:
            M_MALFORMED.inc()
            self.malformed += 1
            detail = str(e)
            pending.put((lambda: protocol.error_frame(
                fid, detail, **ident), False, None))
            return True
        M_REQS.inc()
        inflight[0] += 1
        deadline_s = self._deadline_s(fr.header)
        pending.put((self._submit(fid, family, payload, deadline_s),
                     True, dedup_key))
        return True

    def _deadline_s(self, header: dict) -> float:
        dl = header.get("deadline_ms")
        if isinstance(dl, (int, float)) and dl > 0:
            return min(float(dl), self.gconf.deadline_ms) / 1e3
        return self.gconf.deadline_s

    # ------------------------------------------------------- family plumb
    def _submit(self, fid: int, family: str, payload, deadline_s: float):
        """Submit NOW (on the reader thread — admission and routing are
        non-blocking), return the waiter the writer thread blocks on."""
        ident = self._ident()
        if family == "pair":
            M_QUERIES.inc(len(payload))
            futs = [self.frontend.submit(int(s), int(t))
                    for s, t in payload]
            pairs = [(int(s), int(t)) for s, t in payload]

            def wait_pairs():
                rows = _drain(futs, pairs, deadline_s)
                return protocol.reply_pairs(fid, "pair", rows, **ident)

            return wait_pairs
        # the typed families ride QueryFamilies.submit_line so they
        # inherit the brownout shed exactly like the line protocol
        fam = self.families
        if fam is None:
            def no_families():
                return protocol.reply_shed(
                    fid, family, "ERROR", "family-not-served", **ident)
            return no_families
        if family == "rev":
            M_QUERIES.inc(len(payload))
            futs, pairs = [], []
            for s, t in payload:
                futs.append(fam.submit_line("rev", (int(s), int(t))))
                pairs.append((int(s), int(t)))

            def wait_rev():
                rows = _drain_rev(futs, pairs, deadline_s)
                return protocol.reply_pairs(fid, "rev", rows, **ident)

            return wait_rev
        if family == "mat":
            s, targets = payload
            M_QUERIES.inc(len(targets))
            fut = fam.submit_line("mat", (int(s), [int(t)
                                                   for t in targets]))

            def wait_mat():
                res = _family_result(fut, deadline_s)
                if not hasattr(res, "costs"):   # shed/errored
                    return protocol.reply_shed(
                        fid, "mat", getattr(res, "status", "ERROR"),
                        getattr(res, "detail", ""), **ident)
                return protocol.reply_mat(fid, s, res.costs, **ident)

            return wait_mat
        # alt
        s, t, k = payload
        M_QUERIES.inc()
        fut = fam.submit_line("alt", (int(s), int(t), int(k)))

        def wait_alt():
            res = _family_result(fut, deadline_s)
            if not hasattr(res, "alternatives"):
                return protocol.reply_shed(
                    fid, "alt", getattr(res, "status", "ERROR"),
                    getattr(res, "detail", ""), **ident)
            return protocol.reply_alt(fid, s, t, res.alternatives,
                                      **ident)

        return wait_alt

    # --------------------------------------------------------------- obs
    def statusz(self) -> dict:
        fe_cache = getattr(self.frontend, "cache", None)
        out = {
            "frontend": self.fid,
            "endpoint": self.socket_path,
            "credit": self.gconf.credit,
            "clients": int(self.clients),
            "served": int(self.served),
            "busy": int(self.busy),
            "malformed": int(self.malformed),
            "failovers": int(self.failovers),
            "resubmits_deduped": int(self.deduped),
            "memo": {"entries": len(self._dedup),
                     "cap": DEDUP_MEMO_ENTRIES},
        }
        if self.registry is not None:
            out["lease"] = {
                "lease_s": float(self.registry.lease_s),
                "age_s": round(max(0.0, time.time()
                                   - self._lease_renewed), 3),
                "frozen": bool(self._lease_frozen),
            }
        if fe_cache is not None:
            out["l1_hits"] = int(fe_cache.hits)
            out["l1_misses"] = int(fe_cache.misses)
            out["l1_hit_rate"] = round(fe_cache.hit_rate(), 4)
        return out


def _drain(futs, pairs, deadline_s: float):
    """In-order pair results with ONE deadline budgeted across the
    frame (a stuck shard costs the frame one deadline, not one per
    row) — TimeoutError rows degrade to typed TIMEOUT results."""
    from ..serving.request import TIMEOUT, ServeResult

    end = time.monotonic() + deadline_s
    rows = []
    for fut, (s, t) in zip(futs, pairs):
        try:
            rows.append(fut.result(max(0.0, end - time.monotonic())))
        except TimeoutError:
            rows.append(ServeResult(TIMEOUT, s, t,
                                    detail="gateway-deadline"))
    return rows


def _drain_rev(futs, pairs, deadline_s: float):
    """Rev rows: unwrap each CompositeFuture's ReverseResult back to
    the underlying pair ServeResult (labeled with the ORIGINAL s, t the
    client asked about, like the REV sentence)."""
    from ..serving.request import TIMEOUT, ServeResult

    end = time.monotonic() + deadline_s
    rows = []
    for fut, (s, t) in zip(futs, pairs):
        try:
            res = fut.result(max(0.0, end - time.monotonic()))
        except TimeoutError:
            rows.append(ServeResult(TIMEOUT, s, t,
                                    detail="gateway-deadline"))
            continue
        inner = getattr(res, "result", res)   # ReverseResult | shed
        rows.append(ServeResult(
            inner.status, s, t, cost=int(inner.cost),
            plen=int(inner.plen), finished=bool(inner.finished),
            cached=bool(inner.cached), detail=inner.detail))
    return rows


def _family_result(fut, deadline_s: float):
    from ..serving.request import TIMEOUT, ServeResult

    try:
        return fut.result(deadline_s)
    except TimeoutError:
        return ServeResult(TIMEOUT, -1, -1, detail="gateway-deadline")


class GatewayTier:
    """N replicas under one roof: builds a :class:`GatewayServer` per
    ``(frontend, families)`` pair and aggregates their ``/statusz``
    into the ``gateway`` section ``dos-obs top`` renders. Replicas are
    independent — one replica's death leaves the others serving (the
    kill-one-frontend drill pins this)."""

    def __init__(self, replicas, gconf: GatewayConfig | None = None,
                 socket_paths=None, registry=None, fid_base: int = 0):
        self.gconf = gconf or GatewayConfig.from_env()
        self.registry = registry
        self.servers: list[GatewayServer] = []
        for i, (frontend, families) in enumerate(replicas):
            fid = int(fid_base) + i
            path = (socket_paths[i] if socket_paths is not None
                    else self.gconf.socket_of(fid))
            self.servers.append(GatewayServer(
                frontend, families=families, fid=fid, gconf=self.gconf,
                socket_path=path, registry=registry))

    @property
    def endpoints(self) -> list:
        return [srv.socket_path for srv in self.servers]

    def start(self) -> "GatewayTier":
        for srv in self.servers:
            srv.start()
        return self

    def stop(self, join_s: float = 5.0) -> None:
        for srv in self.servers:
            srv.stop(join_s=join_s)

    def statusz(self) -> dict:
        fes = {str(srv.fid): srv.statusz() for srv in self.servers}
        hits = sum(int(st.get("l1_hits", 0)) for st in fes.values())
        misses = sum(int(st.get("l1_misses", 0)) for st in fes.values())
        total = hits + misses
        out = {
            "replicas": len(self.servers),
            "clients": sum(int(st.get("clients", 0))
                           for st in fes.values()),
            "l1_hit_rate": round(hits / total, 4) if total else 0.0,
            "failovers": sum(int(st.get("failovers", 0))
                             for st in fes.values()),
            "resubmits_deduped": sum(
                int(st.get("resubmits_deduped", 0))
                for st in fes.values()),
            "frontends": fes,
        }
        if self.registry is not None:
            try:
                # peers counts the whole fleet (every --join process),
                # not just this process's replicas
                out["peers"] = len(self.registry.live())
            except Exception as e:  # noqa: BLE001 — status is advisory
                log.debug("gateway tier: registry read failed: %s", e)
            ages = [st["lease"]["age_s"] for st in fes.values()
                    if isinstance(st.get("lease"), dict)]
            if ages:
                out["lease_age_s"] = max(ages)
        return out
