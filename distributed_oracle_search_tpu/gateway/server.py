"""The gateway accept loop and the N-replica tier runner.

One :class:`GatewayServer` is one stateless frontend replica facing
clients: a unix-socket accept loop speaking the
:mod:`.protocol` frame vocabulary over the shared
:mod:`..transport.frames` container, in front of ONE
:class:`~..serving.ServingFrontend` (admission, micro-batching,
hedging, breakers, L1 cache — the whole existing head stack). Replicas
share nothing but ``membership.json`` and the diff-epoch spool, both
already safe for concurrent readers, so :class:`GatewayTier` scales the
head horizontally by just running more of them.

Connection protocol: the gateway sends a ``hello`` advertising its
schema version, replica identity, and per-connection credit window.
Query frames past the window answer an explicit ``busy``; malformed
frames answer a typed ``err`` (never a torn connection) and book
``gateway_frames_malformed_total``. Replies drain through one writer
thread per connection in frame-arrival order — the frame ``id`` is the
multiplexing correlate, in-order completion just keeps the writer
trivially serial.
"""

from __future__ import annotations

import os
import socket
import threading
import queue
import time

from . import protocol
from .config import GatewayConfig
from ..obs import metrics as obs_metrics
from ..obs import recorder as obs_recorder
from ..transport.frames import (FrameReader, FrameWriter, TornFrame,
                                TransportError)
from ..utils.log import get_logger

log = get_logger(__name__)

M_REQS = obs_metrics.counter(
    "gateway_requests_total",
    "query frames admitted past the credit window")
M_QUERIES = obs_metrics.counter(
    "gateway_queries_total",
    "individual queries across batched gateway frames")
M_BUSY = obs_metrics.counter(
    "gateway_busy_total",
    "query frames answered BUSY at the credit window")
M_MALFORMED = obs_metrics.counter(
    "gateway_frames_malformed_total",
    "client frames answered a typed err frame (malformed family, bad "
    "payload, or newer schema) — never a torn connection")
G_CLIENTS = obs_metrics.gauge(
    "gateway_clients", "live client connections across local replicas")


class GatewayServer:
    """One replica's client-facing accept loop (see module docstring)."""

    def __init__(self, frontend, families=None, fid: int = 0,
                 gconf: GatewayConfig | None = None,
                 socket_path: str | None = None):
        self.frontend = frontend
        self.families = families
        self.fid = int(fid)
        self.gconf = gconf or GatewayConfig.from_env()
        self.socket_path = socket_path or self.gconf.socket_of(self.fid)
        self._sock: socket.socket | None = None
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._accept_thread: threading.Thread | None = None
        # plain tallies mutated under the GIL by the conn threads —
        # approximate reads in statusz are fine
        self.clients = 0
        self.served = 0
        self.busy = 0
        self.malformed = 0

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "GatewayServer":
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.bind(self.socket_path)
        sock.listen(128)
        sock.settimeout(0.25)
        self._sock = sock
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"gateway-f{self.fid}-accept")
        self._accept_thread.start()
        obs_recorder.emit("gateway_up", frontend=self.fid,
                          endpoint=self.socket_path,
                          credit=self.gconf.credit)
        log.info("gateway frontend %d serving on %s (credit %d)",
                 self.fid, self.socket_path, self.gconf.credit)
        return self

    def stop(self, join_s: float = 5.0) -> None:
        self._stop.set()
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=join_s)
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        for th in list(self._threads):
            th.join(timeout=join_s)
        if os.path.exists(self.socket_path):
            try:
                os.unlink(self.socket_path)
            except OSError:
                pass
        obs_recorder.emit("gateway_down", frontend=self.fid,
                          endpoint=self.socket_path, served=self.served)

    # ------------------------------------------------------------- serve
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            th = threading.Thread(
                target=self._conn_loop, args=(conn,), daemon=True,
                name=f"gateway-f{self.fid}-conn")
            th.start()
            self._threads.append(th)
            self._threads = [t for t in self._threads if t.is_alive()]

    def _ident(self) -> dict:
        fe = self.frontend
        try:
            epoch = int(fe._membership_epoch())
        except Exception as e:  # noqa: BLE001 — identity is advisory
            log.debug("gateway f%d: membership epoch unreadable: %s",
                      self.fid, e)
            epoch = 0
        return {"frontend": self.fid, "epoch": epoch,
                "diff_epoch": int(getattr(fe, "_diff_epoch", 0))}

    def _conn_loop(self, conn: socket.socket) -> None:
        conn.settimeout(None)
        reader, writer = FrameReader(conn), FrameWriter(conn)
        pending: queue.Queue = queue.Queue()
        inflight = [0]   # mutated by reader, decremented by writer
        wt = threading.Thread(
            target=self._writer_loop, args=(writer, pending, inflight),
            daemon=True, name=f"gateway-f{self.fid}-writer")
        self.clients += 1
        G_CLIENTS.add(1)
        try:
            writer.send(protocol.hello_header(
                self.fid, self.gconf.credit,
                **{k: v for k, v in self._ident().items()
                   if k != "frontend"}))
            wt.start()
            while not self._stop.is_set():
                try:
                    fr = reader.read()
                except TornFrame:
                    break        # client died mid-frame; nothing to
                    # answer — the typed-err contract covers frames
                    # that ARRIVED malformed, not half-sent ones
                if fr is None:
                    break        # clean EOF
                if not self._serve_frame(fr, pending, inflight):
                    break
        except (TransportError, OSError) as e:
            log.debug("gateway f%d connection dropped: %s", self.fid, e)
        finally:
            pending.put(None)
            if wt.is_alive():
                wt.join(timeout=5.0)
            try:
                conn.close()
            except OSError:
                pass
            self.clients -= 1
            G_CLIENTS.add(-1)

    def _writer_loop(self, writer: FrameWriter, pending: queue.Queue,
                     inflight: list) -> None:
        while True:
            item = pending.get()
            if item is None:
                return
            waiter, is_q = item
            try:
                header, arrays = waiter()
            except Exception as e:  # noqa: BLE001 — one bad frame must
                # not wedge the writer; answer it typed and move on
                log.warning("gateway f%d reply build failed: %s",
                            self.fid, e)
                header, arrays = protocol.error_frame(
                    -1, f"internal: {e}", **self._ident())
            try:
                writer.send(header, arrays)
            except (TransportError, OSError):
                return           # client is gone; reader will see EOF
            finally:
                if is_q:
                    inflight[0] -= 1
                    self.served += 1

    def _serve_frame(self, fr, pending: queue.Queue,
                     inflight: list) -> bool:
        """Dispatch one client frame; False ends the connection (only
        the schema gate does — malformed frames answer typed)."""
        ident = self._ident()
        if fr.kind == "hello":
            try:
                protocol.check_hello(fr.header)
            except protocol.GatewaySchemaError as e:
                M_MALFORMED.inc()
                self.malformed += 1
                detail = str(e)
                fid = protocol.frame_id(fr)
                pending.put((lambda: protocol.error_frame(
                    fid, detail, **ident), False))
                return False     # gate-newer: refuse service cleanly
            return True
        if fr.kind == "ping":
            h = dict(ident)
            h.update(kind="health", id=protocol.frame_id(fr),
                     ok=True, clients=self.clients, served=self.served)
            pending.put((lambda: (h, []), False))
            return True
        if fr.kind != "q":
            # unknown kinds are the receiver's to skip (the container
            # contract) — an older gateway ignores a newer client's
            # optional extras rather than erroring them
            log.debug("gateway f%d skipping unknown frame kind %r",
                      self.fid, fr.kind)
            return True
        fid = protocol.frame_id(fr)
        if inflight[0] >= self.gconf.credit:
            M_BUSY.inc()
            self.busy += 1
            pending.put((lambda: protocol.busy_frame(fid, **ident),
                         False))
            return True
        try:
            family, payload = protocol.parse_query_frame(fr)
        except protocol.GatewayProtocolError as e:
            M_MALFORMED.inc()
            self.malformed += 1
            detail = str(e)
            pending.put((lambda: protocol.error_frame(
                fid, detail, **ident), False))
            return True
        M_REQS.inc()
        inflight[0] += 1
        deadline_s = self._deadline_s(fr.header)
        pending.put((self._submit(fid, family, payload, deadline_s),
                     True))
        return True

    def _deadline_s(self, header: dict) -> float:
        dl = header.get("deadline_ms")
        if isinstance(dl, (int, float)) and dl > 0:
            return min(float(dl), self.gconf.deadline_ms) / 1e3
        return self.gconf.deadline_s

    # ------------------------------------------------------- family plumb
    def _submit(self, fid: int, family: str, payload, deadline_s: float):
        """Submit NOW (on the reader thread — admission and routing are
        non-blocking), return the waiter the writer thread blocks on."""
        ident = self._ident()
        if family == "pair":
            M_QUERIES.inc(len(payload))
            futs = [self.frontend.submit(int(s), int(t))
                    for s, t in payload]
            pairs = [(int(s), int(t)) for s, t in payload]

            def wait_pairs():
                rows = _drain(futs, pairs, deadline_s)
                return protocol.reply_pairs(fid, "pair", rows, **ident)

            return wait_pairs
        # the typed families ride QueryFamilies.submit_line so they
        # inherit the brownout shed exactly like the line protocol
        fam = self.families
        if fam is None:
            def no_families():
                return protocol.reply_shed(
                    fid, family, "ERROR", "family-not-served", **ident)
            return no_families
        if family == "rev":
            M_QUERIES.inc(len(payload))
            futs, pairs = [], []
            for s, t in payload:
                futs.append(fam.submit_line("rev", (int(s), int(t))))
                pairs.append((int(s), int(t)))

            def wait_rev():
                rows = _drain_rev(futs, pairs, deadline_s)
                return protocol.reply_pairs(fid, "rev", rows, **ident)

            return wait_rev
        if family == "mat":
            s, targets = payload
            M_QUERIES.inc(len(targets))
            fut = fam.submit_line("mat", (int(s), [int(t)
                                                   for t in targets]))

            def wait_mat():
                res = _family_result(fut, deadline_s)
                if not hasattr(res, "costs"):   # shed/errored
                    return protocol.reply_shed(
                        fid, "mat", getattr(res, "status", "ERROR"),
                        getattr(res, "detail", ""), **ident)
                return protocol.reply_mat(fid, s, res.costs, **ident)

            return wait_mat
        # alt
        s, t, k = payload
        M_QUERIES.inc()
        fut = fam.submit_line("alt", (int(s), int(t), int(k)))

        def wait_alt():
            res = _family_result(fut, deadline_s)
            if not hasattr(res, "alternatives"):
                return protocol.reply_shed(
                    fid, "alt", getattr(res, "status", "ERROR"),
                    getattr(res, "detail", ""), **ident)
            return protocol.reply_alt(fid, s, t, res.alternatives,
                                      **ident)

        return wait_alt

    # --------------------------------------------------------------- obs
    def statusz(self) -> dict:
        fe_cache = getattr(self.frontend, "cache", None)
        out = {
            "frontend": self.fid,
            "endpoint": self.socket_path,
            "credit": self.gconf.credit,
            "clients": int(self.clients),
            "served": int(self.served),
            "busy": int(self.busy),
            "malformed": int(self.malformed),
        }
        if fe_cache is not None:
            out["l1_hits"] = int(fe_cache.hits)
            out["l1_misses"] = int(fe_cache.misses)
            out["l1_hit_rate"] = round(fe_cache.hit_rate(), 4)
        return out


def _drain(futs, pairs, deadline_s: float):
    """In-order pair results with ONE deadline budgeted across the
    frame (a stuck shard costs the frame one deadline, not one per
    row) — TimeoutError rows degrade to typed TIMEOUT results."""
    from ..serving.request import TIMEOUT, ServeResult

    end = time.monotonic() + deadline_s
    rows = []
    for fut, (s, t) in zip(futs, pairs):
        try:
            rows.append(fut.result(max(0.0, end - time.monotonic())))
        except TimeoutError:
            rows.append(ServeResult(TIMEOUT, s, t,
                                    detail="gateway-deadline"))
    return rows


def _drain_rev(futs, pairs, deadline_s: float):
    """Rev rows: unwrap each CompositeFuture's ReverseResult back to
    the underlying pair ServeResult (labeled with the ORIGINAL s, t the
    client asked about, like the REV sentence)."""
    from ..serving.request import TIMEOUT, ServeResult

    end = time.monotonic() + deadline_s
    rows = []
    for fut, (s, t) in zip(futs, pairs):
        try:
            res = fut.result(max(0.0, end - time.monotonic()))
        except TimeoutError:
            rows.append(ServeResult(TIMEOUT, s, t,
                                    detail="gateway-deadline"))
            continue
        inner = getattr(res, "result", res)   # ReverseResult | shed
        rows.append(ServeResult(
            inner.status, s, t, cost=int(inner.cost),
            plen=int(inner.plen), finished=bool(inner.finished),
            cached=bool(inner.cached), detail=inner.detail))
    return rows


def _family_result(fut, deadline_s: float):
    from ..serving.request import TIMEOUT, ServeResult

    try:
        return fut.result(deadline_s)
    except TimeoutError:
        return ServeResult(TIMEOUT, -1, -1, detail="gateway-deadline")


class GatewayTier:
    """N replicas under one roof: builds a :class:`GatewayServer` per
    ``(frontend, families)`` pair and aggregates their ``/statusz``
    into the ``gateway`` section ``dos-obs top`` renders. Replicas are
    independent — one replica's death leaves the others serving (the
    kill-one-frontend drill pins this)."""

    def __init__(self, replicas, gconf: GatewayConfig | None = None,
                 socket_paths=None):
        self.gconf = gconf or GatewayConfig.from_env()
        self.servers: list[GatewayServer] = []
        for fid, (frontend, families) in enumerate(replicas):
            path = (socket_paths[fid] if socket_paths is not None
                    else self.gconf.socket_of(fid))
            self.servers.append(GatewayServer(
                frontend, families=families, fid=fid, gconf=self.gconf,
                socket_path=path))

    @property
    def endpoints(self) -> list:
        return [srv.socket_path for srv in self.servers]

    def start(self) -> "GatewayTier":
        for srv in self.servers:
            srv.start()
        return self

    def stop(self, join_s: float = 5.0) -> None:
        for srv in self.servers:
            srv.stop(join_s=join_s)

    def statusz(self) -> dict:
        fes = {str(srv.fid): srv.statusz() for srv in self.servers}
        hits = sum(int(st.get("l1_hits", 0)) for st in fes.values())
        misses = sum(int(st.get("l1_misses", 0)) for st in fes.values())
        total = hits + misses
        return {
            "replicas": len(self.servers),
            "clients": sum(int(st.get("clients", 0))
                           for st in fes.values()),
            "l1_hit_rate": round(hits / total, 4) if total else 0.0,
            "frontends": fes,
        }
