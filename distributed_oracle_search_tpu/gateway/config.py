"""Gateway knobs (``DOS_GATEWAY_*`` env family).

One frozen dataclass holds every tunable of the client-facing tier so
the accept loops, the tier runner, and the worker-side L2 agree on a
single source of truth, and ``from_env`` follows the repo-wide env
policy (``utils.env``): a malformed value degrades to the default with
a log line, never a crash.
"""

from __future__ import annotations

import dataclasses

from ..utils.env import env_cast, env_str
from ..utils.log import get_logger

log = get_logger(__name__)


@dataclasses.dataclass(frozen=True)
class GatewayConfig:
    """Client-tier tunables.

    * ``replicas`` — how many stateless frontend replicas the tier
      runner hosts. Env: ``DOS_GATEWAY_REPLICAS``.
    * ``socket_dir`` — directory for the per-replica unix sockets
      (``dos-gateway-f<fid>.sock``). Env: ``DOS_GATEWAY_SOCKET_DIR``.
    * ``credit`` — per-connection in-flight frame window advertised in
      the hello; frames past it answer an explicit ``busy`` instead of
      queueing into a timeout. Env: ``DOS_GATEWAY_CREDIT``.
    * ``deadline_ms`` — default per-frame deadline when a query frame
      carries none of its own. Env: ``DOS_GATEWAY_DEADLINE_MS``.
    * ``l2_bytes`` — byte budget of the shard-owner L2 result cache
      each WORKER keeps in front of its kernel; ``0`` (the default)
      disables it, preserving pre-gateway worker behavior exactly.
      Env: ``DOS_GATEWAY_L2_BYTES`` (read worker-side).
    * ``l2_admit`` — L2 admission policy: ``all`` (the default — every
      miss inserts, byte-identical pre-HA behavior) or ``second-hit``
      (a doorkeeper admits a key only on its second miss, keeping
      one-hit-wonder queries from churning the byte budget).
      Env: ``DOS_GATEWAY_L2_ADMIT`` (read worker-side).
    * ``lease_s`` — TTL of a frontend's endpoint lease in
      ``gateway.json``; the heartbeat renews at a third of it, and a
      lease older than it marks the frontend dead for discovery,
      failover, and the control loop. Env: ``DOS_GATEWAY_LEASE_S``.
    """

    replicas: int = 2
    socket_dir: str = "/tmp"
    credit: int = 32
    deadline_ms: float = 10_000.0
    l2_bytes: int = 0
    l2_admit: str = "all"
    lease_s: float = 10.0

    @classmethod
    def from_env(cls, **overrides) -> "GatewayConfig":
        """Env-derived config; keyword overrides (CLI flags) win when
        not ``None``. Env policy (``utils.env``): a well-typed but
        INVALID env value degrades to the default with a log line like
        an unparseable one — only explicit overrides raise."""
        vals = dict(
            replicas=env_cast("DOS_GATEWAY_REPLICAS", cls.replicas, int),
            socket_dir=env_str("DOS_GATEWAY_SOCKET_DIR", cls.socket_dir),
            credit=env_cast("DOS_GATEWAY_CREDIT", cls.credit, int),
            deadline_ms=env_cast("DOS_GATEWAY_DEADLINE_MS",
                                 cls.deadline_ms, float),
            l2_bytes=env_cast("DOS_GATEWAY_L2_BYTES", cls.l2_bytes, int),
            l2_admit=env_str("DOS_GATEWAY_L2_ADMIT", cls.l2_admit),
            lease_s=env_cast("DOS_GATEWAY_LEASE_S", cls.lease_s, float),
        )
        for field, value in list(vals.items()):
            try:
                cls(**{field: value}).validate()
            except ValueError as e:
                log.warning("ignoring invalid DOS_GATEWAY_%s=%r (%s); "
                            "using %r", field.upper(), value, e,
                            getattr(cls, field))
                vals[field] = getattr(cls, field)
        vals.update({k: v for k, v in overrides.items() if v is not None})
        return cls(**vals).validate()

    def validate(self) -> "GatewayConfig":
        if self.replicas <= 0:
            raise ValueError("replicas must be positive")
        if not self.socket_dir:
            raise ValueError("socket_dir must be non-empty")
        if self.credit <= 0:
            raise ValueError("credit must be positive")
        if self.deadline_ms <= 0:
            raise ValueError("deadline_ms must be positive")
        if self.l2_bytes < 0:
            raise ValueError("l2_bytes must be >= 0")
        if self.l2_admit not in ("all", "second-hit"):
            raise ValueError("l2_admit must be 'all' or 'second-hit'")
        if self.lease_s <= 0:
            raise ValueError("lease_s must be positive")
        return self

    @property
    def deadline_s(self) -> float:
        return self.deadline_ms / 1e3

    def socket_of(self, fid: int) -> str:
        import os

        return os.path.join(self.socket_dir, f"dos-gateway-f{fid}.sock")
