"""``DosClient`` — the client library for the gateway tier.

One LOGICAL connection per client, backed by whichever live frontend
discovery currently points at. The constructor resolves candidates —
explicit seed endpoints, plus the leased endpoint registry
(``gateway.json`` via ``registry_dir``) when given — connects to the
first that answers, reads the gateway ``hello`` (gating on a NEWER
schema, tolerating older), and sizes a local credit semaphore to the
advertised window so the client can never trip the gateway's BUSY
answer under its own steam — a ``busy`` frame still surfaces (another
client may have the window) as :class:`GatewayBusy`, which is
retryable by contract.

Frames multiplex: ``submit_*`` returns a handle immediately and a
background reader correlates reply frames back by ``id``, so a caller
can keep the whole credit window full (the bench's open-loop driver
does; :func:`pair_rows` decodes a reply frame it collected itself).
The sync conveniences (``query``, ``matrix``, ``alternatives``,
``reverse``) are submit + wait.

Failover: when the connection dies (reset, clean close, torn frame) —
or, for a client with somewhere else to go, when a reply stays overdue
past its wait budget (the half-open signature of an asymmetric
partition) — the client re-resolves discovery, connects to the next
live frontend, and RESUBMITS every unanswered in-flight frame under
its ORIGINAL id with ``resubmit`` stamped true. Safety comes from the
wire contract, not from guessing: every query frame carries this
client's identity token (``cid``), and a frontend that already
answered ``(cid, id)`` replays its memoized reply instead of
double-booking counters and cache inserts — exactly-once *accounting*
over at-least-once *execution*; answers are deterministic, so a
re-execution on a different frontend is bit-identical. Waits in
flight keep blocking across a failover and simply receive the
resubmitted answer. A request's deadline (``deadline_ms``) is pinned
at SUBMIT time: :meth:`wait` never grants a frame more total lifetime
than it asked for, however late the caller collects it.
"""

from __future__ import annotations

import socket
import threading
import time
import uuid

from . import protocol
from .registry import live_endpoints
from ..obs import metrics as obs_metrics
from ..obs import recorder as obs_recorder
from ..transport.frames import (FrameReader, FrameWriter,
                                FrameSchemaError, TornFrame,
                                TransportError)
from ..utils.locks import OrderedLock
from ..utils.log import get_logger

log = get_logger(__name__)

M_FAILOVERS = obs_metrics.counter(
    "gateway_client_failovers_total",
    "client connection moves to another live frontend (dead endpoint, "
    "half-open connection, or overdue reply), resubmitting unanswered "
    "frames under their original ids")


class GatewayBusy(Exception):
    """The gateway answered ``busy`` — the frame was shed at the credit
    window, nothing was enqueued; retry after backoff."""


class GatewayError(Exception):
    """The gateway answered a typed ``err`` frame."""


class _Slot:
    __slots__ = ("ev", "frame", "payload", "deadline")

    def __init__(self):
        self.ev = threading.Event()
        self.frame = None
        self.payload = None     # (header, arrays) kept for resubmission
        self.deadline = None    # monotonic absolute, pinned at submit


def _open_socket(endpoint: str, timeout_s: float):
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.settimeout(timeout_s)
    try:
        sock.connect(endpoint)
    except OSError:
        sock.close()
        raise
    return sock


def _close_sock(sock) -> None:
    if sock is None:
        return
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class DosClient:
    """One logical connection to the gateway tier (see module
    docstring). ``endpoint`` alone preserves the PR 18 single-endpoint
    shape exactly; ``endpoints`` (several seeds) and/or
    ``registry_dir`` (the ``gateway.json`` directory) arm discovery
    and failover."""

    def __init__(self, endpoint: str | None = None,
                 max_inflight: int | None = None,
                 connect_timeout_s: float = 5.0, *,
                 endpoints=None, registry_dir: str | None = None):
        self.seeds = [e for e in ([endpoint] if endpoint else [])
                      + list(endpoints or []) if e]
        self.registry_dir = registry_dir
        if not self.seeds and not registry_dir:
            raise ValueError("DosClient needs an endpoint, endpoints, "
                             "or a registry_dir to discover from")
        #: this client's identity token — rides every query frame so a
        #: frontend can dedup resubmissions by (cid, id)
        self.cid = uuid.uuid4().hex[:16]
        self.connect_timeout_s = float(connect_timeout_s)
        self._ha = bool(registry_dir) or len(self.seeds) > 1
        # lock order: conn before slots (witness names are per-class)
        self._conn_lock = OrderedLock("gateway.DosClient.conn")
        self._lock = OrderedLock("gateway.DosClient.slots")
        self._sock = None
        self._writer = None
        self._reader = None
        self._gen = 0           # bumps per (re)connect; guards failover
        self._closed = False
        self.endpoint = None
        self.frontend = -1
        self.epoch = 0
        self.diff_epoch = 0
        self.failovers = 0
        #: reply frames with no live waiter — a duplicate answer would
        #: land here, so the chaos drills pin this at zero
        self.unmatched = 0
        candidates = self._candidates()
        if not candidates:
            raise TransportError("gateway discovery found no endpoints "
                                 f"(registry_dir={registry_dir!r})")
        err = None
        for ep in candidates:
            try:
                with self._conn_lock:
                    self._connect_locked(ep)
                err = None
                break
            except (TransportError, TornFrame, FrameSchemaError,
                    OSError) as e:
                err = e
                log.debug("gateway %s unreachable at connect: %s", ep, e)
        if err is not None:
            if len(candidates) == 1:
                raise err     # single-endpoint shape: the real error
            raise TransportError(
                f"no gateway endpoint reachable (tried "
                f"{len(candidates)}): {err}")
        server_credit = self._server_credit
        self.credit = max(1, min(server_credit,
                                 max_inflight or server_credit))
        self._credits = threading.Semaphore(self.credit)
        self._slots: dict[int, _Slot] = {}
        self._next_id = 0       # monotone ACROSS reconnects: (cid, id)
        self._rthread = threading.Thread(
            target=self._read_loop, daemon=True,
            name=f"dos-client-{self.cid[:6]}")
        self._rthread.start()

    # --------------------------------------------------------- discovery
    def _candidates(self, skip: str | None = None) -> list:
        """Live endpoints, discovery order: registry leases (ascending
        fid) then seeds, with the endpoint we just abandoned demoted to
        last resort (it may have respawned under the same path)."""
        eps = live_endpoints(self.registry_dir, seeds=self.seeds)
        out = [e for e in eps if e != skip]
        if skip is not None and skip in eps:
            out.append(skip)
        return out

    def _connect_locked(self, ep: str) -> None:
        """Connect + hello-exchange with ``ep`` and swap it in as the
        live connection (closing the old socket, which wakes a reader
        blocked on it). Caller holds ``_conn_lock``."""
        sock = _open_socket(ep, self.connect_timeout_s)
        reader = FrameReader(sock)
        writer = FrameWriter(sock)
        try:
            hello = reader.read()     # connect timeout still armed
            if hello is None or hello.kind != "hello":
                raise TransportError(f"gateway {ep} sent no hello")
            protocol.check_hello(hello.header)  # gate-newer, tol-older
            writer.send({"kind": "hello",
                         "gv": protocol.GATEWAY_SCHEMA_VERSION,
                         "cid": self.cid})
        except Exception:
            _close_sock(sock)
            raise
        sock.settimeout(None)
        _close_sock(self._sock)
        self._sock, self._writer, self._reader = sock, writer, reader
        self.endpoint = ep
        self.frontend = int(hello.header.get("frontend", -1))
        self.epoch = int(hello.header.get("epoch", 0))
        self.diff_epoch = int(hello.header.get("diff_epoch", 0))
        self._server_credit = int(hello.header.get("credit", 1))

    def _failover(self, dead_gen: int, why: str = "") -> bool:
        """Move to the next live frontend and resubmit unanswered
        frames. ``dead_gen`` is the connection generation the caller
        saw die: if the client already moved on, this is a no-op
        success. False only when NO candidate would take us."""
        with self._conn_lock:
            if self._closed:
                return False
            if self._gen != dead_gen:
                return True       # another thread already moved us
            dead = self.endpoint
            for ep in self._candidates(skip=dead):
                try:
                    self._connect_locked(ep)
                except (TransportError, TornFrame, FrameSchemaError,
                        OSError) as e:
                    log.debug("gateway failover: %s unreachable (%s)",
                              ep, e)
                    continue
                self._gen += 1
                n = self._resubmit_locked()
                self.failovers += 1
                M_FAILOVERS.inc()
                obs_recorder.emit("gateway_failover",
                                  endpoint=str(ep),
                                  from_endpoint=str(dead),
                                  frontend=int(self.frontend),
                                  resubmitted=int(n), why=str(why))
                log.warning("gateway client failed over %s -> %s "
                            "(%d frame(s) resubmitted): %s", dead, ep,
                            n, why)
                return True
            log.warning("gateway client: no live endpoint to fail over "
                        "to from %s: %s", dead, why)
            return False

    def _resubmit_locked(self) -> int:
        """Resend every unanswered in-flight frame on the fresh
        connection, ORIGINAL ids, ``resubmit`` stamped — the server's
        (cid, id) memo replays what it already answered. Caller holds
        ``_conn_lock``; id order is preserved."""
        with self._lock:
            pending = sorted(
                (fid, s) for fid, s in self._slots.items()
                if not s.ev.is_set() and s.payload is not None)
        n = 0
        for _fid, slot in pending:
            header = dict(slot.payload[0])
            header["resubmit"] = True
            try:
                self._writer.send(header, slot.payload[1])
                n += 1
            except (TransportError, OSError) as e:
                # this connection is dying too; the reader notices and
                # the NEXT failover round resubmits the remainder
                log.debug("gateway resubmit stopped mid-way: %s", e)
                break
        return n

    # ----------------------------------------------------------- plumbing
    def _read_loop(self) -> None:
        while True:
            with self._conn_lock:
                gen, reader = self._gen, self._reader
            err: Exception | None = None
            try:
                while True:
                    fr = reader.read()
                    if fr is None:
                        raise TransportError(
                            "gateway closed the connection")
                    self._dispatch(fr)
            except (TransportError, TornFrame, FrameSchemaError,
                    OSError) as e:
                err = e
            if self._closed or not self._failover(gen, why=str(err)):
                if not self._closed:
                    log.debug("gateway client reader down: %s", err)
                break
        self._fail_pending()

    def _dispatch(self, fr) -> None:
        fid = protocol.frame_id(fr)
        with self._lock:
            slot = self._slots.get(fid)
        if slot is None or slot.ev.is_set():
            # unmatched, or the duplicate of an answer that raced a
            # failover resubmission — the first reply won, drop this one
            self.unmatched += 1
            log.debug("gateway client: unmatched frame id %d kind %r",
                      fid, fr.kind)
            return
        slot.frame = fr
        slot.ev.set()
        # the credit returns when the REPLY lands, not when a waiter
        # collects it — a caller that timed out early must not leak
        # its window slot forever
        self._credits.release()

    def _fail_pending(self) -> None:
        with self._lock:
            slots, self._slots = self._slots, {}
        for slot in slots.values():
            if not slot.ev.is_set():
                slot.ev.set()   # frame stays None → TransportError
                self._credits.release()

    def _submit(self, build, timeout: float | None = None,
                deadline_ms=None) -> int:
        """Acquire one credit, send one frame built by ``build(fid)``;
        returns the frame id to :meth:`wait` on. ``deadline_ms`` pins
        the request's total lifetime from NOW."""
        if self._closed:
            raise TransportError("client closed")
        if not self._credits.acquire(timeout=timeout):
            raise GatewayBusy("local credit window exhausted")
        with self._lock:
            fid = self._next_id
            self._next_id += 1
            slot = self._slots[fid] = _Slot()
        if deadline_ms is not None:
            slot.deadline = time.monotonic() + float(deadline_ms) / 1e3
        try:
            header, arrays = build(fid)
            # publish the payload and pick the connection ATOMICALLY:
            # a failover that lands before this block can't see the
            # slot (no payload yet), so we send on the writer it
            # installed; one that lands after resubmits the slot and
            # closes our captured writer, so our own send raises and
            # the gen check below recognises the frame as covered —
            # either way exactly one copy reaches a live frontend
            with self._conn_lock:
                slot.payload = (header, arrays)
                gen, writer = self._gen, self._writer
            try:
                writer.send(header, arrays)
            except (TransportError, OSError) as e:
                # the frame may or may not have left the socket; a
                # successful failover resubmits it either way and the
                # server-side (cid, id) memo absorbs the maybe
                if not self._failover(gen, why=f"submit: {e}"):
                    raise
        except Exception:
            with self._lock:
                self._slots.pop(fid, None)
            self._credits.release()
            raise
        return fid

    def wait(self, fid: int, timeout: float | None = None):
        """Block for frame ``fid``'s reply; returns the decoded frame.
        The wait budget is the SMALLER of ``timeout`` and what remains
        of the request's submit-time deadline — a frame submitted then
        waited-on late does not get a fresh full deadline. Raises
        :class:`GatewayBusy` on a ``busy`` answer,
        :class:`GatewayError` on a typed ``err``, ``TransportError``
        when the connection died with nowhere to fail over to, and
        ``TimeoutError`` past the budget. A timeout on a client WITH
        somewhere else to go (seeds/registry) treats the silent
        connection as half-open — fails over and resubmits — so a
        re-wait can still collect the answer."""
        with self._lock:
            slot = self._slots.get(fid)
        if slot is None:
            raise KeyError(f"no in-flight frame {fid}")
        budget = timeout
        if slot.deadline is not None:
            left = slot.deadline - time.monotonic()
            budget = left if budget is None else min(budget, left)
        if budget is not None:
            budget = max(0.0, budget)   # already-landed replies still
        if not slot.ev.wait(budget):    # return past a spent deadline
            if self._ha and not self._closed:
                with self._conn_lock:
                    gen = self._gen
                self._failover(gen, why=f"reply {fid} overdue")
            raise TimeoutError(f"gateway reply {fid} still pending")
        with self._lock:
            self._slots.pop(fid, None)
        fr = slot.frame
        if fr is None:
            raise TransportError("gateway connection closed mid-flight")
        self.epoch = int(fr.header.get("epoch", self.epoch))
        self.diff_epoch = int(fr.header.get("diff_epoch",
                                            self.diff_epoch))
        if fr.kind == "busy":
            raise GatewayBusy(f"gateway shed frame {fid}")
        if fr.kind == "err":
            raise GatewayError(str(fr.header.get("error", "")))
        return fr

    # ------------------------------------------------------------ submits
    def submit_pairs(self, pairs, deadline_ms=None,
                     timeout: float | None = None) -> int:
        return self._submit(
            lambda fid: protocol.encode_pairs(
                fid, pairs, deadline_ms=deadline_ms,
                epoch=self.epoch, diff_epoch=self.diff_epoch,
                cid=self.cid),
            timeout=timeout, deadline_ms=deadline_ms)

    def submit_rev(self, pairs, deadline_ms=None,
                   timeout: float | None = None) -> int:
        return self._submit(
            lambda fid: protocol.encode_pairs(
                fid, pairs, family="rev", deadline_ms=deadline_ms,
                epoch=self.epoch, diff_epoch=self.diff_epoch,
                cid=self.cid),
            timeout=timeout, deadline_ms=deadline_ms)

    def submit_mat(self, s: int, targets, deadline_ms=None,
                   timeout: float | None = None) -> int:
        return self._submit(
            lambda fid: protocol.encode_mat(
                fid, s, targets, deadline_ms=deadline_ms,
                epoch=self.epoch, diff_epoch=self.diff_epoch,
                cid=self.cid),
            timeout=timeout, deadline_ms=deadline_ms)

    def submit_alt(self, s: int, t: int, k: int, deadline_ms=None,
                   timeout: float | None = None) -> int:
        return self._submit(
            lambda fid: protocol.encode_alt(
                fid, s, t, k, deadline_ms=deadline_ms,
                epoch=self.epoch, diff_epoch=self.diff_epoch,
                cid=self.cid),
            timeout=timeout, deadline_ms=deadline_ms)

    # --------------------------------------------------- sync conveniences
    def query_batch(self, pairs, timeout: float | None = 30.0):
        """``[(status, cost, plen, finished, cached), ...]`` in request
        order — one frame, Q answers."""
        fr = self.wait(self.submit_pairs(pairs, timeout=timeout),
                       timeout=timeout)
        return pair_rows(fr)

    def query(self, s: int, t: int, timeout: float | None = 30.0):
        return self.query_batch([(s, t)], timeout=timeout)[0]

    def reverse_batch(self, pairs, timeout: float | None = 30.0):
        fr = self.wait(self.submit_rev(pairs, timeout=timeout),
                       timeout=timeout)
        return pair_rows(fr)

    def reverse(self, s: int, t: int, timeout: float | None = 30.0):
        return self.reverse_batch([(s, t)], timeout=timeout)[0]

    def matrix(self, s: int, targets, timeout: float | None = 30.0):
        """The MAT row: ``[cost, ...]`` target-ordered, −1 per
        unanswered target. A shed frame raises :class:`GatewayBusy`."""
        fr = self.wait(self.submit_mat(s, targets, timeout=timeout),
                       timeout=timeout)
        _raise_shed(fr)
        return [int(c) for c in fr.arrays[0]]

    def alternatives(self, s: int, t: int, k: int,
                     timeout: float | None = 30.0):
        """``[(cost, via), ...]`` ascending, distinct first edges."""
        fr = self.wait(self.submit_alt(s, t, k, timeout=timeout),
                       timeout=timeout)
        _raise_shed(fr)
        return list(zip((int(c) for c in fr.arrays[0]),
                        (int(v) for v in fr.arrays[1])))

    def ping(self, timeout: float | None = 5.0) -> dict:
        fid = self._submit(lambda fid: ({"kind": "ping", "id": fid,
                                         "cid": self.cid}, []),
                           timeout=timeout)
        return dict(self.wait(fid, timeout=timeout).header)

    def close(self) -> None:
        self._closed = True
        with self._conn_lock:
            _close_sock(self._sock)
        self._rthread.join(timeout=5.0)


def pair_rows(fr):
    statuses = fr.header.get("status") or []
    cached = fr.header.get("cached") or []
    cost, plen, fin = fr.arrays
    return [(statuses[i] if i < len(statuses) else "ERROR",
             int(cost[i]), int(plen[i]), bool(fin[i]),
             bool(cached[i]) if i < len(cached) else False)
            for i in range(len(cost))]


def _raise_shed(fr):
    status = fr.header.get("status")
    if isinstance(status, str) and status != "OK":
        raise GatewayBusy(f"{status}: {fr.header.get('detail', '')}")
