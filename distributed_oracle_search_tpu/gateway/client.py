"""``DosClient`` — the client library for the gateway tier.

One persistent connection per client: the constructor connects, reads
the gateway ``hello`` (gating on a NEWER schema, tolerating older),
and sizes a local credit semaphore to the advertised window so the
client can never trip the gateway's BUSY answer under its own steam — a
``busy`` frame still surfaces (another client may have the window) as
:class:`GatewayBusy`, which is retryable by contract.

Frames multiplex: ``submit_*`` returns a handle immediately and a
background reader correlates reply frames back by ``id``, so a caller
can keep the whole credit window full (the bench's open-loop driver
does; :func:`pair_rows` decodes a reply frame it collected itself).
The sync conveniences (``query``, ``matrix``, ``alternatives``,
``reverse``) are submit + wait.
"""

from __future__ import annotations

import socket
import threading

from . import protocol
from ..transport.frames import (FrameReader, FrameWriter,
                                FrameSchemaError, TornFrame,
                                TransportError)
from ..utils.log import get_logger

log = get_logger(__name__)


class GatewayBusy(Exception):
    """The gateway answered ``busy`` — the frame was shed at the credit
    window, nothing was enqueued; retry after backoff."""


class GatewayError(Exception):
    """The gateway answered a typed ``err`` frame."""


class _Slot:
    __slots__ = ("ev", "frame")

    def __init__(self):
        self.ev = threading.Event()
        self.frame = None


class DosClient:
    """One connection to one gateway replica (see module docstring)."""

    def __init__(self, endpoint: str, max_inflight: int | None = None,
                 connect_timeout_s: float = 5.0):
        self.endpoint = endpoint
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(connect_timeout_s)
        sock.connect(endpoint)
        sock.settimeout(None)
        self._sock = sock
        self._writer = FrameWriter(sock)
        self._reader = FrameReader(sock)
        hello = self._reader.read()
        if hello is None or hello.kind != "hello":
            raise TransportError(f"gateway {endpoint} sent no hello")
        protocol.check_hello(hello.header)   # gate-newer, tolerate-older
        self.frontend = int(hello.header.get("frontend", -1))
        self.epoch = int(hello.header.get("epoch", 0))
        self.diff_epoch = int(hello.header.get("diff_epoch", 0))
        server_credit = int(hello.header.get("credit", 1))
        self.credit = max(1, min(server_credit,
                                 max_inflight or server_credit))
        self._credits = threading.Semaphore(self.credit)
        self._slots: dict[int, _Slot] = {}
        self._next_id = 0
        self._lock = threading.Lock()
        self._closed = False
        self._writer.send({"kind": "hello",
                           "gv": protocol.GATEWAY_SCHEMA_VERSION})
        self._rthread = threading.Thread(
            target=self._read_loop, daemon=True,
            name=f"dos-client-{self.frontend}")
        self._rthread.start()

    # ----------------------------------------------------------- plumbing
    def _read_loop(self) -> None:
        try:
            while True:
                fr = self._reader.read()
                if fr is None:
                    break
                fid = protocol.frame_id(fr)
                with self._lock:
                    slot = self._slots.get(fid)
                if slot is None:
                    log.debug("gateway client: unmatched frame id %d "
                              "kind %r", fid, fr.kind)
                    continue
                slot.frame = fr
                slot.ev.set()
                # the credit returns when the REPLY lands, not when a
                # waiter collects it — a caller that timed out early
                # must not leak its window slot forever
                self._credits.release()
        except (TransportError, TornFrame, FrameSchemaError,
                OSError) as e:
            log.debug("gateway client reader down: %s", e)
        finally:
            with self._lock:
                slots, self._slots = self._slots, {}
            for slot in slots.values():
                if not slot.ev.is_set():
                    slot.ev.set()   # frame stays None → TransportError
                    self._credits.release()

    def _submit(self, build, timeout: float | None = None) -> int:
        """Acquire one credit, send one frame built by ``build(fid)``;
        returns the frame id to :meth:`wait` on."""
        if self._closed:
            raise TransportError("client closed")
        if not self._credits.acquire(timeout=timeout):
            raise GatewayBusy("local credit window exhausted")
        with self._lock:
            fid = self._next_id
            self._next_id += 1
            self._slots[fid] = _Slot()
        try:
            header, arrays = build(fid)
            self._writer.send(header, arrays)
        except Exception:
            with self._lock:
                self._slots.pop(fid, None)
            self._credits.release()
            raise
        return fid

    def wait(self, fid: int, timeout: float | None = None):
        """Block for frame ``fid``'s reply; returns the decoded frame.
        Raises :class:`GatewayBusy` on a ``busy`` answer,
        :class:`GatewayError` on a typed ``err``, ``TransportError``
        when the connection died first."""
        with self._lock:
            slot = self._slots.get(fid)
        if slot is None:
            raise KeyError(f"no in-flight frame {fid}")
        if not slot.ev.wait(timeout):
            raise TimeoutError(f"gateway reply {fid} still pending")
        with self._lock:
            self._slots.pop(fid, None)
        fr = slot.frame
        if fr is None:
            raise TransportError("gateway connection closed mid-flight")
        self.epoch = int(fr.header.get("epoch", self.epoch))
        self.diff_epoch = int(fr.header.get("diff_epoch",
                                            self.diff_epoch))
        if fr.kind == "busy":
            raise GatewayBusy(f"gateway shed frame {fid}")
        if fr.kind == "err":
            raise GatewayError(str(fr.header.get("error", "")))
        return fr

    # ------------------------------------------------------------ submits
    def submit_pairs(self, pairs, deadline_ms=None,
                     timeout: float | None = None) -> int:
        return self._submit(
            lambda fid: protocol.encode_pairs(
                fid, pairs, deadline_ms=deadline_ms,
                epoch=self.epoch, diff_epoch=self.diff_epoch),
            timeout=timeout)

    def submit_rev(self, pairs, deadline_ms=None,
                   timeout: float | None = None) -> int:
        return self._submit(
            lambda fid: protocol.encode_pairs(
                fid, pairs, family="rev", deadline_ms=deadline_ms,
                epoch=self.epoch, diff_epoch=self.diff_epoch),
            timeout=timeout)

    def submit_mat(self, s: int, targets, deadline_ms=None,
                   timeout: float | None = None) -> int:
        return self._submit(
            lambda fid: protocol.encode_mat(
                fid, s, targets, deadline_ms=deadline_ms,
                epoch=self.epoch, diff_epoch=self.diff_epoch),
            timeout=timeout)

    def submit_alt(self, s: int, t: int, k: int, deadline_ms=None,
                   timeout: float | None = None) -> int:
        return self._submit(
            lambda fid: protocol.encode_alt(
                fid, s, t, k, deadline_ms=deadline_ms,
                epoch=self.epoch, diff_epoch=self.diff_epoch),
            timeout=timeout)

    # --------------------------------------------------- sync conveniences
    def query_batch(self, pairs, timeout: float | None = 30.0):
        """``[(status, cost, plen, finished, cached), ...]`` in request
        order — one frame, Q answers."""
        fr = self.wait(self.submit_pairs(pairs, timeout=timeout),
                       timeout=timeout)
        return pair_rows(fr)

    def query(self, s: int, t: int, timeout: float | None = 30.0):
        return self.query_batch([(s, t)], timeout=timeout)[0]

    def reverse_batch(self, pairs, timeout: float | None = 30.0):
        fr = self.wait(self.submit_rev(pairs, timeout=timeout),
                       timeout=timeout)
        return pair_rows(fr)

    def reverse(self, s: int, t: int, timeout: float | None = 30.0):
        return self.reverse_batch([(s, t)], timeout=timeout)[0]

    def matrix(self, s: int, targets, timeout: float | None = 30.0):
        """The MAT row: ``[cost, ...]`` target-ordered, −1 per
        unanswered target. A shed frame raises :class:`GatewayBusy`."""
        fr = self.wait(self.submit_mat(s, targets, timeout=timeout),
                       timeout=timeout)
        _raise_shed(fr)
        return [int(c) for c in fr.arrays[0]]

    def alternatives(self, s: int, t: int, k: int,
                     timeout: float | None = 30.0):
        """``[(cost, via), ...]`` ascending, distinct first edges."""
        fr = self.wait(self.submit_alt(s, t, k, timeout=timeout),
                       timeout=timeout)
        _raise_shed(fr)
        return list(zip((int(c) for c in fr.arrays[0]),
                        (int(v) for v in fr.arrays[1])))

    def ping(self, timeout: float | None = 5.0) -> dict:
        fid = self._submit(lambda fid: ({"kind": "ping", "id": fid},
                                        []), timeout=timeout)
        return dict(self.wait(fid, timeout=timeout).header)

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        self._rthread.join(timeout=5.0)


def pair_rows(fr):
    statuses = fr.header.get("status") or []
    cached = fr.header.get("cached") or []
    cost, plen, fin = fr.arrays
    return [(statuses[i] if i < len(statuses) else "ERROR",
             int(cost[i]), int(plen[i]), bool(fin[i]),
             bool(cached[i]) if i < len(cached) else False)
            for i in range(len(cost))]


def _raise_shed(fr):
    status = fr.header.get("status")
    if isinstance(status, str) and status != "OK":
        raise GatewayBusy(f"{status}: {fr.header.get('detail', '')}")
