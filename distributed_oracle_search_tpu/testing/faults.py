"""Deterministic fault-injection harness (``DOS_FAULTS``).

Every recovery path in the fault-tolerance layer — head retries, dropped
replies, circuit breaking, supervisor respawns — is exercised by tests
through this module instead of hoping a real fault shows up. Production
code marks its injection points with :func:`inject`; the ``DOS_FAULTS``
environment variable arms them.

Spec grammar (comma-separated rules, each ``point[;key=value...]``)::

    DOS_FAULTS="drop-reply;wid=2;times=1,delay;wid=0;delay=0.5;times=2"

Points (enacted by the call sites, see the table in the README's
"Fault tolerance" section):

* ``drop-reply``     server handles the batch but never writes the answer
* ``delay``          server sleeps ``delay`` seconds before replying
* ``crash-engine``   the engine raises mid-batch (answered with ``FAIL``)
* ``corrupt-frame``  the head garbles the request frame on the wire
* ``kill-mid-batch`` the worker dies after reading a request, before
                     replying (``mode=exit`` → ``os._exit(86)``, the
                     real-crash default; ``mode=raise`` → the serve loop
                     returns, for in-thread test servers)
* ``crash-build``    the CPD builder dies between block flushes — after
                     a block's atomic write + ledger line, before the
                     next block starts (``mode=exit`` → ``os._exit(86)``
                     default; ``mode=raise`` → RuntimeError). The
                     kill-mid-build resume test's trigger.
* ``kill-during-reshard``  the membership reconfiguration controller
                     dies between shard catch-up moves — after a move's
                     journal line landed, before the next shard starts
                     (``mode=exit`` / ``mode=raise`` like
                     ``crash-build``). The reshard crash-resume
                     trigger: the dual-read window stays open, the
                     journal resumes the tail.
* ``stale-epoch-reply``  the worker refuses the batch with the
                     ``STALE_EPOCH`` wire sentinel even though its
                     table may be current — the analog of a worker
                     whose membership state is wedged behind the
                     fleet, forcing the head's failover path.
* ``blackhole-conn`` a gateway client connection goes half-open: from
                     the fired frame on, the frontend keeps ACCEPTING
                     (reading) the connection's frames but never
                     replies — the client-visible signature of an
                     asymmetric network partition, forcing the
                     discovery/failover/resubmission path. ``wid``
                     filters by frontend id.
* ``lease-freeze``   a gateway frontend stays alive and serving but
                     stops renewing its ``gateway.json`` endpoint
                     lease (the zombie case): readers watch the lease
                     expire while the process runs on. ``wid`` filters
                     by frontend id; freezing is sticky once fired.
* ``corrupt-resident``  bits flip in a loaded shard's RESIDENT rows
                     after the disk digests verified clean — the
                     in-memory corruption no manifest check can see
                     and the resident-table scrubber's target. ``wid``
                     filters by shard.
* ``corrupt-answer`` bits flip in a reply's answer payload after the
                     answer fingerprint was computed — wire/cache
                     corruption the fingerprint verifier must catch
                     before the value reaches a client. ``wid``
                     filters by shard.

Rule keys: ``wid`` restricts to one worker id, ``after`` skips the first
N eligible events, ``times`` caps fires (``inf`` = always), ``delay`` and
``mode`` parameterize their points.

Determinism across processes: rules fire on the Nth eligible event, and
counts normally live in process memory. When a campaign spans processes
(supervised worker subprocesses) set ``DOS_FAULTS_STATE=<path>``: the
seen/fired counts move to a JSON file updated under an ``fcntl`` lock, so
"kill worker 1 exactly once for the whole campaign" stays true across
respawns.
"""

from __future__ import annotations

import dataclasses
import json
import threading

from ..obs import metrics as obs_metrics
from ..utils.env import env_str
from ..utils.log import get_logger

log = get_logger(__name__)

#: exit status of a ``kill-mid-batch`` hard exit — distinct from engine
#: failures (rc 1) and the transfer script's no-worker guard (rc 3)
KILL_EXIT_CODE = 86

POINTS = ("drop-reply", "delay", "crash-engine", "corrupt-frame",
          "kill-mid-batch", "crash-build", "kill-during-reshard",
          "stale-epoch-reply", "blackhole-conn", "lease-freeze",
          "corrupt-resident", "corrupt-answer")

M_INJECTED = obs_metrics.counter(
    "faults_injected_total", "fault-harness rules fired (DOS_FAULTS)")


@dataclasses.dataclass
class FaultRule:
    """One armed injection rule (see module docstring for the grammar)."""

    point: str
    wid: int | None = None
    times: float = 1          # fires allowed; float("inf") = always
    after: int = 0            # eligible events skipped before firing
    delay: float = 0.0        # seconds (``delay`` point)
    mode: str = "exit"        # kill-mid-batch: exit | raise
    index: int = 0            # position in the spec = cross-process id

    def matches(self, point: str, wid: int | None) -> bool:
        if self.point != point:
            return False
        return self.wid is None or wid is None or self.wid == wid


def parse_faults(spec: str) -> list[FaultRule]:
    """Parse a ``DOS_FAULTS`` value; malformed rules raise ``ValueError``
    (a typo silently disarming a chaos test would be worse)."""
    rules = []
    for idx, raw in enumerate(t for t in spec.split(",") if t.strip()):
        parts = [p.strip() for p in raw.split(";")]
        point = parts[0]
        if point not in POINTS:
            raise ValueError(f"unknown fault point {point!r} "
                             f"(known: {', '.join(POINTS)})")
        rule = FaultRule(point=point, index=idx)
        for kv in parts[1:]:
            if "=" not in kv:
                raise ValueError(f"fault rule key needs '=': {kv!r}")
            k, v = kv.split("=", 1)
            if k == "wid":
                rule.wid = int(v)
            elif k == "times":
                rule.times = float("inf") if v == "inf" else int(v)
            elif k == "after":
                rule.after = int(v)
            elif k == "delay":
                rule.delay = float(v)
            elif k == "mode":
                if v not in ("exit", "raise"):
                    raise ValueError(f"kill mode {v!r} not in exit|raise")
                rule.mode = v
            else:
                raise ValueError(f"unknown fault rule key {k!r}")
        rules.append(rule)
    return rules


class FaultInjector:
    """Holds the armed rules plus their seen/fired counts.

    ``state_path`` (from ``DOS_FAULTS_STATE``) moves the counts to a
    locked JSON file shared across processes; otherwise they live here.
    """

    def __init__(self, rules: list[FaultRule],
                 state_path: str | None = None):
        self.rules = rules
        self.state_path = state_path
        self._lock = threading.Lock()
        self._seen = [0] * len(rules)
        self._fired = [0] * len(rules)

    # ------------------------------------------------------ shared state
    def _with_file_counts(self, fn):
        """Run ``fn(counts)`` with the state file locked; ``counts`` maps
        rule index -> {"seen": n, "fired": n} and mutations persist."""
        import fcntl

        with open(self.state_path, "a+") as f:
            fcntl.flock(f, fcntl.LOCK_EX)
            f.seek(0)
            raw = f.read().strip()
            counts = json.loads(raw) if raw else {}
            out = fn(counts)
            f.seek(0)
            f.truncate()
            json.dump(counts, f)
            f.flush()
            return out

    def fire(self, point: str, wid: int | None = None) -> FaultRule | None:
        """First matching rule that is due to fire, consuming one count;
        None when nothing fires (the overwhelmingly common case)."""
        for i, rule in enumerate(self.rules):
            if not rule.matches(point, wid):
                continue
            if self.state_path:
                def bump(counts, i=i, rule=rule):
                    c = counts.setdefault(str(i), {"seen": 0, "fired": 0})
                    c["seen"] += 1
                    if (c["seen"] > rule.after
                            and c["fired"] < rule.times):
                        c["fired"] += 1
                        return True
                    return False
                fired = self._with_file_counts(bump)
            else:
                with self._lock:
                    self._seen[i] += 1
                    fired = (self._seen[i] > rule.after
                             and self._fired[i] < rule.times)
                    if fired:
                        self._fired[i] += 1
            if fired:
                M_INJECTED.inc()
                log.warning("fault injected: %s (rule %d, wid=%s)",
                            point, rule.index, wid)
                # the black box gets every injection: a chaos drill's
                # timeline starts at this record (import here — the
                # fault layer must stay importable before obs wiring)
                from ..obs import recorder as obs_recorder
                obs_recorder.emit("fault", point=point, wid=wid)
                return rule
        return None


# ------------------------------------------------------------ module API

_cache_lock = threading.Lock()
_cache: tuple[tuple[str, str | None], FaultInjector] | None = None


def active() -> FaultInjector | None:
    """The injector armed by the current environment (cached per value:
    in-process counts survive across calls, and an env change — tests
    monkeypatching ``DOS_FAULTS`` — rebuilds)."""
    global _cache
    spec = env_str("DOS_FAULTS", "")
    if not spec:
        return None
    key = (spec, env_str("DOS_FAULTS_STATE") or None)
    with _cache_lock:
        if _cache is None or _cache[0] != key:
            _cache = (key, FaultInjector(parse_faults(spec),
                                         state_path=key[1]))
        return _cache[1]


def inject(point: str, wid: int | None = None) -> FaultRule | None:
    """The production hook: returns the fired rule, or None. Zero-cost
    (one dict lookup) when ``DOS_FAULTS`` is unset."""
    if not env_str("DOS_FAULTS"):
        return None
    inj = active()
    return inj.fire(point, wid=wid) if inj is not None else None


def reset() -> None:
    """Drop the cached injector (tests: fresh counts for a reused spec)."""
    global _cache
    with _cache_lock:
        _cache = None
