"""Test-support subsystems shipped with the package (not the test suite).

:mod:`.faults` is the deterministic fault-injection harness: production
code calls :func:`faults.inject` at named injection points, and the
``DOS_FAULTS`` environment variable decides — deterministically — which
calls fire. The module is dependency-free and a no-op when ``DOS_FAULTS``
is unset, so the hooks are safe to leave in hot paths.
"""

from . import faults

__all__ = ["faults"]
