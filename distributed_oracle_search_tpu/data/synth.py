"""Deterministic synthetic road networks, scenarios, and congestion diffs.

The reference's data files (``data/melb-both.xy``, ``.diff``, ``full.scen``)
were stripped from the snapshot (``/root/reference/.MISSING_LARGE_BLOBS``), so
benchmarks and tests run on generated city-like graphs instead: a W×H street
grid with jittered coordinates, integer travel times proportional to jittered
euclidean length, optional random arterial shortcuts, and every street
two-way — which keeps the graph strongly connected by construction, like a
real road network under the free-flow assumption.
"""

from __future__ import annotations

import numpy as np

from .graph import Graph


def synth_city_graph(width: int, height: int, seed: int = 0,
                     shortcut_frac: float = 0.02,
                     weight_jitter: float = 0.3) -> Graph:
    """Grid city: ``width*height`` intersections, two-way streets.

    Travel times are int32 in ~[80, 160] per block edge (scaled euclidean
    with multiplicative jitter). ``shortcut_frac`` adds that fraction of
    extra random two-way "arterial" edges with proportionally longer times.
    """
    rng = np.random.default_rng(seed)
    n = width * height
    ids = np.arange(n, dtype=np.int64)
    gx, gy = ids % width, ids // width
    xs = gx * 100 + rng.integers(-20, 21, n)
    ys = gy * 100 + rng.integers(-20, 21, n)

    # grid streets: right and up neighbors, both directions
    right = ids[gx < width - 1]
    up = ids[gy < height - 1]
    su = np.concatenate([right, up])
    sv = np.concatenate([right + 1, up + width])

    if shortcut_frac > 0 and n > 4:
        k = int(len(su) * shortcut_frac)
        a = rng.integers(0, n, k)
        hop = rng.integers(2, 6, k)
        b = np.clip(a + hop * rng.choice([1, -1, width, -width], k), 0, n - 1)
        keep = a != b
        su = np.concatenate([su, a[keep]])
        sv = np.concatenate([sv, b[keep]])

    # both directions
    src = np.concatenate([su, sv])
    dst = np.concatenate([sv, su])
    # drop duplicate directed edges (shortcuts may collide with grid edges)
    key = src * n + dst
    _, uniq = np.unique(key, return_index=True)
    src, dst = src[uniq], dst[uniq]

    dx = xs[src] - xs[dst]
    dy = ys[src] - ys[dst]
    dist = np.sqrt((dx * dx + dy * dy).astype(np.float64))
    jitter = 1.0 + weight_jitter * rng.random(len(src))
    w = np.maximum(1, (dist * jitter).astype(np.int64)).astype(np.int32)
    return Graph(xs, ys, src, dst, w)


def synth_scenario(n_nodes: int, n_queries: int, seed: int = 1) -> np.ndarray:
    """Random s–t pairs with s != t, int64 [Q, 2]."""
    rng = np.random.default_rng(seed)
    s = rng.integers(0, n_nodes, n_queries)
    t = rng.integers(0, n_nodes, n_queries)
    clash = s == t
    t[clash] = (t[clash] + 1) % n_nodes
    return np.stack([s, t], axis=1).astype(np.int64)


def synth_diff(graph: Graph, frac: float = 0.1, seed: int = 2,
               factor_range: tuple[float, float] = (1.5, 4.0)):
    """Congestion diff: slow down a random ``frac`` of edges.

    Returns ``(src, dst, new_w)`` suitable for ``write_diff`` /
    ``Graph.weights_with_diff``.
    """
    rng = np.random.default_rng(seed)
    k = max(1, int(graph.m * frac))
    eids = rng.choice(graph.m, size=k, replace=False)
    factor = rng.uniform(*factor_range, k)
    new_w = np.maximum(1, (graph.w[eids] * factor).astype(np.int64)).astype(np.int32)
    return graph.src[eids], graph.dst[eids], new_w


def ensure_synth_dataset(datadir: str, width: int = 24, height: int = 18,
                         n_queries: int = 512, seed: int = 0) -> dict:
    """Materialize the canned smoke-test dataset on disk (idempotent).

    The no-cluster analog of the reference's demo data: writes
    ``synth-city.xy``, ``synth.scen``, ``synth-city.xy.diff`` under
    ``datadir`` if absent, matching the paths ``utils.config.test_config``
    points at. Returns the path dict.
    """
    import os

    from .formats import write_diff, write_scen, write_xy

    os.makedirs(datadir, exist_ok=True)
    xy = os.path.join(datadir, "synth-city.xy")
    scen = os.path.join(datadir, "synth.scen")
    diff = os.path.join(datadir, "synth-city.xy.diff")
    if not os.path.exists(xy):
        g = synth_city_graph(width, height, seed=seed)
        write_xy(xy, g.xs, g.ys, g.src, g.dst, g.w)
    if not os.path.exists(scen):
        g = Graph.from_xy(xy)
        write_scen(scen, synth_scenario(g.n, n_queries, seed=seed + 1),
                   comment="synthetic smoke-test scenario")
    if not os.path.exists(diff):
        g = Graph.from_xy(xy)
        src, dst, new_w = synth_diff(g, seed=seed + 2)
        write_diff(diff, src, dst, new_w)
    return {"xy": xy, "scen": scen, "diff": diff}
