"""Deterministic synthetic road networks, scenarios, and congestion diffs.

The reference's data files (``data/melb-both.xy``, ``.diff``, ``full.scen``)
were stripped from the snapshot (``/root/reference/.MISSING_LARGE_BLOBS``), so
benchmarks and tests run on generated city-like graphs instead: a W×H street
grid with jittered coordinates, integer travel times proportional to jittered
euclidean length, optional random arterial shortcuts, and every street
two-way — which keeps the graph strongly connected by construction, like a
real road network under the free-flow assumption.
"""

from __future__ import annotations

import numpy as np

from .graph import Graph


def synth_city_graph(width: int, height: int, seed: int = 0,
                     shortcut_frac: float = 0.02,
                     weight_jitter: float = 0.3) -> Graph:
    """Grid city: ``width*height`` intersections, two-way streets.

    Travel times are int32 in ~[80, 160] per block edge (scaled euclidean
    with multiplicative jitter). ``shortcut_frac`` adds that fraction of
    extra random two-way "arterial" edges with proportionally longer times.
    """
    rng = np.random.default_rng(seed)
    n = width * height
    ids = np.arange(n, dtype=np.int64)
    gx, gy = ids % width, ids // width
    xs = gx * 100 + rng.integers(-20, 21, n)
    ys = gy * 100 + rng.integers(-20, 21, n)

    # grid streets: right and up neighbors, both directions
    right = ids[gx < width - 1]
    up = ids[gy < height - 1]
    su = np.concatenate([right, up])
    sv = np.concatenate([right + 1, up + width])

    if shortcut_frac > 0 and n > 4:
        k = int(len(su) * shortcut_frac)
        a = rng.integers(0, n, k)
        hop = rng.integers(2, 6, k)
        b = np.clip(a + hop * rng.choice([1, -1, width, -width], k), 0, n - 1)
        keep = a != b
        su = np.concatenate([su, a[keep]])
        sv = np.concatenate([sv, b[keep]])

    # both directions
    src = np.concatenate([su, sv])
    dst = np.concatenate([sv, su])
    # drop duplicate directed edges (shortcuts may collide with grid edges)
    key = src * n + dst
    _, uniq = np.unique(key, return_index=True)
    src, dst = src[uniq], dst[uniq]

    dx = xs[src] - xs[dst]
    dy = ys[src] - ys[dst]
    dist = np.sqrt((dx * dx + dy * dy).astype(np.float64))
    jitter = 1.0 + weight_jitter * rng.random(len(src))
    w = np.maximum(1, (dist * jitter).astype(np.int64)).astype(np.int32)
    return Graph(xs, ys, src, dst, w)


def synth_road_network(n: int, seed: int = 0) -> Graph:
    """Non-grid, degree-skewed, planar-ish road network — the DIMACS
    stand-in (BASELINE.md configs[5] is USA-road-d.NY, 264k nodes; the
    real file is absent from the snapshot).

    Topology-realistic where the grid city is not: towns of clustered
    density, a connected backbone of local roads whose edges are bridges
    (long detours when congested), a long-tailed degree distribution
    (hub intersections), and a sparse highway layer between town centers.
    NOT id-ordered for the fast build kernels on purpose — the point of
    the DIMACS regime is that ``grid_split`` fails and shift coverage is
    poor until a BFS/RCM reorder (``Graph.rcm_order``) restores id
    locality, exactly like real road inputs.
    """
    rng = np.random.default_rng(seed)
    n_towns = max(4, n // 2000)
    centers = rng.uniform(0, 4_000_000, (n_towns, 2))
    town = rng.integers(0, n_towns, n)
    spread = rng.gamma(2.0, 12_000, n)
    ang = rng.uniform(0, 2 * np.pi, n)
    xs = (centers[town, 0] + spread * np.cos(ang)).astype(np.int64)
    ys = (centers[town, 1] + spread * np.sin(ang)).astype(np.int64)

    # spatial snake order (bands of y, then x) gives a locality window
    # without a kd-tree; ids are then SHUFFLED so the stored graph has no
    # exploitable id structure (that is what reordering is for)
    band = ys // 25_000
    space = np.lexsort((xs, band))

    # connected backbone: each node (in space order) links to a random
    # earlier node within a short window -> spanning tree of local roads
    i = np.arange(1, n)
    back = i - 1 - np.minimum(rng.geometric(0.3, n - 1) - 1, np.minimum(i - 1, 63))
    su = [space[i], ]
    sv = [space[back], ]

    # degree skew: a long-tailed number of extra local edges per node
    # (most 0-1, hubs up to ~12)
    extra = np.minimum(rng.zipf(2.2, n) - 1, 12)
    tot = int(extra.sum())
    owner = np.repeat(np.arange(n), extra)          # position in space order
    off = rng.integers(1, 48, tot)
    nbr = np.clip(owner - off, 0, n - 1)
    keep = nbr != owner
    su.append(space[owner[keep]])
    sv.append(space[nbr[keep]])

    # highway layer: town centers chained by proximity order + a few
    # random long links
    hub = np.empty(n_towns, np.int64)
    d2 = (xs - centers[town, 0]) ** 2 + (ys - centers[town, 1]) ** 2
    for t in range(n_towns):                        # one pass, small loop
        members = np.nonzero(town == t)[0]
        hub[t] = members[np.argmin(d2[members])] if len(members) else 0
    horder = np.lexsort((centers[:, 0], centers[:, 1] // 400_000))
    su.append(hub[horder[:-1]])
    sv.append(hub[horder[1:]])
    k_long = max(1, n_towns // 8)
    su.append(hub[rng.integers(0, n_towns, k_long)])
    sv.append(hub[rng.integers(0, n_towns, k_long)])

    su = np.concatenate(su)
    sv = np.concatenate(sv)
    ok = su != sv
    su, sv = su[ok], sv[ok]
    src = np.concatenate([su, sv])
    dst = np.concatenate([sv, su])
    key = src * n + dst
    _, uniq = np.unique(key, return_index=True)
    src, dst = src[uniq], dst[uniq]

    dx = (xs[src] - xs[dst]).astype(np.float64)
    dy = (ys[src] - ys[dst]).astype(np.float64)
    dist = np.sqrt(dx * dx + dy * dy)
    jitter = 1.0 + 0.3 * rng.random(len(src))
    w = np.maximum(1, (dist * jitter / 100.0).astype(np.int64))
    w = np.minimum(w, 2_000_000).astype(np.int32)

    # destroy id locality: real DIMACS inputs arrive in arbitrary order
    shuf = rng.permutation(n)
    return Graph(xs, ys, src, dst, w).reorder(shuf)


def synth_scenario(n_nodes: int, n_queries: int, seed: int = 1) -> np.ndarray:
    """Random s–t pairs with s != t, int64 [Q, 2]."""
    rng = np.random.default_rng(seed)
    s = rng.integers(0, n_nodes, n_queries)
    t = rng.integers(0, n_nodes, n_queries)
    clash = s == t
    t[clash] = (t[clash] + 1) % n_nodes
    return np.stack([s, t], axis=1).astype(np.int64)


def synth_diff(graph: Graph, frac: float = 0.1, seed: int = 2,
               factor_range: tuple[float, float] = (1.5, 4.0)):
    """Congestion diff: slow down a random ``frac`` of edges.

    Returns ``(src, dst, new_w)`` suitable for ``write_diff`` /
    ``Graph.weights_with_diff``.
    """
    rng = np.random.default_rng(seed)
    k = max(1, int(graph.m * frac))
    eids = rng.choice(graph.m, size=k, replace=False)
    factor = rng.uniform(*factor_range, k)
    new_w = np.maximum(1, (graph.w[eids] * factor).astype(np.int64)).astype(np.int32)
    return graph.src[eids], graph.dst[eids], new_w


def ensure_synth_dataset(datadir: str, width: int = 24, height: int = 18,
                         n_queries: int = 512, seed: int = 0) -> dict:
    """Materialize the canned smoke-test dataset on disk (idempotent).

    The no-cluster analog of the reference's demo data: writes
    ``synth-city.xy``, ``synth.scen``, ``synth-city.xy.diff`` under
    ``datadir`` if absent, matching the paths ``utils.config.test_config``
    points at. Returns the path dict.
    """
    import os

    from .formats import write_diff, write_scen, write_xy

    os.makedirs(datadir, exist_ok=True)
    xy = os.path.join(datadir, "synth-city.xy")
    scen = os.path.join(datadir, "synth.scen")
    diff = os.path.join(datadir, "synth-city.xy.diff")
    if not os.path.exists(xy):
        g = synth_city_graph(width, height, seed=seed)
        write_xy(xy, g.xs, g.ys, g.src, g.dst, g.w)
    if not os.path.exists(scen):
        g = Graph.from_xy(xy)
        write_scen(scen, synth_scenario(g.n, n_queries, seed=seed + 1),
                   comment="synthetic smoke-test scenario")
    if not os.path.exists(diff):
        g = Graph.from_xy(xy)
        src, dst, new_w = synth_diff(g, seed=seed + 2)
        write_diff(diff, src, dst, new_w)
    return {"xy": xy, "scen": scen, "diff": diff}
