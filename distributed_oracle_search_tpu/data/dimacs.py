"""DIMACS 9th-challenge road-network format (.gr / .co) support.

The reference's scale-up config is DIMACS ``USA-road-d.NY`` (BASELINE.md
configs[5]): CPD build + 10M random queries. The actual files are absent
from the snapshot, but the format is standard and public:

``.gr`` (graph)::

    c <comments>
    p sp <n_nodes> <n_arcs>
    a <u> <v> <weight>          (directed arc, nodes 1-indexed)

``.co`` (coordinates)::

    c <comments>
    p aux sp co <n_nodes>
    v <id> <x> <y>              (1-indexed; x/y are signed integers,
                                 longitude/latitude * 10^6 in the road set)

This module reads both into the framework's :class:`Graph` (0-indexed) and
converts to the ``.xy`` wire format so every downstream tool — Python or
native — consumes DIMACS data unchanged:

    python -m distributed_oracle_search_tpu.data.dimacs \
        --gr USA-road-d.NY.gr --co USA-road-d.NY.co -o ny.xy
"""

from __future__ import annotations

import numpy as np

from .formats import INT_WEIGHT_DTYPE, write_xy
from .graph import Graph, INF


def read_gr(path: str):
    """Parse a DIMACS ``.gr`` file → (n, src, dst, w), 0-indexed."""
    n = m = -1
    src = dst = w = None
    ei = 0
    with open(path) as f:
        for line in f:
            tag = line[:1]
            if tag == "a":
                if src is None or ei >= m:
                    raise ValueError(
                        f"{path}: arc before 'p sp' line" if src is None
                        else f"{path}: more than {m} arcs (bad header)")
                _, u, v, ww = line.split()
                src[ei] = int(u) - 1
                dst[ei] = int(v) - 1
                wi = int(ww)
                # mirror the endpoint check: a weight at/over INF (or
                # negative) would wrap the int32 min-plus arithmetic
                # downstream (INF+INF < int32 max is the invariant)
                if not 0 <= wi < int(INF):
                    raise ValueError(
                        f"{path}: arc {u}->{v} weight {wi} outside "
                        f"[0, {int(INF)})")
                w[ei] = wi
                ei += 1
            elif tag == "p":
                toks = line.split()
                if len(toks) != 4 or toks[1] != "sp":
                    raise ValueError(f"{path}: bad problem line {line!r}")
                n, m = int(toks[2]), int(toks[3])
                src = np.empty(m, np.int64)
                dst = np.empty(m, np.int64)
                w = np.empty(m, INT_WEIGHT_DTYPE)
            elif tag in ("c", "", "\n"):
                continue
    if n < 0:
        raise ValueError(f"{path}: no 'p sp' problem line")
    if ei != m:
        raise ValueError(f"{path}: header says {m} arcs, found {ei}")
    if len(src) and (src.min() < 0 or dst.min() < 0
                     or src.max() >= n or dst.max() >= n):
        raise ValueError(f"{path}: arc endpoint out of [1, {n}]")
    return n, src, dst, w


def read_co(path: str):
    """Parse a DIMACS ``.co`` file → (n, xs, ys), 0-indexed by id."""
    n = -1
    xs = ys = None
    seen = 0
    with open(path) as f:
        for line in f:
            tag = line[:1]
            if tag == "v":
                if xs is None:
                    raise ValueError(
                        f"{path}: vertex before 'p aux sp co' line")
                _, i, x, y = line.split()
                idx = int(i) - 1
                if not 0 <= idx < n:
                    raise ValueError(
                        f"{path}: vertex id {i} out of [1, {n}] "
                        "(DIMACS ids are 1-indexed)")
                xs[idx] = int(x)
                ys[idx] = int(y)
                seen += 1
            elif tag == "p":
                toks = line.split()
                if toks[-2:-1] == ["co"] or (len(toks) == 5
                                             and toks[3] == "co"):
                    n = int(toks[-1])
                else:
                    raise ValueError(f"{path}: bad aux line {line!r}")
                xs = np.zeros(n, np.int64)
                ys = np.zeros(n, np.int64)
            elif tag in ("c", "", "\n"):
                continue
    if n < 0:
        raise ValueError(f"{path}: no 'p aux sp co' line")
    if seen != n:
        raise ValueError(f"{path}: header says {n} nodes, found {seen}")
    return n, xs, ys


def graph_from_dimacs(gr_path: str, co_path: str | None = None) -> Graph:
    """Load a DIMACS graph (+ optional coordinates) as a :class:`Graph`.

    Without a ``.co`` file, coordinates default to zeros — everything
    works except coordinate-based query ordering
    (``CPDOracle._length_estimate`` degrades to no sort) and geometric
    heuristics (A*'s h ≡ 0 = plain Dijkstra, still correct).
    """
    n, src, dst, w = read_gr(gr_path)
    if co_path:
        nc, xs, ys = read_co(co_path)
        if nc != n:
            raise ValueError(
                f"{gr_path} has {n} nodes but {co_path} has {nc}")
    else:
        xs = np.zeros(n, np.int64)
        ys = np.zeros(n, np.int64)
    return Graph(xs, ys, src, dst, w)


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        description="Convert DIMACS .gr/.co to the .xy wire format")
    p.add_argument("--gr", required=True, help="DIMACS .gr graph file")
    p.add_argument("--co", default=None, help="DIMACS .co coordinate file")
    p.add_argument("-o", "--output", required=True, help=".xy output path")
    args = p.parse_args(argv)
    g = graph_from_dimacs(args.gr, args.co)
    write_xy(args.output, g.xs, g.ys, g.src, g.dst, g.w)
    print(f"{args.output}: {g.n} nodes, {g.m} arcs")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
