"""On-disk formats: .xy graphs, .scen scenarios, .diff congestion files.

The reference consumes warthog's formats, whose full specs live in the absent
C++ submodule. The Python side pins down only these structural facts, which we
preserve exactly:

* **xy**: the node count is the 2nd whitespace token of the 4th line
  (reference ``process_query.py:126-130``).
* **scen**: query lines start with ``q`` followed by integer fields; drivers
  keep ``[s, t]`` (reference ``process_query.py:22-32``).
* **diff**: a per-edge travel-time perturbation applied at query time only,
  never at CPD-build time (reference ``make_fifos.py:18,21`` vs
  ``make_cpds.py:20``); ``"-"`` means no perturbation (``args.py:169``).

Concrete grammar used by this framework (self-describing, versioned):

xy::

    xy graph
    v 1
    header end
    p <n_nodes> <n_edges> 0          <- 4 tokens, 2nd = node count
    v <x> <y>                        (n_nodes lines; ids implicit 0..n-1)
    e <src> <dst> <weight>           (n_edges lines; weight = int travel time)

scen::

    c <free-form comment lines>
    q <s> <t>                        (one query per line)

diff::

    d <n_entries>
    <src> <dst> <new_weight>         (replaces the weight of edge src->dst)
"""

from __future__ import annotations

import numpy as np

from ..utils.atomicio import atomic_write_bytes

XY_MAGIC = "xy graph"
INT_WEIGHT_DTYPE = np.int32


def write_xy(path: str, xs: np.ndarray, ys: np.ndarray,
             src: np.ndarray, dst: np.ndarray, w: np.ndarray) -> None:
    n, m = len(xs), len(src)
    out = [f"{XY_MAGIC}\nv 1\nheader end\np {n} {m} 0"]
    out += ["v %d %d" % (x, y) for x, y in zip(xs, ys)]
    out += ["e %d %d %d" % (u, v, ww) for u, v, ww in zip(src, dst, w)]
    atomic_write_bytes(path, ("\n".join(out) + "\n").encode())


def xy_node_count(path: str) -> int:
    """Node count from the 4th line, 2nd token — the one structural contract
    the reference relies on (``process_query.py:126-130``)."""
    with open(path) as f:
        for i, line in enumerate(f):
            if i == 3:
                return int(line.split()[1])
    raise ValueError(f"{path}: fewer than 4 header lines")


def read_xy(path: str):
    """Parse an xy graph → (xs, ys, src, dst, w) numpy arrays."""
    with open(path) as f:
        lines = f.read().split("\n")
    if not lines or lines[0].strip() != XY_MAGIC:
        raise ValueError(f"{path}: bad magic (expected {XY_MAGIC!r})")
    toks = lines[3].split()
    n, m = int(toks[1]), int(toks[2])
    xs = np.empty(n, np.int64)
    ys = np.empty(n, np.int64)
    src = np.empty(m, np.int64)
    dst = np.empty(m, np.int64)
    w = np.empty(m, INT_WEIGHT_DTYPE)
    vi = ei = 0
    for line in lines[4:]:
        if not line:
            continue
        tag = line[0]
        if tag == "v":
            _, x, y = line.split()
            xs[vi], ys[vi] = int(x), int(y)
            vi += 1
        elif tag == "e":
            _, u, v, ww = line.split()
            src[ei], dst[ei], w[ei] = int(u), int(v), int(ww)
            ei += 1
    if vi != n or ei != m:
        raise ValueError(f"{path}: header says {n} nodes/{m} edges, "
                         f"found {vi}/{ei}")
    return xs, ys, src, dst, w


def write_scen(path: str, queries: np.ndarray, comment: str = "") -> None:
    out = ["c tpu-oracle scenario v1"]
    if comment:
        out.append(f"c {comment}")
    out += ["q %d %d" % (s, t) for s, t in queries]
    atomic_write_bytes(path, ("\n".join(out) + "\n").encode())


def read_scen(path: str) -> np.ndarray:
    """Read a point-to-point scenario → int64 array [Q, 2] of (s, t).

    Same acceptance rule as the reference reader: only lines whose first
    character is ``q`` count; every other line is ignored
    (``process_query.py:22-32``).
    """
    ss, ts = [], []
    with open(path) as f:
        for line in f:
            if not line.strip() or line[0] != "q":
                continue
            fields = line.split()[1:]
            ss.append(int(fields[0]))
            ts.append(int(fields[1]))
    return np.stack([np.asarray(ss, np.int64), np.asarray(ts, np.int64)],
                    axis=1) if ss else np.zeros((0, 2), np.int64)


def write_diff(path: str, src: np.ndarray, dst: np.ndarray,
               new_w: np.ndarray) -> None:
    out = [f"d {len(src)}"]
    out += ["%d %d %d" % (u, v, ww) for u, v, ww in zip(src, dst, new_w)]
    atomic_write_bytes(path, ("\n".join(out) + "\n").encode())


def read_diff(path: str):
    """Parse a diff file → (src, dst, new_w). ``"-"`` / empty → no entries."""
    if path in ("-", "", None):
        z = np.zeros(0, np.int64)
        return z, z, np.zeros(0, INT_WEIGHT_DTYPE)
    with open(path) as f:
        header = f.readline().split()
        k = int(header[1])
        src = np.empty(k, np.int64)
        dst = np.empty(k, np.int64)
        w = np.empty(k, INT_WEIGHT_DTYPE)
        for i in range(k):
            u, v, ww = f.readline().split()
            src[i], dst[i], w[i] = int(u), int(v), int(ww)
    return src, dst, w
