"""In-memory road-graph representation.

Host-side (NumPy) container with two derived layouts:

* **CSR** (out- and in-edge) — used by the CPU reference oracles (Dijkstra,
  A*), the role warthog's graph classes play in the reference (§C5 of
  SURVEY.md; the C++ submodule itself is absent from the snapshot).
* **Padded ELL** — fixed-width neighbor tables ``[N, K]`` (K = max degree),
  the TPU-friendly layout: every Bellman-Ford relaxation and first-move
  extraction becomes a dense gather + min over the K axis, which XLA tiles
  onto the VPU without dynamic shapes. Road networks have tiny max degree
  (grid-like, K ≲ 8), so padding waste is bounded.

Weights are int32 travel times. ``INF`` is chosen so that ``INF + INF`` still
fits in int32 (no overflow traps inside jitted min-plus updates).

Congestion diffs perturb **query-time** weights only — the CPD is always built
on the free-flow weights, mirroring the reference (diff files are passed to
``fifo_auto`` but never to ``make_cpd_auto``: reference ``make_fifos.py:21``
vs ``make_cpds.py:20``).
"""

from __future__ import annotations

import numpy as np

from .formats import read_xy, read_diff

INF = np.int32(10 ** 9)  # INF + INF < int32 max; real path costs stay far below


def _shift_planes(src, dst, w, n: int, max_shifts: int, cap: int):
    """Extract constant-offset edge planes: ``(shifts, w_shift, covered)``.

    ``w_shift[s, u]`` = weight of edge ``u → u+shifts[s]`` (min over
    parallels; INF absent). Offsets beyond ``±cap`` or past the
    ``max_shifts`` most frequent stay uncovered. Shared by
    :meth:`Graph.shift_split` and :meth:`Graph.grid_split`.
    """
    delta = dst - src
    vals, counts = np.unique(delta, return_counts=True)
    ok = np.abs(vals) <= cap
    vals, counts = vals[ok], counts[ok]
    keep = vals[np.argsort(-counts)[:max_shifts]]
    shifts = tuple(int(s) for s in keep)
    w_shift = np.full((len(shifts), n), int(INF), np.int32)
    covered = np.zeros(len(src), bool)
    for si, s in enumerate(shifts):
        mask = delta == s
        np.minimum.at(w_shift[si], src[mask], w[mask])
        covered |= mask
    return shifts, w_shift, covered


def _leftover_ell(src_l, dst_l, w_l, n: int):
    """Pack uncovered edges into a padded ELL table ``(nbr, w)`` [N, K].

    Shared by :meth:`Graph.shift_split` and :meth:`Graph.grid_split`:
    whatever edges a structured relaxation cannot serve gather-free fall
    back to this (small) table. K may be 0 → empty arrays.
    """
    deg = np.bincount(src_l, minlength=n)
    k_left = int(deg.max()) if len(src_l) else 0
    nbr = np.repeat(np.arange(n, dtype=np.int32)[:, None],
                    max(k_left, 1), axis=1)
    w = np.full((n, max(k_left, 1)), int(INF), np.int32)
    if len(src_l):
        order = np.argsort(src_l, kind="stable")
        starts = np.cumsum(np.concatenate([[0], deg[:-1]]))
        slot = np.arange(len(src_l)) - np.repeat(starts, deg)
        nbr[src_l[order], slot] = dst_l[order].astype(np.int32)
        # parallel uncovered edges to the same dst would collide in the
        # ELL slot only if they shared (src, slot); distinct slots keep
        # them separate, min falls out of the relaxation itself
        w[src_l[order], slot] = w_l[order]
    if k_left == 0:
        nbr = nbr[:, :0]
        w = w[:, :0]
    return nbr, w


class Graph:
    """Directed graph with int32 edge weights.

    Attributes
    ----------
    n, m        : node / edge counts
    xs, ys      : int64 [n] node coordinates
    src, dst    : int64 [m] edge endpoints, file order
    w           : int32 [m] free-flow travel times, file order
    out_ptr     : int64 [n+1] CSR row pointers (by src)
    out_eid     : int64 [m] edge ids sorted by src (CSR order)
    in_ptr/in_eid : same for the reverse graph (by dst)
    """

    def __init__(self, xs, ys, src, dst, w):
        self.xs = np.asarray(xs, np.int64)
        self.ys = np.asarray(ys, np.int64)
        self.src = np.asarray(src, np.int64)
        self.dst = np.asarray(dst, np.int64)
        self.w = np.asarray(w, np.int32)
        self.n = len(self.xs)
        self.m = len(self.src)
        if np.any(self.w < 0):
            raise ValueError("negative edge weights are not supported")
        if self.m and (self.src.min() < 0 or self.src.max() >= self.n
                       or self.dst.min() < 0 or self.dst.max() >= self.n):
            raise ValueError("edge endpoint out of range")

        self.out_ptr, self.out_eid = self._csr(self.src)
        self.in_ptr, self.in_eid = self._csr(self.dst)
        self._edge_key_sorted = None
        self._edge_key_order = None
        self._ell_cache: dict = {}

    # ---------------------------------------------------------------- CSR
    def _csr(self, keys: np.ndarray):
        order = np.argsort(keys, kind="stable")
        ptr = np.zeros(self.n + 1, np.int64)
        np.add.at(ptr, keys + 1, 1)
        np.cumsum(ptr, out=ptr)
        return ptr, order

    def out_edges(self, u: int):
        """(dst, eid) arrays of u's out-edges."""
        eids = self.out_eid[self.out_ptr[u]:self.out_ptr[u + 1]]
        return self.dst[eids], eids

    def in_edges(self, v: int):
        eids = self.in_eid[self.in_ptr[v]:self.in_ptr[v + 1]]
        return self.src[eids], eids

    @property
    def max_out_degree(self) -> int:
        return int(np.max(np.diff(self.out_ptr))) if self.n else 0

    @property
    def max_in_degree(self) -> int:
        return int(np.max(np.diff(self.in_ptr))) if self.n else 0

    # ---------------------------------------------------------------- ELL
    def ell(self, direction: str = "out"):
        """Padded fixed-width neighbor table.

        Returns ``(nbr, eid)``: int32 ``[N, K]`` arrays. ``nbr[u, k]`` is the
        k-th neighbor of ``u`` (out- or in-), ``eid[u, k]`` the edge id for
        weight lookup. Padding: ``nbr = u`` itself, ``eid = m`` (one past the
        last edge — weight arrays handed to the device get an extra INF slot
        so padded lanes never win a min).

        Slot order is ascending edge id, which makes first-move slot indices
        deterministic and lets golden tests compare against the CPU oracle's
        tie-breaking (SURVEY.md §7 "hard parts").
        """
        if direction in self._ell_cache:
            return self._ell_cache[direction]
        if direction == "out":
            ptr, eid_sorted, n = self.out_ptr, self.out_eid, self.n
        elif direction == "in":
            ptr, eid_sorted, n = self.in_ptr, self.in_eid, self.n
        else:
            raise ValueError(direction)
        deg = np.diff(ptr)
        k = max(int(deg.max()) if n else 0, 1)
        nbr = np.repeat(np.arange(n, dtype=np.int32)[:, None], k, axis=1)
        eid = np.full((n, k), self.m, np.int32)
        # scatter each edge into its row slot
        slot = np.arange(self.m, dtype=np.int64) - np.repeat(ptr[:-1], deg)
        rows = np.repeat(np.arange(n, dtype=np.int64), deg)
        eids = eid_sorted
        other = self.dst[eids] if direction == "out" else self.src[eids]
        nbr[rows, slot] = other.astype(np.int32)
        eid[rows, slot] = eids.astype(np.int32)
        self._ell_cache[direction] = (nbr, eid)
        return nbr, eid

    def padded_weights(self, w: np.ndarray | None = None) -> np.ndarray:
        """Weight vector with the extra INF slot addressed by ELL padding."""
        base = self.w if w is None else np.asarray(w, np.int32)
        return np.concatenate([base, np.asarray([INF], np.int32)])

    def padded_weights_multi(self, w_list) -> np.ndarray:
        """``[D, M+1]`` int32 — one padded weight row per diff round
        (``None`` entries mean free flow): the weight operand of every
        fused multi-diff path (walk, streamed, doubled tables)."""
        return np.stack([np.asarray(self.padded_weights(w), np.int32)
                         for w in w_list])

    # --------------------------------------------------------------- diffs
    def _edge_lookup(self):
        if self._edge_key_sorted is None:
            key = self.src * np.int64(self.n) + self.dst
            order = np.argsort(key, kind="stable")
            self._edge_key_sorted = key[order]
            self._edge_key_order = order
        return self._edge_key_sorted, self._edge_key_order

    def edge_ids(self, src: np.ndarray, dst: np.ndarray) -> np.ndarray:
        """Edge ids for (src, dst) pairs; raises if any pair is absent."""
        want_src = np.asarray(src, np.int64)
        want_dst = np.asarray(dst, np.int64)
        if self.m == 0:
            if len(want_src):
                raise KeyError(f"edge {want_src[0]}->{want_dst[0]} not in graph")
            return np.zeros(0, np.int64)
        keys_sorted, order = self._edge_lookup()
        want = want_src * np.int64(self.n) + want_dst
        pos = np.searchsorted(keys_sorted, want)
        ok = (pos < self.m) & (keys_sorted[np.minimum(pos, self.m - 1)] == want)
        if not np.all(ok):
            bad = np.argmin(ok)
            raise KeyError(f"edge {src[bad]}->{dst[bad]} not in graph")
        return order[pos]

    def weights_with_diff(self, diff) -> np.ndarray:
        """Apply a congestion diff → new int32 weight vector (file edge order).

        ``diff`` is a path (``"-"`` → free flow) or ``(src, dst, new_w)``
        arrays. Entries replace the weight of the named edge.
        """
        if isinstance(diff, str) or diff is None:
            dsrc, ddst, dw = read_diff(diff)
        else:
            dsrc, ddst, dw = diff
        w = self.w.copy()
        if len(dsrc):
            w[self.edge_ids(dsrc, ddst)] = dw
        return w

    # ---------------------------------------------------------------- shift
    def shift_split(self, max_shifts: int = 64):
        """Split edges into shift-structured + leftover sets for the
        gather-free relaxation (``ops.shift_relax``).

        Road-network node ids laid out with locality (grid row-major, or
        RCM/BFS orderings) put most edges at a few constant id-offsets
        ``dst - src``. For those, min-plus relaxation needs no gather at
        all: it is a shifted add + min, pure VPU work. The remaining
        edges fall back to a (small) padded ELL gather.

        Returns ``(shifts, w_shift, nbr_left, w_left)``:

        * ``shifts``  tuple of ints, the kept offsets (≤ ``max_shifts``,
          most-frequent first),
        * ``w_shift`` int32 ``[S, N]``: weight of edge ``u → u+shifts[s]``
          (min over parallel edges; INF where absent),
        * ``nbr_left``/``w_left`` int32 ``[N, K_left]`` padded ELL of the
          uncovered edges (``K_left`` may be 0 → empty arrays).

        Free-flow weights only — this feeds the CPD build, which is always
        free-flow (reference semantics).
        """
        # magnitude cap: the relaxation pads the distance array by
        # max|shift| rows every iteration, so one frequent long-range
        # offset must not be allowed to blow up the working set — beyond
        # n/8 an offset goes to the leftover gather instead. The floor
        # keeps small graphs (where even the full width is cheap) intact.
        shifts, w_shift, covered = _shift_planes(
            self.src, self.dst, self.w, self.n, max_shifts,
            cap=max(256, self.n // 8))
        nbr_left, w_left = _leftover_ell(
            self.src[~covered], self.dst[~covered], self.w[~covered], self.n)
        return shifts, w_shift, nbr_left, w_left

    def grid_split(self, width: int | None = None):
        """Split edges into 4 directional grid-lattice arrays + leftover ELL
        for the fast-sweeping relaxation (``ops.grid_sweep``).

        Row-major grid ids (``id = y*width + x``) put street edges at offsets
        ``±1`` (same row) and ``±width``. The sweep build relaxes those with
        sequential line scans; everything else (arterials, wrap-arounds)
        goes to the leftover gather.

        Returns ``(width, height, wl, wr, wd, wu, shifts, w_shift,
        src_left, dst_left, w_left)`` where ``wl[u]`` is the weight of edge
        ``u → u-1`` (same row; INF when absent), ``wr``/``wd``/``wu``
        likewise for ``u+1`` / ``u-width`` / ``u+width``; leftover edges on
        frequent constant offsets become shift planes ``shifts``/``w_shift``
        (relaxed gather-free once per sweep cycle) and true stragglers stay
        an explicit ``src_left``/``dst_left``/``w_left`` edge list for
        scatter-min relaxation. Returns ``None`` when no grid layout fits
        (width not inferable, or ``n`` not a multiple of it). Free-flow
        weights only.
        """
        delta = self.dst - self.src
        if width is None:
            big = np.abs(delta[np.abs(delta) > 1])
            if big.size == 0:
                return None
            vals, counts = np.unique(big, return_counts=True)
            width = int(vals[np.argmax(counts)])
        if width < 2 or self.n % width:
            return None
        height = self.n // width
        sx = self.src % width
        masks = {
            "wr": (delta == 1) & (sx < width - 1),
            "wl": (delta == -1) & (sx > 0),
            "wu": delta == width,
            "wd": delta == -width,
        }
        out = {}
        covered = np.zeros(self.m, bool)
        for name, mask in masks.items():
            arr = np.full(self.n, int(INF), np.int32)
            np.minimum.at(arr, self.src[mask], self.w[mask])
            out[name] = arr
            covered |= mask
        rest = ~covered
        shifts, w_shift, cov_s = _shift_planes(
            self.src[rest], self.dst[rest], self.w[rest], self.n,
            max_shifts=32, cap=max(256, self.n // 8))
        rest_idx = np.nonzero(rest)[0][~cov_s]
        # stragglers stay an explicit edge list (scatter-min relaxation):
        # they are rare (clip artifacts at grid borders), so per-edge cost
        # beats any [N, K] table
        return (width, height, out["wl"], out["wr"], out["wd"], out["wu"],
                shifts, w_shift, self.src[rest_idx].astype(np.int32),
                self.dst[rest_idx].astype(np.int32), self.w[rest_idx])

    # ----------------------------------------------------------- ordering
    def reorder(self, perm: np.ndarray) -> "Graph":
        """Relabel nodes: new id ``i`` is old node ``perm[i]``.

        The analog of the reference's ``--order`` NodeOrdering override
        (reference ``args.py:119``). Node ordering is load-bearing here:
        the shift-coverage and fast-sweeping build gates key on id
        locality (``shift_split``/``grid_split``), so an
        arbitrarily-ordered real graph reordered by BFS/RCM hits the fast
        kernels. Costs and paths are invariant — only labels move (query
        node ids must be mapped through the inverse permutation; see
        ``cli.reorder``).
        """
        perm = np.asarray(perm, np.int64)
        if not np.array_equal(np.sort(perm), np.arange(self.n)):
            raise ValueError("perm must be a permutation of 0..n-1")
        inv = np.empty(self.n, np.int64)
        inv[perm] = np.arange(self.n)
        return Graph(self.xs[perm], self.ys[perm],
                     inv[self.src], inv[self.dst], self.w)

    def _undirected_csr(self):
        """Symmetrized adjacency (ptr, nbr) for ordering algorithms."""
        su = np.concatenate([self.src, self.dst])
        sv = np.concatenate([self.dst, self.src])
        order = np.argsort(su, kind="stable")
        ptr = np.zeros(self.n + 1, np.int64)
        np.add.at(ptr, su + 1, 1)
        np.cumsum(ptr, out=ptr)
        return ptr, sv[order]

    @staticmethod
    def frontier_neighbors(ptr, nbr, frontier):
        """All neighbors of ``frontier`` via CSR, one vectorized gather
        (the shared inner step of every level-synchronous BFS here)."""
        counts = ptr[frontier + 1] - ptr[frontier]
        idx = np.repeat(ptr[frontier], counts) + (
            np.arange(counts.sum())
            - np.repeat(np.cumsum(counts) - counts, counts))
        return np.unique(nbr[idx])

    def _bfs_traversal(self, seed_order, frontier_key=None) -> np.ndarray:
        """Level-synchronous vectorized BFS visit order (restarting per
        component along ``seed_order``); ``frontier_key(nodes) -> key``
        optionally sorts each new frontier (Cuthill–McKee's degree rule).
        A 264k-node graph orders in milliseconds — no per-node Python.
        """
        ptr, nbr = self._undirected_csr()
        visited = np.zeros(self.n, bool)
        out = np.empty(self.n, np.int64)
        k = 0
        si = 0
        while k < self.n:
            while visited[seed_order[si]]:
                si += 1
            frontier = np.asarray([seed_order[si]])
            visited[frontier] = True
            while len(frontier):
                out[k:k + len(frontier)] = frontier
                k += len(frontier)
                nxt = self.frontier_neighbors(ptr, nbr, frontier)
                nxt = nxt[~visited[nxt]]
                visited[nxt] = True
                frontier = (nxt if frontier_key is None
                            else nxt[np.argsort(frontier_key(nxt),
                                                kind="stable")])
        return out

    def bfs_order(self, start: int = 0) -> np.ndarray:
        """BFS permutation (new → old), restarting per component."""
        ids = np.arange(self.n)
        return self._bfs_traversal(
            np.concatenate([[start], ids[ids != start]]))

    def rcm_order(self) -> np.ndarray:
        """Reverse Cuthill–McKee permutation (new → old).

        The classic bandwidth-minimizing ordering: BFS from a low-degree
        peripheral node, neighbors visited in ascending degree, result
        reversed. Low bandwidth = neighbor ids close together = high
        shift coverage for the banded build kernel.
        """
        ptr, _ = self._undirected_csr()
        deg = np.diff(ptr)
        out = self._bfs_traversal(np.argsort(deg, kind="stable"),
                                  frontier_key=lambda nodes: deg[nodes])
        return out[::-1].copy()

    # ----------------------------------------------------------------- io
    @classmethod
    def from_xy(cls, path: str) -> "Graph":
        xs, ys, src, dst, w = read_xy(path)
        return cls(xs, ys, src, dst, w)

    def __repr__(self):
        return f"Graph(n={self.n}, m={self.m}, Kout={self.max_out_degree})"
