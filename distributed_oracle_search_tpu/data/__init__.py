from .graph import Graph
from .formats import (
    read_xy, write_xy, read_scen, write_scen, read_diff, write_diff,
    xy_node_count,
)
from .synth import (synth_city_graph, synth_road_network, synth_scenario,
                    synth_diff, ensure_synth_dataset)
from .dimacs import graph_from_dimacs, read_co, read_gr

__all__ = [
    "Graph", "read_xy", "write_xy", "read_scen", "write_scen",
    "read_diff", "write_diff", "xy_node_count",
    "synth_city_graph", "synth_road_network", "synth_scenario",
    "synth_diff", "ensure_synth_dataset",
    "graph_from_dimacs", "read_co", "read_gr",
]
