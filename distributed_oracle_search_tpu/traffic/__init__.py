"""Live traffic plane: streaming congestion diffs and new query families.

The reference answers queries "optionally on a congestion-perturbed
graph supplied as a ``.diff`` file" — one static file fixed for a whole
campaign or serve session. Production congestion is a *stream*: edge
weights change every few minutes, and the questions are heterogeneous
(ETA matrices, alternative routes, reverse routing), not just single
s–t walks. This package makes the **workload** dynamic the way
``parallel.membership`` made the **fleet** dynamic:

* :mod:`.segments` — the epoch-tagged diff *segment* codec: one JSON
  header line (unknown-key tolerant, rejects only NEWER schema
  versions — the repo-wide wire-compat contract) followed by
  ``src dst new_w`` entries, written atomically;
* :mod:`.stream` — :class:`~.stream.DiffStream` sources: watch a
  segment directory (the shared-nfs deployment) or tail a single
  append-only spool file, tolerating the torn tail a non-atomic
  producer leaves mid-write;
* :mod:`.epochs` — :class:`~.epochs.DiffEpochManager`: merges pending
  segments into ONE fused diff per swap (the fused multi-diff insight —
  bench measures 3.7× fused vs sequential — applied to ingestion: N
  queued segments cost one weights upload, not N), materializes it as
  an ordinary ``.diff`` file the whole existing wire/engine machinery
  serves unchanged, and reports the affected-edge set that drives
  *scoped* cache invalidation. The diff epoch rides ``RuntimeConfig``
  next to the membership epoch with the same tolerate-older /
  gate-newer rule;
* :mod:`.families` — the new query families on the same shard oracle:
  one-to-many ETA matrices (``mat``), k-alternative routes via
  penalized re-walks over distinct first edges (``alt``), and reverse
  source-owner routing (``rev``), each a typed request on the serve
  line protocol;
* :mod:`.scenarios` — the workload generator: grid / road / power-law
  topologies, zipf hotspot query pools, and rush-hour replay traces
  that emit timed diff segments for the bench and the chaos drills.

Knobs (all through ``utils.env``; malformed values degrade, logged):

=============================  ========  ================================
env var                        default   meaning
=============================  ========  ================================
``DOS_TRAFFIC_POLL_MS``        200       epoch-pump poll interval
``DOS_TRAFFIC_KEEP_EPOCHS``    2         fused diff FILES kept in the
                                         spool — >= 2 so a batch pinned
                                         to the previous epoch can
                                         still read its file
``DOS_TRAFFIC_WEIGHT_EPOCHS``  4         per-diff DEVICE weight buffers
                                         the engine keeps resident
                                         (LRU; floor 2 = the swap
                                         double buffer: in-flight
                                         batches finish on the old
                                         epoch's buffer)
``DOS_TRAFFIC_SCOPED_MAX``     4096      affected-edge count above which
                                         scoped invalidation falls back
                                         to a full cache flush
``DOS_TRAFFIC_SIG_MOVES``      64        path-signature moves captured
                                         per cached entry (entries with
                                         longer paths invalidate
                                         conservatively)
=============================  ========  ================================
"""

from .epochs import DiffEpochManager
from .families import QueryFamilies, parse_family_line
from .segments import (
    DiffSegment, SEGMENT_SCHEMA, list_segments, read_segment,
    segment_path, write_segment,
)
from .stream import DiffStream, TailDiffStream

__all__ = [
    "DiffEpochManager", "DiffSegment", "DiffStream", "QueryFamilies",
    "SEGMENT_SCHEMA", "TailDiffStream", "list_segments",
    "parse_family_line", "read_segment", "segment_path",
    "write_segment",
]
