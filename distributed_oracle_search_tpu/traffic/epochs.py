"""Diff epoch manager: fused swap of the active congestion diff.

One :class:`DiffEpochManager` per serving process (frontend or worker)
turns the segment stream into a sequence of **epoch swaps**:

* every ``refresh()`` pulls ALL pending segments and merges them into
  the running edge-weight delta in one pass — the fused multi-diff
  insight (one walk accumulates D diffs' costs; bench measures 3.7×
  fused vs sequential) applied to ingestion: N queued segments cost
  ONE materialized diff, one cache-invalidation pass, and one device
  weights upload, never N sequential swaps;
* the merged delta is materialized as an ordinary ``.diff`` file
  (``fused-e<epoch>.diff`` in the spool dir, atomic write), so the
  entire existing machinery — ``RuntimeConfig`` wire line 2, the
  engine's per-diff weight cache, the FIFO workers — serves the new
  epoch **without restart**: the serve path just starts naming the new
  file. In-flight batches pinned the old file name at dispatch and
  finish on the old epoch's device weights (the engine keeps the last
  ``DOS_TRAFFIC_KEEP_EPOCHS`` weight buffers resident — double
  buffering at the weights-array level);
* each swap reports its **affected-edge set** — the edges whose weight
  actually changed vs the previously active fusion — which is what
  lets the serving cache invalidate *scoped* instead of flushing
  wholesale (``serving.cache.ResultCache.invalidate_scoped``).

The manager never owns a thread: the frontend's epoch pump and the
worker's gate-time refresh call ``refresh()`` from exactly one place
each, so the internal lock only guards the published snapshot, and no
file IO ever happens under it.
"""

from __future__ import annotations

import glob
import os
import time

import numpy as np

from ..data.formats import read_diff, write_diff
from ..obs import metrics as obs_metrics
from ..utils.env import env_cast
from ..utils.locks import OrderedLock
from ..utils.log import get_logger
from .stream import DiffStream

log = get_logger(__name__)

M_SEGS = obs_metrics.counter(
    "traffic_segments_applied_total",
    "diff segments merged into an epoch swap")
M_EDGES = obs_metrics.counter(
    "traffic_edges_updated_total",
    "edges whose weight actually changed across epoch swaps")
G_EPOCH = obs_metrics.gauge(
    "traffic_epoch",
    "active diff epoch (0 = the static base diff, pre-traffic world)")
H_SWAP = obs_metrics.histogram(
    "traffic_swap_seconds",
    "segment merge + fused-diff materialization per epoch swap")


class DiffEpochManager:
    """See module docstring. ``stream`` is a segment source (anything
    with ``poll() -> list[DiffSegment]``) or a directory path (wrapped
    in a :class:`~.stream.DiffStream`). ``materialize=False`` tracks
    epochs without writing fused files — the worker-server gate mode,
    where the head already materialized the file the wire names."""

    def __init__(self, stream, base_diff: str = "-",
                 spool_dir: str | None = None, materialize: bool = True,
                 keep_epochs: int | None = None,
                 scoped_max: int | None = None,
                 sig_moves: int | None = None,
                 poll_ms: float | None = None,
                 on_swap=None):
        if isinstance(stream, str):
            stream = DiffStream(stream)
        self.stream = stream
        self.base_diff = base_diff
        self.materialize = materialize
        default_spool = (os.path.join(stream.dirname, "fused")
                         if isinstance(stream, DiffStream) else None)
        self.spool = spool_dir or default_spool
        if materialize and not self.spool:
            raise ValueError("a materializing DiffEpochManager needs a "
                             "spool dir (tail streams have no default)")
        #: fused diff files (and engine weight buffers) kept live; >= 2
        #: so an in-flight batch can finish on the old epoch's file
        self.keep_epochs = max(
            2, keep_epochs if keep_epochs is not None
            else env_cast("DOS_TRAFFIC_KEEP_EPOCHS", 2, int))
        #: affected-edge count above which scoped invalidation is not
        #: worth the per-entry scan: the cache flushes wholesale
        self.scoped_max = (scoped_max if scoped_max is not None
                           else env_cast("DOS_TRAFFIC_SCOPED_MAX",
                                         4096, int))
        #: path-signature moves the frontend asks the engine for
        self.sig_moves = (sig_moves if sig_moves is not None
                          else env_cast("DOS_TRAFFIC_SIG_MOVES", 64, int))
        self.poll_s = (poll_ms if poll_ms is not None
                       else env_cast("DOS_TRAFFIC_POLL_MS", 200.0,
                                     float)) / 1e3
        # base-diff overlay: (u, v) -> w of the static starting diff,
        # so fused files always carry base + every segment to date
        bsrc, bdst, bw = read_diff(base_diff)
        self._base = {(int(u), int(v)): int(ww)
                      for u, v, ww in zip(bsrc, bdst, bw)}
        self._delta: dict[tuple[int, int], int] = {}
        #: segments polled but not yet published: the stream advances
        #: its cursor inside poll(), so a failed materialization must
        #: NOT drop them — they stay here and the next refresh retries
        #: the fusion (losing one would silently omit its retimes from
        #: every later epoch)
        self._pending: list = []
        self._lock = OrderedLock("traffic.DiffEpochManager")
        self.epoch = 0
        self.difffile = base_diff
        self._affected: frozenset = frozenset()
        self._applied = 0
        #: retime→rebuild trigger hook: called AFTER a swap publishes,
        #: outside the lock, as ``on_swap(epoch, difffile, affected)``
        #: — the seam a delta-rebuild consumer registers on (kick
        #: ``models.cpd.delta_build_index`` for the new weight regime
        #: in the background, then promote the epoch-tagged index via
        #: ``ShardEngine.promote_index``). A raising hook is logged and
        #: never blocks or unwinds the swap itself.
        self.on_swap = on_swap

    # ------------------------------------------------------------- views
    def active(self) -> tuple[int, str, frozenset]:
        """Consistent ``(epoch, difffile, affected_last_swap)``
        snapshot."""
        with self._lock:
            return self.epoch, self.difffile, self._affected

    def weight_of(self, u: int, v: int, default: int) -> int:
        """Edge (u, v)'s weight under the ACTIVE fusion — segments win
        over the base diff, the base diff over ``default`` (the
        free-flow weight). The query-families planner prices first
        edges with this."""
        with self._lock:
            w = self._delta.get((int(u), int(v)))
        if w is None:
            w = self._base.get((int(u), int(v)))
        return int(default if w is None else w)

    def statusz(self) -> dict:
        with self._lock:
            return {
                "diff_epoch": int(self.epoch),
                "difffile": self.difffile,
                "segments_applied": int(self._applied),
                "affected_last_swap": len(self._affected),
            }

    # ------------------------------------------------------------ refresh
    def refresh(self) -> bool:
        """Pull pending segments; on any, fuse them into one new epoch
        and publish it. Returns True iff the epoch advanced. Stream
        errors (a torn mid-stream segment) degrade to "no swap" with a
        log line: serving continues on the last good epoch — the same
        keep-the-current-table rule the membership refresh uses."""
        t0 = time.perf_counter()
        try:
            self._pending.extend(self.stream.poll())
        except (OSError, ValueError) as e:
            log.error("diff stream poll failed: %s (keeping epoch %d)",
                      e, self.epoch)
            return False
        segs = self._pending
        if not segs:
            return False
        new_delta = dict(self._delta)
        affected: set[tuple[int, int]] = set()
        for seg in segs:
            for u, v, w in zip(seg.src, seg.dst, seg.w):
                key = (int(u), int(v))
                prev = new_delta.get(key, self._base.get(key))
                if prev is None or int(prev) != int(w):
                    affected.add(key)
                new_delta[key] = int(w)
        epoch = int(segs[-1].epoch)
        try:
            difffile = self._materialize(epoch, new_delta)
        except OSError as e:
            # keep the segments pending: publishing without the fused
            # file would name a path nobody can read, and dropping them
            # would omit their retimes from every later fusion forever
            log.error("fused diff for epoch %d failed to materialize: "
                      "%s (keeping epoch %d; %d segment(s) stay "
                      "pending)", epoch, e, self.epoch, len(segs))
            return False
        with self._lock:
            self._delta = new_delta
            self.epoch = epoch
            self.difffile = difffile
            self._affected = frozenset(affected)
            self._applied += len(segs)
        self._pending = []
        M_SEGS.inc(len(segs))
        M_EDGES.inc(len(affected))
        G_EPOCH.set(epoch)
        H_SWAP.observe(time.perf_counter() - t0)
        log.info("diff epoch %d active: %d segment(s) fused, %d edge(s) "
                 "changed -> %s", epoch, len(segs), len(affected),
                 difffile)
        if self.on_swap is not None:
            try:
                self.on_swap(epoch, difffile, frozenset(affected))
            except Exception as e:  # noqa: BLE001 — a rebuild trigger
                # must never unwind a published swap; serving continues
                log.error("on_swap hook failed for epoch %d: %s",
                          epoch, e)
        self._prune_spool(epoch)
        return True

    def _materialize(self, epoch: int, delta: dict) -> str:
        """One fused ``.diff`` carrying base + every segment to date —
        the file the wire names from now on (gate-only managers skip
        the write and return the canonical path the head produced)."""
        if not self.materialize:
            # gate-only (worker) mode: the wire names the file the head
            # materialized; this manager only tracks the epoch ladder
            return (self.fused_path(epoch) if self.spool
                    else f"epoch:{epoch}")
        path = self.fused_path(epoch)
        merged = dict(self._base)
        merged.update(delta)
        keys = sorted(merged)           # deterministic bytes per epoch
        src = np.asarray([k[0] for k in keys], np.int64)
        dst = np.asarray([k[1] for k in keys], np.int64)
        w = np.asarray([merged[k] for k in keys], np.int64)
        os.makedirs(self.spool, exist_ok=True)
        write_diff(path, src, dst, w)
        return path

    def fused_path(self, epoch: int) -> str:
        if not self.spool:
            raise ValueError("no spool dir configured")
        return os.path.join(self.spool, f"fused-e{int(epoch):06d}.diff")

    def _prune_spool(self, epoch: int) -> None:
        """Drop fused files older than the keep window. The window is
        >= 2, so the previous epoch's file survives every in-flight
        batch that pinned it at dispatch."""
        if not self.materialize:
            return
        old = sorted(glob.glob(os.path.join(self.spool,
                                            "fused-e*.diff")))
        for p in old[:-self.keep_epochs]:
            try:
                os.remove(p)
            except OSError as e:
                log.warning("cannot prune fused diff %s: %s", p, e)
