"""Epoch-tagged diff segment codec.

A *segment* is one increment of the congestion stream: "these edges'
travel times changed, effective at diff epoch E". On disk it is a plain
text file so the same NFS data plane that carries query files carries
the stream:

.. code-block:: text

    {"kind": "dos-traffic-segment", "schema": 1, "epoch": 5, "entries": 2}
    17 42 900
    42 17 900

Line 1 is a JSON header; the remaining ``entries`` lines are
``src dst new_w`` exactly like a ``.diff`` body (``data.formats``).
The header follows the repo-wide wire-compat contract
(``RuntimeConfig`` / manifest v2 / membership state): **unknown keys
are tolerated** (a newer producer may annotate segments freely) and
**only a NEWER schema version rejects** — an old segment always loads
under new code.

Writers go through ``utils.atomicio`` so a reader can never see a torn
segment *file*; a torn *tail* can still appear when a non-atomic
producer (or a partial copy) is mid-write, which is why
:func:`list_segments` ignores an unreadable newest segment instead of
failing the stream.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
import re

import numpy as np

from ..utils.atomicio import atomic_write_bytes
from ..utils.log import get_logger

log = get_logger(__name__)

#: this writer's segment header schema version; readers reject only
#: NEWER versions (wire-compat contract)
SEGMENT_SCHEMA = 1

SEGMENT_KIND = "dos-traffic-segment"

_SEG_RE = re.compile(r"seg-(\d+)\.diff$")


@dataclasses.dataclass
class DiffSegment:
    """One decoded stream increment: epoch + the edges it retimes."""

    epoch: int
    src: np.ndarray
    dst: np.ndarray
    w: np.ndarray

    def __len__(self) -> int:
        return len(self.src)

    def pairs(self):
        """``(src, dst)`` tuples of the edges this segment updates."""
        return [(int(u), int(v)) for u, v in zip(self.src, self.dst)]


def segment_path(dirname: str, epoch: int) -> str:
    """Canonical on-disk name of epoch ``epoch``'s segment."""
    return os.path.join(dirname, f"seg-{int(epoch):06d}.diff")


def encode_segment(epoch: int, src, dst, w, extra: dict | None = None) -> bytes:
    """Segment bytes: header line + ``src dst new_w`` entries.
    ``extra`` keys ride the header (a reader that predates them filters
    them — that tolerance is pinned by the compat tests)."""
    src = np.asarray(src, np.int64)
    dst = np.asarray(dst, np.int64)
    w = np.asarray(w, np.int64)
    if not (len(src) == len(dst) == len(w)):
        raise ValueError("src/dst/w length mismatch")
    header = {"kind": SEGMENT_KIND, "schema": SEGMENT_SCHEMA,
              "epoch": int(epoch), "entries": int(len(src))}
    if extra:
        header.update(extra)
    out = [json.dumps(header)]
    out += ["%d %d %d" % (u, v, ww) for u, v, ww in zip(src, dst, w)]
    return ("\n".join(out) + "\n").encode()


def write_segment(dirname: str, epoch: int, src, dst, w,
                  extra: dict | None = None) -> str:
    """Atomically write epoch ``epoch``'s segment into the stream
    directory; returns its path. Atomic visibility is what lets a
    :class:`~.stream.DiffStream` watcher poll the directory without a
    coordination channel."""
    os.makedirs(dirname, exist_ok=True)
    path = segment_path(dirname, epoch)
    atomic_write_bytes(path, encode_segment(epoch, src, dst, w, extra))
    return path


def decode_segment(text: str, origin: str = "<segment>") -> DiffSegment:
    """Decode one segment's text. Raises ``ValueError`` with a
    diagnostic naming ``origin`` on any structural problem (torn body,
    bad header, NEWER schema)."""
    lines = text.split("\n")
    try:
        header = json.loads(lines[0])
    except (ValueError, IndexError) as e:
        raise ValueError(f"{origin}: bad segment header: {e}") from e
    if not isinstance(header, dict):
        raise ValueError(f"{origin}: segment header is not an object")
    schema = header.get("schema", 1)
    if isinstance(schema, (int, float)) and schema > SEGMENT_SCHEMA:
        # the only rejection the version gate allows: a NEWER producer's
        # segment may carry semantics this reader would misapply
        raise ValueError(
            f"{origin}: segment schema {schema} is newer than this "
            f"reader's {SEGMENT_SCHEMA}; upgrade to read it")
    try:
        epoch = int(header["epoch"])
        entries = int(header["entries"])
    except (KeyError, TypeError, ValueError) as e:
        raise ValueError(
            f"{origin}: segment header missing epoch/entries: {e}") from e
    src = np.empty(entries, np.int64)
    dst = np.empty(entries, np.int64)
    w = np.empty(entries, np.int64)
    body = [ln for ln in lines[1:] if ln.strip()]
    if len(body) < entries:
        raise ValueError(
            f"{origin}: torn segment — header says {entries} entries, "
            f"found {len(body)}")
    for i in range(entries):
        toks = body[i].split()
        if len(toks) != 3:
            raise ValueError(f"{origin}: bad entry line {i}: {body[i]!r}")
        src[i], dst[i], w[i] = (int(t) for t in toks)
    return DiffSegment(epoch=epoch, src=src, dst=dst, w=w)


def read_segment(path: str) -> DiffSegment:
    """Read + decode one segment file; the file-name epoch (when the
    name matches the canonical pattern) must agree with the header's —
    a renamed segment would silently reorder the stream."""
    with open(path) as f:
        seg = decode_segment(f.read(), origin=path)
    m = _SEG_RE.search(os.path.basename(path))
    if m is not None and int(m.group(1)) != seg.epoch:
        raise ValueError(
            f"{path}: file name says epoch {int(m.group(1))} but header "
            f"says {seg.epoch}")
    return seg


def list_segments(dirname: str, after: int = 0) -> list[DiffSegment]:
    """All complete segments with epoch > ``after``, in epoch order.

    The **torn tail** rule: the newest segment failing to decode is
    skipped silently-but-logged (a non-atomic producer is mid-write;
    the next poll picks it up complete). An unreadable segment that is
    NOT the tail is real data loss in the middle of the stream and
    raises — serving on weights with a silently missing increment would
    be wrong forever, not briefly."""
    paths = []
    for p in glob.glob(os.path.join(dirname, "seg-*.diff")):
        m = _SEG_RE.search(os.path.basename(p))
        if m is not None and int(m.group(1)) > after:
            paths.append((int(m.group(1)), p))
    paths.sort()
    out: list[DiffSegment] = []
    for i, (_, p) in enumerate(paths):
        try:
            out.append(read_segment(p))
        except (OSError, ValueError) as e:
            if i == len(paths) - 1:
                log.info("ignoring torn tail segment %s (%s)", p, e)
                break
            raise ValueError(
                f"unreadable mid-stream segment {p}: {e}") from e
    return out
