"""Diff stream sources: where segments come from.

Two shapes cover the deployments we have:

* :class:`DiffStream` — **directory watch**: a producer drops
  ``seg-<epoch>.diff`` files (atomic writes) into a shared directory;
  each ``poll()`` returns the complete segments newer than the last
  one seen, in epoch order. This is the shared-NFS deployment — the
  same data plane that carries query files carries the stream, no new
  transport.
* :class:`TailDiffStream` — **file tail**: segments appended
  back-to-back to ONE spool file (a producer that can only append —
  a pipe drain, a log shipper). ``poll()`` parses complete frames from
  the last read offset; an incomplete tail frame stays unread until
  its remaining lines land (the torn-tail rule again, applied to a
  byte offset instead of a file name).

Both are *pull* sources with no threads of their own: the serving
frontend's epoch pump (``ServingFrontend``) and the worker server's
gate-time refresh own the polling cadence.
"""

from __future__ import annotations

import os

from ..utils.log import get_logger
from .segments import DiffSegment, decode_segment, list_segments

log = get_logger(__name__)


class DiffStream:
    """Directory-watch segment source (see module docstring)."""

    def __init__(self, dirname: str, start_epoch: int = 0):
        self.dirname = dirname
        #: highest epoch already handed out; poll() only returns newer
        self.seen_epoch = int(start_epoch)
        self._synced = False   # a segment has been handed out before

    def poll(self) -> list[DiffSegment]:
        """Complete segments newer than the last poll, epoch order.
        A missing directory is an empty stream (the operator may start
        the consumer before the producer), not an error.

        Epochs must advance CONTIGUOUSLY once the stream is synced: on
        a shared filesystem a higher-numbered segment can become
        visible before a lower one (cross-client readdir skew), and
        skipping past the gap would omit that segment's retimes from
        every later fusion forever. A segment past a gap is held back
        (with a warning) until the missing epoch appears. The FIRST
        segment a consumer ever sees may carry any epoch — a late
        joiner syncs to wherever the stream is."""
        if not os.path.isdir(self.dirname):
            return []
        segs = list_segments(self.dirname, after=self.seen_epoch)
        out: list[DiffSegment] = []
        for seg in segs:
            if ((self._synced or out)
                    and seg.epoch != self.seen_epoch + 1):
                log.warning(
                    "%s: segment epoch %d visible but epoch %d is "
                    "not; holding it back until the gap fills",
                    self.dirname, seg.epoch, self.seen_epoch + 1)
                break
            self.seen_epoch = seg.epoch
            self._synced = True
            out.append(seg)
        return out


class TailDiffStream:
    """Single append-only spool file segment source."""

    def __init__(self, path: str, start_epoch: int = 0):
        self.path = path
        self.seen_epoch = int(start_epoch)
        self._offset = 0

    def poll(self) -> list[DiffSegment]:
        # binary read end to end: the resume offset both counts and
        # seeks BYTES — a text-mode read would count characters while
        # seek positions bytes, and the first multi-byte header
        # annotation (producers may add keys freely) would desync the
        # frame parse permanently
        try:
            with open(self.path, "rb") as f:
                f.seek(self._offset)
                data = f.read()
        except OSError:
            return []           # producer not started yet
        import json as _json

        out: list[DiffSegment] = []
        # split keeps the unterminated remainder as the LAST element
        # (empty when the data ends on a newline) — a frame may only
        # use fully newline-terminated lines, i.e. indices < len - 1
        lines = data.split(b"\n")
        i = 0
        consumed = 0            # bytes of COMPLETE frames handed out
        while i < len(lines) - 1:
            if not lines[i].strip():
                consumed += len(lines[i]) + 1
                i += 1
                continue
            # a frame is one header line + `entries` body lines; stop
            # at the first incomplete frame (torn tail: the producer is
            # mid-append, the next poll re-reads from this offset)
            try:
                header = _json.loads(lines[i])
                n = int(header["entries"])
            except (ValueError, KeyError, TypeError):
                log.error("%s: undecodable frame header at offset %d; "
                          "tail stream stalled", self.path,
                          self._offset + consumed)
                break
            if i + n >= len(lines) - 1:
                break           # incomplete tail frame
            frame = lines[i:i + 1 + n]
            try:
                seg = decode_segment(
                    (b"\n".join(frame) + b"\n").decode(),
                    origin=self.path)
            except ValueError as e:   # UnicodeDecodeError included
                log.error("%s: undecodable frame at offset %d (%s); "
                          "tail stream stalled", self.path,
                          self._offset + consumed, e)
                break
            consumed += sum(len(ln) + 1 for ln in frame)
            i += 1 + n
            if seg.epoch > self.seen_epoch:
                out.append(seg)
                self.seen_epoch = seg.epoch
        self._offset += consumed
        return out

    def append(self, seg_bytes: bytes) -> None:
        """Producer half (tests / replay): append one encoded frame."""
        with open(self.path, "ab") as f:
            f.write(seg_bytes)
