"""New query families on the shard oracle: matrices, alternatives,
reverse routing.

The serving line protocol (``serving.ingress``) historically speaks one
sentence: ``<s> <t>``. Production traffic asks more kinds of question,
and all three new families decompose into the SAME per-pair shard
dispatch the frontend already batches — they are routing/aggregation
layers, not new kernels:

* ``mat <s> <t1> ... <tk>`` — **one-to-many ETA matrix** row: one pair
  query per target, fanned across target-owner shards (the bulk
  dist-gather path the campaign already drives at 1.1M q/s answers the
  resident-oracle analog), re-assembled in target order. Response:
  ``MAT <s> <k> <c1> ... <ck>`` with ``-1`` for targets that could not
  be answered (unreachable, shed, or errored).
* ``alt <s> <t> <k>`` — **k-alternative routes via penalized
  re-walks**: the oracle's walk follows the free-flow first-move table,
  so penalizing edges cannot bend an existing walk — instead each
  alternative *forces a distinct first edge* out of ``s`` and re-walks
  from that neighbor (cost = live first-edge weight + walk(nbr → t)).
  That is exactly the classic penalize-and-reroute loop collapsed: after
  extracting route i, its first edge is penalized to infinity, and the
  next-best route under that penalty is the best walk through the next
  first edge. All of a node's first edges evaluate in ONE shard batch
  (every sub-query targets ``t`` — same owner), ranked by live cost.
  Response: ``ALT <s> <t> <n> <c1> ... <cn>`` ascending, ``n <= k``.
* ``rev <s> <t>`` — **reverse (source-owner) routing**: the return
  trip ``t -> s``, answered by the worker that owns ``s`` — the
  source-owner of the original pair. On the campaign path the same
  trick is one ``group_queries`` call over the swapped pairs (grouping
  by the reversed target IS grouping by the original source's owner).
  Response: ``REV <s> <t> <cost> <plen> <finished>``.

Every family books its own ``serve_*`` counter so a mixed workload's
composition is visible on the scrape."""

from __future__ import annotations

import time

from ..data.formats import read_diff
from ..obs import metrics as obs_metrics
from ..utils.log import get_logger
from ..serving.request import BUSY, Future, OK, ServeResult

log = get_logger(__name__)

M_MATRIX = obs_metrics.counter(
    "serve_matrix_requests_total",
    "one-to-many ETA matrix requests (mat family)")
M_ALT = obs_metrics.counter(
    "serve_alt_requests_total",
    "k-alternative route requests (alt family)")
M_REV = obs_metrics.counter(
    "serve_reverse_requests_total",
    "reverse source-owner routing requests (rev family)")
M_FAMILY_SHED = obs_metrics.counter(
    "serve_shed_family_total",
    "typed family requests shed by the control plane's brownout ladder")


def parse_family_line(line: str):
    """``(kind, args)`` for a typed family line, or ``None`` for the
    classic pair sentence. Raises ``ValueError`` on a malformed family
    line (the ingress answers it in-order like any malformed line)."""
    toks = line.split()
    kind = toks[0].lower()
    if kind == "mat":
        if len(toks) < 3:
            raise ValueError("want 'mat <s> <t...>'")
        return "mat", (int(toks[1]), [int(t) for t in toks[2:]])
    if kind == "alt":
        if len(toks) != 4:
            raise ValueError("want 'alt <s> <t> <k>'")
        return "alt", (int(toks[1]), int(toks[2]), int(toks[3]))
    if kind == "rev":
        if len(toks) != 3:
            raise ValueError("want 'rev <s> <t>'")
        return "rev", (int(toks[1]), int(toks[2]))
    return None


class CompositeFuture:
    """Waits a list of pair futures and builds one family result.
    ``result(timeout)`` budgets the timeout across the whole set, so a
    stuck shard costs the caller one deadline, not one per target."""

    def __init__(self, futures, build):
        self._futures = futures
        self._build = build

    def result(self, timeout: float | None = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        results = []
        for fut in self._futures:
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            results.append(fut.result(remaining))
        return self._build(results)


class MatrixResult:
    """One ``mat`` answer. ``costs[i]`` is ``-1`` when target i was not
    answered OK+finished (unreachable, shed, errored)."""

    def __init__(self, s: int, targets, results):
        self.s = int(s)
        self.targets = [int(t) for t in targets]
        self.results = results
        self.costs = [int(r.cost) if r.ok and r.finished else -1
                      for r in results]

    @classmethod
    def from_mesh(cls, s: int, targets, costs, finished):
        """Build from an on-mesh ``query_mat`` row (no per-target
        result objects — the join already happened on device): the
        encoded MAT sentence is identical to the fan-out path's."""
        out = cls.__new__(cls)
        out.s = int(s)
        out.targets = [int(t) for t in targets]
        out.results = []
        out.costs = [int(c) if f else -1
                     for c, f in zip(costs, finished)]
        return out

    @property
    def ok(self) -> bool:
        return all(r.ok for r in self.results)

    def encode(self) -> str:
        return " ".join(["MAT", str(self.s), str(len(self.costs))]
                        + [str(c) for c in self.costs])


class AltResult:
    """One ``alt`` answer: up to k (cost, first-neighbor) alternatives,
    ascending cost, distinct first edges."""

    def __init__(self, s: int, t: int, k: int, alternatives, results):
        self.s, self.t, self.k = int(s), int(t), int(k)
        self.alternatives = alternatives      # [(cost, via_node), ...]
        self.results = results

    @property
    def ok(self) -> bool:
        return all(r.ok for r in self.results)

    def encode(self) -> str:
        return " ".join(
            ["ALT", str(self.s), str(self.t),
             str(len(self.alternatives))]
            + [str(c) for c, _via in self.alternatives])


class ReverseResult:
    """One ``rev`` answer: the ``t -> s`` return trip, labeled with the
    ORIGINAL (s, t) so clients correlate request and response."""

    def __init__(self, s: int, t: int, result):
        self.s, self.t = int(s), int(t)
        self.result = result

    @property
    def ok(self) -> bool:
        return self.result.ok

    def encode(self) -> str:
        r = self.result
        if r.status != OK:
            line = f"{r.status} {self.s} {self.t}"
            return f"{line} {r.detail}" if r.detail else line
        return (f"REV {self.s} {self.t} {r.cost} {r.plen} "
                f"{int(r.finished)}")


class QueryFamilies:
    """Family planner over one :class:`~..serving.ServingFrontend`.

    ``graph``/``graph_provider`` supply the road graph the ``alt``
    family needs to enumerate first edges (lazy: a frontend that never
    sees an alt query never loads it). ``traffic`` (a
    :class:`~.epochs.DiffEpochManager`) prices first edges under the
    LIVE fusion; without it, the frontend's static diff file is read
    once per diff and overlaid.

    ``oracle`` (a mesh-resident :class:`~..models.cpd.CPDOracle`):
    the ``mat`` family's ON-MESH path — one ``query_mat`` collective
    per row (walk + scatter + psum join on device) instead of one
    frontend future per target through queue/batcher/dispatcher. The
    row is priced under the frontend's CURRENT diff (live fusion
    included — the diff file is re-read per change, cached), so the
    MAT sentence is identical to the fan-out path's; without an
    oracle the fan-out/join path serves as before."""

    def __init__(self, frontend, graph=None, graph_provider=None,
                 traffic=None, oracle=None):
        self.frontend = frontend
        self._graph = graph
        self._graph_provider = graph_provider
        self.traffic = traffic
        self.oracle = oracle
        self._overlay_cache: tuple[str, dict] | None = None
        self._mat_weights: tuple[str, object] | None = None

    # ------------------------------------------------------------ helpers
    def graph(self):
        if self._graph is None:
            if self._graph_provider is None:
                raise ValueError(
                    "alt queries need a graph (pass graph= or "
                    "graph_provider= to QueryFamilies)")
            self._graph = self._graph_provider()
        return self._graph

    def _edge_weight(self, u: int, v: int, base: int) -> int:
        """(u, v)'s live travel time: traffic fusion > static diff
        overlay > free flow."""
        if self.traffic is not None:
            return self.traffic.weight_of(u, v, base)
        diff = self.frontend.diff
        if diff in ("-", "", None):
            return int(base)
        cached = self._overlay_cache
        if cached is None or cached[0] != diff:
            dsrc, ddst, dw = read_diff(diff)
            cached = (diff, {(int(a), int(b)): int(ww)
                             for a, b, ww in zip(dsrc, ddst, dw)})
            self._overlay_cache = cached
        return int(cached[1].get((int(u), int(v)), base))

    def _mat_query_weights(self, diff: str):
        """The edge-weight array ``query_mat`` prices the row under —
        the frontend's current diff (None = free flow), read once per
        diff change."""
        if diff in ("-", "", None):
            return None
        cached = self._mat_weights
        if cached is None or cached[0] != diff:
            w = self.oracle.graph.weights_with_diff(read_diff(diff))
            cached = (diff, w)
            self._mat_weights = cached
        return cached[1]

    # ----------------------------------------------------------- families
    def matrix(self, s: int, targets) -> CompositeFuture:
        M_MATRIX.inc()
        if self.oracle is not None:
            # on-mesh path: one collective answers the whole row. The
            # diff path doubles as the oracle's device-buffer cache
            # key, so rows under one diff share one weights upload.
            diff = self.frontend.diff
            cost, fin = self.oracle.query_mat(
                int(s), [int(t) for t in targets],
                w_query=self._mat_query_weights(diff),
                w_key=None if diff in ("-", "", None) else str(diff))
            res = MatrixResult.from_mesh(s, targets, cost, fin)
            return CompositeFuture([], lambda _results: res)
        futs = [self.frontend.submit(int(s), int(t)) for t in targets]
        return CompositeFuture(
            futs, lambda results: MatrixResult(s, targets, results))

    def reverse(self, s: int, t: int) -> CompositeFuture:
        M_REV.inc()
        fut = self.frontend.submit(int(t), int(s))   # the return trip:
        # target of the swapped pair is s, so the frontend's
        # target-owner routing IS source-owner routing of the original
        return CompositeFuture(
            [fut], lambda results: ReverseResult(s, t, results[0]))

    def alternatives(self, s: int, t: int, k: int) -> CompositeFuture:
        M_ALT.inc()
        g = self.graph()
        s, t = int(s), int(t)
        # pair queries get this check inside ``frontend.submit``; alt
        # indexes the graph BEFORE any submit, and a negative id would
        # not even raise — it silently wraps to another node's edges
        if not (0 <= s < g.n and 0 <= t < g.n):
            raise ValueError("node-out-of-range")
        nbrs, eids = g.out_edges(s)
        first = [(int(v), self._edge_weight(s, int(v), int(g.w[e])))
                 for v, e in zip(nbrs, eids)]
        # one sub-query per distinct first edge; all target t, so the
        # whole family lands in ONE shard's micro-batch
        futs = [self.frontend.submit(v, t) for v, _w in first]

        def build(results):
            alts = []
            for (v, w_first), r in zip(first, results):
                if r.ok and r.finished:
                    alts.append((int(w_first) + int(r.cost), v))
            alts.sort()
            return AltResult(s, t, k, alts[:max(int(k), 0)], results)

        return CompositeFuture(futs, build)

    # ------------------------------------------------------------ ingress
    def submit_line(self, kind: str, args):
        """Dispatch one parsed family line (``serving.ingress``)."""
        shed = getattr(self.frontend, "shed_families", None)
        if shed and kind in shed:
            # brownout ladder level >= 2: expensive fan-out families
            # answer BUSY immediately (in-order, like any shed) while
            # plain pair queries keep flowing
            M_FAMILY_SHED.inc()
            s = int(args[0]) if args else -1
            t = int(args[1]) if kind != "mat" and len(args) > 1 else -1
            return Future.completed(ServeResult(
                BUSY, s, t, detail="brownout-shed"))
        if kind == "mat":
            return self.matrix(args[0], args[1])
        if kind == "alt":
            return self.alternatives(args[0], args[1], args[2])
        if kind == "rev":
            return self.reverse(args[0], args[1])
        raise ValueError(f"unknown query family {kind!r}")
