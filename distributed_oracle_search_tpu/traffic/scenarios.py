"""Scenario generator: topologies, hotspot query pools, rush-hour
replay traces.

Every new workload needs a headline number (ROADMAP item 5c), so the
generator is deterministic end to end — same seed, same topology, same
queries, same segment bytes — and emits the SAME artifacts the serving
plane consumes (graphs via ``data.synth``/local builders, segments via
``traffic.segments``), never a parallel bench-only format.

* :func:`make_topology` — ``grid`` (street grid city), ``road``
  (degree-skewed DIMACS stand-in), ``powerlaw`` (preferential-
  attachment hub network: the "every trip goes through downtown"
  regime where congestion on a few hub edges touches most routes —
  the worst case for scoped cache invalidation, on purpose);
* :func:`zipf_queries` — zipf-ranked hotspot pools (repeated (s, t)
  pairs are what give result caches and the engine's dedup something
  to do);
* :func:`rush_hour_trace` — a timed list of diff segments following a
  tent profile over a congested corridor: weights ramp up to a peak
  multiplier and back down, epoch by epoch — the replay input for the
  live-swap bench and the chaos drill;
* :func:`replay` — write a trace into a stream directory on schedule
  (interval 0 = as fast as the consumer can swap).
"""

from __future__ import annotations

import time

import numpy as np

from ..data.graph import Graph
from ..data.synth import synth_city_graph, synth_road_network
from ..utils.log import get_logger
from .segments import write_segment

log = get_logger(__name__)


def powerlaw_graph(n: int, m_edges: int = 2, seed: int = 0) -> Graph:
    """Preferential-attachment hub network (Barabási–Albert flavor),
    two-way edges, travel times scaled by coordinate distance like the
    grid city so length estimates stay meaningful."""
    if n < 3:
        raise ValueError("powerlaw topology needs n >= 3")
    rng = np.random.default_rng(seed)
    xs = rng.integers(0, 100_000, n)
    ys = rng.integers(0, 100_000, n)
    su, sv = [0, 1], [1, 2]            # seed chain
    targets_pool = [0, 1, 1, 2]        # degree-weighted sampling pool
    for u in range(3, n):
        picks = set()
        while len(picks) < min(m_edges, u):
            picks.add(int(targets_pool[rng.integers(0,
                                                    len(targets_pool))]))
        for v in picks:
            su.append(u)
            sv.append(v)
            targets_pool.extend([u, v])
    su = np.asarray(su, np.int64)
    sv = np.asarray(sv, np.int64)
    src = np.concatenate([su, sv])
    dst = np.concatenate([sv, su])
    dx = xs[src] - xs[dst]
    dy = ys[src] - ys[dst]
    dist = np.sqrt((dx * dx + dy * dy).astype(np.float64))
    w = np.maximum(1, (dist * 0.01 * (1.0 + 0.3 * rng.random(len(src))))
                   .astype(np.int64)).astype(np.int32)
    return Graph(xs, ys, src, dst, w)


def make_topology(kind: str, n: int = 500, seed: int = 0) -> Graph:
    """One of the three workload topologies by name."""
    if kind == "grid":
        width = max(2, int(np.sqrt(n)))
        return synth_city_graph(width, max(2, n // width), seed=seed)
    if kind == "road":
        return synth_road_network(max(n, 64), seed=seed)
    if kind == "powerlaw":
        return powerlaw_graph(n, seed=seed)
    raise ValueError(f"unknown topology {kind!r} "
                     "(want grid|road|powerlaw)")


def zipf_queries(n_nodes: int, n_queries: int, a: float = 1.3,
                 seed: int = 0) -> np.ndarray:
    """Hotspot query pool: sources and targets drawn from a zipf rank
    distribution over a seeded node permutation (rank 1 = the hottest
    "downtown" node). Self-pairs are re-rolled onto a neighbor rank so
    every query does real work."""
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n_nodes)
    ranks_s = rng.zipf(a, n_queries).clip(1, n_nodes) - 1
    ranks_t = rng.zipf(a, n_queries).clip(1, n_nodes) - 1
    same = ranks_s == ranks_t
    ranks_t[same] = (ranks_t[same] + 1) % n_nodes
    return np.stack([perm[ranks_s], perm[ranks_t]], axis=1)


def pick_corridor(graph: Graph, frac: float = 0.02,
                  seed: int = 0) -> np.ndarray:
    """Edge ids of a congestion corridor: the busiest fraction of edges
    by endpoint degree (hub-adjacent streets — where rush hour actually
    lands), at least one edge."""
    deg = np.diff(graph.out_ptr)
    score = deg[graph.src] + deg[graph.dst]
    k = max(1, int(graph.m * frac))
    rng = np.random.default_rng(seed)
    # jitter breaks degree ties deterministically so corridors differ
    # across seeds even on regular grids
    order = np.argsort(score + rng.random(graph.m), kind="stable")
    return order[-k:]


def rush_hour_trace(graph: Graph, epochs: int = 6, frac: float = 0.02,
                    peak: float = 4.0, seed: int = 0,
                    start_epoch: int = 1) -> list[dict]:
    """Timed segment trace over a corridor: multipliers follow a tent
    profile (ramp to ``peak``, ramp back to free flow) across
    ``epochs`` segments. Returns ``[{"epoch", "src", "dst", "w"}, ...]``
    ready for :func:`replay` (or direct ``write_segment`` calls)."""
    if epochs < 1:
        raise ValueError("epochs must be >= 1")
    eids = pick_corridor(graph, frac=frac, seed=seed)
    src = graph.src[eids]
    dst = graph.dst[eids]
    base = graph.w[eids].astype(np.float64)
    trace = []
    for i in range(epochs):
        # tent profile peaking mid-trace; the last epoch returns to ~free
        # flow so a full replay ends where it began
        x = i / max(epochs - 1, 1)
        factor = 1.0 + (peak - 1.0) * (1.0 - abs(2.0 * x - 1.0))
        w = np.maximum(1, (base * factor)).astype(np.int64)
        trace.append({"epoch": int(start_epoch + i), "src": src.copy(),
                      "dst": dst.copy(), "w": w})
    return trace


def replay(trace: list[dict], dirname: str, interval_s: float = 0.0,
           stop=None) -> int:
    """Write a trace's segments into a stream directory on schedule;
    returns how many were written (a set ``stop`` event ends the replay
    early). ``interval_s=0`` emits as fast as the files can be written —
    the consumer's fused ingestion collapses whatever backlog forms."""
    n = 0
    for seg in trace:
        if stop is not None and stop.is_set():
            break
        write_segment(dirname, seg["epoch"], seg["src"], seg["dst"],
                      seg["w"])
        n += 1
        if interval_s > 0:
            if stop is not None:
                if stop.wait(interval_s):
                    break
            else:
                time.sleep(interval_s)
    return n
