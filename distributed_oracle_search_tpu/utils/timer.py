"""Wall-clock phase timers.

Role parity: the reference wraps its read/partition/send phases in a
context-manager timer and reports intervals in both seconds and nanoseconds
(reference ``timer.py:20-26``, ``process_query.py:93-111``). This is a fresh
implementation with the same jobs: ``with``-block timing, accumulation, and
human-readable formatting.
"""

from __future__ import annotations

import time


class Timer:
    """Context-manager wall-clock timer.

    >>> with Timer() as t:
    ...     do_work()
    >>> t.interval      # seconds (float)
    >>> t.interval_ns   # integer nanoseconds

    ``interval`` is only set on block exit (it reads 0.0 mid-block);
    ``elapsed`` also works inside the ``with`` block, returning the time
    since entry, and equals ``interval`` after exit.
    """

    __slots__ = ("interval", "_start", "_running")

    def __init__(self, interval: float = 0.0):
        self.interval = float(interval)
        self._running = False

    def __enter__(self) -> "Timer":
        self._start = time.perf_counter()
        self._running = True
        return self

    def __exit__(self, *exc) -> None:
        self.interval = time.perf_counter() - self._start
        self._running = False

    @property
    def elapsed(self) -> float:
        """Seconds since block entry while inside the ``with`` block;
        the final ``interval`` once the block has exited."""
        if self._running:
            return time.perf_counter() - self._start
        return self.interval

    @property
    def interval_ns(self) -> int:
        return int(self.interval * 1e9)

    def __add__(self, other) -> "Timer":
        other_s = other.interval if isinstance(other, Timer) else float(other)
        return Timer(self.interval + other_s)

    __radd__ = __add__

    def __str__(self) -> str:
        s = self.interval
        if s >= 1e-2:
            return f"{s:.3f}s"
        if s >= 1e-5:
            return f"{s * 1e3:.3f}ms"
        if s >= 1e-8:
            return f"{s * 1e6:.3f}us"
        return f"{s * 1e9:.0f}ns"

    def __repr__(self) -> str:
        return f"Timer({self.interval!r})"
