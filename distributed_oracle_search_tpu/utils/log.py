"""Logging helpers.

The reference drives a module logger off a counted ``-v`` flag
(``args.py:7,190-196``); we do the same but per-named-logger and without
touching the host application's root logger at import time (library
convention: handlers are attached to our own namespace only).
"""

from __future__ import annotations

import logging

_ROOT = "dos_tpu"


def get_logger(name: str = "") -> logging.Logger:
    return logging.getLogger(f"{_ROOT}.{name}" if name else _ROOT)


def _ensure_handler(root: logging.Logger) -> None:
    if not root.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(name)s %(levelname)s: %(message)s"))
        root.addHandler(handler)
        root.propagate = False


def set_verbosity(verbose: int) -> None:
    """Map a counted -v flag to a log level (0→WARN, 1→INFO, ≥2→DEBUG)."""
    root = logging.getLogger(_ROOT)
    _ensure_handler(root)
    level = logging.WARNING
    if verbose == 1:
        level = logging.INFO
    elif verbose >= 2:
        level = logging.DEBUG
    root.setLevel(level)
