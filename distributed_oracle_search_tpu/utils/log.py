"""Logging helpers.

The reference drives a module logger off a counted ``-v`` flag
(``args.py:7,190-196``); we do the same but per-named-logger and without
touching the host application's root logger at import time (library
convention: handlers are attached to our own namespace only).

Multi-worker runs interleave all workers' records on one stream (N
servers on one host in the smoke modes, or ssh-forwarded stderr on a
cluster), so every record carries a worker id: ``set_worker_id`` tags
the **current thread** (each ``FifoServer.serve_forever`` loop is one
thread, and the engine logs from the same thread), and the handler's
filter stamps ``[w<id>]`` into the format — ``-`` for head-side /
untagged threads.

Records additionally carry the thread's current **trace id**
(``obs.trace.current_trace_id`` — set while a traced batch is in
flight): the ``[w3]`` tag becomes ``[w3 t:5f1c...]``, so grepping a
degraded batch's logs and opening its span timeline in Perfetto use
the same key. Untraced records keep the bare ``[w3]`` form.
"""

from __future__ import annotations

import logging
import threading

_ROOT = "dos_tpu"

_ctx = threading.local()


def set_worker_id(wid: int | str | None) -> None:
    """Tag this thread's subsequent log records with a worker id
    (``None`` untags)."""
    _ctx.wid = wid


def get_worker_id() -> int | str | None:
    return getattr(_ctx, "wid", None)


class _WorkerIdFilter(logging.Filter):
    """Stamp the thread's worker id (``-`` if unset) and, when a traced
    batch is in flight, its trace id onto every record."""

    def filter(self, record: logging.LogRecord) -> bool:
        wid = getattr(_ctx, "wid", None)
        record.worker = "-" if wid is None else wid
        # lazy import: obs.trace is further up the import graph and the
        # filter must work even if the obs package is mid-import
        try:
            from ..obs.trace import current_trace_id
            tid = current_trace_id()
        except ImportError:
            tid = None
        record.trace = f" t:{tid}" if tid else ""
        return True


def get_logger(name: str = "") -> logging.Logger:
    return logging.getLogger(f"{_ROOT}.{name}" if name else _ROOT)


def _ensure_handler(root: logging.Logger) -> None:
    if not root.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(name)s [w%(worker)s%(trace)s] %(levelname)s: "
            "%(message)s"))
        handler.addFilter(_WorkerIdFilter())
        root.addHandler(handler)
        root.propagate = False


def set_verbosity(verbose: int) -> None:
    """Map a counted -v flag to a log level (0→WARN, 1→INFO, ≥2→DEBUG)."""
    root = logging.getLogger(_ROOT)
    _ensure_handler(root)
    level = logging.WARNING
    if verbose == 1:
        level = logging.INFO
    elif verbose >= 2:
        level = logging.DEBUG
    root.setLevel(level)
