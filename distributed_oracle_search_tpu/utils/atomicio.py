"""Crash-safe artifact IO: atomic writes, content digests, debris sweep.

The CPD index *is* the system checkpoint (``models.cpd``): build once,
serve statelessly, reload on restart. That contract only holds if no
observable artifact is ever torn — a build killed mid-``np.save`` must
not leave a half-written block that later loads as garbage. Every
artifact writer in the data plane goes through one discipline:

1. write the full payload to ``<path>.tmp.<pid>`` in the same directory;
2. ``fsync`` the temp file (the bytes are durable before the name is);
3. ``os.rename`` onto the final name (atomic on POSIX: readers see the
   old file or the new file, never a prefix);
4. ``fsync`` the directory so the rename itself survives a power cut.

A crash between (1) and (3) leaves only ``*.tmp.*`` debris, which
:func:`sweep_stale_artifacts` removes at build/campaign start — the
artifact-plane analog of the transport's stale ``answer.*`` FIFO sweep.

Digests are ``crc32:<8 hex>`` over the FULL file bytes (``zlib.crc32``
— the only checksum the container is guaranteed to have; the string
format carries the algorithm name so a future xxhash/crc32c swap stays
wire-compatible). Digesting file bytes rather than array bytes means a
corrupted ``.npy`` header is caught exactly like corrupted payload.
"""

from __future__ import annotations

import contextlib
import glob
import io
import json
import os
import time
import zlib

import numpy as np

from ..obs import metrics as obs_metrics
from .log import get_logger

log = get_logger(__name__)

M_SWEPT = obs_metrics.counter(
    "artifacts_swept_total",
    "stale *.tmp / *.quarantined artifact files removed at start")

#: suffix family of in-flight atomic writes (pid-qualified so concurrent
#: writers in the same dir never collide on the temp name)
TMP_SUFFIX = ".tmp"
#: suffix a corrupt block is renamed to when the load path quarantines it
QUARANTINE_SUFFIX = ".quarantined"


def digest_bytes(data: bytes) -> str:
    """Content digest of a byte payload, algorithm-prefixed."""
    return f"crc32:{zlib.crc32(data) & 0xFFFFFFFF:08x}"


def digest_file(path: str) -> str:
    """Digest of a file's full contents (streamed, bounded memory)."""
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(1 << 20)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
    return f"crc32:{crc & 0xFFFFFFFF:08x}"


def npy_bytes(arr: np.ndarray) -> bytes:
    """Serialize an array to ``.npy`` format in memory — so the digest
    recorded in the build ledger / manifest is computed from the exact
    bytes that hit the disk, with no read-back."""
    buf = io.BytesIO()
    np.save(buf, arr)
    return buf.getvalue()


def _fsync_dir(dirname: str) -> None:
    """Durable-rename half of the protocol; best-effort on filesystems
    that refuse directory fds (the rename is still atomic there)."""
    try:
        fd = os.open(dirname or ".", os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path: str, data: bytes) -> None:
    """tmp-file + fsync + rename: readers never observe a torn ``path``."""
    tmp = f"{path}{TMP_SUFFIX}.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.rename(tmp, path)
    _fsync_dir(os.path.dirname(path))


def atomic_write_json(path: str, obj) -> None:
    atomic_write_bytes(path, (json.dumps(obj, indent=2) + "\n").encode())


@contextlib.contextmanager
def atomic_writer(path: str, mode: str = "w"):
    """Streaming form of :func:`atomic_write_bytes`: yields the open
    temp file so large artifacts (campaign CSVs) stream row by row in
    constant memory, then fsync+rename on clean exit. An exception
    removes the temp file — the final name never appears."""
    tmp = f"{path}{TMP_SUFFIX}.{os.getpid()}"
    f = open(tmp, mode)
    try:
        yield f
        f.flush()
        os.fsync(f.fileno())
    except BaseException:
        f.close()
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    f.close()
    os.rename(tmp, path)
    _fsync_dir(os.path.dirname(path))


def atomic_replace_bytes(path: str, data: bytes) -> None:
    """Atomic VISIBILITY without durability: tmp + rename, no fsync.

    For transient data-plane files (per-batch query/results/paths wire
    sidecars) that are deleted after one round trip: a concurrent
    reader — or a timed-out batch's late writer racing a newer batch's
    file — must never observe torn bytes, but the file outliving a
    power cut is worthless, and an fsync pair per serving batch on a
    shared NFS dir is a hot-path COMMIT round-trip. Durable artifacts
    (index blocks, manifests, ledgers, campaign outputs) keep using
    :func:`atomic_write_bytes`."""
    tmp = f"{path}{TMP_SUFFIX}.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
    os.rename(tmp, path)


def atomic_save_npy(path: str, arr: np.ndarray) -> str:
    """Atomically persist an array; returns the content digest of the
    written file bytes."""
    data = npy_bytes(arr)
    atomic_write_bytes(path, data)
    return digest_bytes(data)


class AtomicNpyWriter:
    """Pre-openable atomic ``.npy`` block writer for the pipelined build.

    Opening the temp file is metadata work (create, fd allocation —
    on NFS a COMMIT round trip) that the build's host-side stager does
    for the NEXT block while the device computes the CURRENT one;
    :meth:`commit` then only pays payload write + fsync + rename.
    Same discipline as :func:`atomic_write_bytes`: the final name never
    names torn bytes. :meth:`abort` removes an un-committed temp file
    (a staged block the build never reached)."""

    def __init__(self, path: str):
        self.path = path
        self._tmp = f"{path}{TMP_SUFFIX}.{os.getpid()}"
        self._f = open(self._tmp, "wb")

    def commit(self, arr: np.ndarray) -> str:
        """Write + fsync + rename; returns the content digest."""
        data = npy_bytes(arr)
        try:
            self._f.write(data)
            self._f.flush()
            os.fsync(self._f.fileno())
        finally:
            self._f.close()
        os.rename(self._tmp, self.path)
        _fsync_dir(os.path.dirname(self.path))
        return digest_bytes(data)

    def abort(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass
        try:
            os.remove(self._tmp)
        except OSError:
            pass


def atomic_copy_file(src: str, dst: str) -> str:
    """Copy a file atomically (tmp + fsync + rename), returning the
    digest of the copied bytes — the delta build's block-reuse path:
    an untouched block moves old index → new epoch index as a streamed
    byte copy, never a recompute, and the returned digest feeds the
    new ledger/manifest without a read-back."""
    tmp = f"{dst}{TMP_SUFFIX}.{os.getpid()}"
    crc = 0
    with open(src, "rb") as fin, open(tmp, "wb") as fout:
        while True:
            chunk = fin.read(1 << 20)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
            fout.write(chunk)
        fout.flush()
        os.fsync(fout.fileno())
    os.rename(tmp, dst)
    _fsync_dir(os.path.dirname(dst))
    return f"crc32:{crc & 0xFFFFFFFF:08x}"


def quarantine(path: str) -> str | None:
    """Move a corrupt artifact aside (``<path>.quarantined``) instead of
    deleting it — the bad bytes stay inspectable until the next sweep.
    Returns the quarantine path, or None when nothing was there."""
    if not os.path.exists(path):
        return None
    qpath = path + QUARANTINE_SUFFIX
    try:
        os.replace(path, qpath)
    except OSError as e:
        log.warning("could not quarantine %s (%s); removing instead",
                    path, e)
        try:
            os.remove(path)
        except OSError:
            return None
        return None
    return qpath


#: default age below which sweep leaves a file alone: stale debris from
#: a dead process is minutes old, while a file this young may be a LIVE
#: atomic write by a resident server self-healing a block in this dir
SWEEP_MIN_AGE_S = 60.0


def sweep_stale_artifacts(dirname: str,
                          min_age_s: float = SWEEP_MIN_AGE_S) -> int:
    """Remove ``*.tmp.*`` debris from killed atomic writes and leftover
    ``*.quarantined`` blocks from previous self-healed loads. Campaigns
    and builds call this once at start, alongside the transport's stale
    answer-FIFO sweep; counted by ``artifacts_swept_total``.

    Files younger than ``min_age_s`` are kept: the sweeping process
    cannot tell its own startup debris from another live process's
    in-flight atomic write (a resident worker may be mid-heal in this
    very directory), and deleting the latter's temp file would turn its
    rename into a crash. Old debris — the thing this sweep exists for —
    is always past the threshold."""
    if not dirname or not os.path.isdir(dirname):
        return 0
    now = time.time()
    n = 0
    for pat in (f"*{TMP_SUFFIX}.*", f"*{QUARANTINE_SUFFIX}"):
        for p in glob.glob(os.path.join(dirname, pat)):
            try:
                if (os.path.isfile(p)
                        and now - os.path.getmtime(p) >= min_age_s):
                    os.remove(p)
                    n += 1
            except OSError:
                continue
    if n:
        log.info("swept %d stale artifact file(s) in %s", n, dirname)
        M_SWEPT.inc(n)
    return n
