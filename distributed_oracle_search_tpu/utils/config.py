"""Cluster configuration.

Schema parity with the reference cluster-conf JSON
(``example-cluster-conf.json:1-11``, documented in reference ``README.md:27-39``):

* ``workers``     list of worker identities. For host-backed execution these
                  are ssh hostnames (reference semantics); for TPU-backed
                  execution use ``partmethod: "tpu"`` and the list length is
                  simply the number of mesh shards (entries may be anything,
                  conventionally ``"tpu:<i>"``).
* ``nfs``         shared scratch directory for query files (host mode only).
* ``projectdir``  working dir used after ssh-ing to a worker (host mode only).
* ``partmethod``  ``div | mod | alloc | tpu`` — how nodes map to workers.
* ``partkey``     integer parameter of the partition method (``alloc`` takes a
                  list of range bounds; ``tpu`` ignores it and derives a
                  contiguous chunking from the node count).
* ``outdir``      directory holding the precomputed CPD index.
* ``xy_file``     input graph path.
* ``scenfile``    query scenario path.
* ``diffs``       list of congestion diff files ("-" = free flow).

New (this framework): ``partmethod: "tpu"`` routes partitions onto a
``jax.sharding.Mesh`` in-process instead of onto ssh hostnames — the north-star
design from BASELINE.json. ``mesh_shape``/``mesh_axes`` optionally pin the mesh
layout (e.g. ``[2, 4]`` with ``["data", "worker"]`` — consumed by
``parallel.mesh.mesh_from_config``, which every TPU-mode entry point uses);
by default a ``(1, maxworker)`` mesh.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Sequence

VALID_PARTMETHODS = ("div", "mod", "alloc", "tpu")


@dataclasses.dataclass
class ClusterConfig:
    workers: list[str]
    partmethod: str = "mod"
    partkey: Any = 1
    outdir: str = "./index"
    xy_file: str = ""
    scenfile: str = ""
    diffs: list[str] = dataclasses.field(default_factory=lambda: ["-"])
    nfs: str = "/tmp"
    projectdir: str = "."
    #: R-way shard replication (host/serving modes): replica rank r of
    #: worker w's rows also lives on worker (w + r) % maxworker, giving
    #: the head failover targets and the frontend hedge targets. 1 =
    #: no replication (today's behavior). ``DOS_REPLICATION`` overrides.
    replication: int = 1
    # TPU-mode extensions (ignored by host mode)
    mesh_shape: Sequence[int] | None = None
    mesh_axes: Sequence[str] | None = None
    # multi-host: {"coordinator": "host:port", "num_processes": N,
    # "process_id": i (or $DOS_PROCESS_ID / TPU auto-detect)} — see
    # parallel/multihost.py
    multihost: dict | None = None

    @property
    def maxworker(self) -> int:
        return len(self.workers)

    def validate(self) -> "ClusterConfig":
        if not self.workers:
            raise ValueError("cluster config needs at least one worker")
        if self.partmethod not in VALID_PARTMETHODS:
            raise ValueError(
                f"partmethod {self.partmethod!r} not in {VALID_PARTMETHODS}")
        if self.partmethod == "alloc":
            if not isinstance(self.partkey, (list, tuple)):
                raise ValueError("alloc partitioning needs a list partkey")
            if len(self.partkey) != self.maxworker:
                raise ValueError("alloc partkey must have one bound per worker")
        elif self.partmethod in ("div", "mod"):
            if not isinstance(self.partkey, int) or self.partkey <= 0:
                raise ValueError(f"{self.partmethod} needs a positive int partkey")
        if (not isinstance(self.replication, int)
                or not 1 <= self.replication <= self.maxworker):
            raise ValueError(
                f"replication must be an int in [1, maxworker="
                f"{self.maxworker}], got {self.replication!r}")
        return self

    def effective_replication(self) -> int:
        """The conf's replication with the ``DOS_REPLICATION`` env
        override applied (env policy: a malformed or out-of-range value
        degrades to the conf's, never crashes)."""
        from .env import env_cast
        from .log import get_logger

        r = env_cast("DOS_REPLICATION", None, int)
        if r is None:
            return self.replication
        if not 1 <= r <= self.maxworker:
            get_logger(__name__).warning(
                "ignoring DOS_REPLICATION=%d outside [1, maxworker=%d]; "
                "using %d", r, self.maxworker, self.replication)
            return self.replication
        return r

    @property
    def is_tpu(self) -> bool:
        return self.partmethod == "tpu"

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d = {k: v for k, v in d.items() if v is not None}
        if d.get("replication") == 1:
            del d["replication"]      # R=1 confs stay byte-identical
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "ClusterConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known}).validate()

    @classmethod
    def load(cls, path: str) -> "ClusterConfig":
        with open(path) as f:
            return cls.from_dict(json.load(f))

    def save(self, path: str) -> None:
        # tmp+fsync+rename: a conf is a durable artifact every worker
        # and campaign reads — never observable torn
        from .atomicio import atomic_write_json
        atomic_write_json(path, self.to_dict())


def test_config(datadir: str = "./data", n_workers: int = 8,
                partmethod: str = "tpu") -> ClusterConfig:
    """Canned smoke-test config.

    Mirrors the reference's ``-t`` mode (``process_query.py:241-256``: 100×
    localhost, mod/100) but defaults to the TPU backend with a shard count
    matched to the local device/virtual-device count.
    """
    if partmethod == "tpu":
        workers = [f"tpu:{i}" for i in range(n_workers)]
        partkey = n_workers
    else:
        workers = ["localhost"] * n_workers
        partkey = n_workers
    return ClusterConfig(
        workers=workers,
        partmethod=partmethod,
        partkey=partkey,
        outdir=os.path.join(datadir, "index"),
        xy_file=os.path.join(datadir, "synth-city.xy"),
        scenfile=os.path.join(datadir, "synth.scen"),
        diffs=[os.path.join(datadir, "synth-city.xy.diff")],
    ).validate()
