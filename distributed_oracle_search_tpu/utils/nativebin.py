"""Locate the native engine's binaries.

The build (``install.sh`` → ``native/Makefile``) drops ``make_cpd_auto``,
``gen_distribute_conf`` and ``fifo_auto`` into ``<repo>/bin`` (entry-point
parity with the reference's install.sh). Search order: ``$DOS_NATIVE_BIN``,
``<repo>/bin``, the Make build trees (fast, then dev).
"""

from __future__ import annotations

import os

from .env import env_str

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SEARCH_DIRS = (
    env_str("DOS_NATIVE_BIN", ""),
    os.path.join(_REPO_ROOT, "bin"),
    os.path.join(_REPO_ROOT, "native", "build", "fast", "bin"),
    os.path.join(_REPO_ROOT, "native", "build", "dev", "bin"),
)


def find_binary(name: str) -> str | None:
    for d in SEARCH_DIRS:
        if not d:
            continue
        path = os.path.join(d, name)
        if os.path.isfile(path) and os.access(path, os.X_OK):
            return path
    return None


def require_binary(name: str) -> str:
    path = find_binary(name)
    if path is None:
        raise FileNotFoundError(
            f"native binary {name!r} not found (searched "
            f"{[d for d in SEARCH_DIRS if d]}); build it with ./install.sh")
    return path
