"""Lock-order discipline: a drop-in lock with a runtime cycle detector.

The threaded stack (serving queues/batchers, hedged dispatch, circuit
breakers, the supervisor, the metrics registry) holds ~20 locks with no
machine-checked ordering — exactly the setting where a refactor
reintroduces an ABBA deadlock that only fires under production
interleavings. :class:`OrderedLock` is the runtime half of the defense
(``dos-lint``'s ``lock-scope`` rule is the static half):

* **off by default** — without ``DOS_LOCK_CHECK`` an acquire is one
  extra attribute hop over a raw ``threading.Lock``; no graph, no
  bookkeeping. Hot paths (every metric increment) stay cheap.
* **witness mode** (``DOS_LOCK_CHECK=1``, set by the tier-1 conftest) —
  every acquire records the edge *held-lock → acquired-lock* in a
  process-wide lock-order graph keyed by lock NAME (a class of locks,
  e.g. ``resilience.CircuitBreaker``, not one instance — the graph must
  generalize across instances to catch an ABBA pair that one run only
  exercises as AB). A new edge that closes a cycle raises
  :class:`LockOrderError` at the acquire that would make deadlock
  *possible*, even though this particular interleaving did not hang.
  Same-instance re-acquire (self-deadlock of a non-reentrant lock) is
  an immediate error too.
* ``DOS_LOCK_CHECK=warn`` records and logs violations without raising
  (production triage mode); :func:`violations` exposes what fired.

The witness graph persists edges across the process lifetime, so the
detector is cumulative: tier-1's threaded serving/replication/obs tests
double as a continuous lock-order regression suite.

This module must stay import-light (stdlib + ``utils.env``/``log``):
``obs.metrics`` builds its locks from here, so importing ``obs`` back
would cycle.
"""

from __future__ import annotations

import threading

from .env import env_str
from .log import get_logger

log = get_logger(__name__)

#: check modes
OFF, RAISE, WARN = "off", "raise", "warn"


def _mode_from_env() -> str:
    raw = (env_str("DOS_LOCK_CHECK", "") or "").strip().lower()
    if raw in ("1", "true", "yes", "on", "raise"):
        return RAISE
    if raw == "warn":
        return WARN
    return OFF


#: process-wide mode, fixed at import (the tier-1 conftest exports
#: DOS_LOCK_CHECK=1 before the package imports); tests may override via
#: set_checking() for their own scoped locks
_MODE = _mode_from_env()


def checking() -> bool:
    return _MODE != OFF


def set_checking(mode: str | bool) -> str:
    """Override the check mode (tests / debug REPLs). Returns the
    previous mode so callers can restore it."""
    global _MODE
    prev = _MODE
    if mode is True:
        _MODE = RAISE
    elif mode is False:
        _MODE = OFF
    elif mode in (OFF, RAISE, WARN):
        _MODE = mode
    else:
        raise ValueError(f"unknown lock-check mode {mode!r}")
    return prev


class LockOrderError(RuntimeError):
    """Acquiring this lock here makes a deadlock possible (cycle in the
    witness graph) or certain (same-instance re-acquire)."""


class _WitnessGraph:
    """The process-wide lock-order graph: edge A -> B means some thread
    acquired a B-named lock while holding an A-named lock. A cycle means
    two code paths disagree about the order — the ABBA precondition."""

    def __init__(self):
        self._edges: dict[str, set[str]] = {}
        self._violations: list[str] = []
        self._mu = threading.Lock()

    def _path(self, src: str, dst: str) -> list[str] | None:
        """DFS path src -> dst over recorded edges (caller holds _mu)."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            for nxt in self._edges.get(node, ()):
                if nxt == dst:
                    return path + [dst]
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def add_edge(self, held: str, acquired: str) -> str | None:
        """Record held -> acquired; returns a violation message when the
        edge closes a cycle (the reverse direction was already
        witnessed), None when the order is consistent."""
        with self._mu:
            if acquired in self._edges.get(held, ()):
                return None     # known-good edge, fast path
            back = (self._path(acquired, held)
                    if held != acquired else [held, held])
            self._edges.setdefault(held, set()).add(acquired)
            if back is None:
                return None
            msg = (f"lock-order cycle: acquiring {acquired!r} while "
                   f"holding {held!r}, but the reverse order "
                   f"{' -> '.join(back)} was already witnessed")
            self._violations.append(msg)
            return msg

    def record(self, msg: str) -> None:
        with self._mu:
            self._violations.append(msg)

    def violations(self) -> list[str]:
        with self._mu:
            return list(self._violations)

    def edges(self) -> dict[str, set[str]]:
        with self._mu:
            return {k: set(v) for k, v in self._edges.items()}

    def reset(self) -> None:
        with self._mu:
            self._edges.clear()
            self._violations.clear()


#: the process-wide graph (tests may instantiate their own)
GRAPH = _WitnessGraph()

#: per-thread stack of (name, lock-instance) currently held
_HELD = threading.local()


def _held_stack() -> list:
    st = getattr(_HELD, "stack", None)
    if st is None:
        st = _HELD.stack = []
    return st


def violations() -> list[str]:
    """Every lock-order violation witnessed so far (warn mode keeps
    running; raise mode usually dies at the first)."""
    return GRAPH.violations()


class OrderedLock:
    """``threading.Lock`` plus the witness bookkeeping above.

    ``name`` identifies the lock's CLASS in the order graph — use one
    name per lock role (``"metrics.Counter"``, ``"serving.ShardQueue"``),
    not per instance. Works as a ``with`` target and as the underlying
    lock of a ``threading.Condition`` (``acquire``/``release`` are the
    whole protocol Condition needs).
    """

    __slots__ = ("name", "_lock", "_graph")

    def __init__(self, name: str, graph: _WitnessGraph | None = None):
        self.name = name
        self._lock = threading.Lock()
        self._graph = graph or GRAPH

    # ------------------------------------------------------------ check
    def _check_acquire(self) -> None:
        stack = _held_stack()
        msg = None
        certain = False
        for held_name, held_lock in stack:
            if held_lock is self:
                msg = (f"self-deadlock: thread re-acquiring "
                       f"non-reentrant lock {self.name!r} it already "
                       f"holds")
                certain = True
                self._graph.record(msg)
                break
        else:
            if stack:
                msg = self._graph.add_edge(stack[-1][0], self.name)
        if msg is not None:
            log.error("%s", msg)
            # warn mode downgrades ORDER cycles (deadlock possible) to
            # a log line, but a same-instance re-acquire is deadlock
            # CERTAIN: proceeding would block this thread forever, so
            # it raises in every checking mode
            if _MODE == RAISE or certain:
                raise LockOrderError(msg)

    # ------------------------------------------------------- lock proto
    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        if _MODE != OFF:
            self._check_acquire()
            got = self._lock.acquire(blocking, timeout)
            if got:
                _held_stack().append((self.name, self))
            return got
        return self._lock.acquire(blocking, timeout)

    def release(self) -> None:
        # pop unconditionally (not gated on _MODE): a set_checking()
        # flip between a thread's acquire and its release must not
        # strand a stale entry that later reads as a false
        # self-deadlock; in off mode nothing was pushed and the scan
        # sees an empty stack
        stack = getattr(_HELD, "stack", None)
        if stack:
            for i in range(len(stack) - 1, -1, -1):
                if stack[i][1] is self:
                    del stack[i]
                    break
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def _is_owned(self) -> bool:
        """Ownership probe for ``threading.Condition``: without this,
        Condition falls back to a non-blocking ``acquire(False)`` on a
        lock the calling thread already holds — which the self-deadlock
        check would (rightly) flag. In checking mode the held stack
        answers exactly; in off mode, stdlib's own approximation."""
        if _MODE != OFF:
            return any(lck is self for _, lck in _held_stack())
        if self._lock.acquire(False):
            self._lock.release()
            return False
        return True

    def __enter__(self) -> "OrderedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<OrderedLock {self.name!r} {'locked' if self.locked() else 'unlocked'}>"


def ordered_condition(name: str) -> threading.Condition:
    """A ``Condition`` whose mutex participates in the order graph
    (``wait`` releases through :meth:`OrderedLock.release`, so the held
    stack stays truthful across waits)."""
    return threading.Condition(OrderedLock(name))
