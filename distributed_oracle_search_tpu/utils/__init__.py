from .timer import Timer
from .log import get_logger, set_verbosity

__all__ = ["Timer", "get_logger", "set_verbosity"]
