"""Env-var parsing for the runtime knobs (``DOS_*``).

One helper, one policy: a missing or malformed value falls back to the
default with a log line — a typo in an ops environment must degrade the
knob, never crash a campaign or silently change semantics per call site.
"""

from __future__ import annotations

import os

from .log import get_logger

log = get_logger(__name__)


def env_cast(name: str, default, cast):
    """``cast(os.environ[name])`` with ``default`` on absence or a value
    ``cast`` rejects (logged)."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return cast(raw)
    except (TypeError, ValueError):
        log.warning("ignoring malformed %s=%r (using %r)", name, raw,
                    default)
        return default
