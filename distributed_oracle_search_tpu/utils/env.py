"""Env-var parsing for the runtime knobs (``DOS_*``).

One helper, one policy: a missing or malformed value falls back to the
default with a log line — a typo in an ops environment must degrade the
knob, never crash a campaign or silently change semantics per call site.
"""

from __future__ import annotations

import os

from .log import get_logger

log = get_logger(__name__)


def env_cast(name: str, default, cast):
    """``cast(os.environ[name])`` with ``default`` on absence or a value
    ``cast`` rejects (logged)."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return cast(raw)
    except (TypeError, ValueError):
        log.warning("ignoring malformed %s=%r (using %r)", name, raw,
                    default)
        return default


def env_str(name: str, default: str | None = None) -> str | None:
    """Raw string knob (paths, host names, fault specs). Same policy
    home as :func:`env_cast` so ``dos-lint``'s ``env-discipline`` rule
    has one module to point every ``DOS_*`` read at."""
    return os.environ.get(name, default)


#: accepted spellings for boolean knobs; anything else is malformed and
#: degrades to the default (logged), matching the env_cast policy
_TRUE = frozenset(("1", "true", "yes", "on"))
_FALSE = frozenset(("0", "false", "no", "off"))


def env_flag(name: str, default: bool) -> bool:
    """Boolean knob. Historically these were parsed ad hoc (``!= "0"``
    for default-on knobs, ``== "1"`` for default-off ones) with a
    different accident waiting at each call site; one parser, one
    degrade path. An EMPTY value counts as absent, not false — the
    ``FLAG=${UNSET_VAR}`` shell-interpolation accident must not
    silently flip a default-on knob off."""
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    v = raw.strip().lower()
    if v in _TRUE:
        return True
    if v in _FALSE:
        return False
    log.warning("ignoring malformed %s=%r (using %r)", name, raw, default)
    return default
