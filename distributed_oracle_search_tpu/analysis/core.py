"""``dos-lint`` framework: contexts, suppressions, runner, reports.

One file at a time: parse once, hand the :class:`FileContext` (source,
lines, AST, package-relative path) to every enabled rule, collect
:class:`Finding` rows, then apply the file's inline suppressions.

Suppression grammar (mandatory justification)::

    risky_call()   # dos-lint: disable=lock-scope -- lane serialization
                   #   is the point; see the lane-lock comment

    # dos-lint: disable=atomic-writes -- scratch file, same-dir tmp
    with open(scratch, "w") as f:
        ...

A trailing comment suppresses its own line; a comment-only line
suppresses the next statement line. ``disable=a,b`` covers several
rules. The ``--`` separator and non-empty justification are REQUIRED —
a bare ``disable=`` is reported as a :data:`BAD_SUPPRESSION` finding
(which cannot itself be suppressed): reviewer folklore is exactly what
this tool exists to replace, so every silenced contract carries its
reason in the diff.

Exit-code convention (shared with ``dos-obs bench-diff`` so the two
gates compose in one pipeline): 0 = clean, 1 = the gate fails
(unsuppressed findings under ``--strict``), 2 = usage/internal error.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re

#: pseudo-rule booked for malformed suppressions; never suppressible
BAD_SUPPRESSION = "bad-suppression"

_SUPPRESS_RE = re.compile(
    r"#\s*dos-lint:\s*disable=([A-Za-z0-9_,-]+)\s*(?:--\s*(.*))?$")


@dataclasses.dataclass
class Finding:
    """One rule violation at one source location."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    suppressed: bool = False
    justification: str = ""

    def render(self) -> str:
        tag = "suppressed: " if self.suppressed else ""
        return (f"{self.path}:{self.line}:{self.col}: {self.rule}: "
                f"{tag}{self.message}")

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Suppression:
    line: int               # the source line the comment sits on
    rules: tuple
    justification: str
    applies_next: bool      # comment-only line: covers the next stmt


class FileContext:
    """Everything a rule may look at for one file."""

    def __init__(self, path: str, source: str,
                 config: "LintConfig"):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.config = config
        #: package-relative posix path when the file lives inside the
        #: package (rules scope allowlists on it); otherwise the
        #: basename — fixture corpora stay subject to every rule
        self.relpath = _package_relpath(path)

    def in_package(self) -> bool:
        return "/" in self.relpath


def _package_relpath(path: str) -> str:
    parts = os.path.abspath(path).replace(os.sep, "/").split("/")
    if "distributed_oracle_search_tpu" in parts:
        i = parts.index("distributed_oracle_search_tpu")
        return "/".join(parts[i:])
    return parts[-1]


@dataclasses.dataclass
class LintConfig:
    """Run-wide knobs. ``metric_doc`` is the text the
    ``metric-registry`` rule checks names against (default: the real
    package's ``obs/__init__`` docstring, loaded lazily); tests inject
    their own to exercise the rule against fixture maps."""

    select: tuple = ()          # rule names to run (empty = all)
    disable: tuple = ()         # rule names to skip
    metric_doc: str | None = None

    def enabled(self, name: str) -> bool:
        if self.select and name not in self.select:
            return False
        return name not in self.disable

    def metric_doc_text(self) -> str:
        if self.metric_doc is None:
            init = os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "obs", "__init__.py")
            try:
                with open(init) as f:
                    self.metric_doc = ast.get_docstring(
                        ast.parse(f.read())) or ""
            except (OSError, SyntaxError):
                self.metric_doc = ""
        return self.metric_doc


# ------------------------------------------------------------ suppressions

def parse_suppressions(lines) -> tuple[list[Suppression], list[Finding]]:
    """Scan source lines for disable comments. Returns the suppressions
    plus BAD_SUPPRESSION findings for any without a justification."""
    sups: list[Suppression] = []
    bad: list[Finding] = []
    for i, raw in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(raw)
        if not m:
            continue
        rules = tuple(r.strip() for r in m.group(1).split(",")
                      if r.strip())
        just = (m.group(2) or "").strip()
        applies_next = raw.lstrip().startswith("#")
        if not just:
            bad.append(Finding(
                BAD_SUPPRESSION, "", i, raw.find("#") + 1,
                f"suppression of {', '.join(rules)} carries no "
                f"justification (write `# dos-lint: disable=<rule> -- "
                f"<why this site is exempt>`)"))
            continue
        sups.append(Suppression(i, rules, just, applies_next))
    return sups, bad


def _covered_lines(sup: Suppression, lines, spans) -> set[int]:
    if not sup.applies_next:
        # trailing comment: cover the whole statement it trails — a
        # finding anchors to the statement's FIRST line, which for a
        # multi-line call is above the comment
        out = {sup.line}
        out.update(spans.get(sup.line, ()))
        return out
    # comment-only line: cover the next non-blank, non-comment line
    # (continuation comments in between extend the search)
    for j in range(sup.line, len(lines)):
        txt = lines[j].strip()     # lines[j] is 1-based line j+1
        if txt and not txt.startswith("#"):
            return {sup.line, j + 1}
    return {sup.line}


#: compound statements span their whole BODY — a suppression inside the
#: body must not reach the header's findings, so they never contribute
#: spans (their header expressions, e.g. a multi-line ``with open(...)``,
#: are separate expr nodes and still do)
_COMPOUND = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
             ast.For, ast.AsyncFor, ast.While, ast.If, ast.With,
             ast.AsyncWith, ast.Try)


def statement_spans(tree) -> dict[int, set[int]]:
    """line -> the start lines of every SIMPLE statement/expression
    spanning it, so a trailing suppression on any physical line of a
    multi-line statement reaches the line its finding anchors to —
    without a disable inside a compound statement's body silencing
    findings anchored at the compound's header."""
    spans: dict[int, set[int]] = {}
    for node in ast.walk(tree):
        if isinstance(node, _COMPOUND):
            continue
        start = getattr(node, "lineno", None)
        end = getattr(node, "end_lineno", None)
        if start is None or end is None or end == start:
            continue
        for ln in range(start, end + 1):
            spans.setdefault(ln, set()).add(start)
    return spans


def apply_suppressions(findings: list[Finding], sups: list[Suppression],
                       lines, spans=None) -> list[Finding]:
    """Mark findings covered by a suppression; BAD_SUPPRESSION rows are
    never suppressible. Several suppressions may cover one line
    (stacked comment-only disables) — each is honored."""
    spans = spans or {}
    cover: dict[int, list[Suppression]] = {}
    for sup in sups:
        for ln in _covered_lines(sup, lines, spans):
            cover.setdefault(ln, []).append(sup)
    for f in findings:
        if f.rule == BAD_SUPPRESSION:
            continue
        for sup in cover.get(f.line, ()):
            if f.rule in sup.rules or "all" in sup.rules:
                f.suppressed = True
                f.justification = sup.justification
                break
    return findings


# ------------------------------------------------------------------ runner

def run_file(path: str, rules, config: LintConfig) -> list[Finding]:
    """Lint one file with every enabled rule. A syntax error is itself
    a finding (a file the checker cannot parse is a file no contract is
    checked in), not a crash."""
    with open(path, encoding="utf-8", errors="replace") as f:
        source = f.read()
    try:
        ctx = FileContext(path, source, config)
    except SyntaxError as e:
        return [Finding("syntax-error", path, e.lineno or 0, 0,
                        f"unparseable: {e.msg}")]
    except ValueError as e:
        # e.g. a null byte — ast.parse raises ValueError, not
        # SyntaxError; one corrupt file must not take down the gate
        return [Finding("syntax-error", path, 0, 0,
                        f"unparseable: {e}")]
    findings: list[Finding] = []
    for rule in rules:
        if not config.enabled(rule.name):
            continue
        for f_ in rule.check(ctx):
            f_.path = path
            findings.append(f_)
    sups, bad = parse_suppressions(ctx.lines)
    for b in bad:
        b.path = path
        findings.append(b)
    apply_suppressions(findings, sups, ctx.lines,
                       statement_spans(ctx.tree))
    findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return findings


def collect_files(paths) -> list[str]:
    """Expand files/dirs into a sorted ``.py`` file list (dirs walked
    recursively, ``__pycache__`` skipped)."""
    out: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs if d != "__pycache__"]
                out.extend(os.path.join(root, f) for f in files
                           if f.endswith(".py"))
        else:
            out.append(p)
    return sorted(set(out))


def run_paths(paths, rules, config: LintConfig | None = None
              ) -> tuple[list[Finding], int]:
    """Lint every file under ``paths``; returns ``(findings, n_files)``."""
    config = config or LintConfig()
    files = collect_files(paths)
    findings: list[Finding] = []
    for path in files:
        findings.extend(run_file(path, rules, config))
    return findings, len(files)


# ----------------------------------------------------------------- reports

def render_text(findings, n_files: int, show_suppressed: bool = False
                ) -> str:
    lines = []
    active = [f for f in findings if not f.suppressed]
    shown = findings if show_suppressed else active
    for f in shown:
        lines.append(f.render())
    n_sup = sum(1 for f in findings if f.suppressed)
    lines.append(
        f"dos-lint: {len(active)} finding(s) in {n_files} file(s)"
        + (f" ({n_sup} suppressed)" if n_sup else ""))
    return "\n".join(lines)


def render_json(findings, n_files: int) -> dict:
    """Machine report, ``dos-obs bench-diff``-convention gate fields:
    ``ok`` mirrors the exit code (0 clean / 1 findings) so a pipeline
    can treat lint and bench-diff outputs uniformly."""
    active = [f for f in findings if not f.suppressed]
    counts: dict[str, int] = {}
    for f in active:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    return {
        "ok": not active,
        "exit_code": 1 if active else 0,
        "files": n_files,
        "counts": counts,
        "suppressed": sum(1 for f in findings if f.suppressed),
        "findings": [f.as_dict() for f in findings],
    }


def exit_code(findings, strict: bool) -> int:
    active = [f for f in findings if not f.suppressed]
    return 1 if (strict and active) else 0
