"""Project-contract static analysis (``dos-lint``).

Six PRs of conventions hold this codebase together: every ``DOS_*`` knob
parses through ``utils.env``, every durable artifact write goes through
``utils.atomicio``, every metric name lives in the ``obs`` metric map,
every wire codec tolerates unknown keys, no blocking call runs under a
lock. None of that survives contact with a refactor unless it is
machine-checked — this package turns the conventions into enforced
invariants:

* :mod:`.core` — the checker framework: per-file AST visitor pipeline,
  inline ``# dos-lint: disable=<rule> -- <justification>`` suppressions
  (justification mandatory — a silenced rule must say why), text/JSON
  reports, and the ``--strict`` gate (exit 0 clean / 1 findings, the
  same convention ``dos-obs bench-diff`` uses so both gates compose in
  one CI pipeline).
* :mod:`.rules` — the project-contract rules themselves (see
  ``dos-lint --list-rules`` or the README's "Static analysis" table).

The runtime companion is :mod:`..utils.locks`: ``dos-lint``'s
``lock-scope`` rule catches blocking-under-lock statically, while
``OrderedLock``'s witness graph (``DOS_LOCK_CHECK=1``) catches
lock-ORDER cycles dynamically under the tier-1 threaded tests.
"""

from .core import (
    BAD_SUPPRESSION, Finding, LintConfig, collect_files, render_json,
    render_text, run_paths,
)
from .rules import ALL_RULES, rule_by_name

__all__ = ["BAD_SUPPRESSION", "Finding", "LintConfig", "ALL_RULES",
           "collect_files", "render_json", "render_text", "run_paths",
           "rule_by_name"]
