"""The ``dos-lint`` project-contract rules.

Each rule encodes one convention a previous PR established and every
later PR must preserve (the README's "Static analysis" table maps rules
to the PRs that established them):

=================  =====================================================
``env-discipline``  every ``DOS_*`` env read goes through ``utils.env``
                    (PR 2's degrade-don't-crash knob policy)
``atomic-writes``   durable artifacts go through ``utils.atomicio``
                    (PR 4's tmp+fsync+rename discipline)
``metric-registry`` metric names live in the ``obs/__init__`` metric
                    map and follow ``_total``/``_seconds`` naming
                    (PR 1's observability contract)
``silent-except``   a broad ``except`` must re-raise, log, or book a
                    metric (PR 2: degradation must be observable)
``wire-compat``     codecs tolerate unknown keys and reject only NEWER
                    schema versions (PR 4's ``validate_manifest`` gate)
``jit-purity``      no Python side effects inside jit/shard_map/pallas
                    functions (trace-time effects fire once, not per
                    call — the silent-wrong-metrics class of bug)
``lock-scope``      no blocking call while holding a lock (the static
                    half of ``utils.locks``' runtime detector)
``fifo-hygiene``    FIFO opens carry PR 2's bounded-deadline pattern
                    (``O_NONBLOCK``/``O_RDWR`` — a blocking open on a
                    dead peer's FIFO wedges forever)
=================  =====================================================

Rules are AST-level and intentionally heuristic where real dataflow
would be needed (``atomic-writes`` tracks string fragments through
simple same-function assignments, nothing more). False positives are
handled by the suppression grammar — WITH a justification, which is the
point: the exemption is then in the diff, not in a reviewer's head.
"""

from __future__ import annotations

import ast
import re

from .core import FileContext, Finding

#: argument name → rule instance registry
ALL_RULES: list = []


def _register(cls):
    ALL_RULES.append(cls())
    return cls


def rule_by_name(name: str):
    for r in ALL_RULES:
        if r.name == name:
            return r
    raise KeyError(name)


# -------------------------------------------------------------- helpers

def dotted(node: ast.AST) -> str:
    """``a.b.c`` for nested Name/Attribute chains, "" otherwise."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def const_str(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def string_fragments(node: ast.AST) -> list[str]:
    """Every string literal under ``node`` (f-strings, concats,
    os.path.join args — the lint-grade substitute for dataflow)."""
    out: list[str] = []
    for n in ast.walk(node):
        s = const_str(n)
        if s is not None:
            out.append(s)
    return out


def walk_shallow(body):
    """Walk statements without descending into nested function/class
    definitions (their bodies run in another frame/time; each function
    gets its own scope pass, so descending here would double-report)."""
    stack = list(body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


class Rule:
    name = ""
    description = ""

    def check(self, ctx: FileContext):
        raise NotImplementedError

    def finding(self, node: ast.AST, message: str) -> Finding:
        return Finding(self.name, "", getattr(node, "lineno", 0),
                       getattr(node, "col_offset", 0) + 1, message)


# -------------------------------------------------------- env-discipline

@_register
class EnvDiscipline(Rule):
    name = "env-discipline"
    description = ("DOS_* env keys are read through utils.env "
                   "(env_cast/env_str/env_flag), nowhere else")

    ALLOWED = ("utils/env.py",)

    def _is_dos_key(self, node) -> bool:
        s = const_str(node)
        return s is not None and s.startswith("DOS_")

    def check(self, ctx: FileContext):
        if ctx.relpath.endswith(self.ALLOWED):
            return
        for node in ast.walk(ctx.tree):
            key = None
            if isinstance(node, ast.Call):
                fn = dotted(node.func)
                if fn in ("os.environ.get", "os.getenv",
                          "os.environ.pop", "os.environ.setdefault") \
                        and node.args \
                        and self._is_dos_key(node.args[0]):
                    key = const_str(node.args[0])
            elif isinstance(node, ast.Subscript):
                if dotted(node.value) == "os.environ" \
                        and isinstance(getattr(node, "ctx", None),
                                       ast.Load) \
                        and self._is_dos_key(node.slice):
                    key = const_str(node.slice)
            elif isinstance(node, ast.Compare):
                if len(node.ops) == 1 \
                        and isinstance(node.ops[0], (ast.In, ast.NotIn)) \
                        and dotted(node.comparators[0]) == "os.environ" \
                        and self._is_dos_key(node.left):
                    key = const_str(node.left)
            if key is not None:
                yield self.finding(
                    node,
                    f"direct os.environ read of {key!r} bypasses "
                    f"utils.env (use env_cast/env_str/env_flag: one "
                    f"parse policy, malformed values degrade instead "
                    f"of crashing)")


# --------------------------------------------------------- atomic-writes

#: substrings marking a path as a durable artifact
_DURABLE = (".json", ".npy", ".npz", ".trace", ".csv", ".xy", ".scen",
            ".diff", ".results", ".paths", "ledger", "manifest")

_WRITE_MODES = ("w", "wb", "w+", "wb+", "+w", "x", "xb")


@_register
class AtomicWrites(Rule):
    name = "atomic-writes"
    description = ("open(mode='w'/'wb') targeting a durable artifact "
                   "path must go through utils.atomicio")

    ALLOWED = ("utils/atomicio.py",)

    def _open_mode(self, call: ast.Call) -> str | None:
        if not (isinstance(call.func, ast.Name)
                and call.func.id == "open"):
            return None
        if len(call.args) >= 2:
            return const_str(call.args[1])
        for kw in call.keywords:
            if kw.arg == "mode":
                return const_str(kw.value)
        return None

    def _durable(self, frags) -> str | None:
        for f in frags:
            for pat in _DURABLE:
                if pat in f:
                    return f
        return None

    def check(self, ctx: FileContext):
        if ctx.relpath.endswith(self.ALLOWED):
            return
        # per-function string-fragment propagation: path = join(d,
        # "degraded.json"); open(path, "w") still resolves
        funcs = [n for n in ast.walk(ctx.tree)
                 if isinstance(n, (ast.FunctionDef,
                                   ast.AsyncFunctionDef))]
        scopes = [(ctx.tree, None)] + [(f, f.name) for f in funcs]
        for scope, fname in scopes:
            body = scope.body if hasattr(scope, "body") else []
            # pass 1: collect every assignment's string fragments (the
            # shallow walk is unordered, and `path = ...` may sit after
            # the open() in traversal order)
            assigned: dict[str, list[str]] = {}
            for node in walk_shallow(body):
                if isinstance(node, ast.Assign):
                    frags = string_fragments(node.value)
                    if frags:
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Name):
                                assigned.setdefault(
                                    tgt.id, []).extend(frags)
            # pass 2: the open() calls
            for node in walk_shallow(body):
                if not isinstance(node, ast.Call):
                    continue
                mode = self._open_mode(node)
                if mode not in _WRITE_MODES:
                    continue
                target = node.args[0] if node.args else None
                frags = string_fragments(target) if target is not None \
                    else []
                if isinstance(target, ast.Name):
                    frags = frags + assigned.get(target.id, [])
                hit = self._durable(frags)
                writer_name = fname or ""
                if hit is None and not (
                        writer_name.startswith(("write_", "save",
                                                "dump", "_write"))):
                    continue
                what = (f"path matches durable artifact {hit!r}"
                        if hit is not None else
                        f"writer function {writer_name!r}")
                yield self.finding(
                    node,
                    f"raw open(..., {mode!r}) — {what}; a crash "
                    f"mid-write leaves a torn artifact readers will "
                    f"load as garbage (use utils.atomicio "
                    f"atomic_write_bytes/_json/_npy: tmp+fsync+rename)")


# ------------------------------------------------------- metric-registry

_METRIC_KINDS = {"counter": "_total", "histogram": "_seconds"}


@_register
class MetricRegistry(Rule):
    name = "metric-registry"
    description = ("metric names appear in the obs/__init__ metric map "
                   "and follow _total/_seconds naming")

    ALLOWED = ("obs/metrics.py",)

    @staticmethod
    def _expand_doc(doc: str) -> str:
        """Expand the map's brace families
        (``serve_cache_{hits,misses,evictions}_total``) into the full
        names so the substring check sees every member."""
        extra = []
        for m in re.finditer(r"(\w+)?\{([\w,]+)\}(\w*)", doc):
            pre, alts, suf = m.group(1) or "", m.group(2), m.group(3)
            extra.extend(f"{pre}{alt}{suf}" for alt in alts.split(","))
        return doc + "\n" + "\n".join(extra)

    def check(self, ctx: FileContext):
        if ctx.relpath.endswith(self.ALLOWED):
            return
        doc = self._expand_doc(ctx.config.metric_doc_text())
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = dotted(node.func)
            kind = fn.rsplit(".", 1)[-1]
            if kind not in ("counter", "gauge", "histogram") \
                    or not node.args:
                continue
            name = const_str(node.args[0])
            prefix = None
            if name is None and isinstance(node.args[0], ast.JoinedStr):
                vals = node.args[0].values
                if vals and isinstance(vals[0], ast.Constant):
                    prefix = str(vals[0].value)
            if name is None and prefix is None:
                continue    # dynamic name: nothing checkable here
            suffix = _METRIC_KINDS.get(kind)
            if name is not None and suffix is not None \
                    and not name.endswith(suffix):
                yield self.finding(
                    node,
                    f"{kind} {name!r} should end {suffix!r} (obs "
                    f"naming contract; exporters and the bench-diff "
                    f"gate key off the unit suffix)")
            if name is not None and kind == "gauge" \
                    and name.endswith(("_total", "_seconds")):
                yield self.finding(
                    node,
                    f"gauge {name!r} wears a counter/histogram unit "
                    f"suffix — scrapes will misread its semantics")
            check = name if name is not None else prefix
            if doc and check and check not in doc:
                yield self.finding(
                    node,
                    f"metric {check!r} is not in the obs/__init__ "
                    f"metric map — undocumented series are invisible "
                    f"to operators (add it to the docstring map)")


# --------------------------------------------------------- silent-except

_LOG_METHODS = ("debug", "info", "warning", "error", "exception",
                "critical", "log")
_BOOK_METHODS = ("inc", "observe", "add", "set")


@_register
class SilentExcept(Rule):
    name = "silent-except"
    description = ("a broad except must re-raise, log, or book a "
                   "metric — degradation stays observable")

    def _is_broad(self, handler: ast.ExceptHandler) -> bool:
        t = handler.type
        if t is None:
            return True
        names = [t] if not isinstance(t, ast.Tuple) else list(t.elts)
        for n in names:
            if dotted(n).rsplit(".", 1)[-1] in ("Exception",
                                                "BaseException"):
                return True
        return False

    def _observable(self, handler: ast.ExceptHandler) -> bool:
        for node in walk_shallow(handler.body):
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, ast.Call):
                fn = dotted(node.func)
                leaf = fn.rsplit(".", 1)[-1]
                root = fn.split(".", 1)[0]
                if leaf in _LOG_METHODS and (
                        "log" in root.lower() or "logging" in fn):
                    return True
                if leaf in _BOOK_METHODS:
                    return True
                if fn.endswith("print_exc") or leaf == "print":
                    return True
            # error-as-data: the caught exception flows into a return
            # value / queue / field — observable by the caller (the
            # statusz "{'error': ...}" idiom)
            if handler.name and isinstance(node, ast.Name) \
                    and node.id == handler.name:
                return True
        return False

    def check(self, ctx: FileContext):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if self._is_broad(node) and not self._observable(node):
                yield self.finding(
                    node,
                    "broad except swallows the failure invisibly: "
                    "re-raise, log, or book a counter (PR-2 policy — "
                    "every degradation must be observable)")


# ----------------------------------------------------------- wire-compat

_CODEC_NAMES = ("from_json", "from_dict")


@_register
class WireCompat(Rule):
    name = "wire-compat"
    description = ("codec parsers tolerate unknown keys and reject "
                   "only NEWER schema versions")

    def _codec(self, fn) -> bool:
        return (fn.name in _CODEC_NAMES or fn.name.startswith("parse_")
                or fn.name.endswith("_from_json"))

    def check(self, ctx: FileContext):
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            if not self._codec(fn):
                continue
            # classify names: raw (straight out of json.loads / the
            # dict param) vs filtered (rebuilt by a comprehension,
            # which is the unknown-key-tolerant idiom)
            raw: set[str] = set()
            filtered: set[str] = set()
            params = [a.arg for a in fn.args.args
                      if a.arg not in ("self", "cls")]
            raw.update(params)
            for node in walk_shallow(fn.body):
                if isinstance(node, ast.Assign):
                    is_filtered = isinstance(node.value, ast.DictComp)
                    is_raw = (isinstance(node.value, ast.Call)
                              and dotted(node.value.func)
                              in ("json.loads", "json.load"))
                    for tgt in node.targets:
                        if not isinstance(tgt, ast.Name):
                            continue
                        if is_filtered:
                            filtered.add(tgt.id)
                            raw.discard(tgt.id)
                        elif is_raw:
                            raw.add(tgt.id)
                        else:
                            raw.discard(tgt.id)
                            filtered.discard(tgt.id)
            for node in walk_shallow(fn.body):
                if isinstance(node, ast.Call):
                    for kw in node.keywords:
                        if kw.arg is not None:
                            continue
                        if isinstance(kw.value, ast.Name) \
                                and kw.value.id in raw \
                                and kw.value.id not in filtered:
                            yield self.finding(
                                node,
                                f"codec {fn.name}() splats the raw "
                                f"decoded dict (**{kw.value.id}) into "
                                f"a constructor: one unknown key from "
                                f"a NEWER peer is a TypeError. Filter "
                                f"to known fields first (the "
                                f"HealthStatus/ClusterConfig idiom)")
                if isinstance(node, ast.Compare) \
                        and len(node.ops) == 1 \
                        and isinstance(node.ops[0], ast.NotEq):
                    sides = [node.left] + node.comparators
                    for side in sides:
                        key = None
                        if isinstance(side, ast.Subscript):
                            key = const_str(side.slice)
                        elif isinstance(side, ast.Call) and \
                                dotted(side.func).endswith(".get") \
                                and side.args:
                            key = const_str(side.args[0])
                        if key and "version" in key.lower():
                            yield self.finding(
                                node,
                                f"codec {fn.name}() gates on "
                                f"{key!r} != — an exact-version gate "
                                f"rejects OLDER data it could read. "
                                f"Reject only NEWER versions (the "
                                f"validate_manifest `>` contract)")
                            break


# ------------------------------------------------------------ jit-purity

_JIT_MARKERS = ("jit", "shard_map", "pallas_call")
_IMPURE_ROOTS = ("time", "os", "random")
_MUTATORS = ("append", "extend", "update", "setdefault", "insert",
             "remove", "clear")


@_register
class JitPurity(Rule):
    name = "jit-purity"
    description = ("no Python side effects (time/os/print/metrics/"
                   "captured-container mutation) inside jit/shard_map/"
                   "pallas functions")

    SCOPE = ("ops/", "models/")

    def _in_scope(self, ctx: FileContext) -> bool:
        if not ctx.in_package():
            return True         # fixture corpora: rule applies
        return any(f"distributed_oracle_search_tpu/{d}" in ctx.relpath
                   for d in self.SCOPE)

    def _jit_decorated(self, fn) -> bool:
        for dec in fn.decorator_list:
            names = [dotted(dec)]
            if isinstance(dec, ast.Call):
                names.append(dotted(dec.func))
                names.extend(dotted(a) for a in dec.args)
                names.extend(dotted(k.value) for k in dec.keywords)
            for n in names:
                leaf = n.rsplit(".", 1)[-1]
                if leaf in _JIT_MARKERS:
                    return True
        return False

    def _wrapped_names(self, tree) -> set[str]:
        """``walk = jax.jit(walk_impl)`` marks ``walk_impl`` jitted."""
        out: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and dotted(node.func).rsplit(".", 1)[-1] \
                    in _JIT_MARKERS:
                for a in list(node.args) + [k.value
                                            for k in node.keywords]:
                    if isinstance(a, ast.Name):
                        out.add(a.id)
        return out

    def _locals(self, fn) -> set[str]:
        out = {a.arg for a in fn.args.args + fn.args.kwonlyargs
               + fn.args.posonlyargs}
        if fn.args.vararg:
            out.add(fn.args.vararg.arg)
        if fn.args.kwarg:
            out.add(fn.args.kwarg.arg)
        for node in walk_shallow(fn.body):
            if isinstance(node, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign)):
                tgts = (node.targets if isinstance(node, ast.Assign)
                        else [node.target])
                for t in tgts:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            out.add(n.id)
            elif isinstance(node, (ast.For, ast.comprehension)):
                for n in ast.walk(node.target):
                    if isinstance(n, ast.Name):
                        out.add(n.id)
            elif isinstance(node, ast.With):
                for item in node.items:
                    if item.optional_vars is not None:
                        for n in ast.walk(item.optional_vars):
                            if isinstance(n, ast.Name):
                                out.add(n.id)
        return out

    def check(self, ctx: FileContext):
        if not self._in_scope(ctx):
            return
        wrapped = self._wrapped_names(ctx.tree)
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            if not (self._jit_decorated(fn) or fn.name in wrapped):
                continue
            local = self._locals(fn)
            for node in walk_shallow(fn.body):
                if isinstance(node, ast.Call):
                    fdot = dotted(node.func)
                    root = fdot.split(".", 1)[0]
                    leaf = fdot.rsplit(".", 1)[-1]
                    if root in _IMPURE_ROOTS and "." in fdot:
                        yield self.finding(
                            node,
                            f"{fdot}() inside a jit-compiled function "
                            f"runs at TRACE time (once per compile), "
                            f"not per call — hoist it out")
                    elif fdot == "print":
                        yield self.finding(
                            node,
                            "print() inside jit fires once per "
                            "compile, not per call (use jax.debug."
                            "print for traced values)")
                    elif leaf in ("inc", "observe") or fdot in (
                            "counter", "gauge", "histogram"):
                        yield self.finding(
                            node,
                            f"metric call {fdot}() inside jit books "
                            f"once per COMPILE, not per execution — "
                            f"silently wrong numbers; record outside "
                            f"the kernel")
                    elif isinstance(node.func, ast.Attribute) \
                            and node.func.attr in _MUTATORS \
                            and isinstance(node.func.value, ast.Name) \
                            and node.func.value.id not in local:
                        yield self.finding(
                            node,
                            f"mutating captured container "
                            f"{node.func.value.id!r}."
                            f"{node.func.attr}() inside jit is a "
                            f"trace-time side effect — it records "
                            f"tracers once, not values per call")
                elif isinstance(node, (ast.Assign, ast.AugAssign)):
                    tgts = (node.targets
                            if isinstance(node, ast.Assign)
                            else [node.target])
                    for t in tgts:
                        if isinstance(t, ast.Subscript) \
                                and isinstance(t.value, ast.Name) \
                                and t.value.id not in local:
                            yield self.finding(
                                node,
                                f"subscript-assign to captured "
                                f"{t.value.id!r} inside jit mutates "
                                f"at trace time (stores a tracer, "
                                f"fires once) — return values or use "
                                f".at[].set on arrays")


# ------------------------------------------------------------ lock-scope

_LOCKISH = ("lock", "cond", "mutex", "_mu")
_BLOCKING_LEAF = ("sleep",)
_BLOCKING_DOTTED_PREFIX = ("subprocess.", "socket.", "urllib.",
                           "requests.", "http.")
_BLOCKING_EXACT = ("os.open", "open", "send_with_retry", "probe",
                   "urlopen")


@_register
class LockScope(Rule):
    name = "lock-scope"
    description = ("no blocking call (sleep/open/subprocess/socket/"
                   "wire send) while holding a lock")

    def _lockish(self, expr) -> str | None:
        node = expr
        if isinstance(node, ast.Call):
            node = node.func
        name = dotted(node)
        leaf = name.rsplit(".", 1)[-1].lower()
        for pat in _LOCKISH:
            if pat in leaf:
                return name
        return None

    def _blocking(self, call: ast.Call, lock_expr: str) -> str | None:
        fn = dotted(call.func)
        if not fn:
            return None
        leaf = fn.rsplit(".", 1)[-1]
        if leaf in _BLOCKING_LEAF:
            return fn
        if fn in _BLOCKING_EXACT or leaf in ("send_with_retry",):
            return fn
        for pre in _BLOCKING_DOTTED_PREFIX:
            if fn.startswith(pre):
                return fn
        # cond.wait on a DIFFERENT object than the with-context blocks
        # while holding this lock; on the same object it releases it
        if leaf == "wait" and fn != lock_expr + ".wait":
            return fn
        return None

    def check(self, ctx: FileContext):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.With):
                continue
            lock_names = [self._lockish(item.context_expr)
                          for item in node.items]
            lock_names = [n for n in lock_names if n]
            if not lock_names:
                continue
            for inner in walk_shallow(node.body):
                if not isinstance(inner, ast.Call):
                    continue
                hit = self._blocking(inner, lock_names[0])
                if hit:
                    yield self.finding(
                        inner,
                        f"blocking call {hit}() while holding "
                        f"{lock_names[0]!r}: every other thread "
                        f"needing this lock now waits on I/O it "
                        f"cannot see (PR-5 deadlock class; move the "
                        f"call outside the critical section)")


# ---------------------------------------------------------- fifo-hygiene

@_register
class FifoHygiene(Rule):
    name = "fifo-hygiene"
    description = ("FIFO opens use the bounded non-blocking pattern "
                   "(os.open + O_NONBLOCK/O_RDWR + deadline); bare "
                   "socket recv/sendall live only in transport/frames "
                   "readers/writers")

    #: the socket half's one sanctioned home: FrameReader/FrameWriter
    #: own every recv/sendall so torn frames surface as typed,
    #: retryable TransportErrors instead of ad-hoc partial reads
    SOCKET_ALLOWED = ("transport/frames.py",)
    _SOCKET_CALLS = ("recv", "recv_into", "sendall")

    def _mentions_fifo(self, node) -> bool:
        for n in ast.walk(node):
            txt = None
            if isinstance(n, ast.Name):
                txt = n.id
            elif isinstance(n, ast.Attribute):
                txt = n.attr
            else:
                txt = const_str(n)
            if txt and "fifo" in txt.lower():
                return True
        return False

    def check(self, ctx: FileContext):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = dotted(node.func)
            if fn == "open" and node.args \
                    and self._mentions_fifo(node.args[0]):
                yield self.finding(
                    node,
                    "blocking builtin open() on a FIFO wedges forever "
                    "when the peer is dead (no reader/writer ever "
                    "arrives): use os.open with O_NONBLOCK and a "
                    "bounded deadline loop (worker.server._reply "
                    "pattern)")
            elif fn == "os.open" and node.args \
                    and self._mentions_fifo(node.args[0]):
                flags = " ".join(
                    dotted(n) for n in ast.walk(node)
                    if isinstance(n, (ast.Attribute, ast.Name)))
                if "O_NONBLOCK" not in flags and "O_RDWR" not in flags:
                    yield self.finding(
                        node,
                        "os.open of a FIFO without O_NONBLOCK (or the "
                        "self-reader O_RDWR pattern) blocks until a "
                        "peer appears — a crashed peer wedges this "
                        "process forever (bound it: O_NONBLOCK + "
                        "deadline retry)")
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in self._SOCKET_CALLS \
                    and not ctx.relpath.endswith(self.SOCKET_ALLOWED):
                # the socket half of the rule: wire reads/writes go
                # through the frame codec's readers/writers, nowhere
                # else — a bare recv can return a partial frame that
                # desyncs the stream, and a bare sendall outside the
                # writer lock can interleave mid-frame
                yield self.finding(
                    node,
                    f"bare socket .{node.func.attr}() outside "
                    f"transport/frames.py: partial reads/interleaved "
                    f"writes tear the frame stream — go through "
                    f"FrameReader/FrameWriter (typed retryable "
                    f"TransportError on every failure mode)")
