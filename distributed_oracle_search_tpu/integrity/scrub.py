"""Resident-table scrubber: re-verify what is actually in memory.

The disk path is already defended (digest-verified loads, quarantine +
heal, replica anti-entropy) — but the resident first-move table a
worker serves from is read billions of times and re-checked never.
This module closes that gap: each pass walks one engine's block files
through the SAME verified load path the engine booted from
(``models.cpd.load_verified_block`` against the manifest), decodes any
pack4/RLE container to dense rows (``models.resident``), and compares
a crc32 of those disk-truth rows against a crc32 of the corresponding
RESIDENT row range — decompressing the resident codec at the point of
check, exactly like the serving path does at the point of use.

Fault taxonomy and response:

* block corrupt/missing ON DISK → the shared ``heal_block``
  quarantine → copy-replica → rebuild path (base table only; an epoch
  index never heals from the free-flow graph — ``promote_index``'s
  wrong-regime rule — it just stops promoting);
* resident rows diverge from verified disk rows → books
  ``scrub_blocks_corrupt_total``, emits a ``scrub_corrupt`` recorder
  event, and re-binds the WHOLE table from disk — a single reference
  swap (``engine.fm`` / the promote gate's ``(epoch, table)`` pair),
  so in-flight batches finish on the old reference and never tear.

Both the base table and an epoch-promoted index are covered; the
promoted gate re-binds under the engine's promote lock keeping its
epoch, or clears to the always-correct base table when the epoch index
is no longer loadable.

The pass is deliberately low-priority: one block is read, decoded, and
compared at a time, with an optional per-pass block budget
(``DOS_SCRUB_BLOCKS_PER_PASS``) and a resume cursor so a huge shard
scrubs incrementally across passes instead of monopolizing the host.
"""

from __future__ import annotations

import glob
import os
import re
import threading
import time
import zlib

import numpy as np

from ..obs import metrics as obs_metrics
from ..obs import recorder as obs_recorder
from ..utils.locks import OrderedLock
from ..utils.log import get_logger

log = get_logger(__name__)

M_SCRUB_CHECKED = obs_metrics.counter(
    "scrub_blocks_checked_total",
    "resident blocks crc32-compared against their digest-verified "
    "on-disk truth by the resident-table scrubber "
    "(DOS_SCRUB_INTERVAL_S)")
M_SCRUB_CORRUPT = obs_metrics.counter(
    "scrub_blocks_corrupt_total",
    "resident blocks whose rows diverged from verified disk rows — "
    "silent in-memory corruption; the table re-binds from disk")
M_SCRUB_PASSES = obs_metrics.counter(
    "scrub_passes_total", "completed resident-scrub passes")
M_SCRUB_SECONDS = obs_metrics.histogram(
    "scrub_pass_seconds", "wall time of one resident-scrub pass")


def _resident_rows(table, lo: int, hi: int) -> np.ndarray:
    """Dense int8 ``[hi-lo, N]`` of the RESIDENT table's row range —
    raw tables slice, compressed tables decompress at the point of
    check (the same ``decompress_rows`` the serving path trusts)."""
    from ..models.resident import CompressedFM

    if isinstance(table, CompressedFM):
        rows = np.arange(lo, hi, dtype=np.int32)
        return np.asarray(table.decompress_rows(rows), np.int8)
    return np.asarray(table[lo:hi], np.int8)


def _shard_block_files(outdir: str, shard: int, replica: int,
                       blocks_meta: dict) -> list[str]:
    """The shard's block files in block order — the manifest's view
    when it has one (it knows blocks the glob cannot see), the glob
    otherwise. Mirrors ``worker.engine.load_shard_rows``'s discovery
    so the scrubber checks exactly what the engine loaded."""
    from ..models.cpd import shard_block_name

    prefix = shard_block_name(shard, 0, replica)[:-len("00000.npy")]
    bid_of = lambda p: int(re.search(r"-b(\d+)\.npy$", p).group(1))  # noqa: E731
    manifested = sorted(
        (os.path.join(outdir, f) for f in blocks_meta
         if f.startswith(prefix)), key=bid_of)
    if manifested:
        return manifested
    return sorted(glob.glob(os.path.join(outdir, f"{prefix}*.npy")),
                  key=bid_of)


def scrub_engine_table(engine, outdir: str, table, epoch: int | None,
                       *, budget: int = 0, cursor: tuple = (0, 0),
                       heal: bool = True) -> tuple[dict, tuple]:
    """Scrub ONE resident table (base when ``epoch is None``, the
    promoted index otherwise) against the block files in ``outdir``.

    Returns ``(report, next_cursor)`` — ``next_cursor`` is ``(0, 0)``
    when the pass reached the end (wrap around), else the
    ``(block_index, row_offset)`` to resume from. The report::

        {"checked": n, "corrupt": [fname...], "healed": [fname...],
         "rebound": bool, "errors": [reason...]}
    """
    from ..models.cpd import (check_manifest_version, heal_block,
                              load_verified_block, read_manifest)
    from ..models.resident import maybe_decode_rows

    report: dict = {"checked": 0, "corrupt": [], "healed": [],
                    "rebound": False, "errors": []}
    manifest: dict | None = None
    try:
        manifest = read_manifest(outdir)
        check_manifest_version(manifest, outdir)
    except (OSError, ValueError) as e:
        # pre-manifest partial build: blocks scrub without digests
        # (resident-vs-disk compare still catches memory rot); a
        # NEWER-schema manifest is the one hard stop
        if "manifest schema" in str(e):
            report["errors"].append(str(e))
            return report, (0, 0)
        manifest = None
    blocks_meta = (manifest or {}).get("blocks", {})
    files = _shard_block_files(outdir, engine.shard, engine.replica,
                               blocks_meta)
    if not files:
        report["errors"].append(f"no blocks for shard {engine.shard} "
                                f"in {outdir}")
        return report, (0, 0)
    start, lo = cursor
    if not (0 <= start < len(files)):
        start, lo = 0, 0            # block set changed: restart
    dirty = False
    i = start
    for i in range(start, len(files)):
        if budget and report["checked"] >= budget:
            return report, (i, lo)
        path = files[i]
        fname = os.path.basename(path)
        rows, status, reason = load_verified_block(
            path, blocks_meta.get(fname))
        if rows is None:
            # disk-side rot found by the scrub read: the shared
            # quarantine→heal path fixes the FILE; the resident table
            # was loaded from the pre-rot bytes and stays authoritative
            if epoch is None and heal and manifest is not None:
                try:
                    rows = heal_block(outdir, manifest, fname,
                                      engine.shard, engine.graph,
                                      engine.dc, status=status,
                                      reason=reason)
                    report["healed"].append(fname)
                except (OSError, ValueError) as e:
                    report["errors"].append(f"{fname}: unhealable: {e}")
                    return report, (0, 0)
            else:
                report["errors"].append(f"{fname}: {status}: {reason}")
                return report, (0, 0)   # row offsets unknowable past it
        else:
            rows = maybe_decode_rows(rows)
        rows = np.ascontiguousarray(np.asarray(rows, np.int8))
        nrows = int(rows.shape[0])
        res = np.ascontiguousarray(
            _resident_rows(table, lo, lo + nrows))
        M_SCRUB_CHECKED.inc()
        report["checked"] += 1
        if zlib.crc32(rows.tobytes()) != zlib.crc32(res.tobytes()):
            M_SCRUB_CORRUPT.inc()
            dirty = True
            report["corrupt"].append(fname)
            log.error("scrub: resident rows of %s (shard %d%s) diverge "
                      "from verified disk rows — re-binding the table",
                      fname, engine.shard,
                      "" if epoch is None else f", epoch {epoch}")
            obs_recorder.emit("scrub_corrupt", wid=engine.wid,
                              shard=engine.shard, file=fname,
                              epoch=epoch,
                              codec=getattr(engine, "resident_codec",
                                            None))
        lo += nrows
    if dirty:
        report["rebound"] = _rebind(engine, epoch)
    return report, (0, 0)


def _rebind(engine, epoch: int | None) -> bool:
    """Republish a table from its verified disk truth — one atomic
    reference swap, exactly the publish discipline ``promote_index``
    uses, so in-flight batches keep their old reference and the epoch
    gate's ``(epoch, table)`` pair can never tear."""
    from ..models.cpd import epoch_index_dir
    from ..worker.engine import load_shard_rows

    if epoch is None:
        rows = load_shard_rows(engine.outdir, engine.shard,
                               dc=engine.dc, graph=engine.graph,
                               replica=engine.replica)
        engine.fm = engine._make_resident(rows)
        return True
    edir = epoch_index_dir(engine.outdir, epoch)
    rows = None
    try:
        # heal=False, no graph: promote_index's rule — an epoch index
        # must never be healed from the free-flow graph
        rows = load_shard_rows(edir, engine.shard, dc=engine.dc,
                               heal=False, replica=engine.replica)
    except (OSError, ValueError, FileNotFoundError) as e:
        log.error("scrub: epoch %d index for shard %d unreloadable "
                  "(%s); dropping the promotion — the base table is "
                  "the correct fallback", epoch, engine.shard, e)
    with engine._promote_lock:
        cur = engine._fm_promoted
        if cur is None or cur[0] != epoch:
            return False            # a newer promotion won the race
        if rows is not None and rows.shape[0] == cur[1].shape[0]:
            engine._fm_promoted = (epoch, engine._make_resident(rows))
        else:
            engine._fm_promoted = None
    return True


class TableScrubber:
    """Background resident-scrub loop over a set of live engines.

    ``engines_fn`` returns the engines to cover (called every pass, so
    engines built lazily by the dispatcher join the rotation as they
    appear). ``scrub_now(shard)`` — the control loop's divergence-
    quarantine hook — wakes the thread immediately and scrubs that
    shard unbudgeted before re-admission probes can pass.
    """

    def __init__(self, engines_fn, interval_s: float,
                 blocks_per_pass: int = 0, clock=time.monotonic):
        self.engines_fn = engines_fn
        self.interval_s = float(interval_s)
        self.blocks_per_pass = int(blocks_per_pass)
        self.clock = clock
        self._lock = OrderedLock("integrity.TableScrubber")
        self._cursors: dict = {}
        self._asap: set[int] = set()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.passes = 0
        self.last_report: list = []
        self.corrupt_blocks = 0
        self.healed_blocks = 0

    # ---------------------------------------------------------- control
    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="dos-scrub", daemon=True)
        self._thread.start()

    def stop(self, join_s: float = 10.0) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=join_s)
            self._thread = None

    def scrub_now(self, shard: int | None = None) -> None:
        """Schedule an immediate, unbudgeted scrub (of one shard, or
        everything) — safe from any thread; returns at once."""
        with self._lock:
            self._asap.add(-1 if shard is None else int(shard))
        self._wake.set()

    # ------------------------------------------------------------- pass
    def run_pass(self, shards: set | None = None,
                 budget: int | None = None) -> list[dict]:
        """One synchronous scrub pass (the thread's body; tests and
        ``scrub_now`` drills call it directly). Returns per-table
        reports."""
        t0 = time.perf_counter()
        budget = self.blocks_per_pass if budget is None else budget
        out = []
        for engine in list(self.engines_fn() or ()):
            if getattr(engine, "alg", None) != "table-search":
                continue
            if getattr(engine, "fm", None) is None:
                continue
            if shards is not None and engine.shard not in shards:
                continue
            out.extend(self._scrub_engine(engine, budget))
        with self._lock:
            self.passes += 1
            self.last_report = out
            self.corrupt_blocks += sum(len(r["corrupt"]) for r in out)
            self.healed_blocks += sum(len(r["healed"]) for r in out)
        M_SCRUB_PASSES.inc()
        M_SCRUB_SECONDS.observe(time.perf_counter() - t0)
        return out

    def _scrub_engine(self, engine, budget: int) -> list[dict]:
        from ..models.cpd import epoch_index_dir

        out = []
        tables = [(engine.outdir, engine.fm, None)]
        promoted = engine._fm_promoted      # one read: (epoch, table)
        if promoted is not None:
            tables.append((epoch_index_dir(engine.outdir, promoted[0]),
                           promoted[1], promoted[0]))
        for outdir, table, epoch in tables:
            if self._stop.is_set():
                break
            key = (id(engine), "base" if epoch is None else epoch)
            with self._lock:
                cursor = self._cursors.get(key, (0, 0))
            try:
                report, nxt = scrub_engine_table(
                    engine, outdir, table, epoch, budget=budget,
                    cursor=cursor)
            except Exception as e:  # noqa: BLE001 — the scrubber must
                # degrade, never take the serve down with it
                log.error("scrub: pass over shard %d failed: %s",
                          engine.shard, e)
                report, nxt = {"checked": 0, "corrupt": [],
                               "healed": [], "rebound": False,
                               "errors": [str(e)]}, (0, 0)
            report.update(shard=engine.shard, epoch=epoch)
            # a rebind replaced the table reference: restart the
            # cursor so the NEW table is verified from block 0
            with self._lock:
                self._cursors[key] = ((0, 0) if report["rebound"]
                                      else nxt)
            out.append(report)
        return out

    def _run(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(timeout=self.interval_s)
            self._wake.clear()
            if self._stop.is_set():
                return
            with self._lock:
                asap, self._asap = self._asap, set()
            if asap:
                # divergence-quarantine path: scrub the implicated
                # shards in full, budget ignored — re-admission waits
                # on this evidence
                self.run_pass(
                    shards=None if -1 in asap else asap, budget=0)
            else:
                self.run_pass()

    # ------------------------------------------------------------ status
    def statusz(self) -> dict:
        with self._lock:
            return {
                "interval_s": self.interval_s,
                "blocks_per_pass": self.blocks_per_pass,
                "passes": self.passes,
                "corrupt_blocks": self.corrupt_blocks,
                "healed_blocks": self.healed_blocks,
                "last": [
                    {k: r.get(k) for k in ("shard", "epoch", "checked",
                                           "corrupt", "healed",
                                           "rebound", "errors")}
                    for r in self.last_report],
            }
