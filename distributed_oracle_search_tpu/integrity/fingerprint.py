"""Answer fingerprints: crc32 over a reply's answer segments.

The fingerprint is computed ONCE where the answer is born (the worker,
right after the engine returns) and re-checked wherever the answer is
about to be trusted — the dispatcher after a wire hop, the serving
cache on every hit. The canonical byte layout (int64 cost ‖ int64 plen
‖ uint8 finished) is deliberately independent of transport: the FIFO
results file, the RPC reply frame, and the in-process dispatcher all
fingerprint the same bytes, so one mismatch counter means the same
thing on every lane.

A mismatch is a *data* fault, not an availability fault: verifiers
book ``answer_fp_mismatch_total`` and raise their transport's dispatch
error so the frontend's existing failover machinery retries the batch
on another candidate — a corrupted answer is never handed to a client.
"""

from __future__ import annotations

import zlib

import numpy as np

from ..obs import metrics as obs_metrics

class FingerprintError(ValueError):
    """An answer fingerprint failed verification — data corruption on
    the wire or in a cache, not an availability fault. Subclasses
    ``ValueError`` so pre-integrity decode-error handlers (the FIFO
    dispatcher's results-sidecar wrap) still fail the batch over to
    another candidate instead of crashing."""


M_FP_MISMATCH = obs_metrics.counter(
    "answer_fp_mismatch_total",
    "replies whose crc32 answer fingerprint failed verification at a "
    "dispatcher (DOS_ANSWER_FP) — the batch is retried on another "
    "candidate, never served")


def answer_fingerprint(cost, plen, finished) -> int:
    """crc32 over a batch's canonical answer bytes (int64 cost ‖ int64
    plen ‖ uint8 finished). Stable across transports and dtypes the
    callers actually hold (device arrays, lists, np arrays)."""
    h = zlib.crc32(np.ascontiguousarray(
        np.asarray(cost, np.int64)).tobytes())
    h = zlib.crc32(np.ascontiguousarray(
        np.asarray(plen, np.int64)).tobytes(), h)
    h = zlib.crc32(np.ascontiguousarray(
        np.asarray(finished).astype(np.uint8)).tobytes(), h)
    return h & 0xFFFFFFFF


def value_fingerprint(value) -> int:
    """Fingerprint of ONE query's cached answer tuple ``(cost, plen,
    finished)`` — what L1/L2 cache entries store and re-check on every
    hit (:mod:`serving.cache`)."""
    c, p, f = value
    return answer_fingerprint([int(c)], [int(p)], [bool(f)])
