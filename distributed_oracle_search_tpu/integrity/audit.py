"""Sampled dual-execution audit: re-run served batches, compare answers.

Fingerprints (:mod:`integrity.fingerprint`) catch corruption *in
flight* — between the engine and the client. They cannot catch an
engine that *computes* the wrong answer: a bitflipped resident row, a
wrong-regime promotion, a kernel miscompile. The audit plane closes
that hole by re-executing ``DOS_AUDIT_RATE`` per-mille of served
batches on an **independent lane** and comparing element-wise, OFF the
reply critical path — the client already has its answer; the audit
decides whether to believe the engine going forward.

Lane choice mirrors ``ops.pallas_walk.choose_walk_kernel``'s
``(choice, why)`` contract (:func:`choose_audit_lane`):

``replica``
    another candidate worker for the same shard — an independent
    resident copy on independent hardware. The strongest check against
    resident-row rot, and the default whenever the membership offers a
    second candidate.
``reference``
    the CPU oracle (:mod:`models.reference`) — an independent
    *algorithm*, immune to kernel bugs too, but O(M log N) per distinct
    target; only batches of at most ``DOS_AUDIT_MAX_REFERENCE`` queries
    take it.
``recompute``
    the same worker, re-dispatched with ``no_cache=True`` so the L2
    key differs and the kernel genuinely re-executes — the weakest
    lane (same resident table), but it still catches transient compute
    faults and cache rot, and it is always available.

Only deadline-free batches (``RuntimeConfig.time == 0``) are sampled:
a deadline-truncated walk legitimately differs between executions and
would drown the signal in false divergences.

A divergence books ``audit_divergence_total``, lands a structured
``audit_divergence`` flight-recorder event carrying the (shard, epoch,
lane, codec/kernel) fingerprint, and surfaces per-shard counts through
:meth:`AnswerAuditor.snapshot` — the control loop's ``DivergenceWatch``
arm reads that to force-open the shard's breaker, trigger a scrub-now,
and re-admit only after clean probes.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import time

import numpy as np

from ..obs import metrics as obs_metrics
from ..obs import recorder as obs_recorder
from ..utils.locks import OrderedLock
from ..utils.log import get_logger

log = get_logger(__name__)

M_AUDITED = obs_metrics.counter(
    "audit_batches_total",
    "served batches re-executed on an independent audit lane "
    "(DOS_AUDIT_RATE sampling)")
M_DIVERGENCE = obs_metrics.counter(
    "audit_divergence_total",
    "audited batches whose independent re-execution disagreed with the "
    "served answer — each lands an audit_divergence recorder event and "
    "feeds the control loop's DivergenceWatch arm")
M_AUDIT_DROPPED = obs_metrics.counter(
    "audit_dropped_total",
    "sampled batches dropped before auditing (queue full or auditor "
    "stopping) — the audit never blocks or backpressures serving")
M_AUDIT_SECONDS = obs_metrics.histogram(
    "audit_lane_seconds",
    "wall time of one audit re-execution + compare, by whichever lane "
    "choose_audit_lane picked")


def choose_audit_lane(candidates, via, nq: int, *,
                      have_reference: bool,
                      max_reference: int) -> tuple[str, str]:
    """Pick the audit lane for one sampled batch → ``(lane, why)``.

    Same shape as ``choose_walk_kernel``: the choice is a pure function
    of what is available, and the ``why`` string is human-readable
    policy provenance for the recorder event. Preference order is
    independence: ``replica`` (other resident copy) > ``reference``
    (other algorithm, small batches only) > ``recompute`` (same worker,
    uncached — always available).
    """
    others = [c for c in (candidates or ()) if c != via]
    if others:
        return "replica", (f"candidate {others[0]} offers an independent "
                           f"resident copy (served by {via})")
    if have_reference and 0 < nq <= max_reference:
        return "reference", (f"no second candidate; batch of {nq} fits "
                             f"the CPU oracle bound {max_reference}")
    return "recompute", ("no second candidate"
                         + ("" if have_reference else ", no reference fn")
                         + f"; batch of {nq} re-executes uncached on {via}")


def make_reference_fn(graph, *, max_fm_cache: int = 1024,
                      max_w_cache: int = 4):
    """Build the CPU-oracle lane: ``fn(queries, config, diff) -> (cost,
    plen, finished)`` int64/int64/bool arrays.

    CPDs are built FREE-FLOW and the congestion diff applies at query
    time (reference semantics, ``models.reference``), so the first-move
    columns are computed once per distinct target on free-flow weights
    and cached (bounded — each column is N int8), while the cost
    accumulates on ``graph.weights_with_diff(diff)`` (also cached per
    diff path, small: the serving plane cycles through few fusions).
    """
    from ..models.reference import first_move_to_target, table_search_walk

    fm_cache: dict[int, np.ndarray] = {}
    w_cache: dict[str, np.ndarray] = {}
    lock = OrderedLock("integrity.reference_fn")

    def _fm_col(t: int) -> np.ndarray:
        with lock:
            col = fm_cache.get(t)
        if col is None:
            col = first_move_to_target(graph, t)
            with lock:
                if len(fm_cache) >= max_fm_cache:
                    fm_cache.clear()
                fm_cache[t] = col
        return col

    def _w_query(diff) -> np.ndarray:
        key = diff if isinstance(diff, str) else "-"
        with lock:
            w = w_cache.get(key)
        if w is None:
            w = (graph.w if key == "-" or not key
                 else graph.weights_with_diff(key))
            with lock:
                if len(w_cache) >= max_w_cache:
                    w_cache.clear()
                w_cache[key] = w
        return w

    def reference(queries, config, diff):
        q = np.asarray(queries, np.int64).reshape(-1, 2)
        w = _w_query(diff)
        k_moves = int(getattr(config, "k_moves", -1) or -1)
        cost = np.zeros(len(q), np.int64)
        plen = np.zeros(len(q), np.int64)
        fin = np.zeros(len(q), bool)
        for i, (s, t) in enumerate(q):
            col = _fm_col(int(t))
            c, p, f, _path = table_search_walk(
                graph, lambda x, _t, col=col: col[x], int(s), int(t),
                w_query=w, k_moves=k_moves)
            cost[i], plen[i], fin[i] = c, p, f
        return cost, plen, fin

    return reference


class AnswerAuditor:
    """Samples served batches and re-executes them off the reply path.

    ``maybe_submit`` is the only call on the serving path: a
    deterministic per-mille accumulator (no RNG — ``DOS_AUDIT_RATE=10``
    audits EXACTLY every 100th eligible batch, so tests and drills are
    reproducible) plus a non-blocking put into a bounded queue. A full
    queue drops the sample (``audit_dropped_total``) — the audit plane
    must never backpressure serving.

    One daemon worker thread drains the queue, picks a lane
    (:func:`choose_audit_lane`), re-executes, compares element-wise,
    and on divergence books the counter, emits the recorder event, and
    bumps the per-shard tally that :meth:`snapshot` exposes to the
    control loop's ``DivergenceWatch``.
    """

    def __init__(self, dispatcher, rate_pm: int, *, reference_fn=None,
                 describe_fn=None, max_reference: int = 64,
                 queue_max: int = 64, clock=time.monotonic):
        self._dispatcher = dispatcher
        self.rate_pm = max(0, min(1000, int(rate_pm)))
        self._reference_fn = reference_fn
        self._describe_fn = describe_fn
        self.max_reference = int(max_reference)
        self._clock = clock
        self._q: queue.Queue = queue.Queue(maxsize=max(1, int(queue_max)))
        self._lock = OrderedLock("integrity.AnswerAuditor")
        self._acc = 0                # per-mille accumulator
        self._divergent: dict[int, int] = {}   # wid -> cumulative count
        self.audited = 0
        self.dropped = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        if self.rate_pm > 0:
            self._thread = threading.Thread(
                target=self._run, name="dos-audit", daemon=True)
            self._thread.start()

    # ------------------------------------------------------- serving path
    def maybe_submit(self, wid: int, via, candidates, queries, config,
                     diff, cost, plen, fin) -> bool:
        """Sample this served batch for audit; returns True if queued.

        Called AFTER the reply is on its way — nothing here can delay
        or fail the client's answer. Deadline-bounded batches
        (``config.time != 0``) are never sampled (legitimately
        nondeterministic under truncation).
        """
        if self.rate_pm <= 0 or self._stop.is_set():
            return False
        if getattr(config, "time", 0):
            return False
        with self._lock:
            self._acc += self.rate_pm
            if self._acc < 1000:
                return False
            self._acc -= 1000
        job = (int(wid), via, tuple(candidates or ()),
               np.array(queries, np.int64, copy=True), config, diff,
               np.asarray(cost).copy(), np.asarray(plen).copy(),
               np.asarray(fin).copy())
        try:
            self._q.put_nowait(job)
            return True
        except queue.Full:
            M_AUDIT_DROPPED.inc()
            with self._lock:
                self.dropped += 1
            return False

    # -------------------------------------------------------- audit lane
    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                job = self._q.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                self._audit(*job)
            except Exception as e:  # never kill the audit thread
                log.error("audit lane failed (batch dropped): %s", e)
                M_AUDIT_DROPPED.inc()
                with self._lock:
                    self.dropped += 1

    def _audit(self, wid, via, candidates, queries, config, diff,
               cost, plen, fin) -> None:
        lane, why = choose_audit_lane(
            candidates, via, len(queries),
            have_reference=self._reference_fn is not None,
            max_reference=self.max_reference)
        t0 = self._clock()
        if lane == "reference":
            c2, p2, f2 = self._reference_fn(queries, config, diff)
        else:
            lane_via = (next(c for c in candidates if c != via)
                        if lane == "replica" else via)
            # no_cache=True is part of the worker's L2 cache key, so the
            # audit can never be served the cached (possibly corrupt)
            # answer echoed back — the kernel genuinely re-executes
            rconf = dataclasses.replace(config, no_cache=True)
            c2, p2, f2 = self._dispatcher.answer_batch(
                wid, queries, rconf, diff, via=lane_via)
        M_AUDIT_SECONDS.observe(self._clock() - t0)
        M_AUDITED.inc()
        with self._lock:
            self.audited += 1
        bad = ((np.asarray(cost, np.int64)
                != np.asarray(c2, np.int64))
               | (np.asarray(plen, np.int64)
                  != np.asarray(p2, np.int64))
               | (np.asarray(fin, bool) != np.asarray(f2, bool)))
        n_bad = int(np.count_nonzero(bad))
        if not n_bad:
            return
        M_DIVERGENCE.inc()
        with self._lock:
            self._divergent[wid] = self._divergent.get(wid, 0) + 1
        fields = dict(wid=wid, via=str(via), lane=lane, why=why,
                      nq=int(len(queries)), mismatches=n_bad,
                      epoch=int(getattr(config, "epoch", -1) or -1),
                      diff_epoch=int(getattr(config, "diff_epoch", -1)
                                     or -1))
        if self._describe_fn is not None:
            try:
                fields.update(self._describe_fn(wid, via) or {})
            except Exception as e:
                log.debug("audit describe_fn failed: %s", e)
        obs_recorder.emit("audit_divergence", **fields)
        log.error("AUDIT DIVERGENCE shard %s: %d/%d answers differ on "
                  "the %s lane (%s)", wid, n_bad, len(queries), lane, why)

    # ---------------------------------------------------------- plumbing
    def snapshot(self) -> dict[int, int]:
        """Per-shard CUMULATIVE divergence counts — the control loop's
        ``SignalReader`` integrity provider polls this and DivergenceWatch
        acts on deltas."""
        with self._lock:
            return dict(self._divergent)

    def statusz(self) -> dict:
        with self._lock:
            return {
                "rate_pm": self.rate_pm,
                "max_reference": self.max_reference,
                "audited": self.audited,
                "dropped": self.dropped,
                "queued": self._q.qsize(),
                "divergent": {str(k): v
                              for k, v in sorted(self._divergent.items())},
            }

    def stop(self, join_s: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=join_s)
