"""Answer-integrity plane: is the oracle still telling the truth?

PRs 4/5/8/14 made the *disk* path verifiable end to end — crc32
manifests, heal-on-load, replica anti-entropy, codec-aware adoption —
but a shard that loaded clean is then resident in device/host memory
for days, and nothing ever re-checked it: a bitflip in the resident
rows, a wrong-regime promotion, or a rotted cache entry serves a wrong
answer silently and forever. At fleet scale silent data corruption is
an operational fact, not a tail risk; this package is the defense in
depth:

:mod:`integrity.scrub`
    A low-priority background pass (``DOS_SCRUB_INTERVAL_S``, default
    off) re-reads each resident shard's block files through the same
    digest-verified load path the engine booted from, decodes them
    (pack4/RLE via ``models.resident``), and crc32-compares the dense
    rows against what is actually resident — base table AND any
    epoch-promoted index. Disk-side rot heals through the shared
    ``heal_block`` quarantine path; resident-side rot triggers an
    atomic table rebind that never drops an in-flight batch.

:mod:`integrity.audit`
    A sampled dual-execution audit (``DOS_AUDIT_RATE`` per-mille):
    served batches re-execute on an independent lane — a replica
    engine, an uncached re-execution, or the CPU reference oracle for
    small batches, chosen by :func:`integrity.audit.choose_audit_lane`
    (mirroring ``ops.pallas_walk.choose_walk_kernel``'s (choice, why)
    contract) — and compare element-wise OFF the reply critical path.
    A divergence books ``audit_divergence_total``, lands a structured
    ``audit_divergence`` flight-recorder event, and feeds the control
    loop's ``DivergenceWatch`` arm: breaker force-open, scrub-now,
    probed re-admission.

:mod:`integrity.fingerprint`
    Optional crc32 answer fingerprints (``DOS_ANSWER_FP``): replies
    carry a checksum over their answer segments (RuntimeConfig wire
    extension, unknown-key tolerant) verified at the dispatcher, and
    serving-cache entries re-check their stored fingerprint on every
    hit — a corrupted entry is dropped and recomputed, never served.

Every knob defaults off: with none set, no thread starts, no metric
family appears, and behavior is byte-identical legacy.
"""

from .config import IntegrityConfig

__all__ = ["IntegrityConfig"]
