"""Integrity-plane knobs (``DOS_SCRUB_*`` / ``DOS_AUDIT_*`` /
``DOS_ANSWER_FP``), one frozen dataclass.

Same policy home as :class:`control.config.ControlConfig`: every knob
is read through :mod:`utils.env` (malformed values degrade to
defaults, logged), ``validate()`` raises on impossible combinations,
and consumers only ever see an immutable snapshot. Every default is
OFF — an unconfigured process builds nothing and behaves byte-
identically to pre-integrity code."""

from __future__ import annotations

import dataclasses

from ..utils.env import env_cast, env_flag
from ..utils.log import get_logger

log = get_logger(__name__)


@dataclasses.dataclass(frozen=True)
class IntegrityConfig:
    """Answer-integrity plane configuration.

    ``scrub_interval_s == 0`` disables the scrubber thread entirely;
    ``audit_rate == 0`` disables the audit sampler; ``answer_fp=False``
    keeps replies and cache entries fingerprint-free."""

    #: DOS_SCRUB_INTERVAL_S — seconds between resident-scrub passes
    #: (0 = scrubber off; the background thread is never started)
    scrub_interval_s: float = 0.0
    #: DOS_SCRUB_BLOCKS_PER_PASS — max blocks checked per engine per
    #: pass (0 = the whole shard each pass); a bounded pass resumes at
    #: a cursor so big shards scrub incrementally at low priority
    scrub_blocks_per_pass: int = 0
    #: DOS_AUDIT_RATE — per-mille of served batches re-executed on an
    #: independent lane (0 = audit off, 1000 = every batch)
    audit_rate: int = 0
    #: DOS_AUDIT_MAX_REFERENCE — largest batch the CPU reference lane
    #: will take (the per-query heap oracle is O(M log N) per distinct
    #: target; bigger batches audit on a replica lane instead)
    audit_max_reference: int = 64
    #: DOS_ANSWER_FP — replies carry a crc32 answer fingerprint
    #: (verified at the dispatcher) and cache entries re-check theirs
    #: on every hit
    answer_fp: bool = False

    @property
    def any_enabled(self) -> bool:
        return (self.scrub_interval_s > 0 or self.audit_rate > 0
                or self.answer_fp)

    @classmethod
    def from_env(cls) -> "IntegrityConfig":
        cfg = cls(
            scrub_interval_s=env_cast("DOS_SCRUB_INTERVAL_S", 0.0,
                                      float),
            scrub_blocks_per_pass=env_cast(
                "DOS_SCRUB_BLOCKS_PER_PASS", 0, int),
            audit_rate=env_cast("DOS_AUDIT_RATE", 0, int),
            audit_max_reference=env_cast(
                "DOS_AUDIT_MAX_REFERENCE", 64, int),
            answer_fp=env_flag("DOS_ANSWER_FP", False),
        )
        try:
            cfg.validate()
        except ValueError as e:
            log.warning("integrity config invalid (%s); disabling the "
                        "integrity plane", e)
            cfg = cls()
        return cfg

    def validate(self) -> None:
        if self.scrub_interval_s < 0:
            raise ValueError("scrub_interval_s must be >= 0")
        if self.scrub_blocks_per_pass < 0:
            raise ValueError("scrub_blocks_per_pass must be >= 0")
        if not (0 <= self.audit_rate <= 1000):
            raise ValueError("audit_rate must be in [0, 1000] "
                             "(per-mille)")
        if self.audit_max_reference < 0:
            raise ValueError("audit_max_reference must be >= 0")
