"""CPU reference oracle (pure NumPy + heapq).

Role parity: the reference designates the C++ warthog library as its compute
engine — Dijkstra sweeps for CPD construction and ``table-search`` first-move
walks for queries (SURVEY.md §C5; the submodule is absent from the snapshot,
contracts reconstructed from call sites). This module is the framework's
**correctness oracle**: a small, obviously-correct implementation used to
generate golden answers for the TPU backend's tests, and as the semantic spec
for tie-breaking.

Not a performance path. The native C++ oracle (``native/``) accelerates the
same contracts for larger graphs; the TPU backend (``ops/``) is the
production path.

Conventions shared with the TPU backend (must stay in lock-step):

* Distances are int32; unreachable = ``INF`` (``data.graph.INF``).
* A **first move** is an *out-edge slot index* in the graph's padded ELL
  layout (``Graph.ell("out")``), not a neighbor id: slots are ordered by
  ascending edge id, ties on path cost break toward the smallest slot.
  ``-1`` = no move (node is the target, or the target is unreachable).
"""

from __future__ import annotations

import heapq

import numpy as np

from ..data.graph import Graph, INF


def dijkstra(graph: Graph, source: int, w: np.ndarray | None = None,
             reverse: bool = False) -> np.ndarray:
    """Single-source shortest-path distances (int64 [N]).

    ``reverse=True`` runs on the transposed graph, i.e. returns the distance
    *from every node to* ``source`` along directed edges — the sweep the CPD
    build does once per owned target (reference ``README.md:95``: one sweep
    per owned node, all threads).
    """
    w = graph.w if w is None else np.asarray(w)
    dist = np.full(graph.n, int(INF), np.int64)
    dist[source] = 0
    pq = [(0, source)]
    edges = graph.in_edges if reverse else graph.out_edges
    while pq:
        d, u = heapq.heappop(pq)
        if d > dist[u]:
            continue
        nbrs, eids = edges(u)
        for v, e in zip(nbrs, eids):
            nd = d + int(w[e])
            if nd < dist[v]:
                dist[v] = nd
                heapq.heappush(pq, (nd, v))
    return dist


def dist_to_target(graph: Graph, target: int,
                   w: np.ndarray | None = None) -> np.ndarray:
    """d(x → target) for all x."""
    return dijkstra(graph, target, w, reverse=True)


def first_move_to_target(graph: Graph, target: int,
                         w: np.ndarray | None = None,
                         dist: np.ndarray | None = None) -> np.ndarray:
    """First-move column: int8 [N] of out-edge **slot** toward ``target``.

    ``fm[x]`` is the slot k (in ``Graph.ell("out")``) minimizing
    ``w[eid[x,k]] + d(nbr[x,k] → target)``; ties break to the smallest k.
    ``fm[target] = -1`` and ``fm[x] = -1`` when target is unreachable from x.
    """
    w = graph.w if w is None else np.asarray(w)
    if dist is None:
        dist = dist_to_target(graph, target, w)
    nbr, eid = graph.ell("out")
    if nbr.shape[1] > 127:
        raise ValueError(
            f"max out-degree {nbr.shape[1]} exceeds the int8 first-move slot "
            "range; road graphs should be far below this")
    w_pad = np.concatenate([np.asarray(w, np.int64), [int(INF)]])
    # [N, K] candidate costs through each slot
    cand = w_pad[eid] + dist[nbr]
    np.minimum(cand, int(INF), out=cand)
    best = cand.min(axis=1)
    fm = np.argmax(cand == best[:, None], axis=1).astype(np.int8)  # first min slot
    fm[best >= int(INF)] = -1
    fm[target] = -1
    return fm


def first_move_matrix(graph: Graph, targets: np.ndarray,
                      w: np.ndarray | None = None) -> np.ndarray:
    """int8 [len(targets), N] first-move table — one column per target.

    Toy-scale only (O(T · M log N)); this is what a worker's CPD shard
    contains, rows indexed by *owned index* of the target.
    """
    return np.stack([first_move_to_target(graph, int(t), w) for t in targets])


def table_search_walk(graph: Graph, fm_of, s: int, t: int,
                      w_query: np.ndarray | None = None,
                      k_moves: int = -1):
    """Reference ``table-search``: iterated first-move lookup from ``s``
    toward ``t``, accumulating cost on the (possibly congestion-perturbed)
    query-time weights ``w_query`` while following the free-flow first moves
    (reference behavior: CPDs are built free-flow, ``fifo_auto`` applies the
    diff at query time — ``make_fifos.py:18,21`` vs ``make_cpds.py:20``).

    ``fm_of(x, t) -> slot`` abstracts where the first-move table lives.
    ``k_moves`` bounds the number of extracted moves (-1 = unbounded,
    reference ``args.py:31-36``).

    Returns ``(cost, plen, finished, path)``.
    """
    w_query = graph.w if w_query is None else np.asarray(w_query)
    nbr, eid = graph.ell("out")
    x = int(s)
    cost = 0
    path = [x]
    steps = 0
    limit = graph.n if k_moves < 0 else k_moves
    while x != t and steps < limit:
        slot = int(fm_of(x, t))
        if slot < 0:
            break
        cost += int(w_query[eid[x, slot]])
        x = int(nbr[x, slot])
        path.append(x)
        steps += 1
    finished = x == t
    return cost, steps, finished, path
