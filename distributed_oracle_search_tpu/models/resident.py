"""Compressed-resident CPD shards: RLE/pack4 rows in device memory.

The paper's worker answers s–t queries by first-move lookups into a
*resident* CPD shard, so per-worker graph scale is capped by how many
raw ``[R, N]`` int8 rows fit in device memory — and R-way replication
(PR 5) multiplies that cost R×. The stream path already proved the
compression ratio on this exact data (``models.streamed``: 1.6 GB raw →
~31 MB wire via RLE/pack4 sidecars); this module makes the RESIDENT
representation compressed and decompresses only at the point of use
(ROADMAP item 1, the last numbered perf item).

Two codecs, selected by ``DOS_CPD_RESIDENT`` (via ``utils.env``;
default ``raw`` = byte-identical legacy behavior):

``pack4``
    Two first-move slots per byte: slots 0..13 pack directly into a
    nibble, 0xF is the ``-1`` "no move" marker (the wire format's
    nibble vocabulary, ``models.streamed`` ``PACK4_ESCAPE``/
    ``PACK4_MARKER``). Unlike the wire format there is NO escape list —
    a resident row must be addressable without a scatter pass — so the
    codec applies only when every entry is < 14 (max out-degree ≤ 14,
    which covers road networks; a hub-heavy graph degrades to ``rle``
    or ``raw``). Fixed 2× ratio, trivially row-addressable: the Pallas
    walk kernel stages the PACKED row through its double-buffered DMA
    tile and unpacks on-chip (``ops.pallas_walk`` ``packed4``) — raw
    rows never exist in HBM at all.

``rle``
    Run-length over the TARGET axis — the same coherence the wire
    format exploits (nearby target rows are reached the same way from
    almost every source; measured mean column-run length 14-34 on road
    chunks). Rows are split into **row groups** of ``group`` rows
    (``DOS_CPD_RLE_GROUP``, default 4096): within a group, each source
    column's runs break at the column and group boundaries, so a run
    start fits uint16 and every run is addressable through the
    per-(group, column) **offsets index** — ``offsets[g * N + s]``
    bounds the run range of one cell, which is what makes an arbitrary
    bucket's rows addressable without decoding the whole shard. Layout
    (flat, no per-cell padding): ``vals`` int8 [T] run first-moves,
    ``starts`` uint16 [T] in-group start rows, ``offsets`` int32
    [n_groups * N + 1] — ~3 bytes per run, measured 4-8× over raw on
    road-shaped tables. Decompression is a bounded binary search per
    (row, source) over the cell's runs (``log2(max cell runs)`` static
    steps) — the "gather over run-starts via searchsorted" XLA path
    that serves BOTH walk kernels, the mesh lanes, and the
    chunked-deadline path.

``auto``
    The smaller viable codec (ties prefer ``rle``); neither viable —
    an incompressible table — degrades to ``raw`` with
    ``cpd_resident_degraded_total`` booked, never a fault.

The same encodings persist on disk: :func:`encode_block` wraps a
block's encoded arrays in a self-describing uint8 container written
through the ordinary atomic ``.npy`` machinery, so digests, ledgers,
quarantine/heal, replica copies, and adopter catch-up all work
unchanged — and a catch-up/anti-entropy copy of a compressed block
ships the compressed bytes. Manifest v2 ``blocks{...}`` entries gain a
``codec`` field (unknown-key tolerant, gate-only-on-NEWER per the wire
contract); the container itself is self-describing, so a manifest-less
partial index still decodes.
"""

from __future__ import annotations

import functools
import json

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import metrics as obs_metrics
from ..utils.env import env_cast, env_str
from ..utils.log import get_logger

log = get_logger(__name__)

#: accepted DOS_CPD_RESIDENT spellings; anything else degrades to raw
RESIDENT_CODECS = ("raw", "pack4", "rle", "auto")

#: nibble vocabulary shared with the streamed wire format
#: (``models.streamed.PACK4_ESCAPE``/``PACK4_MARKER`` — duplicated here
#: because streamed imports models.cpd which imports this module):
#: slots 0..13 pack directly, 15 is the -1 marker. The resident codec
#: has no 0xE escape list — it requires every slot < 14 instead.
PACK4_ESCAPE = 14
PACK4_MARKER = 15

#: rle is viable only when it actually wins: resident bytes must come
#: in under this fraction of the raw table (the wire format's
#: break-even discipline, ``models.streamed.RLE_MAX_FRAC``)
RESIDENT_RLE_MAX_FRAC = 0.9

#: default rows per rle row group; run starts are uint16 so the group
#: is capped at 65536 rows, and smaller groups mean shorter cell
#: searches at slightly more run breakage
_RLE_GROUP_DEFAULT = 4096

M_RESIDENT_BYTES = obs_metrics.gauge(
    "cpd_resident_bytes",
    "device bytes of the most recently materialized resident first-move"
    " table after codec selection (raw bytes when the codec degraded)")
M_RESIDENT_DEGRADED = obs_metrics.counter(
    "cpd_resident_degraded_total",
    "resident tables whose requested DOS_CPD_RESIDENT codec was not "
    "viable (escape slots for pack4, incompressible runs for rle) and "
    "were served raw instead — a degrade, never a fault")
M_DECOMPRESS = obs_metrics.histogram(
    "cpd_decompress_seconds",
    "per-batch decompress-at-use of a compressed-resident shard's "
    "target rows (pack4 nibble unpack / rle run-start search) before "
    "the walk kernel runs")


def resident_choice() -> str:
    """The raw ``DOS_CPD_RESIDENT`` knob: ``raw`` / ``pack4`` / ``rle``
    / ``auto``; malformed values degrade to ``raw`` with a log line
    (the shared ``utils.env`` policy)."""
    raw = (env_str("DOS_CPD_RESIDENT", "raw") or "raw").strip().lower()
    if raw not in RESIDENT_CODECS:
        log.warning("ignoring malformed DOS_CPD_RESIDENT=%r (using "
                    "'raw'; valid: %s)", raw, "/".join(RESIDENT_CODECS))
        return "raw"
    return raw


def rle_group_rows() -> int:
    """``DOS_CPD_RLE_GROUP``: rows per rle row group, clamped to
    [2, 65536] (run starts are uint16)."""
    g = env_cast("DOS_CPD_RLE_GROUP", _RLE_GROUP_DEFAULT, int)
    if g < 2 or g > 65536:
        log.warning("DOS_CPD_RLE_GROUP=%d out of [2, 65536]; using %d",
                    g, _RLE_GROUP_DEFAULT)
        g = _RLE_GROUP_DEFAULT
    return g


# -------------------------------------------------------------- encoders

def encode_pack4(fm: np.ndarray) -> np.ndarray | None:
    """[R, N] int8 fm -> [R, ceil(N/2)] uint8 nibble pairs, or None
    when any entry >= 14 (the wire format escapes those; the resident
    codec refuses instead — rows must decode without a scatter)."""
    fm = np.asarray(fm, np.int8)
    if fm.ndim != 2 or fm.size == 0:
        return None
    if int(fm.max(initial=-1)) >= PACK4_ESCAPE:
        return None
    a = np.where(fm < 0, np.uint8(PACK4_MARKER), fm.astype(np.uint8))
    if a.shape[1] % 2:
        a = np.concatenate(
            [a, np.full((a.shape[0], 1), np.uint8(PACK4_MARKER))],
            axis=1)
    return np.ascontiguousarray(a[:, 0::2] | (a[:, 1::2] << 4))


def encode_rle(fm: np.ndarray, group: int | None = None):
    """[R, N] int8 fm -> ``(starts u16 [T], vals i8 [T],
    offsets i32 [n_groups * N + 1], group)`` in (group, column)-major
    run order, or None when the encoding would not beat
    ``RESIDENT_RLE_MAX_FRAC`` of the raw bytes (incompressible table —
    the caller degrades)."""
    fm = np.asarray(fm, np.int8)
    if fm.ndim != 2 or fm.shape[0] < 2 or fm.shape[1] == 0:
        return None
    r, n = fm.shape
    group = rle_group_rows() if group is None else int(group)
    group = min(group, 65536)
    n_groups = -(-r // group)
    # cheap reject BEFORE the per-group transposes (same trick as the
    # wire encoder): the row-to-row change count bounds the run count
    # from below, so an over-budget table pays one compare pass
    runs_min = int(np.count_nonzero(fm[1:] != fm[:-1])) + n
    if 3 * runs_min >= RESIDENT_RLE_MAX_FRAC * fm.nbytes:
        return None
    starts_l, vals_l, counts_l = [], [], []
    for gi in range(n_groups):
        a = np.ascontiguousarray(fm[gi * group:(gi + 1) * group].T)
        gg = a.shape[1]                                     # [N, gg]
        ch = np.empty((n, gg), bool)
        ch[:, 0] = True
        ch[:, 1:] = a[:, 1:] != a[:, :-1]
        idx = np.flatnonzero(ch.reshape(-1))
        starts_l.append((idx % gg).astype(np.uint16))
        vals_l.append(a.reshape(-1)[idx])
        counts_l.append(np.bincount(idx // gg,
                                    minlength=n).astype(np.int64))
    starts = np.concatenate(starts_l)
    vals = np.concatenate(vals_l)
    offsets64 = np.zeros(n_groups * n + 1, np.int64)
    np.cumsum(np.concatenate(counts_l), out=offsets64[1:])
    if offsets64[-1] >= 2**31:
        return None                       # int32 offsets would wrap
    offsets = offsets64.astype(np.int32)
    nbytes = starts.nbytes + vals.nbytes + offsets.nbytes
    if nbytes >= RESIDENT_RLE_MAX_FRAC * fm.nbytes:
        return None
    return starts, vals, offsets, group


# ------------------------------------------------------ device decoders

@functools.partial(jax.jit, static_argnames=("n",))
def _decode_pack4_rows(packed: jnp.ndarray, rows: jnp.ndarray, n: int):
    """Gather + nibble-unpack the named rows: [C] row ids ->
    [C, N] int8 fm (15 -> -1). Pad/negative row ids clamp to row 0 —
    their lanes are valid=False and never read."""
    r = packed.shape[0]
    rows = jnp.clip(rows.astype(jnp.int32), 0, r - 1)
    pk = packed[rows].astype(jnp.int32)                  # [C, W2]
    cols = jnp.arange(n, dtype=jnp.int32)
    byte = jnp.take(pk, cols // 2, axis=1)               # [C, N]
    v = (byte >> ((cols % 2) * 4)) & 0xF
    return jnp.where(v == PACK4_MARKER, jnp.int8(-1),
                     v.astype(jnp.int8))


@functools.partial(jax.jit,
                   static_argnames=("n", "group", "steps", "r"))
def _decode_rle_rows(starts: jnp.ndarray, vals: jnp.ndarray,
                     offsets: jnp.ndarray, rows: jnp.ndarray, n: int,
                     group: int, steps: int, r: int):
    """Run-start search decode: [C] row ids -> [C, N] int8 fm.

    For row ``row`` and source ``s`` the answer is the value of the run
    covering in-group position ``row % group`` within cell
    ``(row // group, s)`` — a branchless binary search over the cell's
    run range (``offsets`` bounds it; ``steps`` = static
    ``ceil(log2(max cell runs))``). Every cell holds >= 1 run whose
    start is 0, so the invariant ``starts[lo] <= j`` holds from the
    first step."""
    rows = jnp.clip(rows.astype(jnp.int32), 0, r - 1)
    g = rows // group                                    # [C]
    j = (rows % group)[:, None].astype(jnp.int32)        # [C, 1]
    cell = g[:, None] * n + jnp.arange(n, dtype=jnp.int32)[None, :]
    lo = offsets[cell]                                   # [C, N]
    hi = offsets[cell + 1]
    st32 = starts.astype(jnp.int32)

    # branch-free bisection:
    #   starts[mid] <= j  -> answer in [mid, hi)
    #   otherwise         -> answer in [lo, mid)
    def step(_, lohi):
        lo, hi = lohi
        narrow = hi - lo > 1
        mid = (lo + hi) // 2
        right = (st32[mid] <= j) & narrow
        lo = jnp.where(right, mid, lo)
        hi = jnp.where(narrow & ~right, mid, hi)
        return lo, hi

    lo, _ = jax.lax.fori_loop(0, max(steps, 1), step, (lo, hi))
    return vals[lo]


class CompressedFM:
    """A compressed-resident first-move shard: the codec, the logical
    ``(R, N)`` shape, and the device-resident encoded arrays.

    Quacks enough like the raw ``[R, N]`` table for the engine's shape
    checks (``shape``, ``nbytes``); :meth:`decompress_rows` inflates an
    arbitrary row set to a dense ``[C, N]`` int8 block — the
    decompress-at-point-of-use call every serving path funnels
    through."""

    def __init__(self, codec: str, shape: tuple[int, int],
                 arrays: dict, group: int = 0, steps: int = 0):
        self.codec = codec
        self.shape = tuple(shape)
        self.arrays = arrays
        self.group = int(group)
        self.steps = int(steps)

    @property
    def nbytes(self) -> int:
        return int(sum(int(a.nbytes) for a in self.arrays.values()))

    @property
    def packed(self):
        """The pack4 nibble array — what the Pallas kernel's
        decompress-on-tile loader stages directly from HBM."""
        return self.arrays["packed"]

    def decompress_rows(self, rows) -> jnp.ndarray:
        """Inflate the named rows to a dense [C, N] int8 block (device;
        bit-identical to the raw table's ``fm[rows]``)."""
        if self.codec == "pack4":
            return _decode_pack4_rows(self.arrays["packed"], rows,
                                      n=self.shape[1])
        return _decode_rle_rows(
            self.arrays["starts"], self.arrays["vals"],
            self.arrays["offsets"], rows, n=self.shape[1],
            group=self.group, steps=self.steps, r=self.shape[0])


def _rle_steps(offsets: np.ndarray) -> int:
    """Static binary-search depth: ceil(log2(max runs per cell))."""
    cnt = int(np.max(np.diff(np.asarray(offsets, np.int64)),
                     initial=1))
    return max(int(max(cnt - 1, 1)).bit_length(), 1)


def make_resident(rows: np.ndarray, codec: str | None = None,
                  place=None):
    """Materialize one shard's resident first-move table under the
    ``DOS_CPD_RESIDENT`` policy (an explicit ``codec`` wins).

    Returns ``(table, codec_used)`` — ``table`` is the placed raw
    ``jnp`` array for ``raw``, a :class:`CompressedFM` otherwise.
    ``place`` maps a host array onto the caller's device layout (the
    engine's replica-lane / mesh-replicated placement); default is a
    plain ``jnp.asarray``. A requested codec that is not viable
    DEGRADES to raw and books ``cpd_resident_degraded_total`` — the
    fit-degrade is a counter, never a fault."""
    if place is None:
        place = jnp.asarray
    req = resident_choice() if codec is None else str(codec)
    if req not in RESIDENT_CODECS:
        raise ValueError(f"unknown resident codec {req!r}")
    rows = np.asarray(rows, np.int8)
    if req == "raw":
        out = place(rows)
        M_RESIDENT_BYTES.set(int(out.nbytes))
        return out, "raw"
    rle = encode_rle(rows) if req in ("rle", "auto") else None
    p4 = encode_pack4(rows) if req in ("pack4", "auto") else None
    if rle is not None and p4 is not None:
        # auto: the smaller wins, ties prefer rle (it keeps shrinking
        # with run coherence; pack4 is a fixed 2x)
        rle_bytes = sum(int(a.nbytes) for a in rle[:3])
        if rle_bytes > p4.nbytes:
            rle = None
        else:
            p4 = None
    if rle is not None:
        starts, vals, offsets, group = rle
        fm = CompressedFM(
            "rle", rows.shape,
            {"starts": place(starts), "vals": place(vals),
             "offsets": place(offsets)},
            group=group, steps=_rle_steps(offsets))
    elif p4 is not None:
        fm = CompressedFM("pack4", rows.shape, {"packed": place(p4)})
    else:
        log.warning("DOS_CPD_RESIDENT=%s not viable for this %dx%d "
                    "shard (escape slots / incompressible runs); "
                    "serving raw", req, *rows.shape)
        M_RESIDENT_DEGRADED.inc()
        out = place(rows)
        M_RESIDENT_BYTES.set(int(out.nbytes))
        return out, "raw"
    M_RESIDENT_BYTES.set(fm.nbytes)
    log.info("resident %s: %dx%d fm %.1f MB -> %.1f MB (%.1fx)",
             fm.codec, rows.shape[0], rows.shape[1],
             rows.nbytes / 2**20, fm.nbytes / 2**20,
             rows.nbytes / max(fm.nbytes, 1))
    return fm, fm.codec


# --------------------------------------------------- on-disk containers
#
# A compressed block file is an ordinary .npy holding a self-describing
# 1-D uint8 container: magic + json header + the encoded arrays' raw
# bytes. Riding .npy keeps EVERY existing durability path unchanged —
# atomic writers, crc32 digests, ledger journaling, quarantine/heal,
# replica copies, adopter catch-up — and those copies now move the
# compressed bytes (the smaller anti-entropy/catch-up payloads the
# membership plane wants). Raw blocks are 2-D int8, containers 1-D
# uint8 with a magic prefix: the two can never be confused.

BLOCK_MAGIC = b"DOSCPDC1"


def is_container(arr) -> bool:
    """Is this loaded block array a compressed container (vs raw
    2-D int8 fm rows)?"""
    try:
        return (arr.ndim == 1 and arr.dtype == np.uint8
                and arr.shape[0] > len(BLOCK_MAGIC) + 4
                and bytes(np.asarray(arr[:len(BLOCK_MAGIC)]))
                == BLOCK_MAGIC)
    except (AttributeError, TypeError):
        return False


def _container_header(arr) -> tuple[dict, int]:
    """Parse a container's json header; returns (header, body offset).
    Raises ValueError on a torn/foreign payload. Reads ONLY the magic +
    header slice — callers hand in mmap'd block files on the verify
    path, and converting the whole array would materialize the block
    just to read a few hundred bytes."""
    if not is_container(arr):
        raise ValueError("not a compressed CPD block container")
    hl_off = len(BLOCK_MAGIC)
    hlen = int.from_bytes(
        bytes(np.asarray(arr[hl_off:hl_off + 4], np.uint8)), "little")
    body = hl_off + 4 + hlen
    if hlen <= 0 or body > arr.shape[0]:
        raise ValueError("compressed block header length out of range")
    try:
        header = json.loads(bytes(
            np.asarray(arr[hl_off + 4:body], np.uint8)).decode())
    except (UnicodeDecodeError, ValueError) as e:
        raise ValueError(f"compressed block header unparsable: {e}")
    return header, body


def block_codec(arr) -> str | None:
    """Codec recorded in a container block (None for raw blocks).
    Header-slice read only — safe to call on an mmap'd block."""
    if not is_container(arr):
        return None
    header, _ = _container_header(arr)
    return str(header.get("codec"))


def encode_block(rows: np.ndarray, codec: str | None):
    """Encode one block's raw rows for persistence. Returns
    ``(payload uint8 [nbytes], codec_used)`` or None when the block
    should be written raw (codec None/raw, or not viable for these
    rows — each block degrades independently, the manifest records
    what happened)."""
    if codec in (None, "raw"):
        return None
    rows = np.asarray(rows, np.int8)
    header: dict = {"codec": None, "shape": list(rows.shape)}
    rle = encode_rle(rows) if codec in ("rle", "auto") else None
    p4 = encode_pack4(rows) if codec in ("pack4", "auto") else None
    if rle is not None and p4 is not None:
        # auto: the smaller wins, ties prefer rle — the SAME rule as
        # make_resident's, so on-disk auto blocks persist the codec the
        # resident policy would pick for the same rows
        if sum(int(a.nbytes) for a in rle[:3]) > p4.nbytes:
            rle = None
        else:
            p4 = None
    arrays: list[tuple[str, np.ndarray]] = []
    if rle is not None:
        starts, vals, offsets, group = rle
        header.update(codec="rle", group=group)
        arrays = [("starts", starts), ("vals", vals),
                  ("offsets", offsets)]
    elif p4 is not None:
        header["codec"] = "pack4"
        arrays = [("packed", p4)]
    else:
        return None
    header["arrays"] = [[name, str(a.dtype), list(a.shape)]
                        for name, a in arrays]
    hb = json.dumps(header).encode()
    payload = b"".join([BLOCK_MAGIC, len(hb).to_bytes(4, "little"), hb]
                       + [np.ascontiguousarray(a).tobytes()
                          for _, a in arrays])
    return np.frombuffer(payload, np.uint8).copy(), header["codec"]


def decode_block_rows(arr) -> np.ndarray:
    """Container payload -> the raw [C, N] int8 rows it encodes
    (host-side; bit-identical to what was encoded). Raises ValueError
    on a torn/foreign payload — callers treat that as a corrupt
    block."""
    header, off = _container_header(arr)
    got: dict[str, np.ndarray] = {}
    for name, dtype, shape in header.get("arrays", []):
        size = int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
        if off + size > arr.shape[0]:
            raise ValueError(f"compressed block truncated at {name!r}")
        got[name] = np.frombuffer(
            bytes(np.asarray(arr[off:off + size], np.uint8)),
            dtype).reshape(shape)
        off += size
    r, n = (int(x) for x in header["shape"])
    codec = header.get("codec")
    if codec == "pack4":
        packed = got["packed"]
        lo = (packed & 0xF).astype(np.int8)
        hi = ((packed >> 4) & 0xF).astype(np.int8)
        v = np.stack([lo, hi], axis=-1).reshape(r, -1)[:, :n]
        return np.where(v == PACK4_MARKER, np.int8(-1), v)
    if codec != "rle":
        raise ValueError(f"unknown compressed block codec {codec!r}")
    starts = got["starts"].astype(np.int64)
    vals, offsets = got["vals"], got["offsets"].astype(np.int64)
    group = int(header["group"])
    n_groups = -(-r // group)
    out = np.empty((r, n), np.int8)
    for gi in range(n_groups):
        gg = min(group, r - gi * group)
        o0, o1 = int(offsets[gi * n]), int(offsets[(gi + 1) * n])
        st = starts[o0:o1]
        ends = np.empty(o1 - o0, np.int64)
        ends[:-1] = st[1:]
        ends[-1] = gg
        # the last run of each CELL ends at the group height, not at
        # the next cell's (restarted) first start
        cell_last = offsets[gi * n + 1:(gi + 1) * n + 1] - 1 - o0
        ends[cell_last] = gg
        col = np.repeat(vals[o0:o1], ends - st)       # [N * gg]
        out[gi * group:gi * group + gg] = col.reshape(n, gg).T
    return out


def maybe_decode_rows(arr) -> np.ndarray:
    """Raw rows pass through; container payloads decode. The one call
    every consumer that needs dense rows makes after loading a block."""
    a = np.asarray(arr)
    if is_container(a):
        return decode_block_rows(a)
    return a
