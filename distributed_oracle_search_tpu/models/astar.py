"""Weighted A* point-to-point search with full priority-queue telemetry.

The A*-family the reference's knobs imply (``--h-scale --f-scale``,
reference ``args.py:30-57``; counter vocabulary ``n_expanded / n_inserted /
n_touched / n_updated / n_surplus`` from the response schema,
``process_query.py:198-213``). Semantics are shared with the native engine
(``native/src/search.hpp``) and cross-checked by tests:

* heuristic: euclidean distance × the graph's minimum cost-per-coordinate-
  unit (a lower bound over edges, so admissible), scaled by ``hscale`` —
  ``hscale ≤ 1`` keeps optimality, ``hscale > 1`` trades it for speed;
* ``fscale > 0`` additionally prunes nodes whose f exceeds
  ``(1 + fscale) ×`` the best-known goal cost;
* counters: ``n_expanded`` = nodes popped and relaxed, ``n_inserted`` =
  pushes, ``n_touched`` = edge relaxations attempted, ``n_updated`` =
  decrease-key events, ``n_surplus`` = stale pops discarded.

This is the CPU correctness oracle for the family; the resident serve path
remains table-search (reference ``make_fifos.py:20``), with A* available
from the native server via ``--alg astar`` and from the Python worker
engine via ``RuntimeConfig`` when wired by the caller.
"""

from __future__ import annotations

import dataclasses
import heapq
import math

import numpy as np

from ..data.graph import Graph, INF


@dataclasses.dataclass
class AstarStats:
    n_expanded: int = 0
    n_inserted: int = 0
    n_touched: int = 0
    n_updated: int = 0
    n_surplus: int = 0
    plen: int = 0
    finished: int = 0

    def __iadd__(self, o: "AstarStats") -> "AstarStats":
        for f in dataclasses.fields(self):
            setattr(self, f.name, getattr(self, f.name) + getattr(o, f.name))
        return self


def min_cost_per_unit(graph: Graph, w: np.ndarray | None = None) -> float:
    """Lower bound of edge-cost per coordinate distance (heuristic scale).

    Parity: ``native/src/search.hpp min_cost_per_unit``.
    """
    w = graph.w if w is None else np.asarray(w)
    dx = graph.xs[graph.src] - graph.xs[graph.dst]
    dy = graph.ys[graph.src] - graph.ys[graph.dst]
    length = np.sqrt(dx * dx + dy * dy)
    mask = length > 0
    if not mask.any():
        return 0.0
    return float((w[mask] / length[mask]).min())


def astar(graph: Graph, s: int, t: int, w: np.ndarray | None = None,
          hscale: float = 1.0, fscale: float = 0.0,
          cpu: float | None = None,
          stats: AstarStats | None = None):
    """Weighted A* from ``s`` to ``t``. Returns ``(cost, plen, finished)``.

    ``cpu`` = precomputed :func:`min_cost_per_unit` (recomputed if None).
    ``stats`` accumulates telemetry in place when provided.
    """
    w = graph.w if w is None else np.asarray(w)
    if cpu is None:
        cpu = min_cost_per_unit(graph, w)
    st = stats if stats is not None else AstarStats()
    xs, ys = graph.xs, graph.ys

    def h(x: int) -> int:
        return int(math.hypot(float(xs[x] - xs[t]), float(ys[x] - ys[t]))
                   * cpu * hscale)

    gcost = np.full(graph.n, int(INF), np.int64)
    parent_edge = np.full(graph.n, -1, np.int64)
    gcost[s] = 0
    open_pq = [(h(s), s)]
    st.n_inserted += 1
    goal_cost = int(INF)
    while open_pq:
        f, u = heapq.heappop(open_pq)
        if f > gcost[u] + h(u):
            st.n_surplus += 1
            continue
        if u == t:
            goal_cost = int(gcost[u])
            break
        # fscale prune against the incumbent: gcost[t] is live as soon as
        # any relaxation reaches t, before t is ever popped
        if fscale > 0 and gcost[t] < int(INF) \
                and f > (1.0 + fscale) * int(gcost[t]):
            st.n_surplus += 1
            continue
        st.n_expanded += 1
        nbrs, eids = graph.out_edges(u)
        for v, e in zip(nbrs, eids):
            st.n_touched += 1
            ng = int(gcost[u]) + int(w[e])
            if ng < gcost[v]:
                if gcost[v] < int(INF):
                    st.n_updated += 1
                gcost[v] = ng
                parent_edge[v] = e
                heapq.heappush(open_pq, (ng + h(v), int(v)))
                st.n_inserted += 1

    finished = goal_cost < int(INF)
    plen = 0
    if finished:
        x = t
        while x != s:
            plen += 1
            x = int(graph.src[parent_edge[x]])
    st.plen += plen
    st.finished += 1 if finished else 0
    return (goal_cost if finished else 0), plen, finished
