"""Streamed CPD serving: answer campaigns whose index exceeds HBM.

The resident :class:`~..models.cpd.CPDOracle` holds the whole ``[W, R, N]``
first-move tensor on the mesh — perfect until ``N^2 / W`` outgrows HBM
(~16 GB on v5e: a 264k-node graph is a 70 GB single-shard table; the
reference-scale regime of BASELINE.md configs[4-5]). The reference never
faces this because its run-length-compressed CPD lives in host RAM and is
pointer-chased per query (reference ``make_fifos.py:21``, SURVEY.md §C5);
the TPU answer is **streaming**: keep the index on disk (the per-block
``.npy`` checkpoint files ARE the serving format), and per batch upload
only the fm rows the batch actually targets, in bounded row-chunks.

A random scenario of Q queries touches ≤ Q distinct target rows — usually
far fewer than R — and each uploaded ``[C, N]`` chunk answers every query
aimed at those rows in one device walk. Row-chunks are ordered
block-contiguously so the host-side gather reads each mmapped block file
sequentially. Chunk size and padded query counts are compile-stable
(powers of two), so a resident server reuses a handful of programs.

This is deliberately a single-device serving mode: multi-chip scale-out
uses the resident sharded oracle (sharding IS the memory plan); streaming
is the fallback when one chip must serve an index bigger than its HBM,
and the two share the same walk kernel and wire semantics.

Cold chunks upload 4-bit packed — half the bytes over the uplink, the
cold path's bottleneck — with a one-pass device unpack per chunk. High
ELL slots (≥ 14, hub-node rarities) ride a tiny per-chunk exception
list scattered after the unpack, so packing is degree-independent.
Uploaded row-chunks are kept on device in a bounded LRU
(``cache_bytes``):
campaigns whose targets overlap earlier ones — the resident-server usage
pattern, one request round per diff (reference ``process_query.py:178``) —
skip the upload entirely and run at near-resident speed. Range chunks key
on their row range; compacted chunks are content-addressed by row-id
digest (an identical chunk — a replayed campaign — hits). Keys are
independent of the query-time weights: a diff round re-uses every chunk
the free-flow round uploaded, because fm rows hold free-flow FIRST MOVES
while diffs only change the cost accumulation (``ops.table_search``
semantics).
"""

from __future__ import annotations

import functools
import hashlib
import json
import os

import jax
import jax.numpy as jnp
import numpy as np

from ..data.graph import Graph
from ..ops import DeviceGraph
from ..ops.table_search import (
    extract_paths, table_search_batch, table_search_multi,
)
from ..parallel.partition import DistributionController
from .cpd import length_estimate, shard_block_name, validate_manifest
from ..utils.env import env_cast, env_flag
from ..utils.log import get_logger

log = get_logger(__name__)


def _pow2(x: int) -> int:
    return 1 << max(x - 1, 0).bit_length()


#: 4-bit packed uploads: slots 0..13 pack directly into a nibble,
#: 0xF is the -1 "no move" marker, and 0xE escapes to a per-chunk
#: exception list (row, col, true slot) scattered on device after the
#: nibble unpack — so packing works for ANY degree, at half the wire
#: bytes plus ~6 bytes per exceptional entry. Entries with slot >= 14
#: exist only at hub nodes whose shortest path leaves by a high ELL
#: slot (measured <0.5% of entries on the 264k road graph), so the
#: escape traffic is noise. DOS_STREAM_PACK4=0 disables. Packing is
#: skipped only when exceptions stop being rare (the break-even where
#: escape bytes eat the nibble savings).
PACK4_ESCAPE = 14
PACK4_MARKER = 15
#: skip packing when more than this fraction of a chunk's entries
#: escape. Break-even arithmetic: the nibble saves 0.5 bytes/entry;
#: one exception costs 7 bytes (uint16 row + int32 col + int8 val),
#: up to ~14 with the pow2 padding — 0.5 / 14 ≈ 3.5%, rounded down
#: (real road graphs measure ~0.1%)
PACK4_MAX_ESCAPE_FRAC = 0.03


@functools.partial(jax.jit, static_argnames=("n",))
def _unpack4(packed: jnp.ndarray, n: int, exc_r: jnp.ndarray,
             exc_c: jnp.ndarray, exc_v: jnp.ndarray) -> jnp.ndarray:
    """[C, ceil(N/2)] uint8 nibbles -> [C, N] int8 fm.

    0xF -> -1; 0xE entries are overwritten by the scattered exception
    triples. Pad triples are ``(0, 0, fm[0, 0])`` identity writes —
    they re-write position (0, 0)'s true value, so the scatter is
    idempotent whether or not (0, 0) itself escapes."""
    lo = packed & 0xF
    hi = (packed >> 4) & 0xF
    c = packed.shape[0]
    v = jnp.stack([lo, hi], axis=-1).reshape(c, -1)[:, :n]
    v = v.astype(jnp.int8)
    v = jnp.where(v == PACK4_MARKER, jnp.int8(-1), v)
    return v.at[exc_r, exc_c].set(exc_v)


def _pack4(fm_np: np.ndarray):
    """[C, N] int8 fm -> (packed nibbles, exc_rows, exc_cols, exc_vals)
    or None when too many entries escape (degenerate packing)."""
    if fm_np.shape[0] > 65536:
        # escape rows are uint16; a taller chunk would silently wrap
        # the scatter indices and corrupt unpacked moves — fall back
        return None
    esc_r, esc_c = np.nonzero(fm_np >= PACK4_ESCAPE)
    if len(esc_r) > PACK4_MAX_ESCAPE_FRAC * fm_np.size:
        return None
    a = fm_np.astype(np.uint8)
    a = np.where(fm_np < 0, np.uint8(PACK4_MARKER),
                 np.minimum(a, PACK4_ESCAPE))
    if a.shape[1] % 2:
        a = np.concatenate(
            [a, np.full((a.shape[0], 1), np.uint8(PACK4_MARKER))],
            axis=1)
    packed = a[:, 0::2] | (a[:, 1::2] << 4)
    exc_v = fm_np[esc_r, esc_c]
    # pad the exception list to a power of two so one compiled unpack
    # program serves many chunks; pads are (0, 0, fm[0, 0]) identity
    # writes (see _unpack4). uint16 rows: the chunk axis is bounded by
    # row_chunk << 65536; cols span N and need int32.
    cap = 1 << max(int(len(esc_r)) - 1, 0).bit_length()
    cap = max(cap, 1)
    er = np.zeros(cap, np.uint16)
    ec = np.zeros(cap, np.int32)
    ev = np.full(cap, fm_np[0, 0], np.int8)
    er[:len(esc_r)] = esc_r
    ec[:len(esc_r)] = esc_c
    ev[:len(esc_r)] = exc_v
    return packed, er, ec, ev


#: Transposed run-length wire coding. The reference's whole compression
#: premise is that CPD tables are run-heavy (its RLE rows measure 50-100x
#: on road networks, ``native/src/cpd.hpp``) — but OUR rows run along the
#: wrong axis for that: a ``[C, N]`` chunk's row is "first move toward
#: one target FROM every source", and adjacent sources' ELL slot numbers
#: are uncorrelated (measured mean run length 1.5-2.5). The coherence
#: lives on the TARGET axis: nearby targets (owned rows are
#: block-contiguous, RCM/grid ordered) are reached the same way from
#: almost every source — measured 93-97% of entries equal the entry one
#: target-row up, mean column-run length 14-34. So the wire format RLE's
#: the TRANSPOSED chunk: per source column, runs of consecutive target
#: rows sharing a first move.
#:
#: Wire layout (flat, no per-column padding — run counts are skewed and
#: padding to the max would eat the win): ``lens`` uint8 run lengths in
#: column-major order (runs > 255 split), ``vals`` int8 run first-moves,
#: ``counts`` int32 runs per column — ~2 bytes per run + 4 per column.
#: Device decode is one scatter-add of value DELTAS at global run starts
#: into a [N*C] zeros buffer, a cumsum (deltas telescope: any contiguous
#: partial sum is val_b - val_a, bounded +-255, so int16 accumulation is
#: exact), an int8 cast, and a transpose — O(N*C) streaming work, no
#: searchsorted over the output. DOS_STREAM_RLE=0 disables; chunks fall
#: back per-chunk to pack4/raw when runs are too short to pay
#: (RLE_MAX_FRAC of the best dense alternative).
#:
#: The encoding is PERSISTED: the host-side encode is a few full passes
#: over the raw chunk (~8 s for a 419 MB chunk — it would dominate the
#: cold round it exists to speed up), so the first miss writes the wire
#: triple as an ``rle-*.npz`` sidecar next to the block files,
#: fingerprinted against the source blocks' (size, mtime). Later cold
#: rounds read the ~30 MB sidecar instead of the 1.7 GB raw rows — disk
#: traffic shrinks by the same factor as the wire. This mirrors the
#: reference, whose CPD files are THEMSELVES stored run-length
#: compressed and loaded compressed at server start (reference
#: README.md CPD description). DOS_STREAM_RLE_SIDECAR=0 disables
#: persistence (encode-on-the-fly each time); sidecar writes are
#: best-effort (read-only index dirs just skip them).
RLE_MAX_FRAC = 0.9


def _pack_rle(fm_np: np.ndarray, pack4_viable: bool):
    """[C, N] int8 fm -> (lens u8 [T], vals i8 [T], counts i32 [N]) in
    TRANSPOSED (column-major, target-axis-runs) order, or None when the
    encoding would not beat the best dense upload (pack4 when viable,
    else raw)."""
    c, n = fm_np.shape
    if c < 2 or n == 0:
        return None
    dense = fm_np.size // 2 if pack4_viable else fm_np.size
    # cheap reject BEFORE the transposed copy: the total run count is
    # countable straight off the row-major array (runs only grow after
    # the 255-splits, so an over-budget count here is final) — an
    # incompressible chunk then costs one compare pass, not three
    # full-size passes plus a 400 MB transpose
    runs_min = int(np.count_nonzero(fm_np[1:] != fm_np[:-1])) + n
    if 2 * (1 << max(runs_min - 1, 0).bit_length()) + 4 * n >= \
            RLE_MAX_FRAC * dense:
        return None
    a = np.ascontiguousarray(fm_np.T)                    # [N, C]
    ch = np.empty((n, c), bool)
    ch[:, 0] = True
    ch[:, 1:] = a[:, 1:] != a[:, :-1]
    idx = np.flatnonzero(ch.reshape(-1))                 # run starts
    # exact budget after the 255-splits; each run costs 2 wire bytes
    # (+ the fixed 4/column); the dense alternative is n*c/2 (pack4)
    # or n*c (raw)
    lengths = np.diff(idx, append=n * c)
    pieces = -(-lengths // 255)                          # uint8 splits
    tot = int(pieces.sum())
    cap = 1 << max(tot - 1, 0).bit_length()
    wire = 2 * cap + 4 * n
    if wire >= RLE_MAX_FRAC * dense:
        return None
    flat_vals = a.reshape(-1)[idx]
    plen = np.full(cap, 0, np.uint8)
    pval = np.full(cap, flat_vals[-1] if len(flat_vals) else 0, np.int8)
    # split runs longer than 255 into 255-length pieces + remainder;
    # continuation pieces repeat the run's value (delta 0 on device)
    last = np.cumsum(pieces) - 1
    pl = np.full(tot, 255, np.uint8)
    pl[last] = (lengths - 255 * (pieces - 1)).astype(np.uint8)
    plen[:tot] = pl
    pval[:tot] = np.repeat(flat_vals, pieces)
    counts = np.bincount(np.repeat(idx // c, pieces),
                         minlength=n).astype(np.int32)
    return plen, pval, counts


@functools.partial(jax.jit, static_argnames=("c",))
def _unpack_rle(plen: jnp.ndarray, vals: jnp.ndarray,
                counts: jnp.ndarray, c: int) -> jnp.ndarray:
    """Transposed-RLE wire triple -> [C, N] int8 fm.

    Pad runs (length 0, value = last real value) decode to delta 0 at an
    out-of-range start and are dropped by the scatter."""
    n = counts.shape[0]
    t = plen.shape[0]
    pl = plen.astype(jnp.int32)
    s = jnp.cumsum(pl) - pl                              # exclusive
    coff = jnp.cumsum(counts) - counts                   # exclusive
    col = jnp.searchsorted(coff, jnp.arange(t), side="right") - 1
    g_start = col * c + s - s[coff[col]]
    v16 = vals.astype(jnp.int16)
    delta = v16 - jnp.concatenate([jnp.zeros(1, jnp.int16), v16[:-1]])
    out = jnp.zeros(n * c, jnp.int16).at[g_start].add(delta, mode="drop")
    return jnp.cumsum(out).astype(jnp.int8).reshape(n, c).T


def default_cache_bytes() -> int:
    """Device-residency budget for cached fm row-chunks: a quarter of
    the device's reported memory (4 GB on a 16 GB v5e — enough to hold a
    whole 102k-node worker shard, 1.3 GB, with room to spare, while
    never crowding out the walk state), falling back to 1 GB when the
    backend reports no limit. Streaming exists for indexes bigger than
    HBM, so the cache must scale DOWN with the chip, not assume one."""
    try:
        stats = jax.devices()[0].memory_stats() or {}
        limit = int(stats.get("bytes_limit", 0))
        if limit > 0:
            return limit // 4
    except Exception as e:  # noqa: BLE001 — backends without
        # memory_stats fall back to the conservative default
        log.debug("memory_stats unavailable (%s); stream cache "
                  "defaults to 1 GiB", e)
    return 1 << 30


class StreamedCPDOracle:
    """Serve table-search queries from an on-disk CPD index, streaming
    only the rows each batch needs.

    Parameters
    ----------
    graph      : the (free-flow) road graph
    controller : partition controller — must match the built index
    outdir     : CPD index directory (``index.json`` + block files)
    row_chunk  : fm rows resident per upload; the device-memory knob.
                 Working set ≈ ``row_chunk * N`` bytes of int8 fm plus the
                 walk state — e.g. 4096 rows x 264k nodes ≈ 1.1 GB.
    cache_bytes: device bytes of uploaded fm chunks kept in an LRU
                 across :meth:`query` calls (0 disables; None — the
                 default — resolves via :func:`default_cache_bytes`,
                 a quarter of the device's memory). Campaigns with
                 overlapping targets — including every diff round
                 after the first — skip the re-upload.
    """

    def __init__(self, graph: Graph, controller: DistributionController,
                 outdir: str, row_chunk: int = 4096,
                 cache_bytes: int | None = None):
        self.graph = graph
        self.dc = controller
        self.outdir = outdir
        self.row_chunk = int(row_chunk)
        self.cache_bytes = (default_cache_bytes() if cache_bytes is None
                            else int(cache_bytes))
        self.dg = DeviceGraph.from_graph(graph)
        with open(os.path.join(outdir, "index.json")) as f:
            manifest = json.load(f)
        validate_manifest(manifest, controller, outdir)
        self._blocks: dict[tuple[int, int], np.ndarray] = {}
        # bounded LRU of DECODED compressed blocks (see _block);
        # insertion order is the recency order
        self._decoded: dict[tuple[int, int], np.ndarray] = {}
        # LRU of device-resident [C, N] chunks, key (wid, r0); insertion
        # order IS the recency order (moved-to-end on hit)
        self._chunk_cache: dict[tuple[int, int], jnp.ndarray] = {}
        #: 4-bit packed uploads — HALF the uplink bytes on cold chunks
        #: (device unpacks once per upload; the cache holds the unpacked
        #: chunk, so warm rounds are unchanged). High slots ride a tiny
        #: exception list, so this is degree-independent; a chunk whose
        #: escape fraction is degenerate falls back to raw per-chunk.
        self.pack4 = env_flag("DOS_STREAM_PACK4", True)
        #: transposed target-axis RLE — the cold path's big lever
        #: (~7-17x fewer wire bytes measured on road/city chunks vs the
        #: raw fm, vs pack4's fixed 2x); falls back per-chunk via
        #: :func:`_pack_rle`'s break-even check
        self.rle = env_flag("DOS_STREAM_RLE", True)
        #: persist encodings as npz sidecars in the index dir (see the
        #: module-level RLE notes); the first cold round pays the encode,
        #: every later one streams straight off the compressed sidecar
        self.rle_sidecar = (self.rle
                            and env_flag("DOS_STREAM_RLE_SIDECAR", True))
        #: telemetry of the most recent :meth:`query` call
        self.last_stats: dict = {}

    def clear_cache(self) -> None:
        """Drop every device-resident cached chunk (frees device memory;
        the next campaign re-streams from disk)."""
        self._chunk_cache.clear()

    def _cache_get(self, key):
        hit = self._chunk_cache.pop(key, None)
        if hit is not None:
            self._chunk_cache[key] = hit          # refresh recency
        return hit

    def _cache_put(self, key, fm_d: jnp.ndarray) -> None:
        if self.cache_bytes <= 0 or fm_d.nbytes > self.cache_bytes:
            return
        held = sum(v.nbytes for v in self._chunk_cache.values())
        while self._chunk_cache and held + fm_d.nbytes > self.cache_bytes:
            old = self._chunk_cache.pop(
                next(iter(self._chunk_cache)))    # evict least-recent
            held -= old.nbytes
        self._chunk_cache[key] = fm_d

    def _chunk_fingerprint(self, pairs) -> np.ndarray:
        """Stat fingerprint of the block files a chunk reads from:
        ``[bytes, mtime_ns]`` per (wid, bid) pair, ordered. A rebuilt
        index changes it, invalidating any persisted sidecar."""
        out = []
        for wid, bid in pairs:
            st = os.stat(os.path.join(self.outdir,
                                      shard_block_name(wid, bid)))
            out.append((st.st_size, st.st_mtime_ns))
        return np.asarray(out, np.int64)

    def _sidecar_load(self, path: str, fp: np.ndarray):
        """RLE wire triple from a sidecar; ``"fallback"`` when a valid
        sidecar records that this chunk measured incompressible (so the
        multi-pass encode attempt is not re-paid every cold round);
        None when absent / stale / unreadable."""
        try:
            with np.load(path) as z:
                if (z["fp"].shape == fp.shape
                        and (z["fp"] == fp).all()):
                    if "fallback" in z:
                        return "fallback"
                    return z["lens"], z["vals"], z["counts"]
        except Exception as e:  # noqa: BLE001 — corrupt zip, missing
            # keys, IO: any failure means "re-encode", never raise
            log.debug("RLE sidecar %s unusable (%s); re-encoding",
                      path, e)
        return None

    def _sidecar_save(self, path: str, fp: np.ndarray, enc) -> None:
        """Best-effort atomic persist (tmp + rename); read-only index
        dirs and races just skip. ``enc=None`` persists a negative
        marker (chunk measured incompressible)."""
        tmp = f"{path}.{os.getpid()}.tmp.npz"       # savez keeps .npz
        try:
            if enc is None:
                np.savez(tmp, fp=fp, fallback=np.int8(1))
            else:
                np.savez(tmp, fp=fp, lens=enc[0], vals=enc[1],
                         counts=enc[2])
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)     # don't leak tmp files on a full disk
            except OSError:
                pass

    #: decoded compressed blocks kept host-side at once. The streamed
    #: oracle's whole contract is a bounded working set (row_chunk * N
    #: plus handles) — caching every decoded block would silently
    #: re-materialize the raw table exactly when compression matters
    #: most. Chunks read block-contiguously, so a tiny LRU keeps the
    #: within-chunk locality and a swept campaign stays bounded.
    _DECODED_KEEP = 4

    def _block(self, wid: int, bid: int) -> np.ndarray:
        """Memory-mapped block file (cached handle, not cached data).

        Compressed-container blocks (``models.resident``) decode on
        touch — the streamed row reads need dense rows — but the
        DECODED copies live in a small LRU (``_DECODED_KEEP``), not
        the unbounded handle cache: raw mmap handles cost pages, a
        decoded block costs its full dense bytes. The mmap's
        page-cache-speed contiguous reads apply to raw blocks only."""
        from .resident import is_container, maybe_decode_rows

        key = (wid, bid)
        hit = self._decoded.pop(key, None)
        if hit is not None:
            self._decoded[key] = hit          # refresh recency
            return hit
        if key not in self._blocks:
            self._blocks[key] = np.load(
                os.path.join(self.outdir, shard_block_name(wid, bid)),
                mmap_mode="r")
        arr = self._blocks[key]
        if is_container(arr):
            arr = maybe_decode_rows(arr)
            self._decoded[key] = arr
            while len(self._decoded) > self._DECODED_KEEP:
                self._decoded.pop(next(iter(self._decoded)))
        return arr

    def _row_range(self, wid: int, r0: int, count: int) -> np.ndarray:
        """Contiguous owned-row slice [count, N] (tail-padded with stuck
        rows past the worker's last row). Contiguous mmap reads stream at
        disk/page-cache speed — measured 7 GB/s vs 0.2 GB/s for
        row-by-row fancy indexing on the same file — which is why the
        dense serving mode uploads ranges instead of compacted row sets.
        """
        bs = self.dc.block_size
        n_owned = self.dc.n_owned(wid)
        hi = min(r0 + count, n_owned)
        parts = []
        r = r0
        while r < hi:
            bid = r // bs
            stop = min(hi, (bid + 1) * bs)
            parts.append(self._block(wid, bid)[r - bid * bs:
                                               stop - bid * bs])
            r = stop
        if len(parts) == 1 and hi - r0 == count:
            return parts[0]           # zero-copy view of the mmap
        out = np.full((count, self.graph.n), -1, np.int8)
        if parts:
            seg = parts[0] if len(parts) == 1 else np.concatenate(parts)
            out[:hi - r0] = seg
        return out

    def _gather_rows(self, wids: np.ndarray, rows: np.ndarray) -> np.ndarray:
        """Host-side gather of fm rows (wid, owned-row) -> [C, N] int8."""
        bs = self.dc.block_size
        out = np.empty((len(rows), self.graph.n), np.int8)
        bids = rows // bs
        # group by (wid, bid) so each mmapped file is fancy-indexed once
        order = np.lexsort((rows, bids, wids))
        i = 0
        while i < len(order):
            j = i
            wid, bid = wids[order[i]], bids[order[i]]
            while (j < len(order) and wids[order[j]] == wid
                   and bids[order[j]] == bid):
                j += 1
            sel = order[i:j]
            out[sel] = self._block(int(wid), int(bid))[rows[sel] - bid * bs]
            i = j
        return out

    def query(self, queries: np.ndarray, w_query: np.ndarray | None = None,
              k_moves: int = -1, max_steps: int = 0):
        """Answer (s, t) queries in input order: ``(cost, plen, finished)``.

        Matches the resident oracle's :meth:`~.CPDOracle.query` semantics
        exactly (tests pin this); only the memory plan differs.
        """
        w_pad = (self.dg.w_pad if w_query is None
                 else jnp.asarray(self.graph.padded_weights(w_query),
                                  jnp.int32))
        return self._campaign(queries, w_pad, None, k_moves, max_steps)

    def query_paths(self, queries: np.ndarray, k: int):
        """Materialize each query's first ``k`` path nodes from the
        streamed index (the reference's ``--k-moves`` extraction,
        reference ``args.py:31-36``) — per-chunk :func:`extract_paths`
        on the uploaded fm rows, which are already device-resident for
        the walk, so extraction costs one extra scan per chunk and no
        extra bytes. Returns ``(nodes int64 [Q, k+1], moves int64 [Q])``
        with the resident :meth:`~.CPDOracle.query_paths` semantics.
        """
        if k <= 0:
            raise ValueError("k must be positive")
        return self._campaign(queries, self.dg.w_pad, None, -1, 0,
                              paths_k=k)

    def query_multi(self, queries: np.ndarray,
                    w_diffs: list[np.ndarray | None], max_steps: int = 0):
        """Answer queries under D congestion diffs in ONE streamed pass.

        The fused analog of :meth:`~.CPDOracle.query_multi` for the
        streamed memory plan: each uploaded chunk is walked once and
        every diff's costs accumulate together — and with the device
        LRU, a fused D-round campaign after a free-flow round both
        streams zero bytes AND walks once. Returns ``(cost [D, Q],
        plen [Q], finished [Q])`` in input order.
        """
        if not w_diffs:
            raise ValueError("w_diffs must name at least one round")
        w_pads = jnp.asarray(self.graph.padded_weights_multi(w_diffs))
        return self._campaign(queries, None, w_pads, -1, max_steps)

    def _campaign(self, queries, w_pad, w_pads_multi, k_moves, max_steps,
                  paths_k: int = 0):
        """Shared streamed-campaign driver; ``w_pads_multi`` non-None
        selects the fused multi-diff kernel (cost rows per diff);
        ``paths_k`` > 0 selects path-prefix extraction instead of the
        cost walk (returns ``(nodes, moves)``)."""
        queries = np.asarray(queries, np.int64)
        nq = len(queries)
        s_all, t_all = queries[:, 0], queries[:, 1]
        n_multi = (0 if w_pads_multi is None
                   else int(w_pads_multi.shape[0]))

        # distinct targets, ordered block-contiguously for the host gather
        uniq_t, inv = np.unique(t_all, return_inverse=True)
        u_wid = self.dc.worker_of(uniq_t)
        u_row = self.dc.owned_index_of(uniq_t)
        c = self.row_chunk

        # ---- chunking mode. Dense campaigns upload CONTIGUOUS row
        # ranges straight off the mmap — zero host row copies (measured
        # 7 GB/s vs 0.2 GB/s for fancy-index row gathers). Sparse
        # campaigns compact the distinct rows instead — fewer uploaded
        # bytes. Break-even: range wins when density >
        # copy_bw / (copy_bw + uplink_bw) — ~0.45 with the measured
        # 185 MB/s host row-copy vs 257 MB/s uplink here; a fast PCIe
        # link pushes it even lower. DOS_STREAM_RANGE_DENSITY overrides.
        thresh = env_cast("DOS_STREAM_RANGE_DENSITY", 0.45, float)
        n_range = max(-(-max(self.dc.max_owned, 1) // c), 1)
        rkey = u_wid.astype(np.int64) * n_range + u_row // c
        uniq_key = np.unique(rkey)
        density = (len(uniq_t) / (len(uniq_key) * c)
                   if len(uniq_key) else 1.0)
        range_mode = density >= thresh

        if range_mode:
            chunk_of_uniq = np.searchsorted(uniq_key, rkey)
            r0_of_chunk = (uniq_key % n_range) * c
            wid_of_chunk = uniq_key // n_range
            q_chunk = chunk_of_uniq[inv]
            q_row = u_row[inv] - r0_of_chunk[q_chunk]
            n_chunks = len(uniq_key)
        else:
            u_order = np.lexsort((u_row, u_wid))
            pos_of_uniq = np.empty(len(uniq_t), np.int64)
            pos_of_uniq[u_order] = np.arange(len(uniq_t))
            q_pos = pos_of_uniq[inv]          # stream position per query
            q_chunk = q_pos // c
            q_row = q_pos % c
            n_chunks = -(-len(uniq_t) // c) if len(uniq_t) else 0

        if paths_k:
            out_nodes = np.zeros((nq, paths_k + 1), np.int64)
        out_c = np.zeros((n_multi, nq) if n_multi else nq, np.int64)
        out_p = np.zeros(nq, np.int64)
        out_f = np.zeros(nq, bool)
        bytes_streamed = 0
        bytes_raw = 0
        cache_hits = 0
        cache_misses = 0
        chunks_packed = 0
        chunks_rle = 0
        sidecar_hits = 0
        # one sort up front; each chunk's queries are then a slice (the
        # serving hot path must not rescan all Q queries per chunk)
        q_by_chunk = np.argsort(q_chunk, kind="stable")
        # ONE padded query shape for the whole campaign (the max chunk,
        # rounded up): per-chunk pow2 padding would compile a fresh walk
        # program per distinct chunk size
        if n_chunks:
            bounds = np.searchsorted(
                q_chunk[q_by_chunk], np.arange(n_chunks + 1))
            qp_all = _pow2(int(np.diff(bounds).max()))

        def prep(ci):
            """Host read + padding + device upload (async enqueue) for
            one chunk; chunks come from / land in the device LRU so
            overlapping campaigns skip the upload. Range chunks key on
            their row range; compacted chunks (arbitrary row sets) are
            content-addressed by the row-id digest, so only an identical
            chunk repeats — e.g. a replayed or per-diff-round campaign."""
            nonlocal bytes_streamed, bytes_raw, cache_hits, \
                cache_misses, chunks_packed, chunks_rle, sidecar_hits
            if range_mode:
                wid_c, r0_c = int(wid_of_chunk[ci]), int(r0_of_chunk[ci])
                key = (wid_c, r0_c, c)
            else:
                take = u_order[ci * c:(ci + 1) * c]
                key = ("compacted", c,
                       hashlib.blake2b(u_wid[take].tobytes()
                                       + u_row[take].tobytes(),
                                       digest_size=16).digest())
            fm_dev = self._cache_get(key)
            if fm_dev is not None:
                cache_hits += 1
            else:
                cache_misses += 1
                # persisted-RLE fast path: a valid sidecar skips the
                # raw block read AND the encode — the cold round's two
                # dominant costs once the wire itself is small
                # sidecars persist for RANGE chunks only: their names
                # are bounded by the index's row ranges. Compacted
                # chunks are content-addressed per campaign row set —
                # persisting those would grow the index dir without
                # bound as query sets vary (each unseen set a new file,
                # never pruned); they re-encode per miss instead.
                sc_path = fp = rk = None
                if self.rle_sidecar and range_mode:
                    bs = self.dc.block_size
                    hi = min(r0_c + c, self.dc.n_owned(wid_c))
                    pairs = [(wid_c, b) for b in
                             range(r0_c // bs, (hi - 1) // bs + 1)]
                    sc_path = os.path.join(
                        self.outdir,
                        f"rle-w{wid_c:05d}-r{r0_c:09d}-c{c}.npz")
                    fp = self._chunk_fingerprint(pairs)
                    rk = self._sidecar_load(sc_path, fp)
                    if rk is not None:
                        sidecar_hits += 1
                skip_rle = rk == "fallback"
                if skip_rle:
                    rk = None
                if rk is None:
                    if range_mode:
                        fm_np = self._row_range(wid_c, r0_c, c)
                    else:
                        fm_np = self._gather_rows(u_wid[take],
                                                  u_row[take])
                        if len(take) < c:     # stable chunk shape: pad
                            fm_np = np.concatenate(  # with stuck rows
                                [fm_np,
                                 np.full((c - len(take), self.graph.n),
                                         -1, np.int8)])
                    # wire coding, best first: transposed RLE (~7-17x),
                    # then 4-bit pack (2x), then raw — each falls back
                    # per-chunk when its break-even check fails.
                    # RLE's break-even baseline optimistically assumes
                    # pack4 will succeed whenever it is enabled (the
                    # escape-heavy chunks where it would not are the
                    # rare <0.5% hub case); computing the real escape
                    # count here would add a full chunk pass that
                    # _pack4 repeats anyway.
                    rk = (_pack_rle(fm_np, self.pack4)
                          if self.rle and not skip_rle else None)
                    if sc_path is not None and not skip_rle:
                        # persist the encoding OR the negative result —
                        # an incompressible chunk must not re-pay the
                        # encode attempt every cold round
                        self._sidecar_save(sc_path, fp, rk)
                if rk is not None:
                    plen, pval, cnts = rk
                    fm_dev = _unpack_rle(
                        jnp.asarray(plen), jnp.asarray(pval),
                        jnp.asarray(cnts), c=c)
                    bytes_streamed += (plen.nbytes + pval.nbytes
                                       + cnts.nbytes)
                    chunks_rle += 1
                elif self.pack4 and (pk := _pack4(fm_np)) is not None:
                    packed, er, ec, ev = pk
                    fm_dev = _unpack4(
                        jnp.asarray(packed), self.graph.n,
                        jnp.asarray(er), jnp.asarray(ec),
                        jnp.asarray(ev))
                    bytes_streamed += (packed.nbytes + er.nbytes
                                       + ec.nbytes + ev.nbytes)
                    chunks_packed += 1
                else:
                    fm_dev = jnp.asarray(fm_np)
                    bytes_streamed += fm_np.nbytes
                bytes_raw += c * self.graph.n
                self._cache_put(key, fm_dev)
            lo, hi = bounds[ci], bounds[ci + 1]
            q_idx = q_by_chunk[lo:hi]
            # order by expected walk length so the kernel's bucketed
            # while_loops exit early (same trick as CPDOracle.route)
            est = length_estimate(self.graph, s_all[q_idx], t_all[q_idx])
            q_idx = q_idx[np.argsort(est, kind="stable")]
            rows_l = np.zeros(qp_all, np.int32)
            s_l = np.zeros(qp_all, np.int32)
            t_l = np.zeros(qp_all, np.int32)
            valid = np.zeros(qp_all, bool)
            rows_l[:len(q_idx)] = q_row[q_idx]
            s_l[:len(q_idx)] = s_all[q_idx]
            t_l[:len(q_idx)] = t_all[q_idx]
            valid[:len(q_idx)] = True
            dev = [fm_dev] + [jnp.asarray(a)
                              for a in (rows_l, s_l, t_l, valid)]
            return dev, q_idx

        # The pipeline is the XLA stream itself: uploads and walk
        # dispatches only ENQUEUE (async), so while the device DMAs and
        # walks chunk k the host is already gathering chunk k+1 — no
        # explicit prefetch thread (concurrent host threads were measured
        # to degrade transfer bandwidth ~5x over a tunneled device link,
        # and buy nothing that the async stream does not already give).
        #: in-flight chunks (inputs AND outputs) kept on device at once.
        #: Device residency is bounded by DEPTH in-flight chunks PLUS up
        #: to ``cache_bytes`` of LRU-cached fm chunks (cached chunks are
        #: NOT freed on drain — that is the point of the cache); size
        #: ``cache_bytes`` accordingly, or 0 to get pure
        #: DEPTH-bounded streaming back
        DEPTH = 4

        def drain(entries):
            """Fetch + scatter a batch of finished chunks (one host
            round trip for however many are handed in)."""
            host = jax.device_get([o for _, o in entries])
            for (q_idx, _), got in zip(entries, host):
                if paths_k:
                    nodes, moves = got
                    out_nodes[q_idx] = nodes[:len(q_idx)]
                    out_p[q_idx] = moves[:len(q_idx)]
                    continue
                cost, plen, fin = got
                if n_multi:
                    out_c[:, q_idx] = cost[:, :len(q_idx)]
                else:
                    out_c[q_idx] = cost[:len(q_idx)]
                out_p[q_idx] = plen[:len(q_idx)]
                out_f[q_idx] = fin[:len(q_idx)]

        pending = []          # (q_idx, device result triple) per chunk
        for ci in range(n_chunks):
            (fm_d, rows_d, s_d, t_d, v_d), q_idx = prep(ci)
            if paths_k:
                outs = extract_paths(self.dg, fm_d, rows_d, s_d, t_d,
                                     k=paths_k)
            elif n_multi:
                outs = table_search_multi(
                    self.dg, fm_d, rows_d, s_d, t_d, w_pads_multi,
                    valid=v_d, max_steps=max_steps)
            else:
                outs = table_search_batch(
                    self.dg, fm_d, rows_d, s_d, t_d, w_pad,
                    valid=v_d, k_moves=k_moves, max_steps=max_steps)
            pending.append((q_idx, outs))
            if len(pending) >= DEPTH:
                drain(pending[:1])
                pending = pending[1:]
        # remaining chunks drain in ONE deferred host fetch (each
        # separate fetch pays a fixed device->host round trip)
        drain(pending)
        self.last_stats = {
            "n_queries": nq,
            "distinct_targets": int(len(uniq_t)),
            "row_chunks": n_chunks,
            # wire bytes actually uploaded (packed when pack4);
            # bytes_raw = the unpacked fm bytes those chunks represent,
            # so artifacts stay comparable across packing modes
            "bytes_streamed": int(bytes_streamed),
            "bytes_raw": int(bytes_raw),
            # packing that actually RAN, not just the enabled flag
            # (chunks can individually fall back when too many entries
            # escape)
            "pack4": self.pack4,
            "rle": self.rle,
            "chunks_packed": chunks_packed,
            "chunks_rle": chunks_rle,
            "sidecar_hits": sidecar_hits,
            "cache_hits": cache_hits,
            "cache_misses": cache_misses,
            "mode": "range" if range_mode else "compacted",
        }
        if paths_k:
            return out_nodes, out_p
        return out_c, out_p, out_f
