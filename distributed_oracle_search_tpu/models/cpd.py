"""The CPD oracle model: sharded build, persistence, routed batched query.

This is the framework's flagship "model": the Compressed Path Database —
a ``[W, R, N]`` int8 first-move tensor (worker × owned-target-row × node),
axis 0 sharded over the mesh's ``worker`` axis. It bundles the three phases
the reference spreads over ``make_cpd_auto`` / CPD block files /
``fifo_auto`` (SURVEY.md §3):

* ``build()``   — sharded batched min-plus Bellman-Ford (reference: per-node
                  Dijkstra sweeps per worker, ``README.md:95``),
* ``save()`` / ``load()`` — per-(worker, block) ``.npy`` files + an
  ``index.json`` manifest. The CPD index *is* the system checkpoint: build
  once, serve statelessly, reload on restart (reference ``README.md:35,92``,
  ``make_fifos.py:21``; SURVEY.md §5 checkpoint/resume). Blocks follow the
  controller's ``bid``/``bidx`` scheme, so a partial build can resume at
  block granularity.
* ``query()``   — routes each (s, t) to the shard owning t (the invariant of
                  ``process_query.py:56-57``), walks all queries in one XLA
                  call, and scatters results back to input order.

On HBM the table is deliberately **uncompressed** — the reference's
run-length compression trades lookups for pointer chasing, which is exactly
wrong for TPU; sharding is the compression here (SURVEY.md §7 hard parts).
"""

from __future__ import annotations

import functools
import glob
import io
import json
import os
import queue
import re
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..data.graph import Graph, INF
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from ..ops import DeviceGraph
from ..parallel.mesh import (
    make_mesh, make_worker_mesh, worker_sharding,
    WORKER_AXIS, DATA_AXIS, LANE_AXIS,
)
from ..parallel.partition import DistributionController
from ..parallel.sharded import (
    build_fm_lanes, build_tables_multi_sharded, build_tables_sharded,
    pad_targets, build_fm_sharded, query_dist_sharded, query_mat_sharded,
    query_multi_sharded, query_paths_sharded, query_sharded,
    query_tables_multi_sharded, query_tables_sharded,
)
from ..testing import faults
from ..utils.atomicio import (
    SWEEP_MIN_AGE_S, TMP_SUFFIX, AtomicNpyWriter, atomic_copy_file,
    atomic_save_npy, atomic_write_json, digest_bytes, digest_file,
    quarantine,
)
from ..utils.env import env_cast, env_flag
from ..utils.log import get_logger
from .resident import (
    block_codec, encode_block, is_container, maybe_decode_rows,
    resident_choice,
)

log = get_logger(__name__)

#: manifest schema version. v2 adds per-block content digests + shapes
#: (``blocks``) and ``digest_algo``; readers tolerate unknown keys, so a
#: bump is MAJOR only when existing keys change meaning — v1 indexes
#: load under v2 code, v(N+1) indexes are rejected by vN code.
INDEX_VERSION = 2

# artifact-durability counters: every verify/quarantine/rebuild/resume
# event in the index data plane proves it fired through one of these
M_BLOCKS_VERIFIED = obs_metrics.counter(
    "cpd_blocks_verified_total",
    "CPD blocks that passed load-time digest/shape verification")
M_BLOCKS_CORRUPT = obs_metrics.counter(
    "cpd_blocks_corrupt_total",
    "CPD blocks found missing/torn/digest-mismatched at load or verify")
M_BLOCKS_REBUILT = obs_metrics.counter(
    "cpd_blocks_rebuilt_total",
    "corrupt CPD blocks rebuilt in place from the graph")
M_BLOCKS_RESUMED = obs_metrics.counter(
    "build_blocks_resumed_total",
    "blocks skipped by a resumed build (ledger-verified complete)")
M_REPLICA_MISMATCH = obs_metrics.counter(
    "replica_digest_mismatches_total",
    "replica blocks whose digest diverged from the primary's "
    "(anti-entropy pass; quarantined + healed)")
M_REPLICA_COPIED = obs_metrics.counter(
    "replica_blocks_copied_total",
    "replica blocks materialized by copying a digest-valid primary "
    "block instead of recomputing from the graph")
M_BLOCKS_ADOPTED = obs_metrics.counter(
    "reshard_blocks_adopted_total",
    "blocks digest-verified (healing as needed) by a worker adopting "
    "shard ownership during a membership reconfiguration")

# build-pipeline + delta-build series: the throughput plane of the
# road-scale build (ROADMAP item 1) — staging overlap, pipeline stalls,
# and how much work an epoch-keyed delta rebuild actually skipped
M_ROWS_STAGED = obs_metrics.counter(
    "build_rows_staged_total",
    "CPD build rows whose frontier/target inputs the host stager "
    "prepared (pipelined and serial builds both count)")
M_STAGE_OVERLAP = obs_metrics.histogram(
    "build_stage_overlap_seconds",
    "host-side staging time per block (target pad + device upload + "
    "pre-opened block writer); overlapped with device compute when "
    "the pipeline is on — overlap WON, so more is better")
M_PIPE_STALL = obs_metrics.histogram(
    "build_pipeline_stall_seconds",
    "time the build's device-dispatch loop waited for the host stager "
    "(pipelined builds only; the number the async stager exists to "
    "drive to zero)")
M_DELTA_ROWS = obs_metrics.counter(
    "build_delta_rows_recomputed_total",
    "rows a delta rebuild recomputed because the changed-edge pass "
    "marked their first-move entries dirty")
M_DELTA_SKIPPED = obs_metrics.counter(
    "build_delta_skipped_blocks_total",
    "blocks a delta rebuild reused (byte copy from the old index, "
    "digest journaled) instead of recomputing")
M_MESH_COLLECTIVE = obs_metrics.histogram(
    "mesh_collective_seconds",
    "on-mesh collective join per mat-family row (query_mat: walk + "
    "scatter + psum, replacing the head-side fan-out/join)")

#: compressed device->host fm fetch below this raw size is not worth the
#: extra device round trip (the count pass) — plain fetch instead
FETCH_RLE_MIN_BYTES = 16 << 20


@jax.jit
def _fm_run_count(fm: jnp.ndarray) -> jnp.ndarray:
    """Number of target-axis runs in a [C, N] fm block (column-major
    over the transposed layout — the same coherence the streamed wire
    format exploits: ~93-97% of entries equal the entry one target up).
    """
    c = fm.shape[0]
    flat = fm.T.reshape(-1)
    ch = jnp.concatenate([jnp.ones(1, jnp.bool_),
                          flat[1:] != flat[:-1]])
    ch = ch | ((jnp.arange(flat.shape[0]) % c) == 0)
    return ch.sum()


def _fm_rle_encode_impl(fm: jnp.ndarray, cap: int):
    """Device-side transposed RLE of a [C, N] fm block ->
    ``(lens uint16 [cap], vals int8 [cap])`` in column-major run order
    (pads: length 0). Runs break at column boundaries, so a run never
    exceeds C (uint16-safe for C <= 65535; callers gate)."""
    c = fm.shape[0]
    flat = fm.T.reshape(-1)
    total = flat.shape[0]
    ch = jnp.concatenate([jnp.ones(1, jnp.bool_),
                          flat[1:] != flat[:-1]])
    ch = ch | ((jnp.arange(total) % c) == 0)
    idx = jnp.nonzero(ch, size=cap, fill_value=total)[0].astype(jnp.int32)
    vals = flat[jnp.minimum(idx, total - 1)]
    nxt = jnp.concatenate([idx[1:],
                           jnp.full((1,), total, jnp.int32)])
    return (nxt - idx).astype(jnp.uint16), vals


_fm_rle_encode = functools.partial(
    jax.jit, static_argnames=("cap",))(_fm_rle_encode_impl)
#: donating variant for the pipelined build: the encode is the LAST
#: consumer of a block's fm buffer, and donating it releases that HBM
#: immediately instead of holding it live under the next block's kernels
#: (real backends only — CPU donation is unimplemented and would warn
#: per call; selection in fetch_fm)
_fm_rle_encode_donate = functools.partial(
    jax.jit, static_argnames=("cap",),
    donate_argnums=(0,))(_fm_rle_encode_impl)


def _fetch_rle_eligible(shape) -> bool:
    c, n = shape
    return (env_flag("DOS_FETCH_RLE", True) and c >= 2
            and c <= 65535 and c * n >= FETCH_RLE_MIN_BYTES)


def fetch_fm(dev, count_dev=None, donate: bool = False) -> np.ndarray:
    """Device [C, N] int8 fm block -> host numpy, RLE-compressed over
    the wire when it pays.

    The build's device->host fetch is link-bound on tunneled/remote
    devices (measured 12-60 MB/s windows for a 135 MB block — up to
    half the end-to-end build time). fm rows run 14-34 long along the
    target axis, so the device encodes the transposed block (~3 bytes
    per run) and the host expands with one ``np.repeat`` — typically
    5-15x fewer wire bytes. Falls back to a plain fetch for small
    blocks, incompressible blocks, and ``DOS_FETCH_RLE=0``.

    ``count_dev``: optionally the ``_fm_run_count(dev)`` result
    dispatched EAGERLY when the block was computed — pipelined callers
    (``build_worker_shard``) enqueue it right behind the build kernel
    so this fetch never waits on later-dispatched device work for the
    count.

    ``donate=True`` (build callers that never touch ``dev`` again):
    the RLE encode — this buffer's last consumer — DONATES it on real
    backends, so a drained block's fm HBM frees under the next block's
    kernels instead of doubling the pipeline's working set. The
    default keeps the caller's buffer valid: donation is the caller's
    decision, never a buried env check that invalidates someone
    else's array."""
    c, n = dev.shape
    if not _fetch_rle_eligible((c, n)):
        return np.asarray(dev)
    n_runs = int(_fm_run_count(dev) if count_dev is None else count_dev)
    cap = 1 << max(n_runs - 1, 0).bit_length()
    if 3 * cap >= c * n:          # incompressible: plain wins
        return np.asarray(dev)
    enc = (_fm_rle_encode_donate
           if donate and jax.default_backend() != "cpu"
           else _fm_rle_encode)
    lens, vals = enc(dev, cap)
    lens_h, vals_h = jax.device_get((lens, vals))
    flat = np.repeat(vals_h[:n_runs], lens_h[:n_runs].astype(np.int64))
    return np.ascontiguousarray(flat.reshape(n, c).T)


def _host(x) -> np.ndarray:
    """Sharded device result -> host numpy, multi-controller safe.

    Single-process: a plain ``np.asarray`` (device transfer of the local
    shards). With >1 JAX process the array spans non-addressable devices,
    so it rides ``process_allgather`` instead — every controller gets the
    identical global value, preserving the invariant that all processes
    compute the same campaign results."""
    if jax.process_count() > 1:
        from ..parallel.multihost import gather_to_host

        return gather_to_host(x)
    return np.asarray(x)


def _host_tree(tree):
    """Like :func:`_host` over a pytree — but single-process it fetches
    ALL leaves in ONE ``device_get`` (each separate fetch pays a fixed
    ~90 ms round trip over a tunneled TPU link; one call pays it once)."""
    if jax.process_count() > 1:
        return jax.tree.map(_host, tree)
    return jax.device_get(tree)


def shard_block_name(wid: int, bid: int, replica: int = 0) -> str:
    """Block file name. ``replica=0`` (the primary copy) keeps the
    legacy name; replica rank r's copy — the SAME rows, hosted by worker
    ``(wid + r) % W`` — is a separate block set ``cpd-w<wid>-r<r>-b<bid>``
    so primaries and replicas verify/heal independently."""
    if replica:
        return f"cpd-w{wid:05d}-r{replica:02d}-b{bid:05d}.npy"
    return f"cpd-w{wid:05d}-b{bid:05d}.npy"


def block_file_replica(fname: str) -> int:
    """Replica rank encoded in a block file name (0 for primaries)."""
    parts = fname.split("-")
    if len(parts) >= 4 and parts[2].startswith("r"):
        return int(parts[2][1:])
    return 0


def ledger_path(outdir: str, wid: int, replica: int = 0) -> str:
    if replica:
        return os.path.join(outdir,
                            f"build-w{wid:05d}-r{replica:02d}.ledger")
    return os.path.join(outdir, f"build-w{wid:05d}.ledger")


class BuildLedger:
    """Per-worker build journal: one JSON line per completed,
    digest-valid block.

    The ledger is the crash-resume source of truth: a block counts as
    done only when its line is in the journal AND the file on disk still
    matches the recorded digest — a torn write, a swept tmp file, or
    bit-rot all fail the check and the block is recomputed. Appends are
    flushed+fsynced per line; a torn trailing line (crash mid-append)
    is skipped on read, costing at most one block's recompute. Later
    entries for the same file win, so a rebuilt block just appends."""

    def __init__(self, outdir: str, wid: int, replica: int = 0):
        self.path = ledger_path(outdir, wid, replica)

    def entries(self) -> dict[str, dict]:
        out: dict[str, dict] = {}
        try:
            with open(self.path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        ent = json.loads(line)
                    except ValueError:
                        continue          # torn trailing append
                    if isinstance(ent, dict) and "file" in ent:
                        out[ent["file"]] = ent
        except OSError:
            pass
        return out

    def record(self, fname: str, digest: str, shape, dtype: str,
               epoch: int | None = None,
               codec: str | None = None) -> None:
        """Journal one completed block. ``epoch`` keys the line to a
        diff-epoch build (delta rebuilds and their full-degrade path):
        readers that resume an epoch-keyed build treat entries from any
        OTHER epoch as invalid — epoch-keyed block invalidation — while
        legacy readers simply ignore the unknown key (the codec
        contract). ``codec`` records a compressed block's encoding
        (``models.resident``) so the manifest harvest can carry it;
        raw blocks omit the key, keeping legacy ledgers byte-identical."""
        ent = {"file": fname, "digest": digest,
               "shape": list(shape), "dtype": dtype}
        if epoch is not None:
            ent["epoch"] = int(epoch)
        if codec is not None:
            ent["codec"] = str(codec)
        line = json.dumps(ent)
        with open(self.path, "a") as f:
            f.write(line + "\n")
            f.flush()
            os.fsync(f.fileno())


def _block_done(outdir: str, fname: str, entries: dict[str, dict],
                epoch: int | None) -> bool:
    """Resume check with epoch-keyed invalidation: a plain build
    (``epoch=None``) keeps :func:`block_complete`'s rules (legacy
    un-ledgered blocks accepted if they parse); an epoch-keyed build
    requires a ledger line carrying THAT epoch with a matching on-disk
    digest — a parseable block from another weight regime must never be
    skipped into the new index."""
    if epoch is None:
        return block_complete(outdir, fname, entries)
    ent = entries.get(fname)
    if ent is None or ent.get("epoch") != int(epoch):
        return False
    path = os.path.join(outdir, fname)
    try:
        return digest_file(path) == ent.get("digest")
    except OSError:
        return False


def block_complete(outdir: str, fname: str,
                   ledger_entries: dict[str, dict]) -> bool:
    """Is an on-disk block safe to skip on resume? Ledgered blocks must
    match their recorded digest; pre-ledger (legacy) blocks must at
    least parse as a ``.npy`` — a torn legacy write fails the header or
    size check and is rebuilt."""
    path = os.path.join(outdir, fname)
    if not os.path.exists(path):
        return False
    ent = ledger_entries.get(fname)
    if ent is not None:
        return digest_file(path) == ent.get("digest")
    try:
        np.load(path, mmap_mode="r")
        return True
    except Exception as e:  # noqa: BLE001 — any unreadable file means
        # rebuild; say which file and why, or the operator sees an
        # unexplained non-skip on every resume
        log.debug("unledgered block %s unreadable (%s); rebuilding",
                  fname, e)
        return False


def length_estimate(graph: Graph, s: np.ndarray, t: np.ndarray):
    """Cheap host-side walk-length predictor: L1 coordinate distance
    (road networks keep path length ~monotone in it). Zero device work;
    used only to ORDER queries so the bucketed walk groups similar
    lengths — never affects answers. Shared by the resident and streamed
    serving paths."""
    xs, ys = graph.xs, graph.ys
    return np.abs(xs[s] - xs[t]) + np.abs(ys[s] - ys[t])


#: shift coverage below which auto falls back to the ELL gather relaxation
SHIFT_COVERAGE_MIN = 0.9

#: lattice-edge share below which auto will not pick the fast-sweeping
#: build (shift planes keep sweep correct on any graph, but only lattice
#: edges benefit from the quadrant scans)
SWEEP_COVERAGE_MIN = 0.75

#: below this node count the per-hop shift relaxation beats the sweep's
#: scan overhead (measured crossover ~25k nodes on v5e)
SWEEP_MIN_NODES = 32_768

#: modeled ELL+COO split cost ratio below which auto prefers the split
#: over the plain padded-ELL gather (degree-skewed graphs: road networks
#: pad K to the max degree while the mean is ~4)
ELLSPLIT_RATIO_MAX = 0.75

#: below this node count the dense kernels' full sweeps are cheap enough
#: that the frontier queue's per-pop overhead does not pay
FRONTIER_MIN_NODES = 32_768

#: minimum edge id-locality (ops.frontier_relax.locality_fraction) for
#: the delta-stepping frontier build: under it the union wavefront of a
#: clustered target batch degenerates to the whole graph (measured 0.4-
#: 0.6 after RCM/BFS reorder vs 0.02 on shuffled ids)
FRONTIER_LOCALITY_MIN = 0.25


def pick_build_kernel(graph: Graph, method: str = "auto"):
    """Resolve the build-method knob to ``(kind, structure)``.

    ``kind`` ∈ {"sweep", "shift", "frontier", "ellsplit", "ell"};
    ``structure`` is the matching host-side bundle (GridGraph /
    ShiftGraph / FrontierGraph / ELLSplitGraph / None). The coverage
    decisions happen on host-side split arrays — graphs that fall back
    never pay a device transfer.

    ``auto`` picks the fast-sweeping build for large grid-structured
    graphs (O(cycles) not O(hop-diameter) — the only build that scales to
    the 100k+-node regime), the shift relaxation for smaller or
    non-lattice-but-banded graphs, the delta-stepping frontier queue for
    large locality-ordered irregular graphs (road networks after
    BFS/RCM reorder — the only irregular build whose work tracks the
    frontier instead of N x diameter), the ELL+COO split for the
    remaining degree-skewed irregular graphs, and the padded-ELL gather
    otherwise.
    """
    from ..ops.device_graph import JINF
    from ..ops.ell_split import ell_split_graph, split_ratio
    from ..ops.frontier_relax import frontier_graph, locality_fraction
    from ..ops.grid_sweep import GridGraph
    from ..ops.shift_relax import ShiftGraph, split_coverage

    if method not in ("auto", "ell", "ellsplit", "frontier", "shift",
                      "sweep"):
        raise ValueError(f"unknown build method {method!r}")
    if method == "ell":
        return "ell", None
    if method == "frontier":
        return "frontier", frontier_graph(graph)
    if method == "ellsplit":
        _, k0 = split_ratio(np.diff(graph.out_ptr), graph.max_out_degree)
        return "ellsplit", ell_split_graph(graph, k0=k0)
    if method in ("auto", "sweep"):
        split = graph.grid_split()
        if split is not None:
            if method == "sweep":
                return "sweep", GridGraph(*split)
            # lattice share from the HOST arrays (no device transfer for
            # graphs the gate rejects): what the quadrant scans serve
            _, _, wl, wr, wd, wu, _, w_shift, src_left, _, _ = split
            on_grid = sum(int((np.asarray(a) < int(JINF)).sum())
                          for a in (wl, wr, wd, wu))
            total = (on_grid + int((np.asarray(w_shift) < int(JINF)).sum())
                     + len(src_left))
            if (total and on_grid / total >= SWEEP_COVERAGE_MIN
                    and graph.n >= SWEEP_MIN_NODES):
                return "sweep", GridGraph(*split)
        elif method == "sweep":
            raise ValueError("method='sweep' but no grid layout fits "
                             "(Graph.grid_split returned None)")
    shifts, w_shift, nbr_left, w_left = graph.shift_split()
    if method == "auto" and split_coverage(w_shift,
                                           w_left) < SHIFT_COVERAGE_MIN:
        # irregular graph: the frontier queue when ids have locality
        # (post-reorder road networks — its work tracks the wavefront,
        # not N x diameter), else split the padded ELL when the degree
        # skew makes it worthwhile (cost model in ops.ell_split)
        if (graph.n >= FRONTIER_MIN_NODES
                and locality_fraction(graph) >= FRONTIER_LOCALITY_MIN):
            return "frontier", frontier_graph(graph)
        ratio, k0 = split_ratio(np.diff(graph.out_ptr),
                                graph.max_out_degree)
        if ratio <= ELLSPLIT_RATIO_MAX:
            return "ellsplit", ell_split_graph(graph, k0=k0)
        return "ell", None
    return "shift", ShiftGraph(shifts, w_shift, nbr_left, w_left, graph.n)


# ------------------------------------------------------- build pipeline

def build_pipeline_enabled() -> bool:
    """``DOS_BUILD_PIPELINE`` (default on): stage the next block's
    inputs on a background thread while the device runs the current
    one. Off = the serial reference loop (the parity smoke pins the
    two bit-identical)."""
    return env_flag("DOS_BUILD_PIPELINE", True)


def build_stage_depth() -> int:
    """``DOS_BUILD_STAGE_DEPTH`` (default 2): staged blocks the host
    keeps prepared ahead of the device — each holds its padded target
    uploads and a pre-opened block writer, so depth is bounded host
    memory, not correctness."""
    return max(env_cast("DOS_BUILD_STAGE_DEPTH", 2, int), 1)


def build_chunk_rows(graph: Graph, chunk: int, n_owned: int,
                     kind: str = "ell") -> int:
    """Rows per build kernel call. An explicit ``chunk`` wins; with
    ``chunk=0`` and ``DOS_BUILD_HBM_MB`` set, the chunk is sized to
    that HBM budget from the kernel's per-row working-set estimate —
    multi-row frontier batching: the frontier/relax kernels amortize
    their fixed per-dispatch cost (~0.3 ms loop floor + ~90 ms tunneled
    sync) over as many source rows as the budget fits instead of
    dispatching row by row. Power-of-two floored for stable compiled
    shapes across shards; ``DOS_BUILD_HBM_MB`` unset keeps the legacy
    whole-shard batch."""
    if chunk > 0:
        return chunk
    budget_mb = env_cast("DOS_BUILD_HBM_MB", 0.0, float)
    if budget_mb <= 0:
        return max(n_owned, 1)
    k = max(graph.max_out_degree, 1)
    # dominant live arrays per target row: the dense gather's [N, K, B]
    # relax temp (ell/ellsplit) or dist + temp + wake planes (~3x int32)
    per_row = graph.n * ((k + 2) * 4 if kind in ("ell", "ellsplit")
                         else 12)
    rows = int(budget_mb * 1e6) // max(per_row, 1)
    rows = max(min(rows, max(n_owned, 1)), 1)
    return 1 << (int(rows).bit_length() - 1)


def _make_chunk_compute(dg, kind: str, structure, max_iters: int,
                        mesh=None):
    """One dispatch closure per resolved build kernel: takes a padded
    int32 target array (host or pre-uploaded device) and returns the
    ASYNC device fm block plus its eagerly dispatched RLE run count —
    the shared compute unit of the full build loop and the delta
    rebuild's row splice.

    ``mesh``: a worker-local lane mesh (``make_worker_mesh``) routes
    each chunk through :func:`~..parallel.sharded.build_fm_lanes` — the
    chunk's target rows become per-device lanes, bit-identical rows in
    the same order. Callers gate on chunk divisibility by the lane
    count; the pad shape is fixed per build, so the gate is one check."""
    from ..ops import build_fm_columns
    from ..ops.ell_split import build_fm_columns_ellsplit
    from ..ops.frontier_relax import build_fm_columns_frontier
    from ..ops.grid_sweep import build_fm_columns_sweep
    from ..ops.shift_relax import build_fm_columns_shift

    def compute_dev(pad):
        if mesh is not None:
            return build_fm_lanes(dg, np.asarray(pad), mesh, kind,
                                  structure, max_iters=max_iters)
        if kind == "sweep":
            return build_fm_columns_sweep(dg, structure, pad,
                                          max_iters=max_iters)
        if kind == "shift":
            return build_fm_columns_shift(dg, structure, pad,
                                          max_iters=max_iters)
        if kind == "frontier":
            return build_fm_columns_frontier(dg, structure, pad,
                                             max_iters=max_iters)
        if kind == "ellsplit":
            return build_fm_columns_ellsplit(dg, structure, pad,
                                             max_iters=max_iters)
        return build_fm_columns(dg, jnp.asarray(pad),
                                max_iters=max_iters)

    def compute_with_count(pad):
        d = compute_dev(pad)
        cd = (_fm_run_count(d) if _fetch_rle_eligible(d.shape)
              else None)
        return d, cd

    return compute_with_count


class _BackgroundStager:
    """Bounded-depth background staging thread of the pipelined build:
    prepares block b+1's inputs (padded targets, device upload, the
    pre-opened atomic block writer) while the device runs block b.
    Iterating yields the staged items in order; the queue wait is the
    pipeline stall the stager exists to hide
    (``build_pipeline_stall_seconds``). ``close()`` stops the thread
    and aborts every staged-but-unconsumed writer, so error paths
    leave no tmp debris behind."""

    def __init__(self, bids, stage_fn, depth: int, wid: int):
        self._q: queue.Queue = queue.Queue(maxsize=max(depth, 1))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, args=(list(bids), stage_fn),
            name=f"dos-build-stager-w{wid}", daemon=True)
        self._thread.start()

    def _put(self, item) -> bool:
        """Stop-aware bounded put; False when close() raced it."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _run(self, bids, stage_fn) -> None:
        try:
            for bid in bids:
                if self._stop.is_set():
                    return
                item = stage_fn(bid)
                if not self._put(("item", item)):
                    item[-1].abort()      # writer never reaches the loop
                    return
        except BaseException as e:  # noqa: BLE001 — carried to the
            # consuming build loop, which re-raises it in caller context
            self._put(("err", e))
            return
        self._put(("done", None))

    def __iter__(self):
        while True:
            t0 = time.perf_counter()
            kind, val = self._q.get()
            M_PIPE_STALL.observe(time.perf_counter() - t0)
            if kind == "done":
                return
            if kind == "err":
                raise val
            yield val

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=10)
        while True:
            try:
                kind, val = self._q.get_nowait()
            except queue.Empty:
                break
            if kind == "item":
                val[-1].abort()


def build_worker_shard(graph: Graph, dc: DistributionController, wid: int,
                       outdir: str, chunk: int = 0, max_iters: int = 0,
                       resume: bool = True,
                       method: str = "auto", replica: int = 0,
                       epoch: int | None = None,
                       ctx: dict | None = None,
                       codec: str | None = None) -> list[str]:
    """Build and persist ONE worker's CPD block files on the local device.

    This is the host-mode build unit: the reference launches one
    ``make_cpd_auto`` per worker over ssh/tmux (``make_cpds.py:20-21``), each
    emitting per-block CPD files; here one process builds its worker's rows
    block-by-block with the batched min-plus kernel (gather-free shift
    relaxation when the id layout allows) and writes
    ``cpd-w<wid>-b<bid>.npy`` per block — each through a tmp+fsync+rename
    atomic write, journaled (file, digest, shape) in the per-worker build
    ledger. ``resume=True`` skips blocks the ledger records as complete
    AND whose on-disk digest still matches (legacy un-ledgered blocks are
    accepted if they parse) — mid-build restart granularity the reference
    lacks (SURVEY.md §5 checkpoint/resume), now safe against torn writes:
    a build killed mid-flush recomputes exactly the missing tail.

    ``replica``: build the rank-``replica`` REPLICA block set of shard
    ``wid`` (same rows, ``-r<replica>-`` file names, its own ledger) —
    the copy hosted by worker ``(wid + replica) % W``. The kernels are
    deterministic, so a recomputed replica is bit-identical to the
    primary; callers that have a digest-valid primary on the same
    filesystem should prefer :func:`copy_replica_blocks` first and let
    this recompute only what could not be copied.

    The loop is a SOFTWARE PIPELINE (``DOS_BUILD_PIPELINE``, default
    on): a host-side stager thread prepares the NEXT block's padded
    target inputs — device upload included — and pre-opens its atomic
    block writer while the device runs the CURRENT block's kernels and
    the main thread drains/writes the PREVIOUS one; the fm fetch
    donates its buffer into the RLE encode on real backends so a
    drained block's HBM frees under the next block's compute. Results
    are bit-identical to the serial loop (the ``build`` parity smoke
    pins it): staging changes WHEN inputs are prepared, never what the
    kernels compute. ``chunk=0`` with ``DOS_BUILD_HBM_MB`` set sizes
    the per-kernel-call row batch to that HBM budget
    (:func:`build_chunk_rows`).

    ``epoch``: key this build's ledger lines to a diff epoch (delta
    rebuilds): on resume, only blocks journaled under the SAME epoch
    with a matching digest are skipped — a parseable block from
    another weight regime is invalidated, not adopted. Callers that
    TIME the build (bench) pass ``resume=False`` so no journal parse
    lands inside the measured region.

    ``ctx``: an optional dict shared across calls caching the per-graph
    compute setup (DeviceGraph upload + build-kernel resolution + the
    worker lane mesh) — the same hoist as ``delta_build_index``'s
    ``_delta_compute_ctx``: a resident worker (or a bench timing the
    build) rebuilding repeatedly must not pay a CSR re-upload and
    kernel re-pick per call.

    ``codec``: persist blocks compressed (``models.resident``
    RLE/pack4 containers; None resolves ``DOS_CPD_RESIDENT``, whose
    ``raw`` default keeps the legacy byte-identical .npy rows). Each
    block encodes independently and degrades to raw when its rows are
    not viable; the ledger line and the manifest harvest record the
    codec that actually applied.

    With ``DOS_MESH_DEVICES`` > 1 the per-chunk kernel calls run
    lane-parallel on the worker's local mesh (per-device target lanes
    under ``shard_map``, :func:`~..parallel.sharded.build_fm_lanes`) —
    bit-identical blocks; a chunk the lane count does not divide falls
    back to the single-device compute with one log line.
    """
    os.makedirs(outdir, exist_ok=True)
    # sweep THIS worker's atomic-write debris from a killed build; the
    # dir-wide sweep belongs to the campaign/launcher (other workers may
    # be writing their own tmp files in this dir right now). Same age
    # gate as the dir-wide sweep: a young tmp file may be a live write
    # by a concurrent same-wid process (a respawned worker healing while
    # its hung predecessor still drains) — deleting it would turn that
    # process's rename into a crash
    now = time.time()
    tmp_stem = (f"cpd-w{wid:05d}-r{replica:02d}-b*" if replica
                else f"cpd-w{wid:05d}-b*")
    for p in glob.glob(os.path.join(
            outdir, f"{tmp_stem}{TMP_SUFFIX}.*")):
        try:
            if now - os.path.getmtime(p) >= SWEEP_MIN_AGE_S:
                os.remove(p)
        except OSError:
            pass
    owned = dc.owned(wid)
    bs = dc.block_size
    n_blocks = (len(owned) + bs - 1) // bs
    # only the missing blocks are computed — a restart after a partial
    # build pays exactly for what is not yet on disk, and "on disk"
    # means ledger-journaled with a matching digest, not merely named
    ledger = BuildLedger(outdir, wid, replica)
    entries = ledger.entries() if resume else {}
    missing, resumed = [], 0
    for bid in range(n_blocks):
        if resume and _block_done(
                outdir, shard_block_name(wid, bid, replica), entries,
                epoch):
            resumed += 1
        else:
            missing.append(bid)
    if resumed:
        M_BLOCKS_RESUMED.inc(resumed)
        log.info("worker %d build resume: %d/%d block(s) already "
                 "complete and digest-valid", wid, resumed, n_blocks)
    if not missing:
        return []
    # hoistable compute setup: graph upload, kernel pick, lane mesh —
    # cached in the caller's ctx so a repeat build (resident rebuild,
    # bench rep) re-dispatches kernels without re-staging any of it
    ctx = {} if ctx is None else ctx
    if ctx.get("graph") is not graph:
        ctx.clear()
        ctx["graph"] = graph
        ctx["kernel"] = pick_build_kernel(graph, method)
        ctx["dg"] = DeviceGraph.from_graph(graph)
        ctx["mesh"] = make_worker_mesh()
    elif ctx.get("method") not in (None, method):
        ctx["kernel"] = pick_build_kernel(graph, method)
    ctx["method"] = method
    kind, structure = ctx["kernel"]
    dg = ctx["dg"]
    mesh = ctx["mesh"]
    # compute granularity (device working set) is independent of the
    # file granularity: each block file is assembled from `chunk`-row
    # kernel calls, so a 16k-row block never forces a 16k-row device
    # batch; with DOS_BUILD_HBM_MB set the chunk is budget-sized
    chunk = build_chunk_rows(graph, chunk, len(owned), kind=kind)
    if mesh is not None and chunk % mesh.shape[LANE_AXIS]:
        log.warning("worker %d: chunk %d does not divide over %d mesh "
                    "lane(s); building single-device", wid, chunk,
                    mesh.shape[LANE_AXIS])
        mesh = None
    compute_with_count = _make_chunk_compute(dg, kind, structure,
                                             max_iters, mesh=mesh)
    # this build never touches a drained block's device buffers again,
    # so the fetch may donate them into the encode (DOS_BUILD_DONATE).
    # Lane-mesh builds skip donation: the drained block is a GSPMD
    # array sharded across lanes, not a single donatable device buffer
    donate = env_flag("DOS_BUILD_DONATE", True) and mesh is None

    def stage(bid: int):
        """Host-side prep of ONE block: padded target arrays uploaded
        to device (the H2D transfer overlaps the previous block's
        kernels under the pipeline) and the block's atomic writer
        pre-opened — all of it off the device-dispatch critical path."""
        t0 = time.perf_counter()
        blk = owned[bid * bs: min((bid + 1) * bs, len(owned))]
        lens, pads = [], []
        for i in range(0, len(blk), chunk):
            part = blk[i:i + chunk]
            pad = np.full(chunk, -1, np.int32)  # fixed shape -> 1 compile
            pad[:len(part)] = part
            # lane-mesh builds keep the host array: the shard_map's own
            # dispatch shards it over lanes (a single-device pre-upload
            # here would just bounce back through the host)
            pads.append(pad if mesh is not None else jax.device_put(pad))
            lens.append(len(part))
        fname = shard_block_name(wid, bid, replica)
        writer = AtomicNpyWriter(os.path.join(outdir, fname))
        M_ROWS_STAGED.inc(int(len(blk)))
        M_STAGE_OVERLAP.observe(time.perf_counter() - t0)
        return (bid, fname, lens, pads, writer)

    codec_req = resident_choice() if codec is None else codec

    def flush(entry) -> None:
        bid, fname, lens, devs, writer = entry
        # RLE-compressed fetch per chunk (plain for small blocks): the
        # build is link-bound on tunneled devices, and fm compresses
        # 5-15x over the target axis (see fetch_fm). Run counts were
        # dispatched eagerly with each chunk's build, so the count sync
        # here never waits on the NEXT block's kernels; the encode does
        # queue behind them, but it is milliseconds of device work vs
        # the seconds of raw drain it replaces — per block the cost is
        # ~max(compute, tiny drain) either way on a fast link, and
        # compute-bound instead of drain-bound on a slow one.
        parts = [fetch_fm(d, count_dev=cd, donate=donate)
                 for d, cd in devs]
        trimmed = [p[:ln] for p, ln in zip(parts, lens)]
        arr = (trimmed[0] if len(trimmed) == 1
               else np.concatenate(trimmed))
        # compressed persistence (DOS_CPD_RESIDENT / the codec param):
        # the block lands as a self-describing container through the
        # SAME atomic writer — digest, ledger, heal, and replica copies
        # all operate on the container bytes
        enc = encode_block(arr, codec_req)
        if enc is not None:
            arr, blk_codec = enc
        else:
            blk_codec = None
        # atomic write (into the pre-opened tmp), then the ledger line:
        # a kill between the two leaves a complete un-journaled file
        # (the legacy-parse resume path accepts it); a kill MID-write
        # leaves only tmp debris
        digest = writer.commit(arr)
        ledger.record(fname, digest, arr.shape, str(arr.dtype),
                      epoch=epoch, codec=blk_codec)
        # chaos hook: DOS_FAULTS="crash-build;..." dies here, between
        # block flushes — the kill-mid-build resume test's trigger
        rule = faults.inject("crash-build", wid=wid)
        if rule is not None:
            if rule.mode == "exit":
                os._exit(faults.KILL_EXIT_CODE)
            raise RuntimeError("crash-build fault injected")

    pipelined = build_pipeline_enabled() and len(missing) > 1
    stager = (_BackgroundStager(missing, stage, build_stage_depth(), wid)
              if pipelined else None)
    staged_iter = iter(stager) if stager is not None \
        else (stage(bid) for bid in missing)
    written = []
    pending = None                          # one block in flight
    try:
        for item in staged_iter:
            try:
                devs = [compute_with_count(p) for p in item[3]]
                if pending is not None:
                    flush(pending)
            except BaseException:
                item[4].abort()         # staged writer never flushed
                raise
            pending = (item[0], item[1], item[2], devs, item[4])
            written.append(item[1])
        if pending is not None:
            flush(pending)
            pending = None
    finally:
        if pending is not None:
            pending[4].abort()              # error path: no tmp debris
        if stager is not None:
            stager.close()
    return written


# --------------------------------------------------------- delta builds

def epoch_index_dir(outdir: str, epoch: int) -> str:
    """Where a delta rebuild for diff epoch ``epoch`` materializes: a
    sibling-free SUBDIR of the base index, so the epoch-swap machinery
    (worker promotion, the retime→rebuild hook) can find every epoch's
    index from the one path it already knows."""
    return os.path.join(outdir, f"epoch-e{int(epoch):06d}")


def diff_epoch_of(difffile: str) -> int | None:
    """Diff epoch encoded in a fused-diff file name
    (``fused-e<epoch>.diff``, the DiffEpochManager spool convention);
    None for names that don't carry one."""
    m = re.search(r"-e(\d+)\.diff$", os.path.basename(difffile or ""))
    return int(m.group(1)) if m else None


def delta_affected_targets(graph: Graph, changed_eids: np.ndarray,
                           w_old: np.ndarray, w_new: np.ndarray,
                           max_seeds: int | None = None,
                           seed_chunk: int = 512) -> np.ndarray | None:
    """Target rows whose first-move entries CAN change when the named
    edges change weight — the delta build's dirty set.

    The test is the classic tense-edge criterion run as one bounded
    reverse-relaxation pass: compute ``d_old(e → t)`` for every changed
    edge endpoint ``e`` (a batched relaxation on the TRANSPOSED graph —
    the reverse-reachability pass, B = endpoints, not N), then mark
    target ``t`` dirty iff some changed edge ``(u, v)`` satisfies
    ``min(w_old, w_new)(u,v) + d_old(v→t) <= d_old(u→t)``. For an
    INCREASE that condition (with ``w_old``) holds exactly when the
    edge lies on a co-optimal path into ``t`` — otherwise neither
    distances nor any argmin input within row ``t`` move; for a
    DECREASE it (with ``w_new``) holds exactly when the cheaper edge
    becomes tense — otherwise it still strictly loses everywhere. ``<=``
    (not ``<``) keeps argmin TIES dirty, which is what makes a spliced
    delta rebuild bit-identical to a from-scratch build. Unreachable
    ``d_old(v→t) = INF`` rows stay clean: weight changes never create
    reachability.

    Returns the sorted dirty target ids, or ``None`` when the changed
    edge set exceeds the ``max_seeds`` bound
    (``DOS_BUILD_DELTA_MAX_SEEDS``; <= 0 = unbounded) — the caller then
    degrades to a full rebuild, the conservative answer.
    """
    from ..ops.bellman_ford import dist_to_targets

    changed_eids = np.asarray(changed_eids, np.int64)
    if len(changed_eids) == 0:
        return np.zeros(0, np.int64)
    ends_all = np.unique(np.concatenate(
        [graph.src[changed_eids], graph.dst[changed_eids]]))
    if max_seeds is None:
        max_seeds = env_cast("DOS_BUILD_DELTA_MAX_SEEDS", 4096, int)
    if max_seeds > 0 and len(ends_all) > max_seeds:
        log.info("delta pass: %d changed-edge endpoints exceed the "
                 "DOS_BUILD_DELTA_MAX_SEEDS=%d bound; degrading to a "
                 "full rebuild", len(ends_all), max_seeds)
        return None
    # transposed graph under OLD weights: dist_to_targets(gT, e) gives
    # d_T(x -> e) = d_old(e -> x) for every node x in one [B, N] solve
    g_t = Graph(graph.xs, graph.ys, graph.dst, graph.src, w_old)
    dg_t = DeviceGraph.from_graph(g_t)
    minw = np.minimum(np.asarray(w_old, np.int64)[changed_eids],
                      np.asarray(w_new, np.int64)[changed_eids])
    inf64 = int(INF)
    dirty = np.zeros(graph.n, bool)
    per = max(seed_chunk // 2, 1)
    for i in range(0, len(changed_eids), per):
        eids = changed_eids[i:i + per]
        eu = graph.src[eids]
        ev = graph.dst[eids]
        ends = np.unique(np.concatenate([eu, ev]))
        # pad to the pow2 of the ACTUAL endpoint count (capped at the
        # chunk): a 10-edge hotspot must pay a 16-wide solve, not a
        # 512-wide one — the pass's cost tracks the delta's size
        csize = min(seed_chunk,
                    1 << (max(len(ends), 1) - 1).bit_length())
        pad = np.full(csize, -1, np.int32)
        pad[:len(ends)] = ends
        d = np.asarray(dist_to_targets(
            dg_t, jnp.asarray(pad))).astype(np.int64)   # [B, N]
        du = d[np.searchsorted(ends, eu)]
        dv = d[np.searchsorted(ends, ev)]
        tense = (dv < inf64) & (minw[i:i + per][:, None] + dv <= du)
        dirty |= tense.any(axis=0)
    return np.nonzero(dirty)[0].astype(np.int64)


def _compute_rows_batched(compute_with_count, tgts: np.ndarray,
                          chunk_rows: int) -> np.ndarray:
    """Solve fm rows for an arbitrary target list in chunk batches —
    the shared recompute unit of the delta paths. Full batches reuse
    the chunk's compiled shape; the final partial batch pads to its
    own pow2 (capped at the chunk) so a handful of dirty rows never
    pays a whole-chunk solve."""
    donate = env_flag("DOS_BUILD_DONATE", True)
    parts = []
    for i in range(0, len(tgts), chunk_rows):
        part = tgts[i:i + chunk_rows]
        csize = min(chunk_rows,
                    1 << (max(len(part), 1) - 1).bit_length())
        pad = np.full(csize, -1, np.int32)
        pad[:len(part)] = part
        d, cd = compute_with_count(pad)
        parts.append(fetch_fm(d, count_dev=cd,
                              donate=donate)[:len(part)])
    return parts[0] if len(parts) == 1 else np.concatenate(parts)


def _delta_compute_ctx(ctx: dict | None, graph_new: Graph,
                       method: str, max_iters: int) -> dict:
    """Lazily resolved per-DELTA compute context: the build kernel
    choice, the device-resident graph, and the dispatch closure are
    identical across every shard of one delta, so an in-process
    multi-shard driver (``delta_build_index``) shares ONE DeviceGraph
    upload instead of re-uploading the CSR arrays per shard. ``ctx``
    is the shared mutable cache (``None`` = private, standalone
    callers); a delta where every block copies never populates it."""
    if ctx is None:
        ctx = {}
    if "compute" not in ctx:
        kind, structure = pick_build_kernel(graph_new, method)
        dg = DeviceGraph.from_graph(graph_new)
        ctx["kind"] = kind
        ctx["compute"] = _make_chunk_compute(dg, kind, structure,
                                             max_iters)
    return ctx


def delta_build_worker_shard(graph_new: Graph, dc: DistributionController,
                             wid: int, old_outdir: str, outdir: str,
                             dirty: np.ndarray | None,
                             old_blocks_meta: dict | None = None,
                             chunk: int = 0, max_iters: int = 0,
                             resume: bool = True, method: str = "auto",
                             epoch: int = 0,
                             compute_ctx: dict | None = None) -> dict:
    """One worker's shard of a DELTA rebuild: blocks with no dirty row
    are byte-copied from the old index (digest journaled, zero device
    work), dirty blocks recompute ONLY their dirty rows on the retimed
    graph and splice them into the old block's clean rows. ``dirty`` is
    the [N] bool mask from :func:`delta_affected_targets`; ``None`` (or
    a dirty fraction above ``DOS_BUILD_DELTA_MAX_FRAC``) degrades the
    whole shard to a pipelined full rebuild — whole-shard-dirty is the
    regime where splicing only adds overhead. Every block lands through
    the same atomic write + epoch-keyed ledger line as a full build, so
    a crash mid-delta resumes at block granularity and a stale-epoch
    journal never satisfies the resume check."""
    os.makedirs(outdir, exist_ok=True)
    owned = dc.owned(wid)
    bs = dc.block_size
    n_blocks = (len(owned) + bs - 1) // bs
    report = {"blocks": n_blocks, "rows_recomputed": 0,
              "blocks_skipped": 0, "blocks_resumed": 0,
              "degraded_full": False}
    dirty_owned = (np.ones(len(owned), bool) if dirty is None
                   else np.asarray(dirty, bool)[owned])
    max_frac = env_cast("DOS_BUILD_DELTA_MAX_FRAC", 0.75, float)
    if dirty is None or (len(owned)
                         and dirty_owned.mean() > max_frac):
        # the degraded full rebuild keeps the old index's block codec
        # (first recorded one — indexes are built under one knob), so
        # a compressed index's delta chain stays compressed even when
        # the splice does not pay
        codec_hint = next(
            (m.get("codec") for m in (old_blocks_meta or {}).values()
             if isinstance(m, dict) and m.get("codec")), "raw")
        written = build_worker_shard(graph_new, dc, wid, outdir,
                                     chunk=chunk, max_iters=max_iters,
                                     resume=resume, method=method,
                                     epoch=epoch, codec=codec_hint)
        report["degraded_full"] = True
        report["rows_recomputed"] = int(
            min(len(written) * bs, len(owned)))
        M_DELTA_ROWS.inc(report["rows_recomputed"])
        return report
    ledger = BuildLedger(outdir, wid)
    entries = ledger.entries() if resume else {}
    old_blocks_meta = old_blocks_meta or {}

    def crash_point() -> None:
        rule = faults.inject("crash-build", wid=wid)
        if rule is not None:
            if rule.mode == "exit":
                os._exit(faults.KILL_EXIT_CODE)
            raise RuntimeError("crash-build fault injected")

    # pass 1 — classify every block (resume / byte-copy / rebuild) and
    # collect the rebuild blocks' dirty targets, so pass 2 can solve
    # them in SHARD-WIDE chunk batches: per-block solves would shatter
    # the multi-row batching (and its compiled-shape reuse) that makes
    # the kernels fast — the same amortization the full build lives
    # on. Old rows are NOT retained here (only the verify status):
    # pass 2 re-reads each dirty block as it lands, bounding host
    # memory to the recompute batch plus ONE block instead of every
    # dirty block's copy at once.
    todo: list[tuple] = []        # (bid, fname, blk, bmask, old_ok)
    recompute_tgts: list[np.ndarray] = []
    for bid in range(n_blocks):
        fname = shard_block_name(wid, bid)
        if resume and _block_done(outdir, fname, entries, epoch):
            report["blocks_resumed"] += 1
            M_BLOCKS_RESUMED.inc()
            continue
        lo, hi = bid * bs, min((bid + 1) * bs, len(owned))
        blk = owned[lo:hi]
        bmask = dirty_owned[lo:hi].copy()
        old_path = os.path.join(old_outdir, fname)
        old_meta = old_blocks_meta.get(fname)
        if not bmask.any():
            todo.append((bid, fname, blk, None, False))  # byte copy
            continue
        status, reason = check_block(old_path, old_meta)
        old_ok = status in ("ok", "unverified")
        if not old_ok:
            if status != "missing":
                log.warning("delta rebuild of %s: old block is %s "
                            "(%s); recomputing every row", fname,
                            status, reason)
            bmask[:] = True          # no clean base to splice into
        todo.append((bid, fname, blk, bmask, old_ok))
        recompute_tgts.append(blk[bmask])

    rows_new = None
    if recompute_tgts:
        tgts_all = np.concatenate(recompute_tgts)
        compute_ctx = _delta_compute_ctx(compute_ctx, graph_new,
                                         method, max_iters)
        chunk_rows = build_chunk_rows(graph_new, chunk, len(owned),
                                      kind=compute_ctx["kind"])
        rows_new = _compute_rows_batched(compute_ctx["compute"],
                                         tgts_all, chunk_rows)

    # pass 2 — land blocks in bid order through the same atomic write +
    # epoch-keyed ledger discipline as a full build (crash-build fires
    # between flushes, so mid-delta kills resume at block granularity)
    off = 0
    for bid, fname, blk, bmask, old_ok in todo:
        old_path = os.path.join(old_outdir, fname)
        old_meta = old_blocks_meta.get(fname)
        # spliced/recomputed blocks keep the OLD block's codec — a
        # compressed index's delta chain stays compressed (byte copies
        # carry the container verbatim anyway)
        out_codec = (old_meta or {}).get("codec")
        if bmask is None:
            # clean block: byte copy, digest cross-checked against the
            # old manifest — a MISSING source (quarantined, swept) or a
            # torn one recomputes instead of aborting the shard or
            # propagating rot into the new epoch
            try:
                digest = atomic_copy_file(old_path,
                                          os.path.join(outdir, fname))
            except OSError as e:
                log.warning("delta copy of %s failed (%s); "
                            "recomputing", fname, e)
                digest = None
            if digest is None or (old_meta and old_meta.get("digest")
                                  and digest != old_meta["digest"]):
                if digest is not None:
                    log.warning("delta copy of %s does not match the "
                                "old manifest digest (%s != %s); "
                                "recomputing", fname, digest,
                                old_meta["digest"])
                arr = _delta_single_block(graph_new, blk, chunk,
                                          len(owned), method, max_iters,
                                          compute_ctx)
                n_new = len(blk)
            else:
                arr = np.load(os.path.join(outdir, fname),
                              mmap_mode="r")
                ledger.record(fname, digest, arr.shape,
                              str(arr.dtype), epoch=epoch,
                              codec=out_codec)
                report["blocks_skipped"] += 1
                M_DELTA_SKIPPED.inc()
                crash_point()
                continue
        else:
            n_new = int(bmask.sum())
            fresh = rows_new[off:off + n_new]
            off += n_new
            if not old_ok:
                arr = fresh          # bmask was forced all-dirty
            else:
                # old rows re-read HERE, one block at a time (pass 1
                # kept only the verify status) — bounded host memory
                rows_old, status, reason = load_verified_block(
                    old_path, old_meta)
                if rows_old is not None:
                    try:
                        # compressed old blocks inflate for the splice
                        rows_old = maybe_decode_rows(rows_old)
                    except ValueError as e:
                        rows_old, status, reason = (
                            None, "corrupt", f"undecodable: {e}")
                if rows_old is None:
                    # vanished/torn between passes (rare race): the
                    # batched fresh rows only cover bmask, so the
                    # whole block recomputes
                    log.warning("delta splice of %s: old block "
                                "became %s between passes (%s); "
                                "recomputing every row", fname,
                                status, reason)
                    arr = _delta_single_block(graph_new, blk, chunk,
                                              len(owned), method,
                                              max_iters, compute_ctx)
                    n_new = len(blk)
                else:
                    arr = np.asarray(rows_old).copy()
                    arr[bmask] = fresh
        enc = encode_block(arr, out_codec)
        if enc is not None:
            arr, out_codec = enc
        else:
            out_codec = None
        digest = atomic_save_npy(os.path.join(outdir, fname), arr)
        ledger.record(fname, digest, arr.shape, str(arr.dtype),
                      epoch=epoch, codec=out_codec)
        report["rows_recomputed"] += n_new
        M_DELTA_ROWS.inc(n_new)
        crash_point()
    return report


def _delta_single_block(graph_new: Graph, blk: np.ndarray, chunk: int,
                        n_owned: int, method: str, max_iters: int,
                        compute_ctx: dict | None = None) -> np.ndarray:
    """Recompute one whole block outside the shard-wide batch — the
    rare torn-copy fallback path of :func:`delta_build_worker_shard`
    (sharing the delta's compute context, so even this path never
    re-uploads the device graph)."""
    ctx = _delta_compute_ctx(compute_ctx, graph_new, method, max_iters)
    chunk_rows = build_chunk_rows(graph_new, chunk, n_owned,
                                  kind=ctx["kind"])
    return _compute_rows_batched(ctx["compute"], blk, chunk_rows)


def delta_build_index(graph: Graph, dc: DistributionController,
                      old_outdir: str, difffile: str,
                      epoch: int | None = None,
                      out_root: str | None = None, chunk: int = 0,
                      max_iters: int = 0, method: str = "auto",
                      resume: bool = True, workers=None) -> dict:
    """Delta rebuild: old index + a fused diff epoch → a NEW
    epoch-tagged index (``epoch_index_dir``) bit-identical to a
    from-scratch build on the retimed graph, recomputing only the rows
    the changed edges can actually affect.

    The changed edge set is ``w_new != w_old`` where ``w_old`` comes
    from the old manifest's recorded ``diff_file`` (absent = free flow
    — a plain build), so delta-on-delta chains compose. The affected
    rows come from :func:`delta_affected_targets`; untouched blocks
    byte-copy with their ledger/manifest digests reused. The resulting
    index carries ``diff_epoch``/``diff_file`` manifest keys (unknown
    to old readers — the codec contract) so the epoch-swap machinery
    can promote it under a running serve
    (``worker.engine.ShardEngine.promote_index``).
    """
    old_manifest = read_manifest(old_outdir)
    check_manifest_version(old_manifest, old_outdir)
    old_diff = old_manifest.get("diff_file", "-")
    try:
        w_old = graph.weights_with_diff(old_diff)
    except OSError as e:
        # the old index's fused diff was pruned from the spool (the
        # DiffEpochManager keep window outlives only keep_epochs
        # files): without it the changed-edge set is unknowable, so
        # the delta DEGRADES to a full rebuild on the retimed graph —
        # still a correct epoch index, never a failed chain link
        log.warning("old index %s records diff_file %s which is "
                    "unreadable (%s); delta degrades to a full "
                    "rebuild", old_outdir, old_diff, e)
        w_old = None
    w_new = graph.weights_with_diff(difffile)
    changed = (np.nonzero(w_new != w_old)[0] if w_old is not None
               else np.zeros(0, np.int64))
    if epoch is None:
        epoch = diff_epoch_of(difffile)
    if epoch is None:
        epoch = int(old_manifest.get("diff_epoch", 0)) + 1
    outdir = epoch_index_dir(out_root or old_outdir, int(epoch))
    graph_new = Graph(graph.xs, graph.ys, graph.src, graph.dst, w_new)
    if w_old is None:
        dirty = None                          # unknown delta: full
    elif len(changed) == 0:
        dirty = np.zeros(graph.n, bool)       # empty delta: copy all
    else:
        affected = delta_affected_targets(graph, changed, w_old, w_new)
        if affected is None:
            dirty = None                      # degrade to full
        else:
            dirty = np.zeros(graph.n, bool)
            dirty[affected] = True
    report: dict = {
        "epoch": int(epoch), "outdir": outdir,
        "changed_edges": int(len(changed)),
        "affected_rows": (int(graph.n) if dirty is None
                          else int(dirty.sum())),
        "rows_recomputed": 0, "blocks_skipped": 0,
        "blocks_resumed": 0, "degraded_full": False, "shards": 0,
    }
    # one compute context for the WHOLE delta: kernel choice and the
    # device-resident graph are shard-invariant, so the in-process
    # multi-shard loop uploads the CSR arrays once, not per shard
    ctx: dict = {}
    with obs_trace.span("cpd.delta_build", epoch=int(epoch),
                        changed=int(len(changed))):
        for wid in (range(dc.maxworker) if workers is None else workers):
            rep = delta_build_worker_shard(
                graph_new, dc, wid, old_outdir, outdir, dirty,
                old_blocks_meta=old_manifest.get("blocks", {}),
                chunk=chunk, max_iters=max_iters, resume=resume,
                method=method, epoch=int(epoch), compute_ctx=ctx)
            report["shards"] += 1
            report["rows_recomputed"] += rep["rows_recomputed"]
            report["blocks_skipped"] += rep["blocks_skipped"]
            report["blocks_resumed"] += rep["blocks_resumed"]
            report["degraded_full"] |= rep["degraded_full"]
        if workers is None and dc.replication > 1:
            # replica sets copy from the NEW primaries in the same dir
            for host in range(dc.maxworker):
                for r in range(1, dc.replication):
                    copy_replica_blocks(dc, (host - r) % dc.maxworker,
                                        r, outdir, resume=resume)
        if workers is None:
            write_index_manifest(
                outdir, dc,
                rows_per_worker=old_manifest.get("rows_per_worker"),
                extra={"diff_epoch": int(epoch),
                       "diff_file": os.path.abspath(difffile)})
    log.info("delta build epoch %d: %d changed edge(s) -> %d/%d rows "
             "recomputed, %d block(s) copied%s -> %s", epoch,
             report["changed_edges"], report["rows_recomputed"],
             graph.n, report["blocks_skipped"],
             " (degraded to full)" if report["degraded_full"] else "",
             outdir)
    return report


def _primary_codec(outdir: str, shard: int) -> str:
    """The codec shard ``shard``'s PRIMARY blocks were written with
    (ledger first, block sniff second, raw default) — what a replica
    RECOMPUTE must use so its digest can ever match the primary's in
    the anti-entropy cross-check."""
    for ent in BuildLedger(outdir, shard).entries().values():
        if ent.get("codec"):
            return str(ent["codec"])
    try:
        arr = np.load(os.path.join(outdir, shard_block_name(shard, 0)),
                      mmap_mode="r")
        if is_container(arr):
            return str(block_codec(arr))
    except (OSError, ValueError) as e:
        log.debug("primary codec sniff for shard %d failed (%s); "
                  "assuming raw", shard, e)
    return "raw"


def copy_replica_blocks(dc: DistributionController, shard: int,
                        replica: int, outdir: str,
                        resume: bool = True) -> list[str]:
    """Materialize shard ``shard``'s rank-``replica`` block set by
    copying digest-valid PRIMARY blocks — the cheap path when builder
    and primary share a filesystem (the kernels are deterministic, so
    the copy is exactly what a recompute would produce). Blocks whose
    primary is missing or unparsable are skipped (the caller recomputes
    them via :func:`build_worker_shard(..., replica=r)`). Copies go
    through the same atomic-write + ledger journal as built blocks, so
    resume/verify/heal treat them identically. Returns names written."""
    os.makedirs(outdir, exist_ok=True)
    owned = dc.n_owned(shard)
    bs = dc.block_size
    n_blocks = (owned + bs - 1) // bs
    ledger = BuildLedger(outdir, shard, replica)
    entries = ledger.entries() if resume else {}
    prim_ledger = BuildLedger(outdir, shard).entries()
    written = []
    for bid in range(n_blocks):
        fname = shard_block_name(shard, bid, replica)
        if resume and block_complete(outdir, fname, entries):
            continue
        prim = shard_block_name(shard, bid)
        prim_path = os.path.join(outdir, prim)
        prim_ent = prim_ledger.get(prim)
        rows, status, _reason = _verify_block(
            prim_path,
            {"digest": prim_ent["digest"]} if prim_ent else None,
            want_rows=True)
        if rows is None:
            continue        # no healthy primary: caller recomputes
        # a compressed primary copies verbatim — the replica ships
        # (and stores) the compressed container bytes
        digest = atomic_save_npy(os.path.join(outdir, fname),
                                 np.asarray(rows))
        ledger.record(fname, digest, rows.shape, str(rows.dtype),
                      codec=(block_codec(np.asarray(rows))
                             if is_container(rows) else None))
        M_REPLICA_COPIED.inc()
        written.append(fname)
    return written


def build_replica_shards(graph: Graph, dc: DistributionController,
                         host_wid: int, outdir: str, chunk: int = 0,
                         resume: bool = True,
                         method: str = "auto") -> dict[int, list[str]]:
    """Build every replica block set worker ``host_wid`` hosts (ranks
    1..R-1 of :meth:`~..parallel.partition.DistributionController
    .replica_shards`): copy from digest-valid primaries where possible,
    recompute the rest from the graph. No-op at R=1. Returns
    ``{shard: [files written]}``."""
    out: dict[int, list[str]] = {}
    for r in range(1, dc.replication):
        shard = (host_wid - r) % dc.maxworker
        copied = copy_replica_blocks(dc, shard, r, outdir, resume=resume)
        # recomputed replica blocks keep the PRIMARY's codec — a raw
        # recompute of a compressed primary would fail the anti-entropy
        # digest cross-check forever (quarantine/rebuild loop)
        computed = build_worker_shard(graph, dc, shard, outdir,
                                      chunk=chunk, resume=True,
                                      method=method, replica=r,
                                      codec=_primary_codec(outdir,
                                                           shard))
        out[shard] = sorted(set(copied) | set(computed))
        if copied or computed:
            log.info("worker %d: replica r%d of shard %d ready "
                     "(%d copied, %d computed)", host_wid, r, shard,
                     len(copied), len(computed))
    return out


def _block_meta_for(outdir: str, fname: str,
                    ledgers: dict[tuple, dict]) -> dict:
    """Digest/shape/dtype for one block file, cheapest source first:
    the worker's build ledger (digest already computed from the written
    bytes), else read the file once."""
    wid = int(fname.split("-")[1][1:])
    replica = block_file_replica(fname)
    key = (wid, replica)
    if key not in ledgers:
        ledgers[key] = BuildLedger(outdir, wid, replica).entries()
    ent = ledgers[key].get(fname)
    if ent is not None and "digest" in ent:
        meta = {"digest": ent["digest"], "shape": list(ent["shape"]),
                "dtype": ent["dtype"]}
        if ent.get("codec"):
            meta["codec"] = ent["codec"]
        return meta
    path = os.path.join(outdir, fname)
    arr = np.load(path, mmap_mode="r")
    meta = {"digest": digest_file(path), "shape": list(arr.shape),
            "dtype": str(arr.dtype)}
    # compressed containers are self-describing — an un-ledgered one
    # still gets its codec into the manifest
    if is_container(arr):
        meta["codec"] = block_codec(np.asarray(arr))
    return meta


def write_index_manifest(outdir: str, dc: DistributionController,
                         rows_per_worker: int | None = None,
                         workers=None, block_meta: dict | None = None,
                         extra: dict | None = None) -> dict:
    """Write ``index.json`` describing a per-block CPD index (the head
    runs this after all workers' builds finish). Written atomically.

    v2 manifests record per-block content digests, shapes, and dtypes
    under ``blocks`` (``digest_algo`` names the checksum), so every
    later load/verify can tell a valid block from a torn or rotted one.
    ``block_meta`` optionally supplies those entries (digests computed
    at write time); anything missing is harvested from the per-worker
    build ledgers, and only as a last resort read back from disk.

    ``workers``: optional subset of worker ids to enumerate — a PARTIAL
    index for single-worker serving (the analog of the reference's ``-w``
    filter): streamed/resident serving then answers only queries whose
    target those workers own; other workers' rows load as "stuck".

    ``extra``: additional manifest keys (the delta build's
    ``diff_epoch``/``diff_file`` tags) — unknown to older readers,
    which tolerate them per the codec contract; callers must not shadow
    the required partition keys.
    """
    files = []
    replica_files = []
    bs = dc.block_size
    for wid in (range(dc.maxworker) if workers is None else workers):
        n_owned = dc.n_owned(wid)
        for bid in range((n_owned + bs - 1) // bs):
            fname = shard_block_name(wid, bid)
            if not os.path.exists(os.path.join(outdir, fname)):
                raise FileNotFoundError(
                    f"index incomplete: missing {fname} "
                    f"(worker {wid} block {bid})")
            files.append(fname)
            for r in range(1, dc.replication):
                rname = shard_block_name(wid, bid, r)
                if not os.path.exists(os.path.join(outdir, rname)):
                    raise FileNotFoundError(
                        f"index incomplete: missing replica {rname} "
                        f"(shard {wid} block {bid} rank {r}, hosted by "
                        f"worker {(wid + r) % dc.maxworker})")
                replica_files.append(rname)
    ledgers: dict[tuple, dict] = {}
    blocks = {}
    for fname in files + replica_files:
        meta = (block_meta or {}).get(fname)
        blocks[fname] = meta if meta is not None else _block_meta_for(
            outdir, fname, ledgers)
    manifest = {
        "version": INDEX_VERSION,
        "digest_algo": "crc32",
        "nodenum": dc.nodenum,
        "maxworker": dc.maxworker,
        "partmethod": dc.partmethod,
        "partkey": (list(dc.partkey)
                    if isinstance(dc.partkey, (list, tuple)) else dc.partkey),
        "block_size": bs,
        "rows_per_worker": (rows_per_worker if rows_per_worker is not None
                            else max(dc.max_owned, 1)),
        "files": files,
        "blocks": blocks,
    }
    if dc.replication > 1:
        # replica keys ride the same schema version: unknown keys are
        # tolerated by every reader (the compat contract), and an R=1
        # index stays byte-identical to the pre-replication format
        manifest["replication"] = dc.replication
        manifest["replica_files"] = replica_files
    if extra:
        manifest.update(extra)
    atomic_write_json(os.path.join(outdir, "index.json"), manifest)
    return manifest


def validate_manifest(manifest: dict, dc: DistributionController,
                      outdir: str) -> None:
    """Check a loaded ``index.json`` against the serving controller (the
    reference keeps build and serve consistent by passing the same
    partmethod/partkey quadruple everywhere; we verify it).

    Schema compatibility is the wire codecs' contract: unknown keys are
    tolerated (a v1 index loads under v2 code, and a v2 index's digest
    keys are invisible to v1-era fields), and only a manifest whose
    version is NEWER than this code rejects — those may have changed
    the meaning of keys we would silently misread."""
    check_manifest_version(manifest, outdir)
    my_partkey = (list(dc.partkey)
                  if isinstance(dc.partkey, (list, tuple)) else dc.partkey)
    for key, mine in (("nodenum", dc.nodenum),
                      ("maxworker", dc.maxworker),
                      ("partmethod", dc.partmethod),
                      ("partkey", my_partkey),
                      ("block_size", dc.block_size)):
        if key not in manifest:
            raise ValueError(
                f"index {outdir} manifest is missing required key "
                f"{key!r}")
        if manifest[key] != mine:
            raise ValueError(
                f"index {outdir} was built with {key}={manifest[key]}, "
                f"controller has {mine}")
    # replication is NOT a hard cross-check: an R=1 index serves an
    # R>1 controller (replica sets just aren't on disk yet — failover
    # loads fall back to primaries) and vice versa; the key is only
    # meaningful to verify/anti-entropy passes, which read it directly.


def check_manifest_version(manifest: dict, outdir: str) -> None:
    """The version half of :func:`validate_manifest`, callable on its
    own by load paths that have no controller to cross-check (the
    engine's ``load_shard_rows``): a manifest NEWER than this code may
    have changed the meaning of keys we would silently misread — reject
    it outright instead of mis-verifying every block."""
    version = int(manifest.get("version", 1))
    if version > INDEX_VERSION:
        raise ValueError(
            f"index {outdir} has manifest schema v{version}; this build "
            f"reads up to v{INDEX_VERSION} — upgrade the serving code "
            "(unknown keys are tolerated, newer major versions are not)")


def _verify_block(path: str, meta: dict | None, want_rows: bool):
    """One block's verification against its manifest entry — the single
    implementation behind :func:`check_block` (verify-only: streamed
    digest + mmap'd header, no row materialization) and
    :func:`load_verified_block` (one file read: digest over the bytes
    in memory, then parse those same bytes). Returns
    ``(rows | None, status, reason)`` with status one of ``ok``
    (digest-verified), ``unverified`` (parses, but no digest to check —
    v1 manifest), ``missing``, ``corrupt``."""
    if not os.path.exists(path):
        return None, "missing", "file absent"
    need_digest = bool(meta and meta.get("digest"))
    try:
        if want_rows:
            with open(path, "rb") as f:
                data = f.read()
            got = digest_bytes(data) if need_digest else None
            arr = np.load(io.BytesIO(data))
        else:
            got = digest_file(path) if need_digest else None
            arr = np.load(path, mmap_mode="r")
        if need_digest and got != meta["digest"]:
            return None, "corrupt", (f"digest {got} != manifest "
                                     f"{meta['digest']}")
        if meta:
            if ("shape" in meta
                    and list(arr.shape) != list(meta["shape"])):
                return None, "corrupt", (
                    f"shape {list(arr.shape)} != manifest "
                    f"{list(meta['shape'])}")
            if "dtype" in meta and str(arr.dtype) != meta["dtype"]:
                return None, "corrupt", (f"dtype {arr.dtype} != "
                                         f"manifest {meta['dtype']}")
            if meta.get("codec"):
                # compressed block: the container header must parse
                # and name the manifest's codec — a payload that
                # digests clean but decodes to the wrong codec (or to
                # garbage) is corrupt, not servable
                got_codec = (block_codec(np.asarray(arr))
                             if is_container(arr) else None)
                if got_codec != meta["codec"]:
                    return None, "corrupt", (
                        f"codec {got_codec!r} != manifest "
                        f"{meta['codec']!r}")
    except Exception as e:  # noqa: BLE001 — torn header, short file, ...
        return None, "corrupt", f"unreadable: {type(e).__name__}: {e}"
    return (arr if want_rows else None,
            "ok" if need_digest else "unverified", "")


def check_block(path: str, meta: dict | None) -> tuple[str, str]:
    """Verify one block file WITHOUT materializing the rows (streamed
    digest, mmap'd header); returns ``(status, reason)``."""
    _, status, reason = _verify_block(path, meta, want_rows=False)
    return status, reason


def load_verified_block(path: str, meta: dict | None):
    """Load one block's rows with verification in a SINGLE file read;
    returns ``(rows | None, status, reason)`` — rows is None whenever
    status is ``missing``/``corrupt``."""
    return _verify_block(path, meta, want_rows=True)


def heal_block(outdir: str, manifest: dict | None, fname: str, wid: int,
               graph: Graph, dc: DistributionController,
               status: str = "corrupt", reason: str = "") -> np.ndarray:
    """The shared self-heal sequence of both load paths
    (``CPDOracle.load`` and the engine's ``load_shard_rows``):
    quarantine the bad block, rebuild it in place from the graph
    (``build_worker_shard`` with resume recomputes exactly the blocks
    whose ledger/digest check fails — here, only the quarantined one),
    reload, and refresh the manifest entry when the rebuilt digest
    differs from the recorded one — otherwise every later load would
    re-flag the healthy rebuild as corrupt and rebuild it again.
    Returns the rebuilt rows; raises ``ValueError`` when the rebuild
    itself cannot produce a loadable block."""
    path = os.path.join(outdir, fname)
    qpath = quarantine(path)
    replica = block_file_replica(fname)
    meta = (manifest or {}).get("blocks", {}).get(fname)
    log.warning("CPD block %s is %s (%s); %srebuilding from the graph",
                fname, status, reason,
                f"quarantined to {qpath}; " if qpath else "")
    with obs_trace.span("cpd.rebuild", file=fname, wid=wid,
                        replica=replica):
        if replica:
            # a replica heals from its primary when one is on disk
            # (digest-valid copy), recomputing only as a fallback
            copy_replica_blocks(dc, wid, replica, outdir)
        # the rebuild keeps the block's recorded codec so a healed
        # compressed index stays compressed (and vice versa) — the
        # manifest, not the process env, owns the block's format
        build_worker_shard(graph, dc, wid, outdir, replica=replica,
                           codec=(meta or {}).get("codec", "raw"))
    rows, _status2, reason2 = load_verified_block(path, None)
    if rows is None:
        raise ValueError(
            f"CPD block {fname} in {outdir} could not be rebuilt: "
            f"{reason2} (original fault: {reason})")
    M_BLOCKS_REBUILT.inc()
    new_digest = digest_file(path)
    if meta is not None and meta.get("digest") != new_digest:
        if meta.get("digest"):
            log.warning(
                "rebuilt %s has digest %s != manifest %s (different "
                "build kernel?); refreshing the manifest entry",
                fname, new_digest, meta["digest"])
        new_meta = {"digest": new_digest, "shape": list(rows.shape),
                    "dtype": str(rows.dtype)}
        if is_container(rows):
            new_meta["codec"] = block_codec(np.asarray(rows))
        manifest["blocks"][fname] = new_meta
        atomic_write_json(os.path.join(outdir, "index.json"), manifest)
    # callers serve rows, not containers
    return maybe_decode_rows(rows)


def read_manifest(outdir: str) -> dict:
    with open(os.path.join(outdir, "index.json")) as f:
        return json.load(f)


def verify_index(outdir: str, dc: DistributionController | None = None,
                 manifest: dict | None = None) -> dict:
    """Check-only integrity pass over a CPD index: every manifest block
    is digest/shape-verified in place (``make_cpds --verify``, and the
    bench's post-build gate). Returns a report dict::

        {"total": N, "ok": n, "unverified": [...],   # no digest (v1)
         "missing": [...], "corrupt": [{"file","reason"}, ...],
         "fatal": "..."}                              # manifest-level

    ``dc`` additionally cross-checks the partition quadruple. Mapped to
    exit codes by :func:`verify_exit_code` (0/3/4 clean/degraded/
    corrupt, the campaign driver's convention)."""
    report: dict = {"total": 0, "ok": 0, "unverified": [],
                    "missing": [], "corrupt": []}
    if manifest is None:
        try:
            manifest = read_manifest(outdir)
        except (OSError, ValueError) as e:
            report["fatal"] = f"no readable manifest in {outdir}: {e}"
            return report
    if dc is not None:
        try:
            validate_manifest(manifest, dc, outdir)
        except ValueError as e:
            report["fatal"] = str(e)
            return report
    blocks_meta = manifest.get("blocks", {})
    all_files = (list(manifest.get("files", []))
                 + list(manifest.get("replica_files", [])))
    report["total"] = len(all_files)
    for fname in all_files:
        with obs_trace.span("cpd.verify", file=fname):
            status, reason = check_block(os.path.join(outdir, fname),
                                         blocks_meta.get(fname))
        if status == "ok":
            M_BLOCKS_VERIFIED.inc()
            report["ok"] += 1
        elif status == "unverified":
            report["unverified"].append(fname)
        elif status == "missing":
            M_BLOCKS_CORRUPT.inc()
            report["missing"].append(fname)
        else:
            M_BLOCKS_CORRUPT.inc()
            report["corrupt"].append({"file": fname, "reason": reason})
    return report


def anti_entropy(outdir: str, dc: DistributionController,
                 graph: Graph | None = None,
                 manifest: dict | None = None, heal: bool = True) -> dict:
    """Replica anti-entropy pass: cross-check every replica block's
    crc32 digest against its PRIMARY's (the source of truth — primaries
    are verified by the normal load/verify paths), quarantining and
    healing divergent replicas in place.

    For each shard block and replica rank, the pass compares the
    on-disk replica digest to the primary's manifest/on-disk digest. A
    mismatch books ``replica_digest_mismatches_total`` and — with
    ``heal=True`` — quarantines the replica (``<file>.quarantined``)
    and re-materializes it from the primary (or from the graph when
    ``graph`` is given and the primary itself is unreadable), then
    refreshes the manifest entry. Divergence here means a torn/rotted
    replica OR a primary rebuilt under a different kernel since the
    replica was copied; either way the primary wins.

    Returns ``{"checked": n, "mismatched": [...], "healed": [...],
    "missing_primary": [...]}``. No-op (all zeros) at R=1.
    """
    report: dict = {"checked": 0, "mismatched": [], "healed": [],
                    "missing_primary": []}
    if dc.replication <= 1:
        return report
    if manifest is None:
        try:
            manifest = read_manifest(outdir)
        except (OSError, ValueError):
            manifest = None
    blocks_meta = (manifest or {}).get("blocks", {})
    manifest_dirty = False
    bs = dc.block_size
    for shard in range(dc.maxworker):
        n_blocks = (dc.n_owned(shard) + bs - 1) // bs
        for bid in range(n_blocks):
            prim = shard_block_name(shard, bid)
            prim_path = os.path.join(outdir, prim)
            prim_meta = blocks_meta.get(prim)
            prim_digest = (prim_meta or {}).get("digest")
            if prim_digest is None:
                try:
                    prim_digest = digest_file(prim_path)
                except OSError:
                    report["missing_primary"].append(prim)
                    continue      # nothing to cross-check against
            for r in range(1, dc.replication):
                rname = shard_block_name(shard, bid, r)
                rpath = os.path.join(outdir, rname)
                report["checked"] += 1
                try:
                    got = digest_file(rpath)
                except OSError:
                    got = None        # missing replica = divergent
                if got == prim_digest:
                    continue
                M_REPLICA_MISMATCH.inc()
                report["mismatched"].append(
                    {"file": rname, "digest": got,
                     "primary_digest": prim_digest})
                if not heal:
                    continue
                with obs_trace.span("cpd.anti_entropy", file=rname,
                                    shard=shard, replica=r):
                    quarantine(rpath)
                    copied = copy_replica_blocks(dc, shard, r, outdir)
                    if rname not in copied and graph is not None:
                        # recompute with the primary's codec (see
                        # build_replica_shards) so the healed digest
                        # can converge with the cross-check
                        build_worker_shard(
                            graph, dc, shard, outdir, replica=r,
                            codec=(prim_meta or {}).get(
                                "codec", _primary_codec(outdir,
                                                        shard)))
                rows, status, reason = load_verified_block(rpath, None)
                if rows is None:
                    log.error("anti-entropy could not heal %s: %s "
                              "(%s)", rname, status, reason)
                    continue
                report["healed"].append(rname)
                new_digest = digest_file(rpath)
                if (manifest is not None
                        and blocks_meta.get(rname, {}).get("digest")
                        != new_digest):
                    new_meta = {"digest": new_digest,
                                "shape": list(rows.shape),
                                "dtype": str(rows.dtype)}
                    if is_container(rows):
                        new_meta["codec"] = block_codec(
                            np.asarray(rows))
                    blocks_meta[rname] = new_meta
                    manifest_dirty = True
    if manifest_dirty:
        # one atomic manifest rewrite for the whole pass, not one per
        # healed block
        manifest["blocks"] = blocks_meta
        atomic_write_json(os.path.join(outdir, "index.json"), manifest)
    if report["mismatched"]:
        log.warning("anti-entropy: %d/%d replica block(s) diverged "
                    "from their primary (%d healed)",
                    len(report["mismatched"]), report["checked"],
                    len(report["healed"]))
    return report


def adopt_shard_blocks(graph: Graph, dc: DistributionController,
                       shard: int, outdir: str) -> dict:
    """Adopter catch-up for a membership ownership transfer
    (``parallel.membership``): make shard ``shard``'s PRIMARY block set
    servable on this filesystem — every block digest-verified against
    the manifest, anything missing/torn healed through the shared
    quarantine→copy→rebuild path (``heal_block``: a digest-valid
    replica set is copied before any recompute). Idempotent and
    crash-resumable for free: verification re-runs in O(read), and the
    heal path journals rebuilt blocks through the build ledger exactly
    like a normal build — a joining worker killed mid catch-up re-pays
    only the blocks that never landed.

    Returns ``{"shard", "blocks", "ok", "unverified", "healed": [...]}``;
    raises when a block can neither be verified nor healed (the
    migration must not commit over it)."""
    try:
        manifest = read_manifest(outdir)
    except (OSError, ValueError):
        manifest = None             # pre-manifest build: heal from graph
    if manifest is not None:
        check_manifest_version(manifest, outdir)
    blocks_meta = (manifest or {}).get("blocks", {})
    bs = dc.block_size
    n_blocks = (dc.n_owned(int(shard)) + bs - 1) // bs
    report: dict = {"shard": int(shard), "blocks": n_blocks, "ok": 0,
                    "unverified": 0, "healed": []}
    for bid in range(n_blocks):
        fname = shard_block_name(int(shard), bid)
        path = os.path.join(outdir, fname)
        with obs_trace.span("reshard.adopt", file=fname, shard=shard):
            status, reason = check_block(path, blocks_meta.get(fname))
            if status == "ok":
                report["ok"] += 1
            elif status == "unverified":
                report["unverified"] += 1
            else:
                M_BLOCKS_CORRUPT.inc()
                heal_block(outdir, manifest, fname, int(shard), graph,
                           dc, status=status, reason=reason)
                report["healed"].append(fname)
        M_BLOCKS_ADOPTED.inc()
    return report


def verify_exit_code(report: dict) -> int:
    """0 clean (every block ok or legacy-unverified), 3 degraded (some
    blocks bad), 4 corrupt (manifest unreadable/mismatched, or no block
    survived) — mirroring ``process_query``'s 0/3/4 convention."""
    if report.get("fatal"):
        return 4
    bad = len(report["missing"]) + len(report["corrupt"])
    if bad == 0:
        return 0
    good = report["ok"] + len(report["unverified"])
    return 3 if good > 0 else 4


class CPDOracle:
    def __init__(self, graph: Graph, controller: DistributionController,
                 mesh=None):
        self.graph = graph
        self.dc = controller
        self.mesh = mesh if mesh is not None else make_mesh(
            n_workers=min(controller.maxworker, len(jax.devices())))
        if self.mesh.shape[WORKER_AXIS] != controller.maxworker:
            raise ValueError(
                f"mesh worker axis {self.mesh.shape[WORKER_AXIS]} != "
                f"maxworker {controller.maxworker}; partmethod=tpu requires "
                "one mesh shard per worker")
        self.dg = DeviceGraph.from_graph(graph)
        self.targets_wr = pad_targets(controller)
        self.fm = None     # int8 [W, R, N], sharded on worker axis
        self.dists = None  # optional int32 [W, R, N] (build(store_dists=True))
        #: per-diff PADDED device weight buffers for the mat family
        #: (keyed by the caller's w_key, LRU-bounded like the engine's
        #: weight cache): a serving frontend answers many mat rows
        #: under one diff, and re-padding + re-uploading [M+1] ints per
        #: row would dominate the collective it feeds
        self._mat_weights: dict = {}
        # one log line per oracle when a pallas-requested batch falls
        # back to XLA on the VMEM-fit check (not one per query call)
        self._walk_fallback_logged = False

    # ------------------------------------------------------------- build
    def build(self, chunk: int = 0, max_iters: int = 0,
              store_dists: bool = False,
              method: str = "auto") -> "CPDOracle":
        """Precompute all first-move rows, sharded over the mesh.

        ``store_dists=True`` also keeps the converged distance table (4x
        the fm memory) enabling :meth:`query_dist` — free-flow answers by
        one gather instead of a path walk. Distances are free-flow only
        and are not persisted by :meth:`save` (they are a pure derivative
        of the graph; rebuild to get them back).

        ``method``: ``"sweep"`` forces the fast-sweeping build, ``"shift"``
        the gather-free shift relaxation, ``"frontier"`` the
        delta-stepping queue, ``"ell"``/``"ellsplit"`` the (split)
        padded-ELL gather; ``"auto"`` resolves per
        :func:`pick_build_kernel`.
        """
        kind, structure = pick_build_kernel(self.graph, method)
        if store_dists:
            self.fm, self.dists = build_fm_sharded(
                self.dg, self.targets_wr, self.mesh, chunk=chunk,
                max_iters=max_iters, with_dists=True,
                kernel=(kind, structure))
        else:
            self.fm = build_fm_sharded(self.dg, self.targets_wr, self.mesh,
                                       chunk=chunk, max_iters=max_iters,
                                       kernel=(kind, structure))
        return self

    # ------------------------------------------------------- persistence
    def save(self, outdir: str, codec: str | None = None) -> None:
        """Write the CPD index: one .npy per (worker, block) + manifest.

        ``codec``: persist blocks compressed (``models.resident``
        containers; None resolves ``DOS_CPD_RESIDENT`` — the ``raw``
        default keeps the legacy byte-identical layout). Per-block
        degrade to raw when not viable; the manifest's ``blocks``
        entries record the codec that applied (unknown-key tolerant).

        Multi-controller safe: with >1 JAX process each WORKER's slice
        is allgathered separately (its shards live on non-addressable
        devices) and only process 0 writes — host memory peaks at 1/W of
        the table (at the README's NY scale: 8.7 GB instead of 70 GB per
        controller), and concurrent controllers never race on the shared
        index directory."""
        if self.fm is None:
            raise RuntimeError("build() or load() before save()")
        codec_req = resident_choice() if codec is None else codec
        multi = jax.process_count() > 1
        if multi:
            from ..parallel.multihost import is_primary
            primary = is_primary()
        else:
            primary = True
        if primary:
            os.makedirs(outdir, exist_ok=True)
        bs = self.dc.block_size
        block_meta: dict[str, dict] = {}
        for wid in range(self.dc.maxworker):
            n_owned = self.dc.n_owned(wid)
            # ONE fetch per worker: bounded host memory (1/W of the
            # table) without per-block transfer round trips (~90 ms
            # fixed each on a tunneled link). Every process participates
            # in the gather (collective); only the primary writes.
            rows_w = _host(self.fm[wid, :n_owned])
            if primary:
                for b0 in range(0, n_owned, bs):
                    fname = shard_block_name(wid, b0 // bs)
                    arr = np.ascontiguousarray(
                        rows_w[b0:min(b0 + bs, n_owned)])
                    enc = encode_block(arr, codec_req)
                    blk_codec = None
                    if enc is not None:
                        arr, blk_codec = enc
                    digest = atomic_save_npy(
                        os.path.join(outdir, fname), arr)
                    block_meta[fname] = {"digest": digest,
                                         "shape": list(arr.shape),
                                         "dtype": str(arr.dtype)}
                    if blk_codec is not None:
                        block_meta[fname]["codec"] = blk_codec
            del rows_w
        if primary:
            write_index_manifest(
                outdir, self.dc,
                rows_per_worker=int(self.targets_wr.shape[1]),
                block_meta=block_meta)

    def load(self, outdir: str, heal: bool = True) -> "CPDOracle":
        """Load a saved index onto the mesh, validating partition
        consistency (the reference keeps build and serve consistent by
        passing the same partmethod/partkey quadruple everywhere; we
        verify it) AND per-block content: every block is digest/shape
        checked as it loads (v2 manifests), so a torn write or bit-rot
        fails here with a per-block diagnostic instead of poisoning
        queries.

        ``heal=True`` (default): a missing/corrupt block is quarantined
        (``<file>.quarantined``) and rebuilt in place from the graph —
        the oracle always has it resident — then re-verified; the
        manifest entry is refreshed if the rebuilt digest differs (e.g.
        the original index predates the current kernel selection).
        ``heal=False`` raises on the first bad block instead."""
        manifest = read_manifest(outdir)
        validate_manifest(manifest, self.dc, outdir)
        blocks_meta = manifest.get("blocks", {})
        w = self.dc.maxworker
        r = self.targets_wr.shape[1]
        fm = np.full((w, r, self.graph.n), -1, np.int8)
        bs = self.dc.block_size
        for fname in manifest["files"]:
            stem = fname[:-len(".npy")]
            _, wpart, bpart = stem.split("-")
            wid, bid = int(wpart[1:]), int(bpart[1:])
            path = os.path.join(outdir, fname)
            meta = blocks_meta.get(fname)
            with obs_trace.span("cpd.verify", file=fname):
                rows, status, reason = load_verified_block(path, meta)
            if rows is None:
                M_BLOCKS_CORRUPT.inc()
                if not heal:
                    raise ValueError(
                        f"CPD block {fname} in {outdir} is {status}: "
                        f"{reason}")
                rows = heal_block(outdir, manifest, fname, wid,
                                  self.graph, self.dc,
                                  status=status, reason=reason)
            elif status == "ok":
                # only digest-checked blocks count as verified; v1
                # (digest-less) blocks load fine but stay unverified
                M_BLOCKS_VERIFIED.inc()
            # compressed containers inflate here: the mesh oracle is
            # raw-resident (its [W, R, N] tensor shards over workers);
            # compressed RESIDENCY is the ShardEngine's serving path
            rows = maybe_decode_rows(rows)
            fm[wid, bid * bs: bid * bs + len(rows)] = rows
        self.fm = jax.device_put(fm, worker_sharding(self.mesh, rank=3))
        return self

    # ------------------------------------------------------------- query
    def _length_estimate(self, queries: np.ndarray) -> np.ndarray:
        return length_estimate(self.graph, queries[:, 0], queries[:, 1])

    def route(self, queries: np.ndarray, active_worker: int = -1):
        """Pack (s, t) queries into mesh-shaped [D, W, Q] arrays.

        Returns ``(t_rows, s, t, valid, scatter)`` where ``scatter`` maps
        each input query to its (d, w, q) slot for unpacking results.

        Within each worker group, queries are ordered by expected walk
        length (:meth:`_length_estimate`) so the kernel's bucketed
        while_loops (``ops.table_search`` ``n_buckets``) each halt at
        their own bucket's max length instead of the batch max.
        """
        queries = np.asarray(queries, np.int64)
        nq = len(queries)
        d = self.mesh.shape[DATA_AXIS]
        w = self.dc.maxworker
        wids = self.dc.worker_of(queries[:, 1])
        rows = self.dc.owned_index_of(queries[:, 1])

        active = np.ones(nq, bool) if active_worker == -1 \
            else wids == active_worker
        # round-robin each worker's queries over the data axis (vectorized):
        # the k-th query of worker w goes to data slot k % d, column k // d
        slot_d = np.zeros(nq, np.int64)
        slot_q = np.zeros(nq, np.int64)
        est = self._length_estimate(queries)
        # sort by (worker, est): worker-major grouping as before; est
        # ordering within a group makes slot_q ascend with walk length
        idxs = np.nonzero(active)[0][np.lexsort(
            (est[active], wids[active]))]
        wids_sorted = wids[idxs]
        group_sizes = np.bincount(wids_sorted, minlength=w)
        starts = np.concatenate([[0], np.cumsum(group_sizes)[:-1]])
        seq = np.arange(len(idxs)) - np.repeat(starts, group_sizes)
        slot_d[idxs] = seq % d
        slot_q[idxs] = seq // d
        qmax = max(int(np.ceil(group_sizes.max() / d)) if len(idxs) else 0, 1)
        # bucket the padded length to the next power of two: stable shapes
        # across calls -> no recompilation when the batch mix shifts
        qmax = 1 << (qmax - 1).bit_length()

        s_arr = np.zeros((d, w, qmax), np.int32)
        t_arr = np.zeros((d, w, qmax), np.int32)
        r_arr = np.zeros((d, w, qmax), np.int32)
        valid = np.zeros((d, w, qmax), bool)
        s_arr[slot_d[active], wids[active], slot_q[active]] = queries[active, 0]
        t_arr[slot_d[active], wids[active], slot_q[active]] = queries[active, 1]
        r_arr[slot_d[active], wids[active], slot_q[active]] = rows[active]
        valid[slot_d[active], wids[active], slot_q[active]] = True
        scatter = (active, slot_d, wids, slot_q)
        return r_arr, s_arr, t_arr, valid, scatter

    @staticmethod
    def _unroute(scatter, nq: int, arrays, lead_flags):
        """Scatter routed ``[D, W, Q, ...]`` device results back to input
        query order (the inverse of :meth:`route`'s packing). Arrays
        flagged in ``lead_flags`` carry a leading per-diff axis
        (``[Dd, D, W, Q]``) that is preserved. Bool arrays come back
        bool; everything else int64. Inactive queries stay zero, the
        reference's ``-w`` filter semantics (``process_query.py:59``)."""
        active, sd, sw, sq = scatter
        outs = []
        for a, lead in zip(arrays, lead_flags):
            a = np.asarray(a)
            dt = bool if a.dtype == np.bool_ else np.int64
            if lead:
                out = np.zeros((a.shape[0], nq) + a.shape[4:], dt)
                out[:, active] = a[:, sd[active], sw[active], sq[active]]
            else:
                out = np.zeros((nq,) + a.shape[3:], dt)
                out[active] = a[sd[active], sw[active], sq[active]]
            outs.append(out)
        return outs

    def query(self, queries: np.ndarray, w_query: np.ndarray | None = None,
              k_moves: int = -1, active_worker: int = -1,
              max_steps: int = 0):
        """Answer queries in input order.

        ``w_query``: perturbed edge weights (file order), None = free flow.
        Returns ``(cost, plen, finished)`` int64/bool arrays [Q]; queries
        outside ``active_worker`` (when set) come back cost 0 / unfinished,
        like the reference's ``-w`` filter drops them
        (``process_query.py:59``).
        """
        if self.fm is None:
            raise RuntimeError("build() or load() before query()")
        r_arr, s_arr, t_arr, valid, scatter = self.route(
            queries, active_worker)
        # free-flow weights are already device-resident; only diffed runs
        # pay a fresh host->device upload
        w_pad = self.dg.w_pad if w_query is None else jnp.asarray(
            self.graph.padded_weights(w_query), jnp.int32)
        outs = _host_tree(query_sharded(
            self.dg, self.fm, r_arr, s_arr, t_arr, valid, w_pad, self.mesh,
            k_moves=k_moves, max_steps=max_steps,
            kernel=self._walk_kernel(r_arr.shape)))
        return tuple(self._unroute(scatter, len(queries), outs,
                                   (False, False, False)))

    def _walk_kernel(self, routed_shape) -> str:
        """Resolve ``DOS_WALK_KERNEL`` for one routed batch: ``auto``
        picks the Pallas-fused walk on real TPU backends, and a
        pallas choice whose per-device working set exceeds the VMEM
        budget degrades to the XLA reference walk (logged once). The
        policy itself lives in ``ops.pallas_walk.choose_walk_kernel``
        — this method only supplies the shard-local batch size."""
        from ..ops.pallas_walk import choose_walk_kernel

        dgrid, _, qmax = routed_shape
        # the shard-local flat batch: [D/|data|, 1, Q] reshaped to -1
        q_local = max(dgrid // max(self.mesh.shape[DATA_AXIS], 1), 1) \
            * qmax
        kernel, why = choose_walk_kernel(
            self.dg.n, self.dg.k, int(self.dg.w_pad.shape[0]) - 1,
            q_local)
        if why and not self._walk_fallback_logged:
            log.warning("%s", why)
            self._walk_fallback_logged = True
        return kernel

    def query_multi(self, queries: np.ndarray,
                    w_diffs: list[np.ndarray | None],
                    active_worker: int = -1, max_steps: int = 0):
        """Answer queries under D congestion diffs in ONE fused walk.

        The reference campaign runs one round per diff file over the
        same scenario (``process_query.py:178``), re-walking every query
        each round. Trajectories are diff-independent (moves follow the
        free-flow table; diffs only change cost accumulation), so the
        fused kernel walks once and accumulates every diff's costs —
        ~2D/3 fewer gathers than D sequential rounds
        (:func:`~..ops.table_search.table_search_multi`).

        ``w_diffs``: list of per-diff edge-weight arrays (file order);
        ``None`` entries mean free flow. Returns ``(cost [D, Q],
        plen [Q], finished [Q])`` in input query order.
        """
        if self.fm is None:
            raise RuntimeError("build() or load() before query_multi()")
        if not w_diffs:
            raise ValueError("w_diffs must name at least one round")
        r_arr, s_arr, t_arr, valid, scatter = self.route(
            queries, active_worker)
        w_pads = self.graph.padded_weights_multi(w_diffs)
        outs = _host_tree(query_multi_sharded(
            self.dg, self.fm, r_arr, s_arr, t_arr, valid, w_pads,
            self.mesh, max_steps=max_steps))
        return tuple(self._unroute(scatter, len(queries), outs,
                                   (True, False, False)))

    def query_mat(self, s: int, targets,
                  w_query: np.ndarray | None = None,
                  w_key: str | None = None):
        """One ``mat`` family row — one source, K targets — with the
        JOIN ON MESH (``parallel.sharded.query_mat_sharded``): each
        shard walks the targets it owns and the dense ``[K]`` answer
        row assembles by a ``psum`` collective over the mesh axes,
        replacing the serving frontend's head-side fan-out/join (one
        future per target through queue + batcher + dispatcher).

        ``w_key``: a stable identity for ``w_query`` (the diff file
        path) — given one, the padded device weight buffer caches
        across rows (LRU, same bound discipline as the engine's
        per-diff cache), so serving many rows under one diff pays one
        upload, not one per row.

        Returns ``(cost [K] int64, finished [K] bool)`` in target
        order; an out-of-range target comes back unfinished with cost
        0 (the router cannot place it) rather than raising — the
        family layer encodes unanswered targets as ``-1`` either way.
        """
        if self.fm is None:
            raise RuntimeError("build() or load() before query_mat()")
        targets = np.asarray(targets, np.int64).reshape(-1)
        k = len(targets)
        ok = (targets >= 0) & (targets < self.graph.n)
        cost = np.zeros(k, np.int64)
        fin = np.zeros(k, bool)
        if not ok.any() or not (0 <= int(s) < self.graph.n):
            return cost, fin
        tgts = targets[ok]
        queries = np.stack(
            [np.full(len(tgts), int(s), np.int64), tgts], axis=1)
        r_arr, s_arr, t_arr, valid, scatter = self.route(queries)
        # each routed slot's position in the OUTPUT row: the on-mesh
        # scatter-add writes answers straight into target order, so
        # the host does no unroute at all
        active, sd, sw, sq = scatter
        slots = np.full(r_arr.shape, -1, np.int32)
        slots[sd, sw, sq] = np.arange(len(tgts), dtype=np.int32)
        w_pad = self._mat_w_pad(w_query, w_key)
        # the compiled row width pads to the next power of two: k is
        # CLIENT-controlled (one `mat` sentence per width), and an
        # un-padded width would compile-and-cache one program per
        # distinct k forever — the same stable-shape rule as route's
        # qmax and the engine's qpad. Pad slots never receive a
        # scatter, so the host just trims the row.
        k_pad = 1 << (len(tgts) - 1).bit_length()
        t0 = time.perf_counter()
        row_c, row_f = _host_tree(query_mat_sharded(
            self.dg, self.fm, r_arr, s_arr, t_arr, valid, slots,
            w_pad, self.mesh, k_out=k_pad))
        M_MESH_COLLECTIVE.observe(time.perf_counter() - t0)
        cost[ok] = np.asarray(row_c, np.int64)[:len(tgts)]
        fin[ok] = np.asarray(row_f, bool)[:len(tgts)]
        return cost, fin

    def _mat_w_pad(self, w_query, w_key):
        """The padded device weights one mat row walks under — cached
        per ``w_key`` (LRU, engine-style bound) so repeated rows under
        one diff re-use the uploaded buffer."""
        if w_query is None:
            return self.dg.w_pad
        if w_key is not None and w_key in self._mat_weights:
            return self._mat_weights[w_key]
        w_pad = jnp.asarray(self.graph.padded_weights(w_query),
                            jnp.int32)
        if w_key is not None:
            self._mat_weights[w_key] = w_pad
            while len(self._mat_weights) > 4:
                self._mat_weights.pop(next(iter(self._mat_weights)))
        return w_pad

    # ------------------------------------------------- prepared tables
    def table_memory_bytes(self) -> int:
        """Device bytes the prepared tables will occupy: int32 cost +
        sign-packed plen (int16 when N < 2^15) per (worker, row, node)."""
        from ..ops.pointer_doubling import plen_dtype

        w, r = self.targets_wr.shape
        per_entry = 4 + jnp.dtype(plen_dtype(self.graph.n)).itemsize
        return w * r * self.graph.n * per_entry

    @property
    def TABLE_BUDGET(self) -> int:
        """Per-device budget for prepared tables (bytes). Read lazily so
        DOS_TABLE_BUDGET_GB works as a runtime knob; malformed values
        fall back to the default (8 GB — conservative v5e headroom next
        to the resident fm + dists) instead of crashing."""
        gb = env_cast("DOS_TABLE_BUDGET_GB", 8.0, float)
        return int((gb if gb > 0 else 8.0) * 1e9)

    def prepare_weights(self, w_query: np.ndarray | None = None,
                        max_len: int = 0, chunk: int = 2048):
        """Pointer-doubling: precompute cost + packed plen for EVERY
        (source, owned-target) pair under ``w_query`` in O(log L) sweeps
        (``ops.pointer_doubling``). After this, :meth:`query_table`
        answers any query on these weights with one gather — the
        amortization path for huge campaigns, including congestion-diffed
        rounds where :meth:`query_dist` does not apply.

        **Measured trade (BENCH_r04 captures, 9216-node shard, v5e):**
        prepare ~19 s, lookups ~320-520k q/s vs the ~200-310k q/s
        diffed walk → break-even ~9-34M queries per diff round (the
        bench recomputes ``table_breakeven_queries`` from each run's
        own timings; it divides by the small walk-vs-lookup gap, hence
        the band — every point is the 10M-query-campaign regime).
        :meth:`prepare_weights_multi` divides the per-diff break-even
        by ~D. Memory: 6-8 bytes/entry = 6-8x the fm shard; calls
        whose tables exceed the per-device budget
        (``DOS_TABLE_BUDGET_GB``, default 8) raise with the math instead
        of faulting mid-campaign.

        ``chunk`` bounds the per-device rows doubled at once (several
        [rows, N] int32 live arrays per sweep; oversized batches fault).

        Returns an opaque tables handle to pass to :meth:`query_table`.
        """
        if self.fm is None:
            raise RuntimeError("build() or load() before prepare_weights()")
        need = self.table_memory_bytes()
        # tables shard over the WORKER axis only (build_tables_sharded
        # out_specs) — they are REPLICATED across the data axis, so the
        # per-device share divides by W, not by total device count
        n_w = max(self.mesh.shape[WORKER_AXIS], 1)
        budget = self.TABLE_BUDGET
        if need / n_w > budget:
            w, r = self.targets_wr.shape
            raise ValueError(
                f"prepared tables need {need / 1e9:.1f} GB "
                f"({w}x{r}x{self.graph.n} entries x "
                f"{need // (w * r * self.graph.n)} B, sharded over {n_w} "
                f"worker shard(s) = {need / n_w / 1e9:.1f} GB/device) — "
                f"over the {budget / 1e9:.1f} GB/device budget "
                "(DOS_TABLE_BUDGET_GB). At this scale serve via the walk "
                "or StreamedCPDOracle instead; the table trade only pays "
                "past ~10M queries per diff round anyway (measured "
                "break-even band, bench table_breakeven_queries).")
        w_pad = (self.dg.w_pad if w_query is None
                 else jnp.asarray(self.graph.padded_weights(w_query),
                                  jnp.int32))
        return self._chunked_tables(
            lambda fm_, tw_: build_tables_sharded(
                self.dg, fm_, tw_, w_pad, self.mesh, max_len=max_len),
            chunk)

    def _chunked_tables(self, build_one, chunk: int):
        """Run a sharded table builder over equal padded row-chunks of
        the target axis (one compiled program regardless of R) and trim
        the concatenated result — the shared scaffolding of
        :meth:`prepare_weights` and :meth:`prepare_weights_multi`."""
        r = self.targets_wr.shape[1]
        if chunk <= 0 or chunk >= r:
            return build_one(self.fm, self.targets_wr)
        pad = (-r) % chunk
        tw = self.targets_wr
        fm = self.fm
        if pad:
            tw = np.concatenate(
                [tw, np.full((tw.shape[0], pad), -1, tw.dtype)], axis=1)
            fm = jnp.concatenate(
                [fm, jnp.full((fm.shape[0], pad, fm.shape[2]), -1,
                              fm.dtype)], axis=1)
        parts = [build_one(fm[:, i:i + chunk], tw[:, i:i + chunk])
                 for i in range(0, tw.shape[1], chunk)]
        cat = lambda xs: jnp.concatenate(xs, axis=1)[:, :r]  # noqa: E731
        c, p = zip(*parts)
        return cat(c), cat(p)

    def query_table(self, tables, queries: np.ndarray,
                    active_worker: int = -1):
        """Answer queries from :meth:`prepare_weights` tables.

        Returns ``(cost, plen, finished)`` — identical to :meth:`query`
        on the same weights (tests pin this), at gather speed.
        """
        r_arr, s_arr, t_arr, valid, scatter = self.route(
            queries, active_worker)
        outs = _host_tree(query_tables_sharded(
            tables, r_arr, s_arr, valid, self.mesh))
        return tuple(self._unroute(scatter, len(queries), outs,
                                   (False, False, False)))

    def prepare_weights_multi(self, w_diffs: list[np.ndarray | None],
                              max_len: int = 0, chunk: int = 1024):
        """Fused pointer-doubling tables for D diffs at once.

        The doubling recursion is shared across diffs (free-flow
        successor function), so D diff rounds' cost tables cost ~ONE
        prepare's gather traffic
        (:func:`~..ops.pointer_doubling.doubled_tables_multi`) — the
        amortization regime of a multi-diff bulk campaign. Memory:
        ``4D + 2-4`` bytes per (row, node) entry, budget-gated like
        :meth:`prepare_weights`. ``chunk`` defaults lower than the
        single-diff path because each sweep's live working set widens
        by the D cost planes.

        Returns a tables handle for :meth:`query_table_multi`.
        """
        if self.fm is None:
            raise RuntimeError(
                "build() or load() before prepare_weights_multi()")
        if not w_diffs:
            raise ValueError("w_diffs must name at least one round")
        from ..ops.pointer_doubling import plen_dtype

        d = len(w_diffs)
        w, r = self.targets_wr.shape
        per_entry = 4 * d + jnp.dtype(plen_dtype(self.graph.n)).itemsize
        need = w * r * self.graph.n * per_entry
        n_w = max(self.mesh.shape[WORKER_AXIS], 1)
        budget = self.TABLE_BUDGET
        if need / n_w > budget:
            raise ValueError(
                f"fused tables for {d} diffs need {need / 1e9:.1f} GB "
                f"({per_entry} B/entry over {n_w} worker shard(s) = "
                f"{need / n_w / 1e9:.1f} GB/device) — over the "
                f"{budget / 1e9:.1f} GB/device budget "
                "(DOS_TABLE_BUDGET_GB). Prepare fewer diffs per call or "
                "serve via the fused walk (query_multi) instead.")
        w_pads = self.graph.padded_weights_multi(w_diffs)
        return self._chunked_tables(
            lambda fm_, tw_: build_tables_multi_sharded(
                self.dg, fm_, tw_, w_pads, self.mesh, max_len=max_len),
            chunk)

    def query_table_multi(self, tables, queries: np.ndarray,
                          active_worker: int = -1):
        """Answer queries from :meth:`prepare_weights_multi` tables:
        one ``[D]``-wide gather per query. Returns ``(cost [D, Q],
        plen [Q], finished [Q])`` — row d identical to
        :meth:`query_table` on diff d's tables (tests pin this)."""
        r_arr, s_arr, t_arr, valid, scatter = self.route(
            queries, active_worker)
        outs = _host_tree(query_tables_multi_sharded(
            tables, r_arr, s_arr, valid, self.mesh))
        return tuple(self._unroute(scatter, len(queries), outs,
                                   (True, False, False)))

    def query_paths(self, queries: np.ndarray, k: int,
                    active_worker: int = -1):
        """Materialize each query's first ``k`` path nodes (the
        reference's ``--k-moves`` extraction, reference ``args.py:31-36``).

        Returns ``(nodes, moves)``: int64 ``[Q, k+1]`` — row q starts at
        ``s``, the last node repeats once the path ends — and the number
        of real moves taken (≤ k). Queries outside ``active_worker`` get
        all-zero rows, matching :meth:`query`'s filter semantics.
        """
        if self.fm is None:
            raise RuntimeError("build() or load() before query_paths()")
        if k <= 0:
            raise ValueError("k must be positive")
        r_arr, s_arr, t_arr, valid, scatter = self.route(
            queries, active_worker)
        outs = _host_tree(query_paths_sharded(
            self.dg, self.fm, r_arr, s_arr, t_arr, self.mesh, k=k))
        return tuple(self._unroute(scatter, len(queries), outs,
                                   (False, False)))

    def query_dist(self, queries: np.ndarray, active_worker: int = -1):
        """Free-flow fast path: answer d(s → t) by one sharded gather.

        Requires ``build(store_dists=True)``. Returns ``(cost, finished)``
        — no ``plen`` (no path is materialized; that is the point:
        distance-only answers need no extraction, SURVEY.md §5). Costs on
        a diffed graph still need :meth:`query`.
        """
        if self.dists is None:
            raise RuntimeError(
                "distance table not resident; build(store_dists=True)")
        r_arr, s_arr, t_arr, valid, scatter = self.route(
            queries, active_worker)
        cost = _host(query_dist_sharded(self.dists, r_arr, s_arr,
                                             self.mesh))
        nq = len(queries)
        active, sd, sw, sq = scatter
        out_c = np.zeros(nq, np.int64)
        out_f = np.zeros(nq, bool)
        got = cost[sd[active], sw[active], sq[active]]
        fin = got < int(INF)
        out_c[active] = np.where(fin, got, 0)
        out_f[active] = fin
        return out_c, out_f
