from .reference import (
    dijkstra, dist_to_target, first_move_to_target, first_move_matrix,
    table_search_walk,
)

__all__ = [
    "dijkstra", "dist_to_target", "first_move_to_target", "first_move_matrix",
    "table_search_walk",
]
