from .reference import (
    dijkstra, dist_to_target, first_move_to_target, first_move_matrix,
    table_search_walk,
)
from .astar import AstarStats, astar, min_cost_per_unit

__all__ = [
    "dijkstra", "dist_to_target", "first_move_to_target", "first_move_matrix",
    "table_search_walk",
    "AstarStats", "astar", "min_cost_per_unit",
]
