"""Worker-resident components: per-shard engine + FIFO server +
supervisor."""

from .engine import ShardEngine, load_shard_rows
from .server import FifoServer, stop_server
from .supervisor import WorkerSupervisor

__all__ = ["ShardEngine", "load_shard_rows", "FifoServer", "stop_server",
           "WorkerSupervisor"]
