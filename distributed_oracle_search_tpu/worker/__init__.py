"""Worker-resident components: per-shard engine + FIFO server."""

from .engine import ShardEngine, load_shard_rows
from .server import FifoServer, stop_server

__all__ = ["ShardEngine", "load_shard_rows", "FifoServer", "stop_server"]
