"""Per-shard query engine: the worker-resident compute path.

Role parity with the reference's resident ``fifo_auto`` process
(SURVEY.md §2.2 C3): load the graph, the congestion diff, and THIS worker's
CPD shard; then answer query batches for targets this shard owns. The
reference answers each query in a C++ loop over OpenMP threads; here the
whole batch is one XLA call — a vmapped first-move gather walk
(``ops.table_search``) on whatever single device this worker process owns
(TPU chip or CPU).

Runtime knobs honored per batch (reference ``process_query.py:149-160``):
``k_moves`` (move budget), ``itrs`` (repeat count; last result wins),
``no_cache`` (drop the per-diff weight cache). ``time`` (ns budget)
truncates INSIDE a batch like the reference's engine (reference
``args.py:30-57``): the length-sorted batch runs in fixed-size chunks
with the deadline checked between chunks, so an expired budget returns
partial ``finished`` counts (cheapest queries answered first; the first
chunk always runs so a minimal answer exists). Batches at or below one
chunk stay all-or-nothing — a single XLA call cannot stop mid-flight.
``threads``/``thread_alloc`` are accepted for wire parity but are no-ops
under XLA (SPMD inside one device replaces OpenMP, SURVEY.md §2.3).
"""

from __future__ import annotations

import glob
import os
import re
import threading
import time
from collections import OrderedDict

import numpy as np

from ..data.formats import read_diff
from ..data.graph import Graph
from ..obs import device as obs_device
from ..obs import metrics as obs_metrics
from ..obs import quantiles as obs_quantiles
from ..obs import trace as obs_trace
from ..parallel.partition import DistributionController
from ..testing import faults
from ..transport.wire import RuntimeConfig, StatsRow
from ..utils.env import env_cast
from ..utils.locks import OrderedLock
from ..utils.log import get_logger, set_worker_id

log = get_logger(__name__)

# declared at import so a snapshot shows the engine's phase histograms
# even before the first batch (obs/__init__.py maps these to the wire
# stats fields t_receive/t_astar/t_search)
M_RECEIVE = obs_metrics.histogram(
    "worker_receive_seconds", "batch prep incl. weights (t_receive)")
M_WEIGHTS = obs_metrics.histogram(
    "worker_weights_load_seconds", "diff read + device weight upload")
M_SEARCH = obs_metrics.histogram(
    "worker_search_seconds", "steady-state search call (t_astar)")
M_JIT = obs_metrics.histogram(
    "worker_jit_compile_seconds",
    "first call at a new (alg, shape, knobs) key — XLA compile + run, "
    "split out so steady-state latency stays clean")
M_BATCHES = obs_metrics.counter("worker_batches_total")
M_QUERIES = obs_metrics.counter("worker_queries_total")
M_DUPS = obs_metrics.counter(
    "worker_duplicate_queries_total",
    "queries answered from another identical (s, t) pair in the same "
    "batch — the kernel only runs each distinct pair once")
M_WALK_PALLAS = obs_metrics.counter(
    "walk_pallas_batches_total",
    "table-search batches answered by the Pallas-fused walk kernel "
    "(DOS_WALK_KERNEL selection, ops.pallas_walk)")
M_WALK_XLA = obs_metrics.counter(
    "walk_xla_batches_total",
    "table-search batches answered by the XLA reference walk "
    "(includes pallas-requested batches that fell back on VMEM fit)")
M_MESH_DEVICES = obs_metrics.gauge(
    "mesh_devices",
    "devices in this worker's local lane mesh (DOS_MESH_DEVICES "
    "resolution; 1 = the legacy single-device engine)")
M_MESH_WALK = obs_metrics.counter(
    "mesh_walk_batches_total",
    "table-search batches split across the worker's mesh lanes "
    "(per-device bucket subsets under shard_map, bit-identical unsort)")
M_WALK_COMPRESSED = obs_metrics.counter(
    "walk_compressed_batches_total",
    "table-search batches answered from a compressed-resident CPD "
    "shard (DOS_CPD_RESIDENT: pack4 decompress-on-tile in the Pallas "
    "kernel, or the XLA run-start decode feeding either kernel)")


def load_shard_rows(outdir: str, wid: int, dc=None, graph=None,
                    heal: bool = True, replica: int = 0) -> np.ndarray:
    """Load one worker's CPD rows from the block files the builder wrote
    (``cpd-w<wid>-b<bid>.npy``; the index manifest is optional so a shard
    can serve before the whole cluster's build completes).

    When the manifest is present its per-block digests are verified as
    the rows load; a corrupt/torn block is quarantined and — when the
    caller supplies ``graph`` and ``dc`` (``ShardEngine`` does) —
    rebuilt in place, else the load fails with the per-block diagnostic
    instead of serving garbage answers.

    ``replica``: load shard ``wid``'s rank-``replica`` REPLICA block set
    (``cpd-w<wid>-r<r>-b<bid>.npy``) — the failover copy a non-primary
    host serves from. When no replica blocks exist but the primary set
    shares this filesystem (the common shared-nfs deployment), the load
    falls back to the primary files: the rows are identical by
    construction, and a failover must not die on a missing copy of data
    that is sitting right there."""
    from ..models.cpd import (
        M_BLOCKS_CORRUPT, M_BLOCKS_VERIFIED, check_manifest_version,
        heal_block, load_verified_block, read_manifest, shard_block_name,
    )
    from ..models.resident import maybe_decode_rows

    manifest: dict | None = None
    try:
        manifest = read_manifest(outdir)
    except (OSError, ValueError):
        pass                       # pre-manifest partial build: no digests
    if manifest is not None:
        # same schema gate as CPDOracle.load: a NEWER manifest's digest
        # entries must not be misread into mass quarantine/rebuild
        check_manifest_version(manifest, outdir)
    blocks_meta = (manifest or {}).get("blocks", {})
    # name prefix up to the block id: primary names must NOT match
    # replica entries of the same shard (and vice versa)
    prefix = shard_block_name(wid, 0, replica)[:-len("00000.npy")]
    pat = os.path.join(outdir, f"{prefix}*.npy")
    files = sorted(glob.glob(pat),
                   key=lambda p: int(re.search(r"-b(\d+)\.npy$", p).group(1)))
    # the manifest knows blocks the glob cannot see (deleted on disk)
    manifested = sorted(
        (os.path.join(outdir, f) for f in blocks_meta
         if f.startswith(prefix)),
        key=lambda p: int(re.search(r"-b(\d+)\.npy$", p).group(1)))
    files = manifested if manifested else files
    if not files and replica:
        log.warning("no rank-%d replica blocks for shard %d in %s; "
                    "falling back to the primary block set (same rows, "
                    "shared filesystem)", replica, wid, outdir)
        return load_shard_rows(outdir, wid, dc=dc, graph=graph,
                               heal=heal)
    if not files:
        raise FileNotFoundError(f"no CPD blocks for worker {wid} in {outdir}")
    parts = []
    for path in files:
        fname = os.path.basename(path)
        with obs_trace.span("cpd.verify", file=fname, wid=wid):
            rows, status, reason = load_verified_block(
                path, blocks_meta.get(fname))
        if rows is None:
            M_BLOCKS_CORRUPT.inc()
            if not heal or graph is None or dc is None:
                raise ValueError(
                    f"CPD block {fname} in {outdir} is {status}: {reason}"
                    + ("" if heal else " (healing disabled)")
                    + ("" if graph is not None and dc is not None
                       else " — no graph/controller to rebuild from; "
                            "load degraded"))
            rows = heal_block(outdir, manifest, fname, wid, graph, dc,
                              status=status, reason=reason)
        elif status == "ok":
            # only digest-checked blocks count as verified (same rule
            # as CPDOracle.load)
            M_BLOCKS_VERIFIED.inc()
        # compressed containers (models.resident) inflate to dense rows
        # here; whether the RESIDENT table re-compresses is the
        # caller's policy (ShardEngine._make_resident)
        parts.append(maybe_decode_rows(rows))
    return np.concatenate(parts, axis=0)


class ShardEngine:
    def __init__(self, graph: Graph, dc: DistributionController, wid: int,
                 outdir: str, alg: str = "table-search",
                 shard: int | None = None, replica: int | None = None,
                 mesh=None):
        from ..ops import DeviceGraph
        from ..parallel.mesh import LANE_AXIS, make_worker_mesh

        if alg not in ("table-search", "astar"):
            raise ValueError(f"unknown algorithm {alg!r}")
        self.alg = alg
        self.graph = graph
        self.dc = dc
        self.wid = wid
        #: worker-local lane mesh (``DOS_MESH_DEVICES``; an explicit
        #: ``mesh=`` ctor arg wins): the engine drives EVERY lane —
        #: walk batches split into per-device bucket subsets, the fm
        #: table replicated across lanes. ``None`` = the legacy
        #: single-device engine, byte-identical behavior.
        self.mesh = mesh if mesh is not None else make_worker_mesh()
        self.n_lanes = (self.mesh.shape[LANE_AXIS]
                        if self.mesh is not None else 1)
        M_MESH_DEVICES.set(self.n_lanes)
        #: base index directory the rows loaded from — where epoch-
        #: tagged delta-rebuilt indexes (``models.cpd.epoch_index_dir``)
        #: are discovered for background promotion
        self.outdir = outdir
        #: diff epoch of the PROMOTED first-move table (0 = none yet);
        #: bumped by :meth:`promote_index` when a delta-rebuilt epoch
        #: index lands. The base table stays resident: batch dispatch
        #: is epoch-GATED (:meth:`_fm_for`), so only batches naming the
        #: promoted epoch's fused diff walk the new table. The gate
        #: state itself lives in ``_fm_promoted`` as ONE ``(epoch,
        #: table)`` reference (atomic publish under the GIL);
        #: ``index_epoch`` mirrors the epoch for observers
        self.index_epoch = 0
        self._fm_promoted: tuple | None = None
        self._promote_lock = OrderedLock("worker.ShardEngine.promote")
        #: the SHARD whose rows this engine answers — ``wid`` itself for
        #: a primary engine, another shard when this worker serves a
        #: replica (failover/hedge target). The rows load from the
        #: matching replica block set.
        self.shard = wid if shard is None else int(shard)
        #: which block set serves the rows: the rank within the shard's
        #: replica chain, derived from the controller unless the caller
        #: pins it (a membership-migration adopter serves the PRIMARY
        #: set of a shard whose chain it has not joined yet)
        if replica is not None:
            self.replica = int(replica)
        else:
            self.replica = (dc.replica_rank(self.shard, wid)
                            if self.shard != wid else 0)
        #: REPLICA LANE: with a lane mesh, replica rank r pins to mesh
        #: lane ``r % L`` — each hosted replica serves from its OWN
        #: device, so an R>1 deployment on one host gives the breaker/
        #: hedge/failover paths a real second compute target instead of
        #: R engines time-slicing one chip (what let the TPU backend's
        #: R=1 pin lift, ``cli.process_query``). The primary (rank 0)
        #: keeps the whole mesh and lane-splits its batches instead.
        self._lane_device = None
        if self.mesh is not None and self.replica:
            self._lane_device = list(self.mesh.devices.flat)[
                self.replica % self.n_lanes]
        #: device-batch rows per A* chunk; the deadline is checked
        #: between chunks (first chunk always runs)
        self.astar_chunk = 1024
        #: resident-codec bookkeeping (statusz / compressed bench):
        #: what DOS_CPD_RESIDENT actually resolved to for THIS shard
        #: and the device bytes it occupies ("raw"/0 for astar engines)
        self.resident_codec = "raw"
        self.resident_bytes = 0
        if alg == "table-search":  # astar needs no first-move shard
            rows = load_shard_rows(
                outdir, self.shard, dc=dc, graph=graph,
                replica=self.replica)
            if faults.inject("corrupt-resident", self.shard) is not None:
                # flip row 0 AFTER the digest-verified load: in-memory
                # rot no manifest check can see — only the scrubber's
                # dense-row compare (integrity.scrub) catches it
                rows = np.array(rows, np.int8, copy=True)
                rows[0, :] = np.where(rows[0, :] <= 0, 1, 0)
            self.fm = self._make_resident(rows)
            owned = dc.owned(self.shard)
            if len(owned) != self.fm.shape[0]:
                raise ValueError(
                    f"shard w{self.shard}: {self.fm.shape[0]} CPD rows "
                    f"but controller owns {len(owned)} nodes — "
                    "partition mismatch")
        else:
            self.fm = None
        dg = DeviceGraph.from_graph(graph)
        if self._lane_device is not None or self._lane_split:
            # graph arrays follow the fm placement: pinned to the
            # replica's lane, or replicated across the lanes the
            # primary's shard_map walks read from
            dg = DeviceGraph(*(self._place(a) for a in dg))
        self.dg = dg
        #: per-diff device weight buffers, LRU-bounded: the live-traffic
        #: plane swaps fused diffs every few seconds, and an unbounded
        #: cache would pin one HBM weights array per epoch forever. The
        #: bound is >= 2 by construction — the DOUBLE BUFFER: when an
        #: epoch swap lands, in-flight batches still pinned to the old
        #: fused file finish on its resident buffer while new batches
        #: warm the new one (raw host-side astar entries share the
        #: budget; a re-upload after eviction is a read+transfer, never
        #: a correctness event)
        self._weight_cache: OrderedDict[object, object] = OrderedDict()
        self._weight_keep = max(
            2, env_cast("DOS_TRAFFIC_WEIGHT_EPOCHS", 4, int))
        #: (alg, qpad, knobs) keys whose program has already run once —
        #: the first call at a new key pays XLA compilation and is
        #: recorded to ``worker_jit_compile_seconds`` instead of the
        #: steady-state ``worker_search_seconds`` histogram
        self._jit_seen: set[tuple] = set()
        #: device-resident graph arrays for the batched A* serving path
        #: (in-ELL, coords, per-diff padded weights) — uploaded once, not
        #: per request (ops.batched_astar ctx contract)
        self._astar_ctx: dict = {}
        #: path prefixes of the most recent extract batch (see answer())
        self.last_paths: tuple[np.ndarray, np.ndarray] | None = None
        #: one log line per engine when a pallas-requested batch falls
        #: back to XLA on the VMEM-fit check (not one per batch)
        self._walk_fallback_logged = False

    # ------------------------------------------------------------- mesh
    @property
    def _lane_split(self) -> bool:
        """Whether this engine splits its walk batches over mesh lanes:
        the PRIMARY engine of a mesh-driving worker does; replica
        engines pin to their own lane device instead; astar keeps the
        single-device batched kernel (its chunked deadline semantics
        are host-driven)."""
        return (self.mesh is not None and not self.replica
                and self.alg == "table-search")

    def _place(self, arr):
        """Device placement under the worker mesh: replica engines pin
        to their lane's device, the lane-splitting primary replicates
        across lanes (the shard's rows must be visible to every lane —
        any query's target row can be any row), and without a mesh this
        is the plain default-device upload."""
        import jax
        import jax.numpy as jnp
        from ..parallel.mesh import replicated

        if self._lane_device is not None:
            return jax.device_put(np.asarray(arr), self._lane_device)
        if self._lane_split:
            return jax.device_put(np.asarray(arr),
                                  replicated(self.mesh))
        return jnp.asarray(arr)

    def _make_resident(self, rows) -> object:
        """Materialize the resident first-move table under the
        ``DOS_CPD_RESIDENT`` policy (``models.resident``): the placed
        raw array (byte-identical legacy) or a :class:`CompressedFM`
        whose pack4/rle arrays live compressed in device memory and
        inflate per batch at the point of use. Placement (replica
        lane / mesh-replicated) is the same as the raw table's."""
        from ..models.resident import make_resident

        fm, codec = make_resident(rows, place=self._place)
        self.resident_codec = codec
        self.resident_bytes = int(fm.nbytes)
        return fm

    # ---------------------------------------------------------- promotion
    def _fm_for(self, difffile: str):
        """The table a batch walks: the promoted epoch table ONLY when
        the batch names the promoted epoch's fused diff file
        (``fused-e<N>.diff``), the base table otherwise. This gate is
        what keeps promotion safe under mixed traffic — an in-flight
        batch pinned to an older epoch (or a free-flow campaign batch)
        must keep its old-regime routes bit-identical, never pick up
        new-regime moves priced under its own weights. The published
        ``(epoch, table)`` pair is read ONCE — promotion swaps it as a
        single reference, so a concurrent promote can never tear the
        gate into comparing one epoch against another epoch's table."""
        promoted = self._fm_promoted        # one read: (epoch, table)
        if promoted is not None:
            from ..models.cpd import diff_epoch_of

            if diff_epoch_of(difffile) == promoted[0]:
                return promoted[1]
        return self.fm

    def promote_index(self, new_outdir: str, epoch: int) -> bool:
        """Make a delta-rebuilt epoch-tagged index servable under a
        running serve: load this shard's rows from ``new_outdir``
        (digest-verified like any load) and publish them as the
        PROMOTED table. Dispatch is epoch-gated (:meth:`_fm_for`): a
        batch naming that epoch's fused diff now gets OPTIMAL routes
        for the new regime instead of old-regime paths re-priced by
        query-time diff application, while every other batch — older
        epochs in flight, free flow — keeps walking the base table
        unchanged. Returns False (nothing changes) when the load fails:
        promotion is an optimization, never a serve outage.

        NOTE for result-caching frontends: promotion CHANGES the
        correct answer for the promoted epoch (re-priced old paths →
        optimal new paths), so cache entries keyed to that diff epoch
        that were computed before the promotion must be invalidated —
        the serving cache's epoch-scoped flush is the tool."""
        if self.alg != "table-search":
            return False
        try:
            # heal=False, no graph: the self-heal path would rebuild a
            # corrupt epoch-index block from THIS engine's free-flow
            # graph — wrong-regime rows persisted with valid digests
            # and then served as the epoch's optimal table. A bad
            # epoch index simply does not promote; the base table is
            # always a correct fallback.
            rows = load_shard_rows(new_outdir, self.shard, dc=self.dc,
                                   heal=False, replica=self.replica)
        except (OSError, ValueError, FileNotFoundError) as e:
            log.error("worker %d: cannot promote epoch %d index from "
                      "%s: %s (keeping epoch %d)", self.wid, epoch,
                      new_outdir, e, self.index_epoch)
            return False
        if rows.shape[0] != self.fm.shape[0]:
            log.error("worker %d: epoch %d index has %d rows, resident "
                      "table %d — partition mismatch, not promoting",
                      self.wid, epoch, rows.shape[0], self.fm.shape[0])
            return False
        # single-reference publish under the promote lock, MONOTONE in
        # epoch: two async promotions finishing out of order must not
        # let the older one overwrite the newer table (the gate would
        # then refuse current-epoch traffic until the next swap). The
        # lock covers only the check+assign; _fm_for reads stay
        # lock-free on the one published reference.
        with self._promote_lock:
            cur = self._fm_promoted
            if cur is not None and int(epoch) <= cur[0]:
                log.warning("worker %d: not promoting epoch %d over "
                            "already-promoted epoch %d", self.wid,
                            epoch, cur[0])
                return False
            # the promoted table rides the same resident-codec policy
            # as the base one (compressed residency applies per table)
            self._fm_promoted = (int(epoch), self._make_resident(rows))
            self.index_epoch = int(epoch)
        log.info("worker %d: promoted shard %d to diff-epoch %d index "
                 "(%s)", self.wid, self.shard, epoch, new_outdir)
        return True

    def promote_index_async(self, new_outdir: str,
                            epoch: int) -> threading.Thread:
        """Background :meth:`promote_index` — the epoch-swap hook's
        form: the load happens off the serve path and the ``fm`` rebind
        is a single reference swap. Returns the (daemon) thread so
        callers that care about completion can join it."""
        def _run():
            try:
                self.promote_index(new_outdir, epoch)
            except Exception as e:  # noqa: BLE001 — a failed promotion
                # keeps the old table; the serve path must never die
                log.error("worker %d: async promotion to epoch %d "
                          "failed: %s", self.wid, epoch, e)

        t = threading.Thread(
            target=_run, name=f"dos-build-promote-w{self.wid}",
            daemon=True)
        t.start()
        return t

    # ------------------------------------------------------------ weights
    def _weights_for(self, difffile: str, no_cache: bool):
        if difffile in self._weight_cache and not no_cache:
            self._weight_cache.move_to_end(difffile)
            return self._weight_cache[difffile]
        if difffile == "-":
            w_pad = self.dg.w_pad
        else:
            w = self.graph.weights_with_diff(read_diff(difffile))
            # placement follows the fm table (lane-replicated / pinned)
            w_pad = self._place(np.asarray(
                self.graph.padded_weights(w), np.int32))
        if no_cache:
            self._weight_cache.clear()
        else:
            self._weight_cache[difffile] = w_pad
            self._trim_weight_cache()
        return w_pad

    def _trim_weight_cache(self) -> None:
        while len(self._weight_cache) > self._weight_keep:
            self._weight_cache.popitem(last=False)

    # -------------------------------------------------------------- batch
    def answer(self, queries: np.ndarray, config: RuntimeConfig,
               difffile: str = "-") -> tuple[np.ndarray, np.ndarray,
                                             np.ndarray, StatsRow]:
        """Answer a batch; returns (cost, plen, finished, stats).

        With ``config.extract`` and ``k_moves > 0`` the extracted path
        prefixes land on ``self.last_paths`` as ``(nodes [Q, k+1],
        moves [Q])`` — the server materializes them into the batch's
        ``.paths`` file (wire extension, see ``transport.wire``).
        """
        import jax
        import jax.numpy as jnp
        from ..models.resident import M_DECOMPRESS, CompressedFM
        from ..ops.pallas_walk import choose_walk_kernel, pallas_walk_batch
        from ..ops.table_search import extract_paths, table_search_batch

        set_worker_id(self.wid)
        t0 = time.perf_counter()
        self.last_paths = None
        queries = np.asarray(queries, np.int64).reshape(-1, 2)
        # routing invariant FIRST — before any shard-local row lookup,
        # so a misrouted query fails with this diagnostic instead of an
        # opaque index/shape error out of owned_index_of or the kernel
        if len(queries):
            owner = self.dc.worker_of(queries[:, 1])
            if (owner != self.shard).any():
                bad = int((owner != self.shard).sum())
                raise ValueError(
                    f"shard w{self.shard} received {bad} queries for "
                    "other workers — routing invariant violated")
        with obs_trace.span("worker.weights", wid=self.wid,
                            difffile=difffile):
            w_pad = self._weights_for(difffile, config.no_cache)
        # the first-move table is epoch-gated per batch: the promoted
        # delta index serves ONLY the epoch whose fused diff the batch
        # names; everything else keeps the base table (see _fm_for)
        fm_tbl = self._fm_for(difffile)
        M_WEIGHTS.observe(time.perf_counter() - t0)
        nq = len(queries)
        if nq == 0:
            if config.extract and config.k_moves > 0:
                self.last_paths = (
                    np.zeros((0, config.k_moves + 1), np.int64),
                    np.zeros(0, np.int64))
            elif config.sig_k > 0:
                self.last_paths = (
                    np.zeros((0, config.sig_k + 1), np.int64),
                    np.zeros(0, np.int64))
            return (np.zeros(0, np.int64), np.zeros(0, np.int64),
                    np.zeros(0, bool), StatsRow())
        # dedupe identical (s, t) pairs: skewed/online traffic repeats
        # pairs, and the kernel only needs each distinct pair once —
        # answers fan back out through `inverse`, the same machinery
        # as the length-sort's `unsort` below. The A* path keeps the raw
        # batch (its per-query deadline semantics and priority-queue
        # counters measure the work actually done).
        if self.alg == "astar":
            uniq, inverse = queries, None
        else:
            uniq, inverse = np.unique(queries, axis=0,
                                      return_inverse=True)
            inverse = inverse.reshape(-1)
            if len(uniq) < nq:
                M_DUPS.inc(nq - len(uniq))
        nu = len(uniq)
        # order by expected walk length so the kernel's bucketed
        # while_loops exit early (the same trick as CPDOracle.route;
        # answers are unsorted back before returning)
        from ..models.cpd import length_estimate

        order = np.argsort(
            length_estimate(self.graph, uniq[:, 0], uniq[:, 1]),
            kind="stable")
        unsort = np.argsort(order)
        qsorted = uniq[order]
        # pad to the next power of two: stable shapes, no recompiles as the
        # per-worker batch size shifts between campaigns. A lane-mesh
        # engine pads at least to the lane count so EVERY batch splits
        # evenly over the mesh (the extra rows are valid=False lanes)
        qpad = 1 << (nu - 1).bit_length()
        if self._lane_split:
            qpad = max(qpad, self.n_lanes)
        s = np.zeros(qpad, np.int32)
        t = np.zeros(qpad, np.int32)
        valid = np.zeros(qpad, bool)
        s[:nu] = qsorted[:, 0]
        t[:nu] = qsorted[:, 1]
        valid[:nu] = True
        rows = np.zeros(qpad, np.int32)
        rows[:nu] = self.dc.owned_index_of(qsorted[:, 1])

        t1 = time.perf_counter()
        M_RECEIVE.observe(t1 - t0)
        # the compile/steady split keys on the COMPILED PROGRAM's shape:
        # the chunked paths (astar always; table-search under a time
        # budget once the batch exceeds one chunk) reuse a chunk-wide
        # program across batch sizes, so a bigger qpad alone is not a
        # recompile — except with --extract, whose extraction program
        # does compile at the full qpad (kept in the key, conservative)
        extracting = config.extract and config.k_moves > 0
        if self.alg == "astar":
            # the astar program depends only on its chunk shape: hscale/
            # fscale are traced scalars and k_moves/extract never reach
            # it (reference args.py:28), so they stay out of the key
            jit_key = ("astar", min(qpad, self.astar_chunk))
        else:
            if (config.time and qpad > self.astar_chunk
                    and not extracting and config.sig_k <= 0):
                # sig extraction (like extract) runs at the full qpad,
                # so its compile must stay attributable to this key
                shape_key = self.astar_chunk
            else:
                shape_key = qpad
            # compressed residency (DOS_CPD_RESIDENT, models.resident):
            # a pack4 shard feeds the Pallas kernel's decompress-on-
            # tile loader DIRECTLY (packed rows stage through the DMA
            # tile, nibbles unpack on-chip); every other compressed
            # case — rle, mesh lanes, extraction, the XLA kernel, the
            # chunked-deadline path — inflates exactly the batch's
            # distinct target rows first (the XLA run-start decode:
            # decompress at the point of use, raw rows transient)
            compressed = isinstance(fm_tbl, CompressedFM)
            tile_codec = ("pack4" if (compressed
                                      and fm_tbl.codec == "pack4"
                                      and not self._lane_split
                                      and not extracting
                                      and config.sig_k <= 0)
                          else "raw")
            # kernel selection (DOS_WALK_KERNEL): the Pallas-fused walk
            # on real TPU backends under `auto`, the XLA walk otherwise
            # — with a VMEM-fit degrade so an oversized shard falls
            # back to the reference path instead of faulting on-chip.
            # The choice joins the jit key: each kernel compiles (and
            # books its first-call compile time) separately.
            call_q = (self.astar_chunk
                      if config.time and qpad > self.astar_chunk
                      else qpad)
            # lane-split batches: each device walks call_q / L queries,
            # so the VMEM-fit check sees the PER-LANE working set (the
            # same division CPDOracle._walk_kernel applies per shard)
            kernel, why = choose_walk_kernel(
                self.dg.n, self.dg.k, int(self.dg.w_pad.shape[0]) - 1,
                max(call_q // self.n_lanes, 1) if self._lane_split
                else call_q, codec=tile_codec)
            if why and not self._walk_fallback_logged:
                log.warning("%s", why)
                self._walk_fallback_logged = True
            use_tile_pack4 = (tile_codec == "pack4"
                              and kernel == "pallas")
            if kernel == "pallas":
                p4 = use_tile_pack4

                def walk_fn(dgx, fmx, r_, s_, t_, w_, valid=None,
                            k_moves=-1):
                    return pallas_walk_batch(dgx, fmx, r_, s_, t_, w_,
                                             valid=valid,
                                             k_moves=k_moves,
                                             packed4=p4)
            else:
                walk_fn = table_search_batch
            (M_WALK_PALLAS if kernel == "pallas" else M_WALK_XLA).inc()
            jit_key = (self.alg, shape_key, config.k_moves, extracting,
                       config.sig_k if config.sig_k > 0 else 0, kernel)
            if self._lane_split:
                # lane programs compile separately from single-device
                # ones (and per lane count): bookkeeping stays split
                jit_key = jit_key + (("lanes", self.n_lanes),)
                M_MESH_WALK.inc()
            fm_walk = fm_tbl
            if compressed:
                M_WALK_COMPRESSED.inc()
                td0 = time.perf_counter()
                if use_tile_pack4:
                    fm_walk = fm_tbl.packed
                else:
                    # inflate the batch's DISTINCT target rows once and
                    # remap the row ids onto the dense block — bounded
                    # by the batch, freed with it; bit-identical to
                    # walking the raw table (tests pin it)
                    urows, rinv = np.unique(rows[:nu],
                                            return_inverse=True)
                    rpad = 1 << (len(urows) - 1).bit_length()
                    rows_u = np.zeros(rpad, np.int32)
                    rows_u[:len(urows)] = urows
                    fm_walk = fm_tbl.decompress_rows(
                        self._place(rows_u))
                    jax.block_until_ready(fm_walk)
                    rows = np.zeros(qpad, np.int32)
                    rows[:nu] = rinv.reshape(-1).astype(np.int32)
                M_DECOMPRESS.observe(time.perf_counter() - td0)
                # compressed programs compile separately (the fm
                # operand's shape/dtype differs per codec + row pad)
                jit_key = jit_key + (
                    ("resident", fm_tbl.codec, int(fm_walk.shape[0])),)
        first_call = jit_key not in self._jit_seen
        if self.alg == "astar":
            deadline = t1 + config.time / 1e9 if config.time else None
            for _ in range(max(config.itrs, 1)):
                cost, plen, fin, counters = self._answer_astar(
                    queries, config, difffile, deadline=deadline)
                if deadline is not None and time.perf_counter() > deadline:
                    break
            t2 = time.perf_counter()
            self._finish_search(jit_key, first_call, nq, t2 - t1)
            stats = StatsRow(
                **counters, t_receive=t1 - t0, t_astar=t2 - t1,
                t_search=t2 - t0)
            return cost, plen, fin, stats
        def run_walk(rows_h, s_h, t_h, valid_h):
            """One walk call: split across the worker's mesh lanes when
            active (contiguous per-lane subsets of the est-sorted batch
            under shard_map — each lane runs its own bucket grid through
            the selected kernel unchanged), the plain single-device
            kernel otherwise. Answers are bit-identical either way; the
            unsort below never changes."""
            if self._lane_split:
                from ..parallel.sharded import walk_lanes

                return walk_lanes(
                    self.dg, fm_walk, rows_h, s_h, t_h, valid_h, w_pad,
                    self.mesh, k_moves=config.k_moves, kernel=kernel)
            return walk_fn(
                self.dg, fm_walk, jnp.asarray(rows_h), jnp.asarray(s_h),
                jnp.asarray(t_h), w_pad, valid=jnp.asarray(valid_h),
                k_moves=config.k_moves)

        deadline = t1 + config.time / 1e9 if config.time else None
        for _ in range(max(config.itrs, 1)):
            if deadline is None or qpad <= self.astar_chunk:
                cost, plen, fin = run_walk(rows, s, t, valid)
                jax.block_until_ready(fin)
            else:
                # ns budget truncates INSIDE the batch (reference
                # semantics: the time limit cuts searches short in the
                # engine, reference args.py:30-57): the sorted batch
                # runs in fixed-size chunks — cheap queries first — and
                # the deadline is checked between chunks. The first
                # chunk always runs (an expired budget still yields a
                # minimal answer, same rule as the A* chunk path);
                # skipped chunks come back unfinished, so `finished`
                # counts are partial like the reference's.
                ch = self.astar_chunk         # pow2, divides qpad
                cost, plen, fin = (np.zeros(qpad, np.int64),
                                   np.zeros(qpad, np.int64),
                                   np.zeros(qpad, bool))
                # one chunk stays in flight ahead (dispatch k+1, then
                # block on k): a generous budget keeps most of the
                # single-call pipelining; truncation granularity is one
                # extra chunk at worst
                pending = None       # (slice, async device triple)

                def _land(entry):
                    sl_p, (c_p, p_p, f_p) = entry
                    jax.block_until_ready(f_p)
                    cost[sl_p], plen[sl_p], fin[sl_p] = (
                        np.asarray(c_p), np.asarray(p_p), np.asarray(f_p))
                for off in range(0, qpad, ch):
                    if off and time.perf_counter() > deadline:
                        break
                    sl = slice(off, off + ch)
                    outs = run_walk(rows[sl], s[sl], t[sl], valid[sl])
                    if pending is not None:
                        _land(pending)
                    pending = (sl, outs)
                if pending is not None:
                    _land(pending)
            if deadline is not None and time.perf_counter() > deadline:
                break
        if config.extract and config.k_moves > 0:
            nodes, moves = extract_paths(
                self.dg, fm_walk, jnp.asarray(rows), jnp.asarray(s),
                jnp.asarray(t), k=config.k_moves)
            nodes = np.asarray(nodes[:nu], np.int64)[unsort]
            moves = np.asarray(moves[:nu], np.int64)[unsort]
            if inverse is not None:
                nodes, moves = nodes[inverse], moves[inverse]
            self.last_paths = (nodes, moves)
        elif config.sig_k > 0:
            # bounded path SIGNATURE for the serving cache's scoped
            # invalidation (RuntimeConfig.sig_k wire extension): the
            # same extraction scan as --extract but decoupled from
            # k_moves, so the walk's move budget — and therefore every
            # answer — is untouched
            nodes, moves = extract_paths(
                self.dg, fm_walk, jnp.asarray(rows), jnp.asarray(s),
                jnp.asarray(t), k=int(config.sig_k))
            nodes = np.asarray(nodes[:nu], np.int64)[unsort]
            moves = np.asarray(moves[:nu], np.int64)[unsort]
            if inverse is not None:
                nodes, moves = nodes[inverse], moves[inverse]
            self.last_paths = (nodes, moves)
        t2 = time.perf_counter()
        self._finish_search(jit_key, first_call, nq, t2 - t1)
        if first_call and obs_device.enabled():
            # one XLA cost/memory analysis per compiled-program key
            # (FLOPs, bytes accessed, HBM footprint -> /metrics gauges +
            # BENCH_DETAIL.json): the AOT re-lower is cheap and runs
            # once, outside the timed search interval — the roofline
            # evidence ROADMAP item 1 is judged against. The analyzed
            # shape is the search program the loop above ACTUALLY ran
            # (chunk-wide whenever the deadline path chunked — which,
            # unlike shape_key, it does even under --extract), so the
            # lower/compile is a cache hit, never a fresh compile of a
            # never-executed shape
            cap_n = (self.astar_chunk
                     if deadline is not None and qpad > self.astar_chunk
                     else qpad)
            sl = slice(0, cap_n)
            if self._lane_split:
                # the mesh path ran the lane-split shard_map program,
                # not the single-device one — lower THAT (the roofline
                # gauges used to go dark on meshed workers). The helper
                # hands back the SAME cached jit walk_lanes dispatched,
                # with operands lane-sharded exactly as it shipped them,
                # so the AOT lower/compile is an XLA cache hit; the key
                # carries the lane count because lane programs compile
                # per lane count (the jit_key says the same)
                from ..parallel.sharded import lane_walk_program
                tag = "[pallas]" if kernel == "pallas" else ""
                fn_l, ops_l = lane_walk_program(
                    self.dg, fm_walk, rows[sl], s[sl], t[sl],
                    valid[sl], w_pad, self.mesh,
                    k_moves=config.k_moves, kernel=kernel)
                obs_device.capture(
                    f"table-search{tag}[lanes{self.n_lanes}]"
                    f"/q{cap_n}/k{config.k_moves}",
                    fn_l, *ops_l)
            elif kernel == "pallas":
                # the fused kernel's statics live in a closure so the
                # capture's AOT lower sees only array operands (its
                # interpret/bucket resolution runs at trace time)
                km = config.k_moves
                p4c = use_tile_pack4

                def _cap_fn(dgx, fmx, r_, s_, t_, w_, v_):
                    return pallas_walk_batch(dgx, fmx, r_, s_, t_, w_,
                                             valid=v_, k_moves=km,
                                             packed4=p4c)

                obs_device.capture(
                    f"table-search[pallas]/q{cap_n}/k{config.k_moves}",
                    _cap_fn, self.dg, fm_walk, jnp.asarray(rows[sl]),
                    jnp.asarray(s[sl]), jnp.asarray(t[sl]), w_pad,
                    jnp.asarray(valid[sl]))
            else:
                obs_device.capture(
                    f"table-search/q{cap_n}/k{config.k_moves}",
                    table_search_batch, self.dg, fm_walk,
                    jnp.asarray(rows[sl]), jnp.asarray(s[sl]),
                    jnp.asarray(t[sl]), w_pad,
                    valid=jnp.asarray(valid[sl]), k_moves=config.k_moves)

        cost = np.asarray(cost[:nu], np.int64)[unsort]
        plen = np.asarray(plen[:nu], np.int64)[unsort]
        fin = np.asarray(fin[:nu], bool)[unsort]
        if inverse is not None:
            # fan deduped answers back out to every original query —
            # the stats sums below stay per ORIGINAL query by summing
            # AFTER this expansion
            cost, plen, fin = cost[inverse], plen[inverse], fin[inverse]
        stats = StatsRow(
            n_expanded=int(plen.sum()),   # node expansions = moves walked
            n_touched=nq,
            plen=int(plen.sum()),
            finished=int(fin.sum()),
            t_receive=t1 - t0,
            t_astar=t2 - t1,
            t_search=t2 - t0,
        )
        return cost, plen, fin, stats

    def _finish_search(self, jit_key: tuple, first_call: bool, nq: int,
                       seconds: float) -> None:
        """Book one batch's search interval: first call at a new program
        key goes to the compile histogram (XLA compilation dominates it),
        repeats to the steady-state one; the span mirrors the split."""
        self._jit_seen.add(jit_key)
        (M_JIT if first_call else M_SEARCH).observe(seconds)
        if not first_call:
            # live window mirrors the steady-state histogram (a cold
            # compile would own the window's p99 for a whole rotation);
            # the exemplar id is the batch's wire trace id when set
            obs_quantiles.observe(
                "worker_search_seconds", seconds,
                trace_id=obs_trace.current_trace_id())
        M_BATCHES.inc()
        M_QUERIES.inc(nq)
        obs_trace.add_span("worker.search", seconds, wid=self.wid,
                           alg=self.alg, queries=nq,
                           first_call=first_call)

    def _raw_weights_for(self, difffile: str, no_cache: bool):
        """Raw (unpadded) query weights + heuristic scale, cached per diff
        like the device-side weight cache."""
        from ..models.astar import min_cost_per_unit

        key = ("raw", difffile)
        if key in self._weight_cache and not no_cache:
            self._weight_cache.move_to_end(key)
            return self._weight_cache[key]
        w = (self.graph.w if difffile == "-"
             else self.graph.weights_with_diff(read_diff(difffile)))
        entry = (w, min_cost_per_unit(self.graph, w))
        if no_cache:
            self._weight_cache.pop(key, None)
        else:
            self._weight_cache[key] = entry
            self._trim_weight_cache()
        return entry

    def _answer_astar(self, queries: np.ndarray, config: RuntimeConfig,
                      difffile: str = "-", deadline: float | None = None):
        """hscale/fscale weighted A* — the serving path is the **batched
        device kernel** (``ops.batched_astar``): the whole batch searches
        in lock-step sweeps, chunked to bound the working set, with the
        ``time`` deadline checked between chunks — the FIRST chunk always
        runs (an expired budget still yields a minimal answer, like the
        per-query CPU oracle), remaining chunks stay unfinished. ``config.debug`` instead runs the
        per-query CPU heap oracle (``models.astar``) — the deterministic,
        expansion-order-faithful repro path, matching the reference's
        debug mode forcing single-threaded runs (reference
        ``offline.py:143-147``).

        Honors ``hscale``/``fscale``/``itrs``/``time``/``no_cache``.
        ``k_moves`` is deliberately NOT applied: per the reference,
        "K-moves are only available with extractions while hScale only
        influences A*" (reference ``args.py:28``).
        """
        if not config.debug:
            from ..ops.batched_astar import astar_batch_np

            w, cpu = self._raw_weights_for(difffile, config.no_cache)
            if config.no_cache:
                # no_cache = re-read the diff from disk next time; stale
                # device copies keyed by the diff path must go too
                for k in [k for k in self._astar_ctx
                          if isinstance(k, tuple) and k[0] == "w_pad"]:
                    del self._astar_ctx[k]
            cost, plen, fin, counters = astar_batch_np(
                self.graph, queries, w, hscale=config.hscale,
                fscale=config.fscale, deadline=deadline, cpu=cpu,
                chunk=self.astar_chunk, ctx=self._astar_ctx,
                w_key=None if config.no_cache else difffile)
            counters["plen"] = int(plen.sum())
            counters["finished"] = int(fin.sum())
            return cost, plen, fin, counters

        from ..models.astar import AstarStats, astar

        w, cpu = self._raw_weights_for(difffile, config.no_cache)
        st = AstarStats()
        cost = np.zeros(len(queries), np.int64)
        plen = np.zeros(len(queries), np.int64)
        fin = np.zeros(len(queries), bool)
        for i, (s, t) in enumerate(queries):
            if deadline is not None and time.perf_counter() > deadline:
                break
            cost[i], plen[i], fin[i] = astar(
                self.graph, int(s), int(t), w, hscale=config.hscale,
                fscale=config.fscale, cpu=cpu, stats=st)
        counters = dict(
            n_expanded=st.n_expanded, n_inserted=st.n_inserted,
            n_touched=st.n_touched, n_updated=st.n_updated,
            n_surplus=st.n_surplus, plen=st.plen, finished=st.finished)
        return cost, plen, fin, counters
