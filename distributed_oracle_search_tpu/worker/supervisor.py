"""Worker supervisor: launch resident servers, monitor, respawn.

The reference leaves crashed servers dead until a human re-runs
``make_fifos`` (its tmux sessions are forensics, not recovery). The
supervisor closes that loop for local workers:

* **launch** — one ``worker.server`` subprocess per worker id (its own
  process group, stdout/stderr to a per-worker logfile), readiness
  confirmed by a liveness probe, not FIFO existence (a hard crash leaves
  a stale FIFO behind that would fool an existence check);
* **monitor** — a named ``dos-supervisor`` daemon thread polls each
  subprocess and pings it through the command FIFO
  (``transport.fifo.probe``) every ``ping_interval_s``;
* **respawn** — a dead process is relaunched with capped exponential
  backoff (``base * 2^k`` up to ``cap``); the backoff step resets once
  the respawned worker answers a ping. Hung-worker recovery (process
  alive, pings failing) is opt-in via ``unhealthy_pings`` because a
  single-threaded server legitimately goes quiet for the length of a
  batch (cold XLA compiles run minutes) — enable it only with a ping
  interval comfortably above your worst batch.

Env knobs: ``DOS_SUPERVISOR_PING_S`` (default 2), ``DOS_SUPERVISOR_BACKOFF_BASE_S``
(default 0.5), ``DOS_SUPERVISOR_BACKOFF_CAP_S`` (default 30),
``DOS_SUPERVISOR_UNHEALTHY_PINGS`` (default 0 = ping-based respawn off).

Remote hosts keep the reference's ssh+tmux launch path
(``cli.make_fifos``); supervision there means running this module on the
worker host itself (``python -m ...cli.make_fifos --supervise`` with a
conf whose workers are local).
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time

from ..obs import metrics as obs_metrics
from ..obs import recorder as obs_recorder
from ..transport import fifo as fifo_transport
from ..utils.config import ClusterConfig
from ..utils.env import env_cast
from ..utils.locks import OrderedLock
from ..utils.log import get_logger

log = get_logger(__name__)

M_RESPAWNS = obs_metrics.counter(
    "supervisor_respawns_total", "worker subprocesses relaunched")
M_SUP_PINGS = obs_metrics.counter(
    "supervisor_pings_total", "liveness pings sent by the supervisor")
M_SUP_PING_FAIL = obs_metrics.counter(
    "supervisor_ping_failures_total",
    "supervisor pings that got no healthy reply")
G_ALIVE = obs_metrics.gauge(
    "supervisor_workers_alive", "supervised worker processes running")


class SupervisedWorker:
    """Book-keeping for one supervised worker process."""

    __slots__ = ("wid", "fifo", "proc", "respawns", "backoff_k",
                 "next_spawn_at", "ping_failures", "healthy_once")

    def __init__(self, wid: int, fifo: str):
        self.wid = wid
        self.fifo = fifo
        self.proc: subprocess.Popen | None = None
        self.respawns = 0
        self.backoff_k = 0
        self.next_spawn_at = 0.0
        self.ping_failures = 0
        self.healthy_once = False


class WorkerSupervisor:
    """Launch + monitor + respawn local resident query servers.

    ``spawn_fn(worker) -> subprocess.Popen`` and
    ``probe_fn(worker) -> HealthStatus | None`` are injectable so tests
    can supervise cheap dummy processes; the defaults launch the real
    ``worker.server`` module and ping it over its command FIFO.
    """

    def __init__(self, conf: ClusterConfig, conf_path: str | None = None,
                 wids=None, alg: str = "table-search",
                 fifo_dir: str | None = None,
                 logdir: str | None = None,
                 ping_interval_s: float | None = None,
                 backoff_base_s: float | None = None,
                 backoff_cap_s: float | None = None,
                 unhealthy_pings: int | None = None,
                 probe_timeout_s: float = 10.0,
                 spawn_fn=None, probe_fn=None,
                 traffic_dir: str | None = None,
                 rpc_dir: str | None = None):
        #: where spawned servers bind their streaming-RPC unix sockets
        #: (DOS_TRANSPORT=rpc/auto): overrides DOS_RPC_SOCKET_DIR so a
        #: test fleet's sockets land beside its FIFOs, not in /tmp
        self.rpc_dir = rpc_dir
        self.conf = conf
        self.conf_path = conf_path
        self.alg = alg
        self.fifo_dir = fifo_dir
        self.logdir = logdir
        #: diff segment stream for the spawned servers' STALE_DIFF
        #: gate (None = workers never gate on diff epochs)
        self.traffic_dir = traffic_dir
        self.ping_interval_s = (
            ping_interval_s if ping_interval_s is not None
            else env_cast("DOS_SUPERVISOR_PING_S", 2.0, float))
        self.backoff_base_s = (
            backoff_base_s if backoff_base_s is not None
            else env_cast("DOS_SUPERVISOR_BACKOFF_BASE_S", 0.5, float))
        self.backoff_cap_s = (
            backoff_cap_s if backoff_cap_s is not None
            else env_cast("DOS_SUPERVISOR_BACKOFF_CAP_S", 30.0, float))
        self.unhealthy_pings = (
            unhealthy_pings if unhealthy_pings is not None
            else env_cast("DOS_SUPERVISOR_UNHEALTHY_PINGS", 0, int))
        self.probe_timeout_s = probe_timeout_s
        self.spawn_fn = spawn_fn or self._spawn_server
        self.probe_fn = probe_fn or self._probe_server
        wids = list(wids) if wids is not None else list(
            range(conf.maxworker))
        self.workers = {wid: SupervisedWorker(wid, self._fifo_for(wid))
                        for wid in wids}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = OrderedLock("supervisor.WorkerSupervisor")

    # --------------------------------------------------------- defaults
    def _fifo_for(self, wid: int) -> str:
        if self.fifo_dir:
            return os.path.join(self.fifo_dir, f"worker{wid}.fifo")
        return fifo_transport.command_fifo_path(wid)

    def _rpc_socket_for(self, wid: int) -> str:
        from ..transport import rpc as rpc_transport

        if self.rpc_dir:
            return os.path.join(self.rpc_dir,
                                f"dos-rpc-worker{wid}.sock")
        return rpc_transport.rpc_socket_path(wid)

    def _spawn_server(self, w: SupervisedWorker) -> subprocess.Popen:
        if not self.conf_path:
            raise ValueError("supervising real servers needs conf_path")
        cmd = [sys.executable, "-m",
               "distributed_oracle_search_tpu.worker.server",
               "-c", self.conf_path, "--workerid", str(w.wid),
               "--fifo", w.fifo, "--alg", self.alg]
        if self.traffic_dir:
            cmd += ["--traffic-dir", self.traffic_dir]
        # streaming data plane: when the fleet runs DOS_TRANSPORT=rpc/
        # auto (or the caller pinned a socket dir), spawned servers get
        # an explicit per-worker socket so respawns land on the SAME
        # endpoint the head's persistent clients reconnect to
        from ..transport import rpc as rpc_transport
        if self.rpc_dir or rpc_transport.resolve_transport() != "fifo":
            cmd += ["--rpc-socket", self._rpc_socket_for(w.wid)]
        out = subprocess.DEVNULL
        if self.logdir:
            os.makedirs(self.logdir, exist_ok=True)
            out = open(os.path.join(self.logdir, f"worker{w.wid}.log"),
                       "ab")
        # DOS_OBS_PORT names ONE port: the supervisor's own obs server
        # binds it; letting N children inherit it would put every
        # worker in contention for the same socket (give workers their
        # own ports via per-worker --obs-port wiring when needed)
        env = {k: v for k, v in os.environ.items()
               if k != "DOS_OBS_PORT"}
        return subprocess.Popen(cmd, cwd=self.conf.projectdir,
                                stdout=out, stderr=subprocess.STDOUT,
                                start_new_session=True, env=env)

    def _probe_server(self, w: SupervisedWorker):
        return fifo_transport.probe(
            "localhost", w.wid, command_fifo=w.fifo, nfs=self.conf.nfs,
            timeout=self.probe_timeout_s)

    # ---------------------------------------------------------- control
    def start(self, wait_ready_s: float = 120.0) -> None:
        """Spawn every worker, wait until each answers a ping, then
        start the monitor thread. A startup failure stops the workers
        already spawned before re-raising — they were launched in their
        own sessions and would otherwise outlive the failed supervisor,
        squatting on the command FIFOs of the operator's retry run."""
        try:
            self._start_inner(wait_ready_s)
        except BaseException:
            self.stop(join_s=5.0)
            raise

    def _start_inner(self, wait_ready_s: float) -> None:
        for w in self.workers.values():
            w.proc = self.spawn_fn(w)
            log.info("supervisor: spawned worker %d (pid %d)", w.wid,
                     w.proc.pid)
        deadline = time.monotonic() + wait_ready_s
        pending = set(self.workers)
        while pending and time.monotonic() < deadline:
            for wid in sorted(pending):
                w = self.workers[wid]
                if w.proc.poll() is not None:
                    raise RuntimeError(
                        f"worker {wid} died during startup "
                        f"(rc={w.proc.returncode})")
                st = self.probe_fn(w)
                if st is not None and getattr(st, "ok", False):
                    w.healthy_once = True
                    pending.discard(wid)
            if pending:
                time.sleep(0.2)
        if pending:
            raise RuntimeError(
                f"workers {sorted(pending)} not ready within "
                f"{wait_ready_s:.0f}s")
        G_ALIVE.set(len(self.workers))
        self._thread = threading.Thread(target=self._monitor,
                                        daemon=True,
                                        name="dos-supervisor")
        self._thread.start()
        log.info("supervisor: %d worker(s) ready", len(self.workers))

    def stop(self, join_s: float = 10.0) -> None:
        """Stop monitoring, then stop the servers (graceful token first,
        SIGTERM/SIGKILL escalation after)."""
        from .server import stop_server

        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=join_s)
            self._thread = None
        workers = self._snapshot()
        for w in workers:
            if w.proc is None or w.proc.poll() is not None:
                continue
            stop_server(w.fifo, deadline_s=1.0)
        for w in workers:
            if w.proc is None:
                continue
            try:
                w.proc.wait(timeout=join_s)
            except subprocess.TimeoutExpired:
                w.proc.terminate()
                try:
                    w.proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    w.proc.kill()
                    w.proc.wait(timeout=5.0)
        G_ALIVE.set(0)

    def __enter__(self) -> "WorkerSupervisor":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------ elastic membership
    def _snapshot(self) -> list[SupervisedWorker]:
        """Consistent view of the supervised set: join/leave mutate the
        dict from other threads while the monitor iterates."""
        with self._lock:
            return list(self.workers.values())

    def add_worker(self, wid: int, fifo: str | None = None,
                   wait_ready_s: float = 120.0) -> SupervisedWorker:
        """Drain-free JOIN support: spawn and supervise one more worker
        without touching the running fleet. Readiness is confirmed by a
        liveness ping (same rule as :meth:`start`); the reconfiguration
        controller flips routing only after the adopter is serving."""
        with self._lock:
            if wid in self.workers:
                raise ValueError(f"worker {wid} is already supervised")
        w = SupervisedWorker(wid, fifo or self._fifo_for(wid))
        # spawn BEFORE publishing: the monitor thread iterates the
        # supervised set concurrently, and an entry with proc=None
        # would read as a dead worker — scheduling a respawn that races
        # this spawn for the same command FIFO
        w.proc = self.spawn_fn(w)
        with self._lock:
            if wid in self.workers:
                w.proc.terminate()
                raise ValueError(f"worker {wid} is already supervised")
            self.workers[wid] = w
        log.info("supervisor: joined worker %d (pid %d)", wid,
                 w.proc.pid)
        deadline = time.monotonic() + wait_ready_s
        try:
            while time.monotonic() < deadline:
                if w.proc.poll() is not None:
                    raise RuntimeError(
                        f"joining worker {wid} died during startup "
                        f"(rc={w.proc.returncode})")
                st = self.probe_fn(w)
                if st is not None and getattr(st, "ok", False):
                    w.healthy_once = True
                    return w
                time.sleep(0.2)
            raise RuntimeError(
                f"joining worker {wid} not ready within "
                f"{wait_ready_s:.0f}s")
        except BaseException:
            # a raising probe (monitor wraps the same call) must not
            # strand a half-joined worker supervised: the caller sees
            # the failure, so the joiner must be fully unwound
            self._abandon_join(w)
            raise

    def _abandon_join(self, w: SupervisedWorker) -> None:
        """Failed join cleanup: unsupervise, then stop whatever process
        is CURRENTLY attached — the monitor may have respawned the
        worker while add_worker was still polling readiness, and that
        respawn must not outlive supervision as an orphan."""
        with self._lock:
            self.workers.pop(w.wid, None)
        if w.proc is not None and w.proc.poll() is None:
            w.proc.terminate()

    def remove_worker(self, wid: int, join_s: float = 10.0) -> bool:
        """Drain-free LEAVE support: unsupervise the worker (so the
        monitor cannot respawn it), push the graceful stop token — the
        server finishes the frame it already read, answers it, and
        exits 0 — then escalate to SIGTERM/SIGKILL only if the drain
        stalls. Call AFTER the membership commit moved its shards.
        Returns True when the worker exited 0 (a clean drain)."""
        from .server import stop_server

        with self._lock:
            w = self.workers.pop(wid, None)
        if w is None:
            log.warning("supervisor: worker %d is not supervised", wid)
            return False
        if w.proc is not None and w.proc.poll() is None:
            stop_server(w.fifo, deadline_s=2.0)
            try:
                w.proc.wait(timeout=join_s)
            except subprocess.TimeoutExpired:
                log.warning("supervisor: worker %d drain stalled; "
                            "escalating", wid)
                w.proc.terminate()
                try:
                    w.proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    w.proc.kill()
                    w.proc.wait(timeout=5.0)
        rc = w.proc.returncode if w.proc is not None else None
        log.info("supervisor: worker %d left the fleet (rc=%s)", wid, rc)
        return rc == 0

    # ---------------------------------------------------- obs endpoints
    def health(self) -> dict:
        """``/healthz``: ok iff every supervised worker process is
        currently running (a worker mid-backoff reports unhealthy —
        exactly when an orchestrator should hold traffic)."""
        workers = self._snapshot()
        running = sum(
            1 for w in workers
            if w.proc is not None and w.proc.poll() is None)
        return {"ok": running == len(workers),
                "alive": running, "workers": len(workers)}

    def statusz(self) -> dict:
        """``/statusz`` section: per-worker process/respawn/ping state."""
        workers = {}
        for w in self._snapshot():
            workers[str(w.wid)] = {
                "pid": w.proc.pid if w.proc is not None else None,
                "running": (w.proc is not None
                            and w.proc.poll() is None),
                "respawns": w.respawns,
                "backoff_step": w.backoff_k,
                "ping_failures": w.ping_failures,
                "healthy_once": w.healthy_once,
                "fifo": w.fifo,
            }
        h = self.health()
        return {"alive": h["alive"], "workers_total": h["workers"],
                "respawns": sum(w.respawns
                                for w in self._snapshot()),
                "ping_interval_s": self.ping_interval_s,
                "workers": workers}

    def kick(self, wid: int) -> bool:
        """Control-plane respawn accelerator: clear ``wid``'s backoff
        schedule so the monitor's next tick respawns a dead worker
        immediately instead of waiting out the exponential backoff.
        The control daemon calls this when it has *decided* the worker
        is sick — evidence the backoff's "maybe it is flapping" caution
        no longer applies to. Returns True when an immediate respawn
        was scheduled (the worker is currently dead)."""
        with self._lock:
            w = self.workers.get(wid)
        if w is None:
            return False
        w.backoff_k = 0
        dead = w.proc is None or w.proc.poll() is not None
        if dead:
            # overwrite any already-scheduled backoff wait; 0.0 is the
            # "death not yet observed" sentinel so schedule explicitly
            w.next_spawn_at = time.monotonic()
        obs_recorder.emit("supervisor_kick", wid=w.wid,
                          respawn_scheduled=dead)
        return dead

    # --------------------------------------------------------- monitor
    def _backoff_s(self, w: SupervisedWorker) -> float:
        return min(self.backoff_cap_s,
                   self.backoff_base_s * (2 ** w.backoff_k))

    def _monitor(self) -> None:
        while not self._stop.wait(self.ping_interval_s):
            alive = 0
            for w in self._snapshot():
                if self._stop.is_set():
                    return
                try:
                    alive += self._monitor_one(w)
                except Exception:  # noqa: BLE001 — a spawn/probe bug
                    # must not kill the only thread doing recovery; the
                    # next tick retries (respawns under backoff)
                    log.exception("supervisor: monitoring worker %d "
                                  "failed; will retry", w.wid)
            G_ALIVE.set(alive)

    def _monitor_one(self, w: SupervisedWorker) -> int:
        """Returns 1 if the worker process is running, else 0."""
        if w.proc is None or w.proc.poll() is not None:
            self._maybe_respawn(w, "process died")
            return 0
        M_SUP_PINGS.inc()
        st = self.probe_fn(w)
        healthy = st is not None and getattr(st, "ok", False)
        if healthy:
            w.ping_failures = 0
            if not w.healthy_once:
                w.healthy_once = True
                w.backoff_k = 0   # respawn confirmed good
            return 1
        M_SUP_PING_FAIL.inc()
        w.ping_failures += 1
        if (self.unhealthy_pings
                and w.ping_failures >= self.unhealthy_pings):
            log.error("supervisor: worker %d unresponsive after "
                      "%d pings; killing for respawn", w.wid,
                      w.ping_failures)
            w.proc.kill()
            try:
                w.proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                pass
            self._maybe_respawn(w, "hung (ping failures)")
            return 0
        return 1

    def _maybe_respawn(self, w: SupervisedWorker, why: str) -> None:
        now = time.monotonic()
        if w.next_spawn_at == 0.0:
            # first observation of this death: schedule the respawn
            delay = self._backoff_s(w)
            w.next_spawn_at = now + delay
            rc = w.proc.returncode if w.proc is not None else None
            log.error("supervisor: worker %d down (%s, rc=%s); respawn "
                      "in %.2fs (backoff step %d)", w.wid, why, rc,
                      delay, w.backoff_k)
            return
        if now < w.next_spawn_at:
            return
        with self._lock:
            if self.workers.get(w.wid) is not w:
                # unsupervised between ticks (remove_worker / a failed
                # add_worker): respawning now would orphan a process
                # nothing manages
                return
        w.next_spawn_at = 0.0
        w.backoff_k += 1
        w.ping_failures = 0
        w.healthy_once = False      # reset backoff only after a good ping
        proc = self.spawn_fn(w)     # outside the lock: spawning blocks
        with self._lock:
            # re-check after the spawn: remove_worker can win the race
            # between the pre-spawn identity check and spawn_fn — the
            # process must not be published into an unsupervised entry
            adopted = self.workers.get(w.wid) is w
            if adopted:
                w.proc = proc
                w.respawns += 1
        if not adopted:
            log.warning("supervisor: worker %d unsupervised during "
                        "respawn; terminating orphan pid %d", w.wid,
                        proc.pid)
            proc.terminate()
            return
        M_RESPAWNS.inc()
        obs_recorder.emit("respawn", wid=w.wid, pid=proc.pid,
                          respawn=w.respawns, why=why)
        log.warning("supervisor: respawned worker %d (pid %d, "
                    "respawn #%d)", w.wid, proc.pid, w.respawns)


def supervise_forever(conf: ClusterConfig, conf_path: str,
                      alg: str = "table-search",
                      logdir: str | None = None,
                      obs_port: int | None = None,
                      traffic_dir: str | None = None) -> int:
    """``make_fifos --supervise`` entry: run until interrupted.
    ``obs_port`` (or ``DOS_OBS_PORT``) additionally serves live
    ``/metrics`` ``/healthz`` ``/statusz`` for the whole supervised
    fleet — healthz goes 503 the moment any worker is down."""
    from ..obs.http import start_obs_server

    from ..obs import telemetry as obs_telemetry

    from ..control import maybe_daemon

    sup = WorkerSupervisor(conf, conf_path, alg=alg, logdir=logdir,
                           traffic_dir=traffic_dir)
    obs_srv = None
    publisher = None
    daemon = None
    try:
        sup.start()
        # closed-loop control (DOS_CONTROL=1): supervise-side the
        # daemon senses the supervisor only — it accelerates respawns
        # of workers it has decided are sick and journals the incident
        daemon = maybe_daemon(supervisor=sup)
        providers = {"supervisor": sup.statusz}
        if daemon is not None:
            providers["control"] = daemon.statusz
        # inside the try: a bind failure (port taken) must tear the
        # just-spawned workers down, not orphan them unsupervised
        obs_srv = start_obs_server(
            obs_port, health_fn=sup.health,
            status_providers=providers)
        # fleet telemetry: the supervisor's own counters (respawns,
        # ping failures) ride the sidecar lane beside the workers' —
        # its file lands in the FIFO directory the head already polls
        if sup.workers and obs_telemetry.interval_s() > 0:
            fifo_dir = os.path.dirname(
                next(iter(sup.workers.values())).fifo) or "."
            publisher = obs_telemetry.TelemetryPublisher(
                source="supervisor",
                sinks=[obs_telemetry.sidecar_sink(os.path.join(
                    fifo_dir,
                    "supervisor" + obs_telemetry.SIDECAR_SUFFIX))],
            ).start()
        print(f"supervising {len(sup.workers)} worker(s); "
              "Ctrl-C to stop")
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        log.info("supervisor: interrupted; stopping workers")
    finally:
        if daemon is not None:
            daemon.stop()
        if publisher is not None:
            publisher.stop()
        if obs_srv is not None:
            obs_srv.close()
        sup.stop()
    return 0
