"""Per-worker CPD build program: the framework's ``make_cpd_auto``.

CLI parity with reference C1 (SURVEY.md §2.2; invoked at reference
``make_cpds.py:20``)::

    python -m distributed_oracle_search_tpu.worker.build \
        --input <xy> --partmethod <div|mod|alloc|tpu> --partkey <int...> \
        --workerid <int> --maxworker <int> [--outdir <dir>] [--chunk N]

Computes the first-move rows for the node subset owned by ``workerid`` —
the reference runs one Dijkstra sweep per owned node over all OpenMP cores
(reference ``README.md:95``); here the whole shard is built by the batched
min-plus kernel on the local accelerator — and writes one ``.npy`` per
block (``bid``/``bidx`` scheme of the distribution controller). Re-running
resumes at block granularity.
"""

from __future__ import annotations

import argparse
import os
import sys

from ..data.graph import Graph
from ..models.cpd import build_worker_shard
from ..parallel.partition import DistributionController
from ..utils.log import get_logger, set_verbosity

log = get_logger(__name__)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--input", required=True, help="graph .xy file")
    p.add_argument("--partmethod", required=True,
                   choices=["div", "mod", "alloc", "tpu"])
    p.add_argument("--partkey", type=int, nargs="+", default=[1])
    p.add_argument("--workerid", type=int, required=True)
    p.add_argument("--maxworker", type=int, required=True)
    p.add_argument("--outdir", default=None,
                   help="default: the input file's directory "
                        "(reference README.md:93)")
    p.add_argument("--chunk", type=int, default=0,
                   help="build-step rows (0 = whole shard at once)")
    p.add_argument("--block-size", type=int, default=0,
                   help="rows per block FILE (0 = the controller "
                        "default, which is what the serving CLIs "
                        "expect; the manifest records the value and "
                        "make_cpds --verify honors it — non-default "
                        "sizes are for tooling/chaos tests whose "
                        "consumers build a matching controller)")
    p.add_argument("--method", default="auto",
                   choices=["auto", "sweep", "shift", "frontier",
                            "ellsplit", "ell"],
                   help="relaxation kernel: fast-sweeping grid scans, "
                        "gather-free shift path, delta-stepping frontier "
                        "queue, ELL+COO split (degree-skewed graphs), "
                        "padded-ELL gather, or auto by structure gates "
                        "(models.cpd.pick_build_kernel)")
    p.add_argument("--no-resume", action="store_true",
                   help="rebuild every block from scratch (default: "
                        "resume — skip blocks the build ledger records "
                        "as complete with a matching on-disk digest)")
    p.add_argument("--adopt-shard", type=int, default=None,
                   metavar="SHARD",
                   help="membership catch-up mode: instead of building "
                        "this worker's own rows, digest-verify (and "
                        "heal via the copy/rebuild path) the named "
                        "shard's primary block set — what a joining "
                        "worker runs before the reconfiguration "
                        "controller commits the epoch bump. Idempotent "
                        "and crash-resumable (build-ledger journaled)")
    p.add_argument("--replication", type=int, default=None,
                   help="R-way shard replication: after the primary "
                        "rows, also build this worker's hosted replica "
                        "block sets (rank r of shard (wid - r) %% W; "
                        "copied from digest-valid primaries when "
                        "sharing a filesystem, recomputed otherwise). "
                        "Default: DOS_REPLICATION or 1")
    p.add_argument("--codec", default=None,
                   choices=["raw", "pack4", "rle", "auto"],
                   help="persist blocks compressed (models.resident "
                        "RLE/pack4 containers; per-block degrade to "
                        "raw when not viable). Default: the "
                        "DOS_CPD_RESIDENT knob (raw = legacy format)")
    p.add_argument("--metrics-dump", default="",
                   help="write a JSON obs-metrics snapshot here on exit "
                        "(build_blocks_resumed_total etc.)")
    p.add_argument("-v", "--verbose", action="count", default=0)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    set_verbosity(args.verbose)
    outdir = args.outdir or os.path.dirname(os.path.abspath(args.input))
    partkey = args.partkey if args.partmethod == "alloc" else args.partkey[0]

    from ..utils.env import env_cast

    replication = args.replication
    if replication is None:
        replication = env_cast("DOS_REPLICATION", 1, int)
    if not 1 <= replication <= args.maxworker:
        # env policy: degrade, don't crash — and match the head, which
        # ignores an out-of-range DOS_REPLICATION the same way
        # (ClusterConfig.effective_replication)
        log.warning("ignoring replication=%d outside [1, maxworker=%d]"
                    "; building primaries only", replication,
                    args.maxworker)
        replication = 1
    graph = Graph.from_xy(args.input)
    dc_kw = ({"block_size": args.block_size} if args.block_size > 0
             else {})
    dc = DistributionController(args.partmethod, partkey, args.maxworker,
                                graph.n, replication=replication,
                                **dc_kw)
    if args.adopt_shard is not None:
        from ..models.cpd import adopt_shard_blocks

        report = adopt_shard_blocks(graph, dc, args.adopt_shard, outdir)
        log.info("worker %d: adopted shard %d (%d block(s): %d ok, "
                 "%d unverified, %d healed)", args.workerid,
                 args.adopt_shard, report["blocks"], report["ok"],
                 report["unverified"], len(report["healed"]))
        print(f"worker {args.workerid}: adopted shard "
              f"{args.adopt_shard} ({report['blocks']} block(s), "
              f"{len(report['healed'])} healed) -> {outdir}")
        if args.metrics_dump:
            from ..obs import metrics as obs_metrics

            obs_metrics.REGISTRY.dump_json(args.metrics_dump)
        return 0
    written = build_worker_shard(graph, dc, args.workerid, outdir,
                                 chunk=args.chunk,
                                 resume=not args.no_resume,
                                 method=args.method, codec=args.codec)
    n_replica = 0
    if dc.replication > 1:
        from ..models.cpd import build_replica_shards

        replica_written = build_replica_shards(
            graph, dc, args.workerid, outdir, chunk=args.chunk,
            resume=not args.no_resume, method=args.method)
        n_replica = sum(len(v) for v in replica_written.values())
    log.info("worker %d: wrote %d primary block(s)%s to %s",
             args.workerid, len(written),
             f" + {n_replica} replica block(s)" if n_replica else "",
             outdir)
    print(f"worker {args.workerid}: {len(written)} block(s)"
          + (f" + {n_replica} replica block(s)" if dc.replication > 1
             else "") + f" -> {outdir}")
    if args.metrics_dump:
        from ..obs import metrics as obs_metrics

        obs_metrics.REGISTRY.dump_json(args.metrics_dump)
    return 0


if __name__ == "__main__":
    sys.exit(main())
