"""Resident query server: the framework's ``fifo_auto``.

Behavior parity with reference C3 (SURVEY.md §2.2): on start, load the
graph, the first diff, and this worker's CPD shard; create the command FIFO
``/tmp/worker<wid>.fifo`` and block on it. Per request: parse the 2-line
config (JSON knobs + ``queryfile answerfifo difffile``), read the query
file, answer the batch, write ONE CSV stats line to the answer FIFO. Stays
resident across requests.

Extensions over the reference:

* a ``__DOS_STOP__`` line on the command FIFO shuts the server down cleanly
  (the reference can only be killed via tmux);
* errors answer the FIFO with an all-zero failure row instead of leaving the
  head blocked forever on ``cat <answer>``;
* launched as ``python -m distributed_oracle_search_tpu.worker.server -c
  conf.json --workerid N`` (by ``cli.make_fifos`` or by hand).
"""

from __future__ import annotations

import os
import sys

import numpy as np

from ..data.graph import Graph
from ..parallel.partition import DistributionController
from ..transport.wire import (
    Request, StatsRow, paths_file_for, read_query_file, write_paths_file,
)
from ..transport.fifo import command_fifo_path
from ..utils.config import ClusterConfig
from ..utils.log import get_logger, set_verbosity
from .engine import ShardEngine

log = get_logger(__name__)

STOP_TOKEN = "__DOS_STOP__"


class FifoServer:
    def __init__(self, conf: ClusterConfig, wid: int,
                 command_fifo: str | None = None,
                 alg: str = "table-search"):
        self.conf = conf
        self.wid = wid
        self.command_fifo = command_fifo or command_fifo_path(wid)
        graph = Graph.from_xy(conf.xy_file)
        dc = DistributionController(conf.partmethod, conf.partkey,
                                    conf.maxworker, graph.n)
        self.engine = ShardEngine(graph, dc, wid, conf.outdir, alg=alg)
        # preload the first diff's weights like the reference server does
        # (make_fifos.py:18 loads only diffs[0])
        if conf.diffs:
            self.engine._weights_for(conf.diffs[0], no_cache=False)

    # ------------------------------------------------------------ serving
    def _ensure_fifo(self) -> None:
        if os.path.exists(self.command_fifo):
            os.remove(self.command_fifo)
        os.mkfifo(self.command_fifo)

    def handle(self, req: Request) -> StatsRow:
        queries = read_query_file(req.queryfile)
        _, _, _, stats = self.engine.answer(queries, req.config,
                                            req.difffile)
        if self.engine.last_paths is not None:
            # extraction rides the shared dir, not the stats FIFO (wire
            # extension: transport.wire.paths_file_for)
            write_paths_file(paths_file_for(req.queryfile),
                             *self.engine.last_paths)
        return stats

    def serve_forever(self) -> None:
        self._ensure_fifo()
        log.info("worker %d serving on %s", self.wid, self.command_fifo)
        try:
            while True:
                # blocking open = rendezvous with the head's writer
                with open(self.command_fifo) as f:
                    text = f.read()
                if STOP_TOKEN in text:
                    log.info("worker %d: stop requested", self.wid)
                    return
                if not text.strip():
                    continue
                try:
                    req = Request.decode(text)
                except ValueError as e:
                    log.error("bad request: %s", e)
                    self._answer_malformed(text)
                    continue
                try:
                    stats = self.handle(req)
                except Exception as e:  # noqa: BLE001 — never leave the
                    # head blocked on `cat answer`; send a failure row
                    log.exception("batch failed: %s", e)
                    stats = StatsRow.failed()
                self._reply(req.answerfifo, stats.encode_wire() + "\n")
        finally:
            if os.path.exists(self.command_fifo):
                os.remove(self.command_fifo)

    #: how long to wait for the head to open its answer-FIFO reader
    REPLY_DEADLINE_S = 30.0

    def _reply(self, answerfifo: str, line: str) -> None:
        """Write the stats line without ever wedging the server: a
        blocking ``open(fifo, 'w')`` would hang forever if the head's
        ``cat <answer>`` was killed before opening its end. Non-blocking
        open with a bounded deadline; drop the reply (logged) if no
        reader appears."""
        import errno
        import time as _time

        deadline = _time.monotonic() + self.REPLY_DEADLINE_S
        fd = -1
        while fd < 0:
            try:
                fd = os.open(answerfifo, os.O_WRONLY | os.O_NONBLOCK)
            except OSError as e:
                if e.errno not in (errno.ENXIO, errno.ENOENT):
                    log.error("cannot open %s: %s", answerfifo, e)
                    return
                if _time.monotonic() > deadline:
                    log.error("no reader on %s within %.0fs; dropping "
                              "reply", answerfifo, self.REPLY_DEADLINE_S)
                    return
                _time.sleep(0.05)
        try:
            # reader present: restore blocking mode for the write itself
            import fcntl
            fcntl.fcntl(fd, fcntl.F_SETFL,
                        fcntl.fcntl(fd, fcntl.F_GETFL) & ~os.O_NONBLOCK)
            os.write(fd, line.encode())
        finally:
            os.close(fd)

    def _answer_malformed(self, text: str) -> None:
        """Best effort: recover the answer FIFO path from line 2 of a
        malformed request and send the failure sentinel, so the head's
        ``cat <answer>`` never blocks forever."""
        lines = text.strip("\n").split("\n")
        if len(lines) < 2:
            return
        tokens = lines[1].split()
        if len(tokens) < 2:
            return
        answerfifo = tokens[1]
        if os.path.exists(answerfifo):
            self._reply(answerfifo, StatsRow.failed().encode_wire() + "\n")

    def stop_file(self) -> None:
        """Write the stop token into our own FIFO (for another process)."""
        with open(self.command_fifo, "w") as f:
            f.write(STOP_TOKEN + "\n")


def stop_server(command_fifo: str) -> None:
    with open(command_fifo, "w") as f:
        f.write(STOP_TOKEN + "\n")


def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-c", default="./example-cluster-conf.json",
                   help="cluster config JSON")
    p.add_argument("-w", "--workerid", type=int, required=True)
    p.add_argument("--fifo", default=None,
                   help="command FIFO path override")
    p.add_argument("--alg", default="table-search",
                   choices=["table-search", "astar"],
                   help="serving algorithm (reference hard-codes "
                        "table-search, make_fifos.py:20; astar serves the "
                        "hscale/fscale family)")
    p.add_argument("-v", "--verbose", action="count", default=0)
    args = p.parse_args(argv)
    set_verbosity(args.verbose)

    conf = ClusterConfig.load(args.c)
    server = FifoServer(conf, args.workerid, command_fifo=args.fifo,
                        alg=args.alg)
    server.serve_forever()
    return 0


if __name__ == "__main__":
    sys.exit(main())
