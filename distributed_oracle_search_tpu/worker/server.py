"""Resident query server: the framework's ``fifo_auto``.

Behavior parity with reference C3 (SURVEY.md §2.2): on start, load the
graph, the first diff, and this worker's CPD shard; create the command FIFO
``/tmp/worker<wid>.fifo`` and block on it. Per request: parse the 2-line
config (JSON knobs + ``queryfile answerfifo difffile``), read the query
file, answer the batch, write ONE CSV stats line to the answer FIFO. Stays
resident across requests.

Extensions over the reference:

* a ``__DOS_STOP__`` line on the command FIFO shuts the server down cleanly
  (the reference can only be killed via tmux);
* errors answer the FIFO with an all-zero failure row instead of leaving the
  head blocked forever on ``cat <answer>``;
* launched as ``python -m distributed_oracle_search_tpu.worker.server -c
  conf.json --workerid N`` (by ``cli.make_fifos`` or by hand).
"""

from __future__ import annotations

import collections
import json
import os
import sys
import threading

import numpy as np

from ..data.graph import Graph
from ..integrity.fingerprint import answer_fingerprint
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from ..parallel.partition import DistributionController
from ..testing import faults
from ..transport.wire import (
    HealthStatus, PING_TOKEN, Request, StatsRow, paths_file_for,
    read_query_file, results_file_for, write_paths_file,
    write_results_file,
)
from ..transport.fifo import command_fifo_path
from ..utils.config import ClusterConfig
from ..utils.env import env_cast
from ..utils.locks import OrderedLock
from ..utils.log import get_logger, set_verbosity, set_worker_id
from .engine import ShardEngine

log = get_logger(__name__)

STOP_TOKEN = "__DOS_STOP__"

# serve-loop health counters, declared at import so a snapshot shows the
# failure paths at zero even when they never fired (the reference had no
# visibility into any of these — frames and replies just vanished)
M_FRAMES = obs_metrics.counter(
    "server_frames_received_total", "frame starts seen on the command FIFO")
M_MALFORMED = obs_metrics.counter(
    "server_frames_malformed_total",
    "stray non-frame lines + undecodable 2-line requests")
M_HALF = obs_metrics.counter(
    "server_frames_half_total",
    "frames whose second line never arrived (timeout or config-only)")
M_BATCH_FAIL = obs_metrics.counter(
    "server_batches_failed_total", "engine exceptions answered with FAIL")
M_REPLIES = obs_metrics.counter(
    "server_replies_sent_total", "stats lines written to answer FIFOs")
M_DROPPED = obs_metrics.counter(
    "server_replies_dropped_total",
    "replies dropped: no reader within the deadline, or reader vanished")
M_REPLY_WAIT = obs_metrics.histogram(
    "server_reply_open_wait_seconds",
    "time a reply waited for the head to open its answer-FIFO reader")
M_PINGS = obs_metrics.counter(
    "server_pings_answered_total",
    "__DOS_PING__ control frames answered with a health line")
M_PING_DROPS = obs_metrics.counter(
    "server_ping_replies_dropped_total",
    "health replies dropped (prober gone) — kept separate from "
    "server_replies_dropped_total so data-plane drop alerts stay clean")
M_REPLICA_BATCHES = obs_metrics.counter(
    "server_replica_batches_total",
    "batches answered from a hosted REPLICA shard (failover/hedge "
    "traffic re-routed off the shard's primary)")
M_STALE_EPOCH = obs_metrics.counter(
    "server_stale_epoch_total",
    "batches refused with STALE_EPOCH: the request was routed under a "
    "NEWER partition-table epoch than this worker has, even after a "
    "membership refresh")
M_STALE_DIFF = obs_metrics.counter(
    "server_stale_diff_total",
    "batches refused with STALE_DIFF: the request named a fused diff "
    "from a NEWER traffic epoch than this worker's segment stream "
    "shows, even after a refresh")
G_RPC_CONNS = obs_metrics.gauge(
    "rpc_server_connections",
    "live client connections on this worker's RPC accept loop")
M_RPC_BATCHES = obs_metrics.counter(
    "rpc_server_batches_total",
    "batches answered over the socket transport (the RPC twin of "
    "server_replies_sent_total)")
M_RPC_DROPPED = obs_metrics.counter(
    "rpc_server_replies_dropped_total",
    "RPC replies dropped (drop-reply fault, or the client vanished "
    "before the reply frame)")
M_RPC_MALFORMED = obs_metrics.counter(
    "rpc_server_frames_malformed_total",
    "request frames whose config was undecodable (answered FAIL, "
    "never a wedge) — the socket twin of server_frames_malformed_total")
M_L2_HITS = obs_metrics.counter(
    "worker_l2_hits_total",
    "queries answered from the shard-owner L2 cache before the kernel")
M_L2_MISSES = obs_metrics.counter(
    "worker_l2_misses_total",
    "L2 lookups that fell through to the kernel")
M_L2_ADMIT_DENIED = obs_metrics.counter(
    "gateway_l2_admit_denied_total",
    "L2 inserts withheld by the second-hit admission doorkeeper "
    "(DOS_GATEWAY_L2_ADMIT=second-hit): first-miss keys only mark the "
    "ghost list, one-hit wonders never churn the byte budget")


class FifoServer:
    def __init__(self, conf: ClusterConfig, wid: int,
                 command_fifo: str | None = None,
                 alg: str = "table-search",
                 traffic_dir: str | None = None):
        from ..parallel import membership

        self.conf = conf
        self.wid = wid
        self.alg = alg
        #: live-traffic gate (``--traffic-dir``): a gate-only epoch
        #: manager over the shared segment stream — it never
        #: materializes fused files (the head did), it only tracks the
        #: stream's epoch so a request stamped with a NEWER diff epoch
        #: triggers a refresh-then-refuse instead of a failed open() on
        #: a fused file this worker's NFS view has not seen yet
        self.traffic = None
        if traffic_dir:
            from ..traffic import DiffEpochManager

            self.traffic = DiffEpochManager(traffic_dir,
                                            materialize=False)
            self.traffic.refresh()
        #: shard-owner L2 result cache (gateway tier, ``DOS_GATEWAY_
        #: L2_BYTES``): hot (s, t) entries answered BEFORE the kernel,
        #: keyed like the frontend L1 (diff path + knob fingerprint +
        #: both epochs) so fleet cache capacity scales with workers.
        #: Default 0 keeps pre-gateway workers byte-identical.
        from ..gateway.config import GatewayConfig
        from ..serving.cache import ResultCache

        gconf = GatewayConfig.from_env()
        self.l2 = ResultCache(gconf.l2_bytes)
        #: L2 admission policy (``DOS_GATEWAY_L2_ADMIT``): ``all``
        #: inserts every miss (byte-identical pre-HA behavior);
        #: ``second-hit`` keeps a ghost list of once-missed keys and
        #: admits only on the second miss, so one-hit-wonder queries
        #: cannot churn the byte budget
        self._l2_admit = gconf.l2_admit
        self._l2_seen: collections.OrderedDict = collections.OrderedDict()
        self._l2_seen_lock = OrderedLock("worker.FifoServer.l2_admit")
        if self.l2.enabled and self.traffic is not None:
            # scoped invalidation LOCAL to the shard owning the updated
            # edges: the gate-only epoch manager still computes each
            # swap's affected-edge delta, so the L2 re-keys its
            # provably-safe survivors exactly like the head's L1 did
            self._l2_prev = self.traffic.active()[:2]
            self.traffic.on_swap = self._l2_on_swap
        self.command_fifo = command_fifo or command_fifo_path(wid)
        self.graph = Graph.from_xy(conf.xy_file)
        self.dc = DistributionController(
            conf.partmethod, conf.partkey, conf.maxworker, self.graph.n,
            replication=conf.effective_replication())
        # elastic membership: the durable assignment (epoch + shard
        # owners) next to the index overrides the conf's static
        # identity — absent for a pre-elastic fleet (epoch 0)
        self._membership_state = membership.load_state(conf.outdir)
        if self._membership_state is not None:
            self.dc = membership.apply_state(self.dc,
                                             self._membership_state)
        self.epoch = self.dc.epoch
        #: lazily-loaded engines for the REPLICA shards this worker
        #: hosts (rank 1..R-1): failover traffic pays the replica load
        #: on first use, never at startup
        self._replica_engines: dict[int, ShardEngine] = {}
        # the eager primary engine serves the first shard this worker
        # OWNS (identity assignment: its own wid — today's behavior).
        # A fresh joiner owns nothing until its first epoch commits; it
        # starts engine-less and loads adopted shards lazily through
        # engine_for_shard, so join really is drain-free
        own = next((s for s in range(self.dc.maxworker)
                    if self.dc.owner_of(s) == wid), None)
        self.engine: ShardEngine | None = None
        if own is not None:
            self.engine = ShardEngine(self.graph, self.dc, wid,
                                      conf.outdir, alg=alg, shard=own)
            self._replica_engines[own] = self.engine
            # preload the first diff's weights like the reference
            # server does (make_fifos.py:18 loads only diffs[0])
            if conf.diffs:
                self.engine._weights_for(conf.diffs[0], no_cache=False)
        else:
            log.info("worker %d owns no shard at epoch %d (fresh "
                     "joiner); engines load lazily on adoption "
                     "traffic", wid, self.epoch)
        #: serializes engine answers across the FIFO and RPC serve
        #: loops (one ShardEngine, two transports over it)
        self._answer_lock = OrderedLock("worker.FifoServer.answer")

    @property
    def answer_lock(self) -> OrderedLock:
        """The cross-transport answer mutex; created lazily so bare
        test servers that skip ``__init__`` still serve."""
        lock = getattr(self, "_answer_lock", None)
        if lock is None:
            lock = self._answer_lock = OrderedLock(
                "worker.FifoServer.answer")
        return lock

    def engine_for_shard(self, shard: int) -> ShardEngine:
        """The engine serving ``shard``'s rows — the primary engine for
        an owned shard, a lazily-created replica engine for shards whose
        replica this worker hosts (or that it is mid-ADOPTING during a
        membership migration window), and a routing-invariant error for
        anything else (the engine's own check would catch it, but this
        diagnostic names the replica map)."""
        from ..parallel import membership

        eng = self._replica_engines.get(shard)
        if eng is None:
            def _hosted():
                return membership.hosted_shards(
                    getattr(self, "_membership_state", None), self.dc,
                    self.wid)

            hosted = _hosted()
            if shard not in hosted:
                # before refusing, re-read membership: a migration
                # WINDOW opens without an epoch bump, so a worker
                # started before `begin` only learns it is the adopter
                # when dual-read traffic actually lands here
                self._refresh_membership()
                hosted = _hosted()
            if shard not in hosted:
                raise ValueError(
                    f"worker {self.wid} hosts no replica of shard "
                    f"{shard} (hosted: {sorted(hosted)})"
                    " — routing invariant violated")
            log.info("worker %d: loading shard %d for failover/"
                     "adoption traffic", self.wid, shard)
            try:
                rank = self.dc.replica_rank(shard, self.wid)
            except ValueError:
                # mid-adoption: not in the shard's replica chain yet —
                # serve the primary block set the catch-up verified
                rank = 0
            eng = ShardEngine(self.graph, self.dc, self.wid,
                              self.conf.outdir, alg=self.alg,
                              shard=shard, replica=rank)
            self._replica_engines[shard] = eng
        return eng

    # ------------------------------------------------------------ serving
    def _ensure_fifo(self) -> None:
        if os.path.exists(self.command_fifo):
            os.remove(self.command_fifo)
        os.mkfifo(self.command_fifo)

    def handle(self, req: Request) -> StatsRow:
        if req.config.trace_id:
            # wire extension (obs.trace): the head stamped this batch
            # with a trace id — capture our spans under it and ship them
            # back as a sidecar next to the query file, like .paths
            with obs_trace.capture(req.config.trace_id) as cap:
                stats = self._handle(req)
            try:
                obs_trace.write_events(
                    obs_trace.trace_sidecar_for(req.queryfile),
                    cap.events)
            except OSError as e:
                log.error("cannot write trace sidecar for %s: %s",
                          req.queryfile, e)
            return stats
        return self._handle(req)

    def _handle(self, req: Request) -> StatsRow:
        with obs_trace.span("worker.receive", wid=self.wid,
                            queryfile=req.queryfile):
            queries = read_query_file(req.queryfile)
        cost, plen, fin, stats, paths = self.answer_queries(
            queries, req.config, req.difffile)
        if paths is not None:
            # extraction rides the shared dir, not the stats FIFO (wire
            # extension: transport.wire.paths_file_for)
            write_paths_file(paths_file_for(req.queryfile), *paths)
        if req.config.results and (len(queries)
                                   or self.engine is not None):
            # per-query answers for the online serving frontend — same
            # shared-dir sidecar pattern as .paths (wire extension:
            # transport.wire.results_file_for). The guard preserves the
            # pre-refactor shape exactly: an engine-less empty batch
            # answered the empty row without materializing a sidecar
            fp = None
            if req.config.answer_fp:
                # fingerprint at answer birth (integrity wire
                # extension); the corrupt-answer fault fires AFTER, so
                # the head's verifier is what must catch the rot
                fp = answer_fingerprint(cost, plen, fin)
                if faults.inject("corrupt-answer", self.wid) is not None:
                    cost = np.array(cost, np.int64, copy=True)
                    if len(cost):
                        cost[0] ^= 1
            write_results_file(results_file_for(req.queryfile),
                               cost, plen, fin, fp=fp)
        return stats

    def answer_queries(self, queries: np.ndarray, config, difffile: str):
        """The file-less core of one batch — shard-aware engine
        selection, the engine answer, captured path prefixes — shared
        by the FIFO serve loop (which wraps it in query-file/sidecar
        IO) and the RPC serve loop (which ships the same outputs as
        reply-frame payload segments). Returns ``(cost, plen, fin,
        stats, paths)`` with ``paths = engine.last_paths`` or None."""
        engine = self.engine
        if len(queries):
            # shard-aware dispatch: a failover/hedge batch targets a
            # shard we host as a replica — or one we own/are adopting
            # under an elastic membership assignment — serve it from
            # that shard's engine instead of failing the primary's
            # routing invariant. The scan runs unconditionally (one
            # np.unique over the batch targets): it is also how a
            # worker started BEFORE a migration window discovers it is
            # the adopter (engine_for_shard refreshes membership on a
            # hosted miss), and a genuine misroute still fails with
            # the routing-invariant diagnostic, now naming the full
            # hosted-shard map.
            shards = np.unique(self.dc.worker_of(queries[:, 1]))
            if len(shards) == 1 and (engine is None
                                     or int(shards[0]) != engine.shard):
                engine = self.engine_for_shard(int(shards[0]))
                if (engine is not self.engine
                        and int(self.dc.owner_of(int(shards[0])))
                        != self.wid):
                    # count only genuinely re-routed traffic: after a
                    # leave consolidates two OWNED shards onto this
                    # worker, the non-eager one's batches are
                    # authoritative, not failover
                    M_REPLICA_BATCHES.inc()
        if engine is None:
            if len(queries):
                # a fresh joiner got a batch it has no engine for (the
                # single-shard case resolved above would have raised or
                # loaded one; this is a multi-shard misroute): FAIL it
                # loudly so failover walks on — an ok=True zero row
                # would silently swallow the queries
                raise ValueError(
                    f"worker {self.wid} owns no shard and the batch "
                    f"spans shards "
                    f"{np.unique(self.dc.worker_of(queries[:, 1])).tolist()}"
                    " — routing invariant violated")
            # an empty batch needs no engine: answer the empty row
            return (np.zeros(0, np.int64), np.zeros(0, np.int64),
                    np.zeros(0, bool), StatsRow(), None)
        l2 = getattr(self, "l2", None)
        if (l2 is not None and l2.enabled
                and not (getattr(config, "extract", False)
                         and getattr(config, "k_moves", 0) > 0)):
            # extraction batches need the REAL per-move prefixes on the
            # paths sidecar; everything else can short-circuit
            return self._answer_l2(engine, queries, config, difffile)
        cost, plen, fin, stats = engine.answer(queries, config,
                                               difffile)
        return cost, plen, fin, stats, engine.last_paths

    def _answer_l2(self, engine, queries: np.ndarray, config,
                   difffile: str):
        """The two-level cache plane's worker half: per-query L2
        lookups before the kernel, the kernel only over the misses,
        results merged back in query order. Keys mirror the frontend
        L1 (diff path, knob fingerprint, membership epoch, diff epoch)
        so an entry can never outlive the state that computed it; for
        sig-requesting callers a hit fabricates its paths row from the
        stored signature (sentinel ``moves=-1`` when it cannot — the
        frontend then conservatively treats the entry sig-less)."""
        from ..serving.cache import knob_fingerprint

        l2 = self.l2
        fp = knob_fingerprint(config)
        epoch = int(getattr(self, "epoch", 0))
        depoch = int(getattr(config, "diff_epoch", 0) or 0)
        q = np.asarray(queries)
        n = len(q)
        keys = [(int(q[i, 0]), int(q[i, 1]), str(difffile), fp,
                 epoch, depoch) for i in range(n)]
        sig_k = int(getattr(config, "sig_k", 0) or 0)
        width = sig_k + 1 if sig_k > 0 else 0
        cost = np.zeros(n, np.int64)
        plen = np.zeros(n, np.int64)
        fin = np.zeros(n, bool)
        nodes = np.zeros((n, width), np.int64) if width else None
        moves = np.full(n, -1, np.int64) if width else None
        miss_idx = []
        for i, key in enumerate(keys):
            hit = l2.get_with_sig(key)
            if hit is None:
                miss_idx.append(i)
                continue
            (c, p, f), sig = hit
            cost[i], plen[i], fin[i] = int(c), int(p), bool(f)
            if (width and sig is not None and 0 < len(sig) <= width
                    and len(sig) - 1 == int(p)):
                srt = sorted(sig)
                nodes[i, :len(srt)] = srt
                moves[i] = len(srt) - 1
        M_L2_HITS.inc(n - len(miss_idx))
        M_L2_MISSES.inc(len(miss_idx))
        stats = StatsRow()
        if miss_idx:
            idx = np.asarray(miss_idx)
            c2, p2, f2, stats = engine.answer(
                np.ascontiguousarray(q[idx]), config, difffile)
            cost[idx], plen[idx], fin[idx] = c2, p2, f2
            lp = engine.last_paths
            lp_ok = (width and lp is not None
                     and lp[0].shape[1] == width)
            if lp_ok:
                nodes[idx] = lp[0]
                moves[idx] = lp[1]
            for j, i in enumerate(miss_idx):
                sig = None
                if lp_ok and int(lp[1][j]) == int(p2[j]):
                    sig = frozenset(
                        int(x) for x in lp[0][j, :int(lp[1][j]) + 1])
                if self._l2_admit_key(keys[i]):
                    l2.put(keys[i],
                           (int(c2[j]), int(p2[j]), bool(f2[j])), sig)
        paths = (nodes, moves) if width else None
        return cost, plen, fin, stats, paths

    def _l2_admit_key(self, key) -> bool:
        """Admission doorkeeper for one missed key. ``all`` admits
        everything; ``second-hit`` admits only a key whose FIRST miss
        already marked the ghost list (bounded FIFO of key hashes —
        a ghost entry costs a set slot, not a cached value's bytes)."""
        if self._l2_admit != "second-hit":
            return True
        cap = max(1024, int(self.l2.max_bytes) // 256)
        with self._l2_seen_lock:
            if self._l2_seen.pop(key, None) is not None:
                return True
            self._l2_seen[key] = True
            while len(self._l2_seen) > cap:
                self._l2_seen.popitem(last=False)
        M_L2_ADMIT_DENIED.inc()
        return False

    def _l2_on_swap(self, epoch: int, difffile: str,
                    affected) -> None:
        """Diff-epoch swap hook (gate-only epoch manager): scoped
        invalidation of this shard's L2 — entries whose cached walk
        provably avoids every updated edge re-key to the new fusion,
        the rest drop. Runs on whichever thread refreshed the stream,
        outside the manager's lock."""
        old_diff, old_epoch = "", 0
        prev = getattr(self, "_l2_prev", None)
        if prev is not None:
            old_epoch, old_diff = int(prev[0]), str(prev[1])
        self._l2_prev = (epoch, difffile)
        dropped, kept, reason = self.l2.invalidate_scoped(
            affected, difffile, epoch,
            max_edges=self.traffic.scoped_max,
            old_diff=old_diff, old_depoch=old_epoch)
        log.info("worker %d L2 swap epoch %d -> %d: %d dropped (%s), "
                 "%d re-keyed", self.wid, old_epoch, epoch, dropped,
                 reason, kept)

    def serve_forever(self) -> None:
        """Framed request loop over a PERSISTENT command-FIFO read session.

        The reference documents a FIFO race (reference README.md:125-127)
        that a naive open-to-EOF session per request re-inherits: if
        writer B opens the FIFO before the server sees writer A's EOF,
        B's request lands in the dying session and is silently dropped —
        B then blocks forever on its answer FIFO. So instead the server
        opens the FIFO once with ``O_RDWR`` (its own write end guarantees
        ``readline`` never sees EOF, only blocks) and parses requests
        frame-by-frame: exactly two newline-terminated lines each.
        Back-to-back writers simply queue in the pipe buffer — a request
        under ``PIPE_BUF`` (4 KiB on Linux, far above any real request)
        is written atomically, so frames can never interleave.
        """
        import time as _time

        self._ensure_fifo()
        set_worker_id(self.wid)      # tag this serve thread's log records
        log.info("worker %d serving on %s", self.wid, self.command_fifo)
        # liveness state answered to __DOS_PING__ control frames (set
        # here, not __init__: bare test servers skip __init__, and the
        # uptime clock should start when serving does)
        self._t_start = _time.monotonic()
        self._batches = 0
        self._batch_failures = 0
        self._last_error = ""
        fd = os.open(self.command_fifo, os.O_RDWR)
        self._rdbuf = b""
        try:
            while True:
                line1 = self._next_line(fd)
                if STOP_TOKEN in line1:
                    log.info("worker %d: stop requested", self.wid)
                    return
                if not line1.strip():
                    continue
                if line1.lstrip().startswith(PING_TOKEN):
                    # single-line control frame: never counts as a data
                    # frame, never touches the engine
                    self._answer_ping(line1)
                    continue
                M_FRAMES.inc()
                if not line1.lstrip().startswith("{"):
                    # frame starts are self-identifying: a config line is
                    # always a JSON object, a paths line never is. A stray
                    # non-JSON line is garbage — handle it standalone so
                    # it can NEVER pair with (and eat) the next writer's
                    # config line; best-effort FAIL any FIFO it names
                    log.error("stray non-frame line: %r", line1)
                    M_MALFORMED.inc()
                    self._answer_malformed(line1)
                    continue
                # a legit writer ships both lines in ONE atomic write, so
                # line 2 is already in the pipe; bound the wait so a
                # config-only garbage frame cannot desync the stream
                line2 = self._next_line(fd, timeout=self.FRAME_TIMEOUT_S)
                if line2 is None:
                    log.error("half frame (no line 2 within %.1fs): %r",
                              self.FRAME_TIMEOUT_S, line1)
                    M_HALF.inc()
                    continue
                if STOP_TOKEN in line2:
                    # a stop chasing a truncated 1-line request must
                    # still win: never strand the shutdown token
                    log.info("worker %d: stop requested", self.wid)
                    return
                if line2.lstrip().startswith("{"):
                    # a config line where the paths line belongs: the
                    # previous writer truncated. Push it back to start the
                    # next frame instead of corrupting two requests
                    log.error("config-only half frame: %r", line1)
                    M_HALF.inc()
                    self._rdbuf = line2.encode() + self._rdbuf
                    continue
                text = line1 + line2
                try:
                    req = Request.decode(text)
                except ValueError as e:
                    log.error("bad request: %s", e)
                    M_MALFORMED.inc()
                    self._answer_malformed(text)
                    continue
                stale = (self._epoch_gate(req.config)
                         or self._traffic_gate(req.config))
                if stale is not None:
                    # version-gated refusal: the head routed this batch
                    # under a NEWER partition table than we can see —
                    # answer the sentinel so failover walks on instead
                    # of us serving rows we may no longer own
                    self._reply(req.answerfifo,
                                stale.encode_wire() + "\n")
                    continue
                kill = faults.inject("kill-mid-batch", wid=self.wid)
                if kill is not None:
                    # the injected analog of a worker crash between
                    # reading a request and answering it — the exact
                    # failure that wedges the reference head forever
                    log.error("fault: worker %d dying mid-batch",
                              self.wid)
                    if kill.mode == "exit":
                        os._exit(faults.KILL_EXIT_CODE)
                    return  # mode=raise: in-thread server dies quietly
                try:
                    if faults.inject("crash-engine",
                                     wid=self.wid) is not None:
                        raise RuntimeError("injected fault: crash-engine")
                    with self.answer_lock:
                        stats = self.handle(req)
                    self._batches += 1
                except Exception as e:  # noqa: BLE001 — never leave
                    # the head blocked on `cat answer`; send a failure
                    log.exception("batch failed: %s", e)
                    M_BATCH_FAIL.inc()
                    self._batches += 1
                    self._batch_failures += 1
                    self._last_error = f"{type(e).__name__}: {e}"
                    stats = StatsRow.failed()
                delay = faults.inject("delay", wid=self.wid)
                if delay is not None:
                    log.warning("fault: delaying reply %.2fs", delay.delay)
                    _time.sleep(delay.delay)
                if faults.inject("drop-reply", wid=self.wid) is not None:
                    log.error("fault: dropping reply to %s",
                              req.answerfifo)
                    M_DROPPED.inc()
                    continue
                self._reply(req.answerfifo, stats.encode_wire() + "\n")
        finally:
            os.close(fd)
            if os.path.exists(self.command_fifo):
                os.remove(self.command_fifo)

    #: bound on the gap between a frame's two lines (one atomic writer
    #: write puts both in the pipe together; only garbage arrives alone)
    FRAME_TIMEOUT_S = 2.0

    def _next_line(self, fd: int, timeout: float | None = None):
        """Next newline-terminated line off the persistent FIFO fd (own
        buffering — a buffered file object would hide pipe data from
        ``select``). ``timeout`` bounds the TOTAL wait (None = forever):
        the deadline is absolute, so a byte-trickling writer that keeps
        waking ``select`` without ever completing a line cannot hold a
        half-frame wait open indefinitely. Returns None on timeout."""
        import select
        import time as _time

        deadline = (None if timeout is None
                    else _time.monotonic() + timeout)
        while True:
            nl = self._rdbuf.find(b"\n")
            if nl >= 0:
                line = self._rdbuf[:nl + 1]
                self._rdbuf = self._rdbuf[nl + 1:]
                return line.decode(errors="replace")
            if deadline is not None:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    return None
                ready, _, _ = select.select([fd], [], [], remaining)
                if not ready:
                    return None
            chunk = os.read(fd, 4096)
            if not chunk:       # cannot happen with our own O_RDWR write
                _time.sleep(0.01)  # defensive: never spin
            self._rdbuf += chunk

    @property
    def reply_deadline_s(self) -> float:
        """How long to wait for the head to open its answer-FIFO reader.
        Read lazily (not at import) so tests/monkeypatched env work; a
        malformed value falls back to the default instead of crashing."""
        v = env_cast("DOS_REPLY_DEADLINE_S", 30.0, float)
        # a zero/negative deadline would drop every reply whose reader
        # has not already opened — same guard as the native server's
        return v if v > 0 else 30.0

    def _reply(self, answerfifo: str, line: str,
               deadline_s: float | None = None,
               drop_counter=None) -> None:
        """Write the stats line without ever wedging the server: a
        blocking ``open(fifo, 'w')`` would hang forever if the head's
        ``cat <answer>`` was killed before opening its end. Non-blocking
        open with a bounded deadline (``deadline_s`` overrides the
        configured one); drop the reply (logged) if no reader appears.
        ``drop_counter`` overrides which counter books the drop (control
        frames must not pollute the data-plane drop alert)."""
        import errno
        import time as _time

        dropped = drop_counter if drop_counter is not None else M_DROPPED
        wait_s = (deadline_s if deadline_s is not None
                  else self.reply_deadline_s)
        t_wait0 = _time.monotonic()
        deadline = t_wait0 + wait_s
        fd = -1
        while fd < 0:
            try:
                fd = os.open(answerfifo, os.O_WRONLY | os.O_NONBLOCK)
            except OSError as e:
                if e.errno not in (errno.ENXIO, errno.ENOENT):
                    log.error("cannot open %s: %s", answerfifo, e)
                    dropped.inc()
                    return
                if _time.monotonic() > deadline:
                    log.error("no reader on %s within %.0fs; dropping "
                              "reply", answerfifo, wait_s)
                    dropped.inc()
                    return
                _time.sleep(0.05)
        M_REPLY_WAIT.observe(_time.monotonic() - t_wait0)
        try:
            # reader present: restore blocking mode for the write itself
            import fcntl
            fcntl.fcntl(fd, fcntl.F_SETFL,
                        fcntl.fcntl(fd, fcntl.F_GETFL) & ~os.O_NONBLOCK)
            os.write(fd, line.encode())
            M_REPLIES.inc()
        except OSError as e:
            # reader vanished between open and write (BrokenPipe):
            # drop the reply, never crash the serve loop
            log.error("reply to %s failed: %s", answerfifo, e)
            dropped.inc()
        finally:
            os.close(fd)

    #: reader-wait for best-effort malformed replies: a garbage frame's
    #: "answer FIFO" may be a stray path nobody reads, and the full
    #: reply deadline (default 30 s) would stall the single-threaded
    #: serve loop that long PER garbage frame
    MALFORMED_REPLY_DEADLINE_S = 2.0

    def _answer_malformed(self, text: str) -> None:
        """Best effort: find an answer-FIFO path among the tokens of a
        malformed request (any line — a stray paths line carries it in
        token 2, a full 2-line frame in line 2) and send the failure
        sentinel, so the head's ``cat <answer>`` never blocks forever."""
        import stat

        for line in text.strip("\n").split("\n"):
            for tok in line.split():
                try:
                    if stat.S_ISFIFO(os.stat(tok).st_mode):
                        self._reply(tok,
                                    StatsRow.failed().encode_wire() + "\n",
                                    deadline_s=self
                                    .MALFORMED_REPLY_DEADLINE_S)
                        return
                except OSError:
                    continue

    #: reader-wait for ping replies: the prober is already blocked on its
    #: answer FIFO when the ping lands, so a long wait only ever means
    #: the prober died — don't stall the serve loop for it
    PING_REPLY_DEADLINE_S = 5.0

    def _answer_ping(self, line: str) -> None:
        """Answer a ``__DOS_PING__ <answerfifo>`` control frame with one
        health JSON line (:class:`~..transport.wire.HealthStatus`)."""
        toks = line.split()
        if len(toks) < 2:
            log.error("ping frame names no answer FIFO: %r", line)
            return
        status = self._health_status()
        self._reply(toks[1], status.to_json() + "\n",
                    deadline_s=self.PING_REPLY_DEADLINE_S,
                    drop_counter=M_PING_DROPS)
        M_PINGS.inc()

    def stop_file(self) -> None:
        """Write the stop token into our own FIFO (for another process)."""
        stop_server(self.command_fifo)

    # -------------------------------------------------- membership gate
    def _epoch_gate(self, config) -> StatsRow | None:
        """The wire-compat version gate applied to routing state: a
        request stamped with a NEWER partition-table epoch than ours
        first triggers a membership refresh (the commit may simply not
        have been read yet — the normal case right after an epoch
        bump), and only if we are STILL older is it refused with the
        ``STALE_EPOCH`` sentinel. Requests from older epochs are always
        served (the dual-read window depends on it). Returns the
        refusal row, or None to proceed."""
        if faults.inject("stale-epoch-reply", wid=self.wid) is not None:
            # the injected analog of a worker whose membership state
            # is wedged behind the fleet: refuse even though our table
            # may be current, forcing the head's failover path
            log.error("fault: worker %d replying STALE_EPOCH", self.wid)
            M_STALE_EPOCH.inc()
            return StatsRow(ok=False, stale_epoch=True)
        req_epoch = int(getattr(config, "epoch", 0) or 0)
        if req_epoch <= getattr(self, "epoch", 0):
            return None
        self._refresh_membership()
        if req_epoch <= getattr(self, "epoch", 0):
            return None
        M_STALE_EPOCH.inc()
        log.warning("worker %d at epoch %d refusing batch from epoch "
                    "%d (membership state has no newer commit)",
                    self.wid, getattr(self, "epoch", 0), req_epoch)
        return StatsRow(ok=False, stale_epoch=True)

    def _traffic_gate(self, config) -> StatsRow | None:
        """The tolerate-older / gate-newer rule applied to the DIFF
        epoch (``RuntimeConfig.diff_epoch`` wire extension): a request
        fused at a NEWER traffic epoch than our segment stream shows
        first refreshes the stream (the segment may simply not have
        been polled yet — the normal case right after a swap), and only
        if we are STILL older refuses with the ``STALE_DIFF`` sentinel
        so the head fails over instead of this worker failing an open()
        on a not-yet-visible fused file. Requests from older diff
        epochs are always served (the spool's keep window holds their
        files). Workers without ``--traffic-dir`` never gate — the
        difffile on the wire is a concrete path they can read or fail
        loudly on."""
        traffic = getattr(self, "traffic", None)
        if traffic is None:
            return None
        req_depoch = int(getattr(config, "diff_epoch", 0) or 0)
        if req_depoch <= traffic.epoch:
            return None
        traffic.refresh()
        if req_depoch <= traffic.epoch:
            return None
        M_STALE_DIFF.inc()
        log.warning("worker %d at diff epoch %d refusing batch from "
                    "diff epoch %d (segment stream has no newer "
                    "segment)", self.wid, traffic.epoch, req_depoch)
        return StatsRow(ok=False, stale_diff=True)

    def _refresh_membership(self) -> None:
        """Re-read the durable membership state (epoch + owners +
        in-flight migration) and swap in a controller reflecting it.
        A same-epoch state still applies when its CONTENT changed —
        `begin` opens a migration window without bumping the epoch,
        and the adopter must see the window to host dual-read traffic.
        An older epoch never applies (a lagging reader must not roll
        routing back). Loaded engines keep serving — the node→shard
        map never changes, only ownership."""
        from ..parallel import membership

        if not hasattr(self, "conf"):       # bare test server
            return
        try:
            state = membership.load_state(self.conf.outdir)
        except ValueError as e:
            log.error("membership refresh failed: %s", e)
            return
        if state is None or state.epoch < getattr(self, "epoch", 0):
            return
        cur = getattr(self, "_membership_state", None)
        if cur is not None and state.to_dict() == cur.to_dict():
            return
        self._membership_state = state
        self.dc = membership.apply_state(self.dc, state)
        old_epoch = getattr(self, "epoch", 0)
        self.epoch = state.epoch
        l2 = getattr(self, "l2", None)
        if l2 is not None and l2.enabled and state.epoch != old_epoch:
            # old-epoch L2 keys are unreachable after a commit (the
            # epoch is in the key) — flush so the budget serves the
            # new assignment instead of pinning dead entries
            n = l2.invalidate()
            log.info("worker %d L2 flushed %d entries on epoch "
                     "%d -> %d", self.wid, n, old_epoch, state.epoch)
        log.info("worker %d refreshed membership (epoch %d%s)",
                 self.wid, self.epoch,
                 ", migration window open"
                 if state.migration is not None else "")

    # ----------------------------------------------------- obs endpoints
    def _health_status(self) -> HealthStatus:
        """One health truth for both probes: the ``__DOS_PING__``
        control frame and the ``/healthz`` endpoint serialize this
        same object."""
        import time as _time

        return HealthStatus(
            ok=True, wid=self.wid, pid=os.getpid(),
            uptime_s=_time.monotonic() - getattr(self, "_t_start", 0.0),
            batches=getattr(self, "_batches", 0),
            batch_failures=getattr(self, "_batch_failures", 0),
            dropped=int(M_DROPPED.value),
            last_error=getattr(self, "_last_error", ""),
        )

    def health(self) -> dict:
        """``/healthz`` payload — the same :class:`HealthStatus`
        a ``__DOS_PING__`` probe gets, minus the FIFO."""
        import dataclasses as _dc

        return _dc.asdict(self._health_status())

    def statusz(self) -> dict:
        """``/statusz`` section: serve-loop health plus what this worker
        actually hosts — its shard, any lazily-loaded replica engines
        (is failover traffic landing here?), and the build ledger's
        journaled-block count (how far a crash-resumed build got)."""
        from ..models.cpd import BuildLedger

        out = dict(self.health())
        out["alg"] = self.alg
        out["command_fifo"] = self.command_fifo
        out["shard"] = self.wid
        # worker mesh shape: how many local devices this worker's
        # engine drives (1 = legacy single-device). Older workers omit
        # the key; `dos-obs top` renders a blank, never a crash.
        eng = self.engine
        out["mesh"] = {
            "devices": int(getattr(eng, "n_lanes", 1) or 1),
            "axis": "lane",
        }
        # compressed residency: what DOS_CPD_RESIDENT resolved to for
        # this shard and the device bytes the table occupies (older
        # workers omit the key; `dos-obs top` renders a blank)
        out["resident"] = {
            "codec": str(getattr(eng, "resident_codec", "raw")),
            "bytes": int(getattr(eng, "resident_bytes", 0) or 0),
        }
        out["replica_shards_loaded"] = sorted(
            s for s in self._replica_engines if s != self.wid)
        if self.dc.replication > 1:
            out["replica_shards_hosted"] = sorted(
                int(s) for s in self.dc.replica_shards(self.wid))
        # elastic membership: which table version this worker serves
        # under, and (when a reconfiguration is in flight) the window —
        # a pre-elastic worker simply omits both keys, and consumers
        # (`dos-obs top`) render blanks for a missing key, never crash
        out["epoch"] = int(getattr(self, "epoch", 0))
        # live-traffic column: present only when this worker gates the
        # diff stream (`dos-obs top` renders a blank otherwise — the
        # same mixed-schema tolerance as the membership columns)
        traffic = getattr(self, "traffic", None)
        if traffic is not None:
            out["diff_epoch"] = int(traffic.epoch)
        # gateway cache plane: present only when the shard-owner L2 is
        # enabled (pre-gateway fleets omit the key; `dos-obs top`
        # renders blanks, never a crash)
        l2 = getattr(self, "l2", None)
        if l2 is not None and l2.enabled:
            out["l2"] = {
                "entries": len(l2),
                "max_bytes": l2.max_bytes,
                "hits": int(l2.hits),
                "misses": int(l2.misses),
                "hit_rate": round(l2.hit_rate(), 4),
                "admit": str(getattr(self, "_l2_admit", "all")),
            }
        state = getattr(self, "_membership_state", None)
        if state is not None and state.migration is not None:
            out["migration"] = dict(state.migration)
        # streaming-transport column: present only when the RPC accept
        # loop is serving (`dos-obs top` renders blanks for pre-RPC
        # workers — the same mixed-schema tolerance as the rest)
        rpc_loop = getattr(self, "rpc_loop", None)
        if rpc_loop is not None:
            out["transport"] = rpc_loop.statusz()
        # telemetry column: present only when this worker publishes
        # ticks (pre-telemetry workers omit it; consumers blank it)
        publisher = getattr(self, "telemetry", None)
        if publisher is not None:
            out["telemetry"] = publisher.statusz()
        try:
            out["build_ledger_blocks"] = len(
                BuildLedger(self.conf.outdir, self.wid).entries())
        except (OSError, ValueError):
            out["build_ledger_blocks"] = 0
        return out


class RpcServeLoop:
    """The socket accept loop beside the FIFO serve loop.

    One :class:`FifoServer` (engine, membership/diff epoch gates,
    health state, fault-injection points) served over persistent
    connections: length-prefixed frames (:mod:`..transport.frames`),
    multiplexed by frame id, queries/results as raw ndarray payload
    segments instead of shared-dir files. Each connection gets a
    ``hello`` frame advertising the credit window; requests past the
    window answer an explicit ``busy`` frame instead of queueing into a
    timeout. ``ping`` frames answer the same
    :class:`~..transport.wire.HealthStatus` the ``__DOS_PING__``
    control frame does.

    Every fault point of the FIFO loop fires here too — ``crash-engine``
    (answered FAIL), ``delay``, ``drop-reply`` (reply frame withheld;
    the client times out retryable), ``kill-mid-batch`` (``mode=exit``
    hard-exits; ``mode=raise`` tears the transport down, the in-thread
    test analog of a crash) — so chaos drills exercise the socket lane
    through the same ``DOS_FAULTS`` specs."""

    def __init__(self, server: FifoServer, socket_path: str | None = None,
                 tcp_port: int | None = None, credit: int | None = None):
        from ..transport import rpc as rpc_transport

        self.fs = server
        self.socket_path = (socket_path if socket_path is not None
                            else rpc_transport.rpc_socket_path(server.wid))
        self.tcp_port = tcp_port
        self.credit = (credit if credit is not None
                       else max(1, env_cast("DOS_RPC_CREDIT", 8, int)))
        self._listener = None
        self._threads: list = []
        self._conns: list = []
        self._writers: dict = {}    # sock -> FrameWriter (broadcasts)
        self._stop = threading.Event()
        self._lock = OrderedLock("worker.RpcServeLoop")
        self._inflight = 0
        self._served = 0

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "RpcServeLoop":
        import socket as _socket
        import threading as _threading

        if self.tcp_port is not None:
            lst = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
            lst.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
            lst.bind(("0.0.0.0", int(self.tcp_port)))
            self.endpoint = f"tcp:*:{lst.getsockname()[1]}"
        else:
            if os.path.exists(self.socket_path):
                os.remove(self.socket_path)
            lst = _socket.socket(_socket.AF_UNIX, _socket.SOCK_STREAM)
            lst.bind(self.socket_path)
            self.endpoint = f"unix:{self.socket_path}"
        lst.listen(16)
        self._listener = lst
        self.fs.rpc_loop = self     # the /statusz transport section
        t = _threading.Thread(target=self._accept_loop, daemon=True,
                              name=f"dos-rpc-accept-w{self.fs.wid}")
        self._threads.append(t)
        t.start()
        log.info("worker %d rpc serving on %s (credit %d)", self.fs.wid,
                 self.endpoint, self.credit)
        return self

    def stop(self, join_s: float = 5.0) -> None:
        self._stop.set()
        from ..transport.rpc import shutdown_close

        lst, self._listener = self._listener, None
        if lst is not None:
            shutdown_close(lst)
        with self._lock:
            conns, self._conns = list(self._conns), []
        for c in conns:
            shutdown_close(c)
        with self._lock:
            threads, self._threads = list(self._threads), []
        for t in threads:
            t.join(timeout=join_s)
        if self.tcp_port is None and os.path.exists(self.socket_path):
            try:
                os.remove(self.socket_path)
            except OSError as e:
                log.debug("rpc socket unlink failed: %s", e)

    # ------------------------------------------------------------ serving
    def _accept_loop(self) -> None:
        import threading as _threading

        while not self._stop.is_set():
            try:
                sock, _ = self._listener.accept()
            except (OSError, AttributeError):
                return      # listener closed by stop()
            with self._lock:
                self._conns.append(sock)
            G_RPC_CONNS.add(1)
            t = _threading.Thread(
                target=self._conn_loop, args=(sock,), daemon=True,
                name=f"dos-rpc-conn-w{self.fs.wid}")
            with self._lock:
                self._threads.append(t)
            t.start()

    def _conn_loop(self, sock) -> None:
        from ..transport import frames

        reader = frames.FrameReader(sock)
        writer = frames.FrameWriter(sock)
        with self._lock:
            self._writers[sock] = writer    # telemetry broadcast lane
        try:
            writer.send({"kind": "hello", "wid": self.fs.wid,
                         "credit": self.credit})
            while not self._stop.is_set():
                fr = reader.read()
                if fr is None:
                    return                  # clean client hangup
                if fr.kind == "ping":
                    self._answer_ping(fr, writer)
                elif fr.kind == "req":
                    if not self._serve_req(fr, writer):
                        return              # kill-mid-batch mode=raise
                else:
                    # unknown kinds are the schema-tolerance rule
                    # applied to frames: skip, never kill the session
                    log.warning("ignoring unknown rpc frame kind %r",
                                fr.kind)
        except frames.TransportError as e:
            log.warning("rpc connection to worker %d died: %s",
                        self.fs.wid, e)
        except frames.FrameSchemaError as e:
            log.error("rpc peer speaks a newer frame schema: %s", e)
        finally:
            from ..transport.rpc import shutdown_close
            shutdown_close(sock)
            me = threading.current_thread()
            with self._lock:
                self._writers.pop(sock, None)
                if sock in self._conns:
                    self._conns.remove(sock)
                # prune this handler from the join list: every breaker
                # probe opens a fresh connection, and a long-lived
                # worker must not accumulate dead Thread objects
                if me in self._threads:
                    self._threads.remove(me)
            G_RPC_CONNS.add(-1)

    def _answer_ping(self, fr, writer) -> None:
        from ..transport import frames

        status = self.fs._health_status()
        try:
            writer.send({"kind": "health", "id": fr.header.get("id"),
                         "status": json.loads(status.to_json())})
            M_PINGS.inc()
        except frames.TransportError as e:
            log.warning("rpc health reply failed: %s", e)
            M_PING_DROPS.inc()

    def _serve_req(self, fr, writer) -> bool:
        """Answer one ``req`` frame; False tears the transport down
        (the ``kill-mid-batch`` in-thread analog)."""
        import time as _time

        from ..transport import frames, rpc as rpc_transport
        from ..transport.wire import StatsRow as _StatsRow

        fs = self.fs
        fid = fr.header.get("id")
        with self._lock:
            busy = self._inflight >= self.credit
            if not busy:
                self._inflight += 1
        if busy:
            # explicit backpressure: the client books BUSY now instead
            # of discovering a saturated worker by timeout
            rpc_transport.M_BUSY.inc()
            try:
                writer.send({"kind": "busy", "id": fid})
            except frames.TransportError as e:
                log.warning("rpc busy reply failed: %s", e)
            return True
        try:
            try:
                rconf = rpc_transport.config_from_wire(
                    fr.header.get("config"))
                queries = (np.asarray(fr.arrays[0], np.int64)
                           .reshape(-1, 2) if fr.arrays
                           else np.zeros((0, 2), np.int64))
            except (ValueError, TypeError) as e:
                log.error("malformed rpc request: %s", e)
                M_RPC_MALFORMED.inc()
                self._reply(writer, {"kind": "rep", "id": fid,
                                     "stats": _StatsRow.failed()
                                     .encode_wire()})
                return True
            diff = str(fr.header.get("diff") or "-")
            stale = fs._epoch_gate(rconf) or fs._traffic_gate(rconf)
            if stale is not None:
                self._reply(writer, {"kind": "rep", "id": fid,
                                     "stats": stale.encode_wire()})
                return True
            kill = faults.inject("kill-mid-batch", wid=fs.wid)
            if kill is not None:
                log.error("fault: worker %d dying mid-batch (rpc)",
                          fs.wid)
                if kill.mode == "exit":
                    os._exit(faults.KILL_EXIT_CODE)
                # mode=raise: the in-thread server dies — stop
                # accepting, close the listener so new connects are
                # refused; the torn socket is the client's signal
                self._stop.set()
                lst, self._listener = self._listener, None
                if lst is not None:
                    rpc_transport.shutdown_close(lst)
                return False
            header = {"kind": "rep", "id": fid}
            arrays: list = []
            try:
                if faults.inject("crash-engine", wid=fs.wid) is not None:
                    raise RuntimeError("injected fault: crash-engine")
                cost, plen, fin, stats, paths = self._answer(
                    rconf, queries, diff, header)
                fs._batches = getattr(fs, "_batches", 0) + 1
                if rconf.results:
                    header["res"] = True
                    cost = np.asarray(cost, np.int64)
                    plen = np.asarray(plen, np.int64)
                    fin_u8 = np.asarray(fin).astype(np.uint8)
                    if rconf.answer_fp:
                        # integrity wire extension: fingerprint the
                        # segments at birth, ride the reply header; the
                        # corrupt-answer fault fires AFTER so the
                        # head's check is what must catch it
                        header["fp"] = answer_fingerprint(
                            cost, plen, fin_u8)
                        if faults.inject("corrupt-answer",
                                         fs.wid) is not None:
                            cost = cost.copy()
                            if len(cost):
                                cost[0] ^= 1
                    arrays += [cost, plen, fin_u8]
                if paths is not None:
                    header["paths"] = True
                    arrays += [np.asarray(paths[0], np.int64),
                               np.asarray(paths[1], np.int64)]
            except Exception as e:  # noqa: BLE001 — never leave the
                # client waiting on a reply that cannot come; FAIL it
                log.exception("rpc batch failed: %s", e)
                M_BATCH_FAIL.inc()
                fs._batches = getattr(fs, "_batches", 0) + 1
                fs._batch_failures = getattr(fs, "_batch_failures",
                                             0) + 1
                fs._last_error = f"{type(e).__name__}: {e}"
                stats = _StatsRow.failed()
                header = {"kind": "rep", "id": fid}
                arrays = []
            delay = faults.inject("delay", wid=fs.wid)
            if delay is not None:
                log.warning("fault: delaying rpc reply %.2fs",
                            delay.delay)
                _time.sleep(delay.delay)
            if faults.inject("drop-reply", wid=fs.wid) is not None:
                log.error("fault: dropping rpc reply id=%r", fid)
                M_RPC_DROPPED.inc()
                return True
            header["stats"] = stats.encode_wire()
            self._reply(writer, header, arrays)
            M_RPC_BATCHES.inc()
            with self._lock:
                self._served += 1
            return True
        finally:
            with self._lock:
                self._inflight -= 1

    def _answer(self, rconf, queries, diff, header):
        """The engine answer under the cross-transport mutex, with
        worker-side span capture shipped back IN the reply header
        (``trace`` events) instead of a ``.trace`` sidecar file."""
        fs = self.fs
        if rconf.trace_id:
            with obs_trace.capture(rconf.trace_id) as cap:
                with fs.answer_lock:
                    out = fs.answer_queries(queries, rconf, diff)
            header["trace"] = cap.events
            return out
        with fs.answer_lock:
            return fs.answer_queries(queries, rconf, diff)

    def _reply(self, writer, header, arrays=()) -> None:
        from ..transport import frames

        try:
            writer.send(header, arrays)
        except frames.TransportError as e:
            # client vanished before the reply: drop, never crash the
            # conn loop (its next recv sees the same dead socket)
            log.warning("rpc reply dropped: %s", e)
            M_RPC_DROPPED.inc()

    def broadcast(self, tick: dict) -> None:
        """Push one telemetry tick on every live connection — fire and
        forget, no ``id``, no reply. A dead socket just drops its copy
        (its conn loop is already on the way out); the FrameWriter lock
        keeps the push from interleaving with an in-flight reply."""
        from ..transport import frames

        with self._lock:
            writers = list(self._writers.values())
        for w in writers:
            try:
                w.send({"kind": "telemetry", "tick": tick})
            except frames.TransportError as e:
                log.debug("telemetry broadcast dropped: %s", e)

    # ------------------------------------------------------------- status
    def statusz(self) -> dict:
        with self._lock:
            return {
                "endpoint": getattr(self, "endpoint", ""),
                "connections": len(self._conns),
                "inflight": int(self._inflight),
                "credit": int(self.credit),
                "served": int(self._served),
            }


def stop_server(command_fifo: str, deadline_s: float = 2.0) -> bool:
    """Push the stop token; never wedge the caller.

    A blocking ``open(fifo, "w")`` hangs forever when the server is
    already dead (a hard crash leaves the FIFO behind with no reader), so
    open non-blocking and give up — logged, not raised — after
    ``deadline_s``. Returns True iff the token was delivered. A live
    server always has a reader (its own ``O_RDWR`` open), so the fast
    path succeeds on the first try.
    """
    import errno
    import time as _time

    deadline = _time.monotonic() + deadline_s
    fd = -1
    while fd < 0:
        try:
            fd = os.open(command_fifo, os.O_WRONLY | os.O_NONBLOCK)
        except OSError as e:
            if e.errno == errno.ENOENT:
                log.info("no FIFO at %s; server already gone",
                         command_fifo)
                return False
            if e.errno != errno.ENXIO:
                log.error("cannot open %s to stop server: %s",
                          command_fifo, e)
                return False
            if _time.monotonic() > deadline:
                log.warning("no server reading %s within %.1fs; "
                            "skipping stop", command_fifo, deadline_s)
                return False
            _time.sleep(0.05)
    try:
        os.write(fd, (STOP_TOKEN + "\n").encode())
        return True
    except OSError as e:
        log.warning("stop token to %s failed: %s", command_fifo, e)
        return False
    finally:
        os.close(fd)


def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-c", default="./example-cluster-conf.json",
                   help="cluster config JSON")
    p.add_argument("-w", "--workerid", type=int, required=True)
    p.add_argument("--fifo", default=None,
                   help="command FIFO path override")
    p.add_argument("--alg", default="table-search",
                   choices=["table-search", "astar"],
                   help="serving algorithm (reference hard-codes "
                        "table-search, make_fifos.py:20; astar serves the "
                        "hscale/fscale family)")
    p.add_argument("-v", "--verbose", action="count", default=0)
    p.add_argument("--metrics-dump", default="",
                   help="write a JSON metrics snapshot (obs.metrics) to "
                        "this path on clean shutdown")
    p.add_argument("--obs-port", type=int, default=None,
                   help="serve live /metrics /healthz /statusz on this "
                        "port (0 = ephemeral; default off; "
                        "DOS_OBS_PORT)")
    p.add_argument("--traffic-dir", default=None,
                   help="diff segment stream directory: gate requests "
                        "whose diff epoch is newer than the stream "
                        "shows (STALE_DIFF wire sentinel)")
    p.add_argument("--rpc-socket", default=None,
                   help="unix socket for the streaming RPC serve loop "
                        "(default under DOS_TRANSPORT=rpc/auto: "
                        "DOS_RPC_SOCKET_DIR/dos-rpc-worker<wid>.sock)")
    p.add_argument("--rpc-port", type=int, default=None,
                   help="TCP port for the RPC serve loop (cross-host; "
                        "DOS_RPC_PORT+wid when the env base is set)")
    args = p.parse_args(argv)
    set_verbosity(args.verbose)
    set_worker_id(args.workerid)

    conf = ClusterConfig.load(args.c)
    server = FifoServer(conf, args.workerid, command_fifo=args.fifo,
                        alg=args.alg, traffic_dir=args.traffic_dir)
    # the streaming data plane serves BESIDE the FIFO loop (same
    # engine, same gates): on under DOS_TRANSPORT=rpc/auto or when an
    # explicit endpoint flag names one; off (byte-identical legacy)
    # under the default DOS_TRANSPORT=fifo
    from ..transport import rpc as rpc_transport
    rpc_loop = None
    want_rpc = (args.rpc_socket is not None or args.rpc_port is not None
                or rpc_transport.resolve_transport() != "fifo")
    if want_rpc:
        port = args.rpc_port
        if port is None:
            base = env_cast("DOS_RPC_PORT", 0, int)
            port = base + args.workerid if base > 0 else None
        rpc_loop = RpcServeLoop(server, socket_path=args.rpc_socket,
                                tcp_port=port).start()
        server.rpc_loop = rpc_loop
    from ..obs.http import start_obs_server
    obs_srv = start_obs_server(
        args.obs_port, health_fn=server.health,
        status_providers={"worker": server.statusz})
    # fleet telemetry: push this worker's counters/gauges/windows to the
    # head on the DOS_TELEMETRY_INTERVAL_S cadence — over the RPC lane
    # when it serves (a `telemetry` frame on every live connection) and
    # always via the FIFO sidecar file the head polls
    from ..obs import telemetry as obs_telemetry
    publisher = None
    if obs_telemetry.interval_s() > 0:
        sinks = [obs_telemetry.sidecar_sink(
            server.command_fifo + obs_telemetry.SIDECAR_SUFFIX)]
        if rpc_loop is not None:
            sinks.append(rpc_loop.broadcast)
        publisher = obs_telemetry.TelemetryPublisher(
            source=f"w{args.workerid}", sinks=sinks).start()
        server.telemetry = publisher
    try:
        server.serve_forever()
    finally:
        if publisher is not None:
            publisher.stop()
        if rpc_loop is not None:
            rpc_loop.stop()
        if obs_srv is not None:
            obs_srv.close()
        if args.metrics_dump:
            obs_metrics.REGISTRY.dump_json(args.metrics_dump)
    return 0


if __name__ == "__main__":
    sys.exit(main())
