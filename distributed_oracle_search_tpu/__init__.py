"""distributed_oracle_search_tpu — a TPU-native distributed pathfinding oracle.

A from-scratch JAX/XLA (pjit / shard_map / pallas) framework with the
capabilities of the reference system ``eggeek/distributed-oracle-search``:

* precompute Compressed Path Databases (CPDs) — per-target first-move
  shortest-path tables on a road network — sharded across workers by a node
  partitioning function (reference: ``make_cpd_auto`` + OpenMP, launched over
  ssh/tmux; here: batched min-plus Bellman-Ford sharded over a
  ``jax.sharding.Mesh``), and
* answer s–t shortest-path queries, optionally on a congestion-perturbed
  graph, by routing each query to the shard owning its **target** node
  (reference: resident ``fifo_auto --alg table-search`` C++ processes behind
  named FIFOs + NFS; here: a vmapped first-move gather/scan answering an
  entire scenario file in one XLA call).

Package layout:

``data/``      graph + scenario + diff file formats, synthetic road networks
``parallel/``  partitioning (DistributionController) and device-mesh sharding
``ops/``       JAX compute kernels (Bellman-Ford, first-move, table-search)
``models/``    oracle model families (CPD oracle, CPU reference oracles)
``transport/`` wire protocol, FIFO transport, ssh/tmux job launch
``worker/``    worker-resident shard engine, FIFO server, shard builder
``cli/``       drivers mirroring the reference entry points
``utils/``     timers, config, logging
"""

__version__ = "0.1.0"
