"""Bounded LRU result cache for skewed online traffic.

Keyed on ``(s, t, diff, knob fingerprint)`` — everything that can change
an answer. The diff is part of the key, so entries from different
congestion rounds never collide; the frontend still calls
:meth:`ResultCache.invalidate` on a diff *change* because a diff *path*
can be rewritten in place (the engine's own weight cache has the same
``no_cache`` hatch for that reason).

Capacity is a byte budget, not an entry count: entries are fixed-size
(three small ints under a small tuple key), so the budget divides by a
conservative per-entry estimate (``ENTRY_BYTES``) into a max entry
count. Thread-safe — the frontend reads on the submit path while shard
batcher threads fill on the completion path.
"""

from __future__ import annotations

from collections import OrderedDict

from ..utils.locks import OrderedLock

from ..obs import metrics as obs_metrics

#: conservative per-entry budget: key tuple (4 elements + a short diff
#: string) + 3-int value tuple + OrderedDict node overhead, measured
#: ~230 bytes on CPython 3.10; rounded up so the budget errs small
ENTRY_BYTES = 256

M_HITS = obs_metrics.counter(
    "serve_cache_hits_total", "requests short-circuited by the cache")
M_MISSES = obs_metrics.counter(
    "serve_cache_misses_total", "cache lookups that fell through")
M_EVICT = obs_metrics.counter(
    "serve_cache_evictions_total", "LRU entries evicted at the budget")
G_ENTRIES = obs_metrics.gauge(
    "serve_cache_entries", "entries resident in the result cache")
G_BYTES = obs_metrics.gauge(
    "serve_cache_bytes", "estimated bytes resident in the result cache")


def knob_fingerprint(config) -> tuple:
    """The answer-affecting subset of :class:`~..transport.wire.
    RuntimeConfig`: two frontends sharing a cache (or one frontend
    reconfigured) must never serve an answer computed under different
    knobs. ``threads``/``thread_alloc``/``verbose`` are presentation or
    no-op knobs and stay out; ``itrs`` repeats the same computation
    (last result wins) so it stays out too."""
    return (config.hscale, config.fscale, config.time, config.k_moves,
            config.debug, config.no_cache)


class ResultCache:
    """LRU over ``key -> (cost, plen, finished)``."""

    def __init__(self, max_bytes: int):
        self.max_bytes = int(max_bytes)
        self.max_entries = self.max_bytes // ENTRY_BYTES
        self._od: OrderedDict[tuple, tuple] = OrderedDict()
        self._lock = OrderedLock("serving.ResultCache")

    @property
    def enabled(self) -> bool:
        return self.max_entries > 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._od)

    def get(self, key: tuple):
        """``(cost, plen, finished)`` or None; books hit/miss."""
        if not self.enabled:
            return None
        with self._lock:
            entry = self._od.get(key)
            if entry is None:
                M_MISSES.inc()
                return None
            self._od.move_to_end(key)
            M_HITS.inc()
            return entry

    def put(self, key: tuple, value: tuple) -> None:
        if not self.enabled:
            return
        with self._lock:
            if key in self._od:
                self._od.move_to_end(key)
                self._od[key] = value
                return
            self._od[key] = value
            while len(self._od) > self.max_entries:
                self._od.popitem(last=False)
                M_EVICT.inc()
            self._set_gauges_locked()

    def invalidate(self, diff: str | None = None) -> int:
        """Drop every entry (``diff=None``) or only one diff's entries;
        returns how many were dropped. Called on diff change — see the
        module docstring for why keys alone are not enough."""
        with self._lock:
            if diff is None:
                n = len(self._od)
                self._od.clear()
            else:
                doomed = [k for k in self._od if k[2] == diff]
                for k in doomed:
                    del self._od[k]
                n = len(doomed)
            self._set_gauges_locked()
        return n

    def _set_gauges_locked(self) -> None:
        G_ENTRIES.set(len(self._od))
        G_BYTES.set(len(self._od) * ENTRY_BYTES)
