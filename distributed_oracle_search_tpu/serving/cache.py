"""Bounded LRU result cache for skewed online traffic.

Keyed on ``(s, t, diff, knob fingerprint, membership epoch, diff
epoch)`` — everything that can change an answer OR who computed it. The
membership epoch is in the key because a post-reshard hit could
otherwise serve a result computed by a worker that no longer owns the
shard; the diff epoch is in the key because the live-traffic plane
swaps the active fusion under a long-lived service. The diff *path* is
still part of the key too: a static diff file can be rewritten in place
(the engine's own weight cache has the same ``no_cache`` hatch), which
is why the frontend still calls :meth:`ResultCache.invalidate`
wholesale on a manual diff change.

**Scoped invalidation** (the live-traffic path): a diff epoch swap does
NOT have to flush everything. Each entry can carry a *path signature* —
the node set of the cached walk (``RuntimeConfig.sig_k`` extraction).
An entry whose signature provably avoids every edge the swap updated is
still correct under the new fusion (the walk follows the free-flow
first-move table, so neither its trajectory nor its cost changed) and
is **re-keyed** to the new epoch instead of dropped. Entries without a
signature (old servers, paths longer than ``sig_k``) invalidate
conservatively, and a swap touching more than the configured edge
bound falls back to the wholesale flush — the scan would cost more
than the misses.

Capacity is a byte budget, not an entry count: a signature-less entry
costs a measured flat estimate, and a signature-carrying entry is
additionally charged per signature node — a 64-node frozenset is ~16x
the flat entry, so live-traffic workloads (where most entries carry
signatures) would blow a flat-estimate budget several-fold while the
bytes gauge claimed otherwise. Thread-safe — the frontend reads on the
submit path while shard batcher threads fill on the completion path.
"""

from __future__ import annotations

from collections import OrderedDict

from ..integrity.fingerprint import value_fingerprint
from ..utils.locks import OrderedLock

from ..obs import metrics as obs_metrics

#: signature-less per-entry budget: key tuple (6 elements + a short
#: diff string) + 3-int value tuple + OrderedDict node overhead,
#: measured ~230 bytes on CPython 3.10; rounded up so the budget errs
#: small
ENTRY_BYTES = 256

#: additional budget per path-signature node: one frozenset slot plus
#: its int object (~56 bytes measured, rounded up) — entries are
#: charged for the signature they actually hold, never a flat guess
SIG_NODE_BYTES = 64

M_HITS = obs_metrics.counter(
    "serve_cache_hits_total", "requests short-circuited by the cache")
M_MISSES = obs_metrics.counter(
    "serve_cache_misses_total", "cache lookups that fell through")
M_EVICT = obs_metrics.counter(
    "serve_cache_evictions_total", "LRU entries evicted at the budget")
G_ENTRIES = obs_metrics.gauge(
    "serve_cache_entries", "entries resident in the result cache")
G_BYTES = obs_metrics.gauge(
    "serve_cache_bytes", "estimated bytes resident in the result cache")
M_INV_SCOPED = obs_metrics.counter(
    "serve_cache_invalidated_scoped_total",
    "entries dropped by SCOPED invalidation (path touches an updated "
    "edge, or no signature to prove it does not)")
M_INV_FULL = obs_metrics.counter(
    "serve_cache_invalidated_full_total",
    "entries dropped by FULL flushes (manual diff change, or a swap "
    "past the scoped-edge bound)")
M_REKEYED = obs_metrics.counter(
    "serve_cache_rekeyed_total",
    "scoped-invalidation survivors re-keyed to the new diff epoch "
    "(their path provably avoids every updated edge)")
M_FP_BAD = obs_metrics.counter(
    "cache_fingerprint_mismatch_total",
    "cache hits whose stored crc32 answer fingerprint no longer "
    "matched the entry (DOS_ANSWER_FP) — the entry is dropped and the "
    "query recomputed, never served")


def knob_fingerprint(config) -> tuple:
    """The answer-affecting subset of :class:`~..transport.wire.
    RuntimeConfig`: two frontends sharing a cache (or one frontend
    reconfigured) must never serve an answer computed under different
    knobs. ``threads``/``thread_alloc``/``verbose`` are presentation or
    no-op knobs and stay out; ``itrs`` repeats the same computation
    (last result wins) so it stays out too; ``sig_k`` only adds the
    signature extraction, never changes an answer."""
    return (config.hscale, config.fscale, config.time, config.k_moves,
            config.debug, config.no_cache)


class ResultCache:
    """LRU over ``key -> (cost, plen, finished)`` with optional
    per-entry path signatures (see module docstring)."""

    #: index of the diff path / diff epoch inside the frontend's key
    #: tuple — :meth:`invalidate_scoped` re-keys survivors through them
    KEY_DIFF = 2
    KEY_DEPOCH = 5

    def __init__(self, max_bytes: int, fingerprint: bool = False):
        self.max_bytes = int(max_bytes)
        #: DOS_ANSWER_FP: entries store a crc32 over their answer tuple
        #: at put time and re-check it on EVERY hit — a rotted entry is
        #: dropped (``cache_fingerprint_mismatch_total``) and the miss
        #: path recomputes; a corrupt answer is never served from cache
        self.fingerprint = bool(fingerprint)
        self._od: OrderedDict[tuple, tuple] = OrderedDict()
        self._sigs: dict[tuple, frozenset] = {}
        self._fps: dict[tuple, int] = {}
        self.fp_mismatches = 0
        self._bytes = 0
        #: per-INSTANCE hit/miss tallies beside the process-global
        #: counters: a gateway process hosts N replica L1s (and a test
        #: process may host L1s and a worker L2 together), and the
        #: per-replica hit rate in /statusz must not read a shared
        #: registry counter that conflates them
        self.hits = 0
        self.misses = 0
        self._lock = OrderedLock("serving.ResultCache")

    @property
    def enabled(self) -> bool:
        return self.max_bytes >= ENTRY_BYTES

    @staticmethod
    def _cost(sig: frozenset | None) -> int:
        return ENTRY_BYTES + (len(sig) * SIG_NODE_BYTES if sig else 0)

    def __len__(self) -> int:
        with self._lock:
            return len(self._od)

    def _fp_ok_locked(self, key: tuple, entry: tuple) -> bool:
        """Re-check the entry's stored fingerprint (no-op without one).
        A mismatch drops the entry on the spot — the caller books a
        miss and the query recomputes through the normal path."""
        want = self._fps.get(key)
        if want is None or value_fingerprint(entry) == want:
            return True
        M_FP_BAD.inc()
        self.fp_mismatches += 1
        del self._od[key]
        self._fps.pop(key, None)
        self._bytes -= self._cost(self._sigs.pop(key, None))
        self._set_gauges_locked()
        return False

    def get(self, key: tuple):
        """``(cost, plen, finished)`` or None; books hit/miss."""
        if not self.enabled:
            return None
        with self._lock:
            entry = self._od.get(key)
            if entry is None or not self._fp_ok_locked(key, entry):
                M_MISSES.inc()
                self.misses += 1
                return None
            self._od.move_to_end(key)
            M_HITS.inc()
            self.hits += 1
            return entry

    def get_with_sig(self, key: tuple):
        """``((cost, plen, finished), sig_or_None)`` or None; books
        hit/miss. The shard-owner L2 path uses this: a sig-requesting
        frontend needs the cached walk's node set back so the worker
        can fabricate the paths payload a fresh kernel answer would
        have carried."""
        if not self.enabled:
            return None
        with self._lock:
            entry = self._od.get(key)
            if entry is None or not self._fp_ok_locked(key, entry):
                M_MISSES.inc()
                self.misses += 1
                return None
            self._od.move_to_end(key)
            M_HITS.inc()
            self.hits += 1
            return entry, self._sigs.get(key)

    def hit_rate(self) -> float:
        """This instance's lifetime hit rate (0.0 before any lookup)."""
        n = self.hits + self.misses
        return self.hits / n if n else 0.0

    def put(self, key: tuple, value: tuple,
            sig: frozenset | None = None) -> None:
        """Insert/refresh. ``sig`` is the walk's node set when the
        dispatch captured a COMPLETE path signature (None = unknown —
        the entry then invalidates conservatively on epoch swaps)."""
        if not self.enabled:
            return
        with self._lock:
            if key in self._od:
                self._od.move_to_end(key)
                self._od[key] = value
                if sig is not None:
                    self._bytes += (self._cost(sig)
                                    - self._cost(self._sigs.get(key)))
                    self._sigs[key] = sig
            else:
                self._od[key] = value
                if sig is not None:
                    self._sigs[key] = sig
                self._bytes += self._cost(sig)
            if self.fingerprint:
                self._fps[key] = value_fingerprint(value)
            # evict on BOTH paths: a refresh that attaches a signature
            # to a previously signature-less entry grows the footprint
            # too — a stable hot pool re-answering with signatures
            # would otherwise pin far past the budget with no new key
            # ever triggering eviction
            while self._bytes > self.max_bytes and self._od:
                old_key, _ = self._od.popitem(last=False)
                self._bytes -= self._cost(self._sigs.pop(old_key, None))
                self._fps.pop(old_key, None)
                M_EVICT.inc()
            self._set_gauges_locked()

    def invalidate(self, diff: str | None = None) -> int:
        """Drop every entry (``diff=None``) or only one diff's entries;
        returns how many were dropped. Called on a manual diff change —
        see the module docstring for why keys alone are not enough."""
        with self._lock:
            if diff is None:
                n = len(self._od)
                self._od.clear()
                self._sigs.clear()
                self._fps.clear()
                self._bytes = 0
            else:
                doomed = [k for k in self._od
                          if k[self.KEY_DIFF] == diff]
                for k in doomed:
                    del self._od[k]
                    self._bytes -= self._cost(self._sigs.pop(k, None))
                    self._fps.pop(k, None)
                n = len(doomed)
            M_INV_FULL.inc(n)
            self._set_gauges_locked()
        return n

    def invalidate_scoped(self, pairs, new_diff: str, new_depoch: int,
                          max_edges: int, old_diff: str,
                          old_depoch: int) -> tuple[int, int, str]:
        """Epoch-swap invalidation: drop entries whose cached path
        touches an updated edge (or that cannot prove it does not),
        re-key the provably-safe survivors to ``(new_diff,
        new_depoch)`` so post-swap traffic keeps hitting them.

        ``pairs`` is the swap's affected-edge set (``(u, v)`` node
        tuples) — the DELTA from ``(old_diff, old_depoch)``, the active
        fusion the swap replaced. Only entries keyed at exactly that
        fusion are eligible to survive: an entry under any OTHER epoch
        (e.g. a late put from a batch that was in flight across the
        previous swap) was never tested against the intermediate
        deltas, so re-keying it could resurrect a stale cost — it
        drops unconditionally. Survivorship is therefore inductive:
        every resident entry at epoch E was verified against every
        delta between its compute epoch and E.

        Above ``max_edges`` the per-entry scan is not worth it and the
        whole cache flushes. Returns ``(dropped, kept, reason)`` with
        reason ``"scoped"`` or ``"full"``."""
        pairs = list(pairs)
        with self._lock:
            n = len(self._od)
            if n == 0:
                return 0, 0, "scoped"
            if max_edges >= 0 and len(pairs) > max_edges:
                self._od.clear()
                self._sigs.clear()
                self._fps.clear()
                self._bytes = 0
                M_INV_FULL.inc(n)
                self._set_gauges_locked()
                return n, 0, "full"
            touched = {u for u, _v in pairs} | {v for _u, v in pairs}
            # index the delta by source node: the per-entry check walks
            # the signature's own nodes (O(|sig| x deg)) instead of the
            # whole pair list — a flat scan would be O(entries x pairs)
            # inside this lock, stalling every submit for the swap's
            # duration on hub-heavy deltas
            adj: dict[int, set] = {}
            for u, v in pairs:
                adj.setdefault(u, set()).add(v)
            new_od: OrderedDict[tuple, tuple] = OrderedDict()
            new_sigs: dict[tuple, frozenset] = {}
            new_fps: dict[tuple, int] = {}
            dropped = 0
            new_bytes = 0
            for key, value in self._od.items():
                sig = self._sigs.get(key)
                safe = (sig is not None
                        and len(key) > self.KEY_DEPOCH
                        and key[self.KEY_DIFF] == old_diff
                        and key[self.KEY_DEPOCH] == int(old_depoch)
                        and (sig.isdisjoint(touched)
                             or not any(v in sig
                                        for u in sig if u in adj
                                        for v in adj[u])))
                if not safe:
                    dropped += 1
                    continue
                new_key = (key[:self.KEY_DIFF] + (new_diff,)
                           + key[self.KEY_DIFF + 1:self.KEY_DEPOCH]
                           + (int(new_depoch),))
                new_od[new_key] = value
                new_sigs[new_key] = sig
                fp = self._fps.get(key)
                if fp is not None:
                    new_fps[new_key] = fp
                new_bytes += self._cost(sig)
            self._od = new_od
            self._sigs = new_sigs
            self._fps = new_fps
            self._bytes = new_bytes
            M_INV_SCOPED.inc(dropped)
            M_REKEYED.inc(len(new_od))
            self._set_gauges_locked()
            return dropped, len(new_od), "scoped"

    def _set_gauges_locked(self) -> None:
        G_ENTRIES.set(len(self._od))
        G_BYTES.set(self._bytes)
