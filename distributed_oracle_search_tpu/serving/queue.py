"""Bounded per-shard request queue — the admission-control half of the
online path.

``try_put`` NEVER blocks: a full queue returns False and the frontend
sheds the request ``BUSY`` immediately (load beyond the bound must turn
into fast, explicit rejections, not latency). ``get_batch`` is the
micro-batcher's collection primitive: block for the first request, then
keep collecting until the batch hits ``max_batch`` or ``max_wait_s``
has elapsed since that FIRST request was enqueued — the adaptive
trade of a few milliseconds of waiting for fuller compiled-program
batches.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from ..obs import metrics as obs_metrics
from ..utils.locks import ordered_condition
from .request import ServeRequest

G_DEPTH = obs_metrics.gauge(
    "serve_queue_depth", "requests queued across all shard queues")

#: idle wakeup tick: bounds how long get_batch sleeps past a stop/close
#: signal (waits are condition-based, so real work wakes it instantly)
_IDLE_TICK_S = 0.05


class ShardQueue:
    def __init__(self, depth: int, gauge=None):
        if depth <= 0:
            raise ValueError("queue depth must be positive")
        self.depth = int(depth)
        #: optional per-shard depth gauge (the replicated frontend wires
        #: one per queue so failover load shifts are visible per shard;
        #: the aggregate ``serve_queue_depth`` always updates)
        self._gauge = gauge
        self._q: deque[ServeRequest] = deque()
        self._cond = ordered_condition("serving.ShardQueue")
        self._closed = False

    def _book(self, delta: int) -> None:
        G_DEPTH.add(delta)
        if self._gauge is not None:
            self._gauge.add(delta)

    def __len__(self) -> int:
        with self._cond:
            return len(self._q)

    @property
    def closed(self) -> bool:
        return self._closed

    def try_put(self, req: ServeRequest) -> bool:
        """Admit ``req`` unless the queue is full or closed. Never
        blocks; stamps ``req.t_enqueue`` on success."""
        with self._cond:
            if self._closed or len(self._q) >= self.depth:
                return False
            req.t_enqueue = time.monotonic()
            self._q.append(req)
            self._book(1)
            self._cond.notify()
            return True

    def close(self) -> None:
        """Refuse new requests; pending ones stay collectable so a
        drain can finish them."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def drain(self) -> list[ServeRequest]:
        """Take everything still queued (shutdown path: the caller
        completes them so no waiter ever hangs)."""
        with self._cond:
            out = list(self._q)
            self._q.clear()
            if out:
                self._book(-len(out))
            return out

    def get_batch(self, max_batch: int, max_wait_s: float,
                  stop: threading.Event) -> list[ServeRequest]:
        """Collect the next batch (see module docstring). Returns ``[]``
        when ``stop`` is set (or the queue closed) and nothing is
        queued. If requests already waited past ``max_wait_s`` while an
        earlier batch was in flight, the flush is immediate."""
        with self._cond:
            while not self._q:
                if stop.is_set() or self._closed:
                    return []
                self._cond.wait(_IDLE_TICK_S)
            flush_at = self._q[0].t_enqueue + max_wait_s
            while len(self._q) < max_batch and not stop.is_set():
                remaining = flush_at - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(min(remaining, _IDLE_TICK_S))
            n = min(max_batch, len(self._q))
            batch = [self._q.popleft() for _ in range(n)]
            self._book(-n)
            return batch
