"""Hedged dispatch: tail-latency insurance for replicated shards.

"The Tail at Scale" recipe: when a batch has been in flight on its
primary longer than the shard's recent latency quantile says it should
be, issue a DUPLICATE request to a replica and take whichever answer
lands first. The slow primary (GC pause, wedged FIFO reader, overloaded
host) stops defining the batch's latency; the duplicate work is bounded
by a hedge-rate budget so hedging can never amplify an overload (a
saturated cluster makes everything slow — hedging *more* there would be
gasoline).

Pieces:

* :class:`HedgeConfig` — the ``DOS_HEDGE_*`` env knobs (same
  degrade-don't-crash policy as ``DOS_SERVE_*``):
  ``DOS_HEDGE_QUANTILE`` (which latency quantile arms the hedge,
  default 0.95), ``DOS_HEDGE_MIN_MS`` (delay floor — also the cold
  default before enough samples exist), ``DOS_HEDGE_BUDGET`` (max
  fraction of dispatched batches that may hedge, default 0.1),
  ``DOS_HEDGE_WINDOW`` (per-shard latency samples kept),
  ``DOS_HEDGE_DISABLE=1`` (failover still works, no duplicates).
* :class:`HedgeTracker` — per-shard latency ring buffers (the adaptive
  delay) plus the budget accounting. Thread-safe; one per frontend.

The frontend drives it: primary dispatch starts, and if no answer lands
within ``tracker.delay_s(wid)`` AND ``tracker.try_issue()`` grants
budget, a duplicate goes to the next live replica; first answer
completes the batch (``hedges_won_total`` when the replica beat the
primary). The loser's thread finishes in the background and its result
is discarded — the wire/engine layers are idempotent (same rows, same
deterministic kernels), so a duplicate answer is merely redundant,
never wrong.
"""

from __future__ import annotations

import dataclasses
from collections import deque

from ..obs import metrics as obs_metrics
from ..utils.env import env_cast
from ..utils.locks import OrderedLock
from ..utils.log import get_logger

log = get_logger(__name__)

M_ISSUED = obs_metrics.counter(
    "hedges_issued_total",
    "duplicate (hedged) batch dispatches sent to a replica")
M_WON = obs_metrics.counter(
    "hedges_won_total",
    "hedged dispatches whose replica answered before the primary")
M_BUDGET_DENIED = obs_metrics.counter(
    "hedges_budget_denied_total",
    "hedge opportunities declined because the hedge-rate budget was "
    "spent (the overload-amplification guard)")


@dataclasses.dataclass(frozen=True)
class HedgeConfig:
    """Hedged-dispatch tunables (``DOS_HEDGE_*`` family)."""

    enabled: bool = True
    quantile: float = 0.95
    min_delay_ms: float = 2.0
    budget: float = 0.1
    window: int = 128

    @classmethod
    def from_env(cls, **overrides) -> "HedgeConfig":
        vals = dict(
            enabled=env_cast("DOS_HEDGE_DISABLE", 0, int) != 1,
            quantile=env_cast("DOS_HEDGE_QUANTILE", cls.quantile, float),
            min_delay_ms=env_cast("DOS_HEDGE_MIN_MS", cls.min_delay_ms,
                                  float),
            budget=env_cast("DOS_HEDGE_BUDGET", cls.budget, float),
            window=env_cast("DOS_HEDGE_WINDOW", cls.window, int),
        )
        vals.update({k: v for k, v in overrides.items()
                     if v is not None})
        return cls(**vals).validate()

    def validate(self) -> "HedgeConfig":
        if not 0.0 < self.quantile < 1.0:
            raise ValueError(
                f"hedge quantile must be in (0, 1), got {self.quantile}")
        if self.min_delay_ms < 0:
            raise ValueError("hedge min delay must be >= 0")
        if not 0.0 <= self.budget <= 1.0:
            raise ValueError(
                f"hedge budget must be a fraction in [0, 1], got "
                f"{self.budget}")
        if self.window <= 0:
            raise ValueError("hedge window must be positive")
        return self


class HedgeTracker:
    """Per-shard dispatch-latency quantiles + the hedge-rate budget.

    ``observe(wid, seconds)`` feeds winners' dispatch latencies;
    ``delay_s(wid)`` answers "how long may this shard's batch run
    before it counts as slow" — the configured quantile over the last
    ``window`` samples, floored at ``min_delay_ms`` (which is also the
    cold-start answer before :data:`MIN_SAMPLES` observations exist,
    so a fresh shard doesn't hedge off noise).

    The budget is global (not per shard): ``try_issue`` grants a hedge
    while ``hedges <= budget * dispatches`` over this tracker's
    lifetime, with a small constant grace so the very first slow batch
    of a run can still hedge.
    """

    #: samples required before the measured quantile replaces the floor
    MIN_SAMPLES = 8
    #: hedges allowed before the proportional budget kicks in
    BUDGET_GRACE = 2

    def __init__(self, config: HedgeConfig | None = None):
        self.config = config or HedgeConfig()
        self._lat: dict[int, deque] = {}
        self._dispatches = 0
        self._hedges = 0
        self._lock = OrderedLock("serving.HedgeTracker")

    # ------------------------------------------------------------ stats
    def observe(self, wid: int, seconds: float) -> None:
        with self._lock:
            self._dispatches += 1
            buf = self._lat.get(wid)
            if buf is None:
                buf = self._lat[wid] = deque(maxlen=self.config.window)
            buf.append(float(seconds))

    def delay_s(self, wid: int) -> float:
        floor = self.config.min_delay_ms / 1e3
        with self._lock:
            buf = self._lat.get(wid)
            if buf is None or len(buf) < self.MIN_SAMPLES:
                return floor
            data = sorted(buf)
        # nearest-rank quantile: index ceil(q*n) - 1
        n = len(data)
        idx = max(0, min(n - 1,
                         int(-(-self.config.quantile * n // 1)) - 1))
        return max(floor, data[idx])

    # ----------------------------------------------------------- budget
    def would_issue(self) -> bool:
        """Read-only budget check (no grant, no counters): could a
        hedge fire right now? The frontend uses it to skip the
        thread-spawning dispatch path entirely while the budget is
        spent — batches that could never hedge stay on the cheap
        inline path."""
        if not self.config.enabled or self.config.budget <= 0:
            return False
        with self._lock:
            return (self._hedges < self.BUDGET_GRACE
                    + self.config.budget * self._dispatches)

    def try_issue(self) -> bool:
        """Grant one hedge if the rate budget allows; books the grant."""
        if not self.config.enabled or self.config.budget <= 0:
            return False
        with self._lock:
            allowed = (self._hedges < self.BUDGET_GRACE
                       + self.config.budget * self._dispatches)
            if allowed:
                self._hedges += 1
                M_ISSUED.inc()
            else:
                M_BUDGET_DENIED.inc()
            return allowed

    def hedge_rate(self) -> float:
        """Hedged fraction of dispatched batches so far (the budget's
        observable)."""
        with self._lock:
            return self._hedges / max(self._dispatches, 1)
